"""Command-line interface.

The reference's "CLI" is: edit a hardcoded dataset string / constants in
main(), recompile with the commands in README.md:110-181, submit one of four
SLURM scripts; the single real flag in the codebase is `gpu_svm_main4
<n_limit>` (SURVEY.md §5.6, C26). This module is the framework replacement —
one argparse entry point whose defaults are the reference's constants, so a
zero-flag run is a parity run.

    python -m tpusvm train --train train.csv --test test.csv
    python -m tpusvm train --synthetic mnist-like --n 60000 --mode cascade \
        --topology star --shards 8
    python -m tpusvm ingest --train train.csv --out shards/
    python -m tpusvm train --data shards/ --mode cascade --shards 8
    python -m tpusvm predict --model model.npz --data test.csv
    python -m tpusvm predict --model model.npz --data shards/
    python -m tpusvm train --synthetic rings --n 500 --convergence 128 \
        --trace run.jsonl
    python -m tpusvm report run.jsonl
    python -m tpusvm info

Output reproduces the reference's diagnostics contract (SURVEY.md
Appendix A): n / n_features, iteration count, b at 15 dp, the KKT gap
residual (b_high-b_low)/2*1e10, SV count, accuracy as correct/m, and the
three phase timings; cascade runs add per-round `=== Round k ===` lines.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Tuple

import numpy as np


def _build_parser() -> argparse.ArgumentParser:
    # --platform is accepted both before and after the subcommand (launcher
    # scripts append user flags after `train`). The subparser copy defaults
    # to SUPPRESS so that when the flag is absent there, it does not
    # overwrite a value the root parser already captured.
    platform_help = (
        "force a JAX platform (set before backend init, so it works even "
        "where site configuration overrides the JAX_PLATFORMS env var); "
        "combine with XLA_FLAGS=--xla_force_host_platform_device_count=N "
        "for an N-device simulated CPU mesh"
    )
    distributed_help = (
        "initialise jax.distributed before backend use (the reference's "
        "MPI_Init, mpi_svm_main3.cpp:416-419): launch the same command on "
        "every host of a multi-host pod/cluster to form one global mesh. "
        "On TPU pods coordinator/process geometry is discovered from the "
        "TPU metadata; elsewhere pass --coordinator-address / "
        "--num-processes / --process-id explicitly"
    )
    def add_shared(parser, suppress):
        """One definition of the pre/post-subcommand flags. The subparser
        copies default to SUPPRESS so an absent flag there never overwrites
        a value the root parser already captured."""
        d = argparse.SUPPRESS if suppress else None
        parser.add_argument("--platform", choices=["cpu", "tpu"],
                            default=d, help=platform_help)
        parser.add_argument(
            "--distributed", action="store_true",
            default=argparse.SUPPRESS if suppress else False,
            help=distributed_help,
        )
        parser.add_argument("--coordinator-address", default=d,
                            metavar="HOST:PORT",
                            help="with --distributed off-TPU: coordinator "
                            "endpoint")
        parser.add_argument("--num-processes", type=int, default=d,
                            help="with --distributed off-TPU: world size")
        parser.add_argument("--process-id", type=int, default=d,
                            help="with --distributed off-TPU: this "
                            "process's rank")
        parser.add_argument(
            "--faults", metavar="PLAN.json", default=d,
            help="activate a seeded fault-injection plan (tpusvm.faults) "
            "for this run: named injection points on the I/O and scoring "
            "paths raise transients / inject latency / corrupt bytes / "
            "simulate kills per the plan — deterministic chaos testing; "
            "also honoured from the TPUSVM_FAULTS env var",
        )

    common = argparse.ArgumentParser(add_help=False)
    add_shared(common, suppress=True)
    p = argparse.ArgumentParser(
        prog="tpusvm",
        description="TPU-native parallel SVM training (JAX/XLA/Pallas).",
    )
    add_shared(p, suppress=False)
    sub = p.add_subparsers(dest="command", required=True)

    def add_data_source(parser, sharded: bool = True):
        """The --train/--synthetic/--data source group (train/tune/ingest)."""
        src = parser.add_argument_group(
            "data source (one of --train / --synthetic"
            + (" / --data)" if sharded else ")"))
        src.add_argument("--train", metavar="CSV",
                         help="training CSV (last column = label)")
        src.add_argument("--test", metavar="CSV",
                         help="held-out CSV to evaluate on")
        src.add_argument(
            "--synthetic",
            choices=["mnist-like", "blobs", "rings", "sine"],
            help="generate a deterministic synthetic dataset instead of "
            "reading CSVs (sine: continuous targets — --task svr only)",
        )
        if sharded:
            src.add_argument(
                "--data", metavar="DIR", dest="data",
                help="ingested sharded dataset directory (tpusvm ingest): "
                "out-of-core streaming source — the scaler comes from "
                "manifest stats and shards are loaded one at a time",
            )
        src.add_argument("--n", type=int, default=60000,
                         help="synthetic train size (default 60000)")
        src.add_argument("--n-test", type=int, default=10000,
                         help="synthetic test size (default 10000)")
        src.add_argument("--d", type=int, default=784,
                         help="synthetic feature count (default 784)")
        src.add_argument("--seed", type=int, default=587,
                         help="synthetic data seed")
        src.add_argument(
            "--n-limit", type=int, default=None, metavar="N",
            help="cap training rows (the reference's gpu_svm_main4 argv[1])",
        )
        src.add_argument(
            "--positive-label", type=int, default=1, metavar="K",
            help="CSV binary mode: the class mapped to +1 (label != K -> "
            "-1); default 1, the reference's hard-coded digit",
        )

    tr = sub.add_parser("train", parents=[common],
                        help="train a model and optionally evaluate")
    add_data_source(tr)

    mode = tr.add_argument_group("training mode")
    mode.add_argument(
        "--mode", choices=["single", "cascade", "pod", "oracle"],
        default="single",
        help="single = on-device SMO (GPU-build capability); cascade = "
        "distributed cascade over the device mesh (MPI capability); "
        "pod = out-of-core cascade over worker PROCESSES (tpusvm.pod: "
        "each leaf streams only its manifest shards; requires --data); "
        "oracle = serial NumPy SMO (main3.cpp capability)",
    )
    mode.add_argument(
        "--solver-opt", action="append", default=[], metavar="KEY=VALUE",
        help="extra static solver knob, repeatable (blocked solver: q, "
        "max_outer, max_inner, wss, refine, max_refines, inner, "
        "matmul_precision, selection, fused_fupdate, pallas_layout — "
        "e.g. --solver-opt q=2048 "
        "--solver-opt matmul_precision=default --solver-opt refine=4096); "
        "integer values are auto-converted")
    mode.add_argument(
        "--solver", choices=["blocked", "pair", "fleet"], default=None,
        help="on-device solver for --mode single, each cascade shard, and "
        "each --multiclass class: blocked working-set (TPU-first, default "
        "for single/cascade), pair (reference-faithful "
        "one-pair-per-iteration; vmapped over classes with --multiclass, "
        "its default there), or fleet (--multiclass only: every "
        "one-vs-rest head in ONE batched blocked-solver launch, "
        "tpusvm.fleet — the --fleet flag is shorthand)",
    )
    mode.add_argument("--fleet", action="store_true",
                      help="with --multiclass/--task ovr: train all "
                      "one-vs-rest heads as one batched fleet program "
                      "(shorthand for --solver fleet)")
    mode.add_argument("--fleet-compact", type=int, default=0, metavar="R",
                      help="fleet: compact converged problems out of the "
                      "batch every R outer rounds (power-of-two problem "
                      "buckets; 0 = one monolithic launch)")
    mode.add_argument("--topology", choices=["tree", "star"], default="tree",
                      help="cascade merge topology (tree = mpi_svm_main3, "
                      "star = mpi_svm_main2)")
    mode.add_argument("--shards", type=int, default=None,
                      help="cascade shard count P (default: all local "
                      "devices; --mode pod: worker process count, "
                      "default 4)")
    mode.add_argument("--stratify", action="store_true",
                      help="cascade: per-class round-robin sharding instead "
                      "of the reference's contiguous scatter (safe on "
                      "label-sorted input, which otherwise hands a leaf a "
                      "single-class shard)")
    mode.add_argument("--sv-capacity", type=int, default=4096,
                      help="padded SV buffer capacity per shard")
    mode.add_argument("--checkpoint", metavar="NPZ",
                      help="crash-safe training: cascade/pod mode writes "
                      "per-round state here; single mode (blocked "
                      "solver) writes the solver's outer-loop carry "
                      "every --checkpoint-every rounds (atomic, "
                      "format-versioned; resumed runs are bit-identical "
                      "to uninterrupted ones); with --resume, restart "
                      "from it")
    mode.add_argument("--resume", action="store_true",
                      help="resume from --checkpoint if it exists "
                      "(missing file = fresh run)")
    mode.add_argument("--checkpoint-every", type=int, default=64,
                      metavar="K",
                      help="single-mode checkpoint cadence in outer "
                      "rounds (default 64)")
    mode.add_argument("--shrink-every", type=int, default=0, metavar="E",
                      help="active-set shrinking (blocked solver, --mode "
                      "single): every E outer rounds, freeze alphas that "
                      "have been at-bound and Keerthi-stable for "
                      "--shrink-stable consecutive rounds and compact "
                      "the live rows into a power-of-two bucket — solver "
                      "work then scales with the active set, not n; an "
                      "un-shrink full-f rebuild re-validates every "
                      "convergence claim, so the final stopping check "
                      "is identical to the unshrunk criterion. 0 = off")
    mode.add_argument("--shrink-stable", type=int, default=3, metavar="S",
                      help="rounds a row must stay at-bound and "
                      "Keerthi-safe before --shrink-every may freeze it "
                      "(default 3)")
    mode.add_argument("--multiclass", action="store_true",
                      help="one-vs-rest over all labels instead of the "
                      "reference's binary '1 vs rest' mapping")
    mode.add_argument(
        "--convergence", type=int, default=0, metavar="T",
        help="carry a T-slot convergence ring through the blocked "
        "solver's outer loop (per-round Keerthi gap / update count / "
        "status, zero host syncs, bit-transparent to the solution); "
        "0 = off. Requires --mode single with the blocked solver; "
        "renders via `tpusvm report` when combined with --trace")
    mode.add_argument("--class-parallel", action="store_true",
                      help="with --multiclass: shard the class axis over "
                      "the device mesh (one-vs-rest problems train "
                      "chip-parallel; requires the pair solver)")

    kt = tr.add_argument_group("kernel / task (tpusvm.kernels)")
    kt.add_argument("--kernel",
                    choices=["rbf", "linear", "poly", "sigmoid", "rff",
                             "nystrom"],
                    default="rbf",
                    help="kernel family; rbf (default) = the reference's "
                    "kernel, linear gets a primal-friendly fast path, "
                    "poly = (gamma*x.z + coef0)^degree, sigmoid = "
                    "tanh(gamma*x.z + coef0); rff / nystrom are the "
                    "APPROXIMATE rbf families (tpusvm.approx): a seeded "
                    "explicit feature map routes every solve through the "
                    "linear primal fast path — the linear-cost regime "
                    "for row counts the exact path cannot reach; with "
                    "--data they train fully out-of-core (per-shard "
                    "mapping in the prefetch hook + the streaming "
                    "primal solver)")
    kt.add_argument("--degree", type=int, default=3,
                    help="polynomial degree (--kernel poly)")
    kt.add_argument("--coef0", type=float, default=0.0,
                    help="polynomial/sigmoid additive term (--kernel "
                    "poly/sigmoid)")
    kt.add_argument("--rff-dim", type=int, default=2048, metavar="D",
                    help="--kernel rff: mapped feature width (must be a "
                    "multiple of the 128-lane TPU tile; default 2048)")
    kt.add_argument("--rff-seed", type=int, default=0, metavar="S",
                    help="--kernel rff/nystrom: deterministic map seed — "
                    "the same seed reproduces bit-identical features "
                    "across ingest/train/predict/serve (default 0)")
    kt.add_argument("--landmarks", type=int, default=256, metavar="K",
                    help="--kernel nystrom: landmark row count = mapped "
                    "width (tile-aligned like --rff-dim; must be <= n; "
                    "default 256)")
    kt.add_argument("--task", choices=["svc", "svr", "ovr"], default="svc",
                    help="svc = classification (default); svr = "
                    "epsilon-insensitive regression over the doubled "
                    "variable set (CSV/synthetic labels are then "
                    "CONTINUOUS targets); ovr = one-vs-rest multiclass "
                    "classification (synonym for --multiclass)")
    kt.add_argument("--epsilon", type=float, default=0.1,
                    help="SVR tube half-width (--task svr)")
    kt.add_argument("--calibrate", type=int, default=0, metavar="K",
                    help="fit Platt-scaled predict_proba on K held-out "
                    "folds after training (binary --task svc, --mode "
                    "single); the saved model then serves a proba field")

    hp = tr.add_argument_group("hyperparameters (defaults = reference constants)")
    hp.add_argument("--preset", choices=["mnist", "banknote", "debug"],
                    default=None, help="named (C, gamma) preset")
    hp.add_argument("--C", type=float, default=10.0)
    hp.add_argument("--gamma", type=float, default=0.00125)
    hp.add_argument("--tau", type=float, default=1e-5)
    hp.add_argument("--eps", type=float, default=1e-12)
    hp.add_argument("--sv-tol", type=float, default=1e-8)
    hp.add_argument("--max-iter", type=int, default=100000)
    hp.add_argument("--max-rounds", type=int, default=50)

    num = tr.add_argument_group("numerics")
    num.add_argument("--dtype", choices=["float32", "bfloat16", "float64"],
                     default="float32", help="feature/kernel dtype")
    num.add_argument(
        "--precision", choices=["f32", "bf16_f32", "bf16_f32c"],
        default="f32",
        help="MXU precision rung for the solver's dominant f-update "
        "contraction (blocked solver): f32 = full-f32 trust anchor "
        "(default); bf16_f32 = bfloat16 operands with exact f32 "
        "accumulation (single-pass MXU throughput; pair with "
        "--shrink-every, whose un-shrink rebuild re-validates claims, "
        "or --solver-opt refine=N); bf16_f32c adds a compensated "
        "residual pass. Raw single-pass bf16 stays solver-opt-only "
        "(matmul_precision=default, refine-gated)")
    num.add_argument(
        "--accum", choices=["none", "float64"], default="float64",
        help="solver accumulator dtype; float64 (default) is the mixed-"
        "precision mode matching the f64 reference's convergence at f32 speed",
    )
    num.add_argument("--no-scale", action="store_true",
                     help="skip min-max feature scaling")

    out = tr.add_argument_group("output")
    out.add_argument("--save", metavar="NPZ", help="save the trained model")
    out.add_argument("--jsonl", metavar="PATH",
                     help="append structured run events to a JSONL file")
    out.add_argument("--trace", metavar="PATH",
                     help="write a schema-versioned JSONL telemetry trace "
                     "(phase spans, cascade rounds, convergence records, "
                     "metric counters); render with `tpusvm report PATH`")
    out.add_argument("--profile", "--xprof", metavar="DIR", dest="profile",
                     help="capture a jax.profiler trace of training "
                     "(kernel-level; view in TensorBoard/Perfetto)")
    out.add_argument("--smoke", action="store_true",
                     help="CI gate: tiny synthetic run with convergence "
                     "telemetry on; asserts convergence, held-out "
                     "accuracy, and (with --trace) a well-formed trace; "
                     "non-zero exit on any failure")
    out.add_argument("-q", "--quiet", action="store_true")

    ing = sub.add_parser(
        "ingest", parents=[common],
        help="convert a CSV or synthetic generator into a sharded "
        "on-disk dataset (tpusvm.stream): packed .npz shards + a JSON "
        "manifest with per-shard stats and checksums")
    add_data_source(ing, sharded=False)
    ing.set_defaults(multiclass=False)
    ing.add_argument("--multiclass", action="store_true",
                     help="keep raw integer labels instead of the binary "
                     "one-vs-rest mapping")
    ing.add_argument("--out", metavar="DIR",
                     help="output dataset directory (required unless "
                     "--smoke)")
    ing.add_argument("--kernel", choices=["raw", "rff", "nystrom"],
                     default="raw",
                     help="feature handling for approximate-kernel "
                     "training: shards always store RAW features — "
                     "naming rff/nystrom here explains (with an error) "
                     "that the map is applied STREAM-SIDE at train time "
                     "(per-shard in the prefetch hook), so one ingested "
                     "dataset serves every (D, seed) without re-ingest")
    ing.add_argument("--rff-dim", type=int, default=2048, metavar="D",
                     help=argparse.SUPPRESS)
    ing.add_argument("--rff-seed", type=int, default=0, metavar="S",
                     help=argparse.SUPPRESS)
    ing.add_argument("--landmarks", type=int, default=256, metavar="K",
                     help=argparse.SUPPRESS)
    ing.add_argument("--rows-per-shard", type=int, default=65536,
                     help="rows per .npz shard (default 65536)")
    ing.add_argument("--resume", action="store_true",
                     help="continue a killed ingest of the SAME source "
                     "from its journal (ingest.journal.json): verified "
                     "durable shards are kept, remaining rows are "
                     "re-streamed — the finished dataset is identical "
                     "to an uninterrupted ingest")
    ing.add_argument("--block-rows", type=int, default=8192,
                     help="CSV streaming block size (peak ingest memory)")
    ing.add_argument("--smoke", action="store_true",
                     help="CI gate: ingest a tiny synthetic dataset to a "
                     "temp dir, then assert manifest integrity "
                     "(checksums/stats validate OK), reader round-trip "
                     "parity with the generator, scaler-from-stats parity "
                     "with a full-array fit, and the prefetch residency "
                     "bound; non-zero exit on any failure")
    ing.add_argument("--trace", metavar="PATH",
                     help="write ingest phase spans + shard/stream "
                     "counters to a JSONL telemetry trace")
    ing.add_argument("--profile", "--xprof", metavar="DIR", dest="profile",
                     help="capture a jax.profiler trace of the ingest "
                     "phase (view in TensorBoard/Perfetto)")
    ing.add_argument("-q", "--quiet", action="store_true")

    pr = sub.add_parser("predict", parents=[common],
                        help="evaluate a saved model on a CSV or an "
                        "ingested sharded dataset")
    pr.add_argument("--model", required=True, metavar="NPZ",
                    help="binary or --multiclass model (auto-detected)")
    pr.add_argument("--data", required=True, metavar="CSV|DIR",
                    help="test CSV, or a sharded dataset directory "
                    "(streamed batched scoring with bounded memory)")
    pr.add_argument("--n-limit", type=int, default=None)
    pr.add_argument("--positive-label", type=int, default=1, metavar="K",
                    help="CSV binary mode: the class mapped to +1")
    pr.add_argument("--batch-size", type=int, default=4096,
                    help="sharded --data: rows per scoring batch")
    pr.add_argument("--scores", action="store_true",
                    help="print decision scores instead of accuracy (one "
                    "line per row; multiclass: one column per class; "
                    "svr: the regressed values)")
    pr.add_argument("--proba", action="store_true",
                    help="print Platt-calibrated P(y=+1) per row "
                    "(requires a binary model trained with --calibrate)")
    pr.add_argument("--mesh-predict", action="store_true",
                    help="shard the test rows over the local device mesh "
                    "(zero-collective sharded serving)")

    sv = sub.add_parser(
        "serve", parents=[common],
        help="serve saved models over HTTP with deadline-aware "
        "micro-batching (tpusvm.serve)")
    sv.add_argument("--model", action="append", default=[],
                    metavar="[NAME=]NPZ", dest="models",
                    help="model to host, repeatable; NAME defaults to the "
                    "file stem (binary vs multiclass auto-detected). "
                    "Optional when --state names a manifest to restore "
                    "or --watch a directory to load from")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8471,
                    help="HTTP port (0 = ephemeral; default 8471)")
    sv.add_argument("--max-batch", type=int, default=64,
                    help="micro-batch coalescing cap = largest pad bucket")
    sv.add_argument("--max-delay-ms", type=float, default=2.0,
                    help="max latency added waiting for batch co-riders")
    sv.add_argument("--queue-size", type=int, default=1024,
                    help="backpressure bound; full queue fast-fails")
    sv.add_argument("--timeout-ms", type=float, default=1000.0,
                    help="default per-request deadline")
    sv.add_argument("--shed-threshold", type=float, default=None,
                    metavar="FRAC",
                    help="degraded mode: shed requests with OVERLOADED "
                    "once the queue holds FRAC of its capacity "
                    "(0 < FRAC <= 1; default: off — only the hard "
                    "QUEUE_FULL bound applies)")
    sv.add_argument("--breaker-threshold", type=int, default=5,
                    help="consecutive scoring failures that trip a "
                    "model's circuit breaker (requests then fail fast "
                    "with UNAVAILABLE; default 5)")
    sv.add_argument("--breaker-cooldown-s", type=float, default=30.0,
                    help="open-breaker cooldown before a half-open "
                    "probe is admitted (default 30)")
    sv.add_argument("--dtype", choices=["float32", "float64"],
                    default="float32", help="serving compute dtype")
    sv.add_argument("--no-warmup", action="store_true",
                    help="skip AOT-compiling the bucket executables (first "
                    "request per bucket then pays the compile)")
    rr = sv.add_argument_group("restart robustness / continuous serving")
    rr.add_argument("--cache-dir", metavar="DIR", default=None,
                    help="persist the compile cache: bucket executables "
                    "compile through jax's persistent compilation cache "
                    "in DIR (plus a bucket-signature manifest), so a "
                    "restarted server — or a replica sharing DIR — "
                    "reaches first prediction with ZERO fresh XLA "
                    "compiles (BENCH_r01's 22.3s cold start becomes a "
                    "cache read); also honoured from TPUSVM_CACHE_DIR")
    rr.add_argument("--assert-cached", action="store_true",
                    help="with --cache-dir: exit non-zero unless EVERY "
                    "compile this run was served from the persistent "
                    "cache (cache misses == 0) — the CI restart gate "
                    "run as the second of two smokes sharing DIR")
    rr.add_argument("--state", metavar="JSON", default=None,
                    help="serialized registry manifest (serve_state."
                    "json): restored at startup (the full model set "
                    "reloads with its generation history) and "
                    "atomically rewritten after every successful "
                    "load/swap")
    rr.add_argument("--watch", metavar="DIR", default=None,
                    help="poll DIR for model .npz files: a new stem "
                    "loads as a new model, a newer mtime on a hosted "
                    "stem hot-swaps it in (staged off to the side, "
                    "probe-verified, atomic generation flip — a bad "
                    "artifact rolls back and the old generation keeps "
                    "serving); the `tune`/`refresh` --save handoff")
    rr.add_argument("--watch-interval-s", type=float, default=2.0,
                    help="--watch poll period (default 2.0)")
    sv.add_argument("--smoke", action="store_true",
                    help="no HTTP: warm up, fire concurrent in-process "
                    "requests, print metrics, exit non-zero on any error "
                    "or post-warm-up recompile (the CI gate)")
    sv.add_argument("--smoke-threads", type=int, default=8)
    sv.add_argument("--smoke-requests", type=int, default=32,
                    help="requests per smoke thread")
    sv.add_argument("--trace", metavar="PATH",
                    help="write serve phase spans + final per-model "
                    "metric snapshots to a JSONL telemetry trace")
    sv.add_argument("--trace-max-bytes", type=int, default=None,
                    metavar="N",
                    help="size-cap the trace file: rotate PATH -> PATH.1 "
                    "at N bytes (displaced records are counted in the "
                    "obs.trace_dropped_records metric) so a long-running "
                    "serve --trace cannot fill the disk; default: "
                    "unbounded")
    sv.add_argument("--profile", "--xprof", metavar="DIR", dest="profile",
                    help="capture a jax.profiler trace of the serving "
                    "section (smoke run, or the HTTP serve loop)")
    slo = sv.add_argument_group("serving SLOs (performance observatory)")
    slo.add_argument("--slo-p99-ms", type=float, default=None,
                     metavar="MS",
                     help="per-model p99 latency target: at most 1%% of "
                     "windowed requests may exceed it; burn-rate gauges "
                     "are exported on /metrics and /healthz degrades "
                     "while a budget burns (default: no SLO)")
    slo.add_argument("--slo-error-budget", type=float, default=0.001,
                     metavar="FRAC",
                     help="allowed windowed error fraction "
                     "(errors/timeouts/unavailable; default 0.001)")
    slo.add_argument("--slo-window-s", type=float, default=60.0,
                     help="sliding SLO evaluation window (default 60)")
    slo.add_argument("--slo-shed", action="store_true",
                     help="admission control: shed new requests "
                     "(OVERLOADED, retryable) while the latency budget "
                     "burns; requires --slo-p99-ms")

    rf = sub.add_parser(
        "refresh", parents=[common],
        help="crash-safe online refresh: warm-start a refit from a "
        "DEPLOYED model's duals (binary/OvR/SVR), checkpoint it, save "
        "atomically, and hot-swap it into a running `tpusvm serve` "
        "(tpusvm.serve.refresh); --data DIR reads an (append-grown) "
        "sharded dataset")
    add_data_source(rf)
    rf.set_defaults(multiclass=False, task="svc")
    rf.add_argument("--model", metavar="NPZ",
                    help="the deployed artifact to refresh (required "
                    "unless --smoke); its config and duals seed the "
                    "refit — the new data must keep its training rows "
                    "as a prefix (appended micro-batches; binary, OvR "
                    "and SVR artifacts dispatch automatically)")
    rf.add_argument("--save", metavar="NPZ",
                    help="refreshed artifact output (atomic write; "
                    "required unless --smoke) — drop it in a serve "
                    "--watch directory or name it with --swap")
    rf.add_argument("--cold", action="store_true",
                    help="skip the warm seed (the control arm the warm "
                    "path's update savings are measured against)")
    rf.add_argument("--checkpoint", metavar="NPZ",
                    help="crash-safe refit: solver-carry checkpoints "
                    "every --checkpoint-every outer rounds; a killed "
                    "refresh resumed with --resume is bit-identical to "
                    "an uninterrupted one")
    rf.add_argument("--checkpoint-every", type=int, default=64,
                    metavar="K")
    rf.add_argument("--resume", action="store_true",
                    help="resume from --checkpoint if it exists")
    rf.add_argument("--swap", metavar="URL", dest="swap_url",
                    help="after saving, POST /admin/swap on this "
                    "running serve frontend (e.g. "
                    "http://127.0.0.1:8471) — the staged atomic flip; "
                    "a refused swap reports the server's rollback "
                    "reason")
    rf.add_argument("--swap-name", metavar="NAME", default=None,
                    help="hosted model name to swap (default: the "
                    "--save file stem)")
    rf.add_argument("--smoke", action="store_true",
                    help="CI gate: deploy a tiny model, grow the data, "
                    "refresh warm + cold control, hot-swap in-process; "
                    "asserts convergence, warm update savings, and "
                    "bit-identical served scores post-swap")
    rf.add_argument("-q", "--quiet", action="store_true")

    ap = sub.add_parser(
        "autopilot", parents=[common],
        help="supervised closed-loop online learning: watch an "
        "append-grown dataset, decide retrains off deterministic drift "
        "detectors, and drive crash-safe refresh + hot-swap unattended "
        "(tpusvm.autopilot)")
    ap.add_argument("--data", metavar="DIR",
                    help="the sharded dataset to watch (grown by "
                    "stream appends; required unless --smoke)")
    ap.add_argument("--model", metavar="NPZ",
                    help="the deployed artifact the first refresh "
                    "warm-starts from (required unless --smoke); later "
                    "refreshes chain from the last swapped artifact")
    ap.add_argument("--save", metavar="NPZ", default=None,
                    help="refreshed-artifact output (atomic replace; "
                    "default: <model>.refresh.npz) — point a serve "
                    "--watch dir here for zero-coordination deploys")
    ap.add_argument("--state", metavar="JSON", default=None,
                    help="crash-safe supervisor state (atomic, "
                    "versioned, CRC-fingerprinted; default: "
                    "DATA/autopilot_state.json); --resume replays it")
    ap.add_argument("--resume", action="store_true",
                    help="resume a killed supervisor from --state: "
                    "decisions replay identically and an in-flight "
                    "refresh continues from its own checkpoint")
    ap.add_argument("--name", default=None,
                    help="hosted model name for swaps (default: the "
                    "--save file stem)")
    ap.add_argument("--swap", metavar="URL", dest="swap_url",
                    help="POST /admin/swap on this running serve "
                    "frontend after each refresh (omit for "
                    "artifact-drop mode: serve --watch picks up --save)")
    ap.add_argument("--interval-s", type=float, default=30.0,
                    help="tick period (default 30)")
    ap.add_argument("--max-ticks", type=int, default=None,
                    help="stop after N ticks (default: run forever)")
    det = ap.add_argument_group("drift detectors (None/off when unset)")
    det.add_argument("--growth-threshold", type=float, default=0.25,
                     help="refresh when appended rows exceed this "
                     "fraction of the rows at the last refresh "
                     "(default 0.25; -1 disables)")
    det.add_argument("--feature-threshold", type=float, default=0.10,
                     help="refresh when appended shards' min/max "
                     "escapes the deployed scaler's fitted range by "
                     "this relative fraction (default 0.10; -1 "
                     "disables)")
    det.add_argument("--score-threshold", type=float, default=0.20,
                     help="refresh when the served-score positive-rate "
                     "since the last refresh shifts this much vs the "
                     "baseline (needs --swap-less in-process serving "
                     "or smoke mode; -1 disables; default 0.20)")
    det.add_argument("--staleness-s", type=float, default=None,
                     help="refresh after this many seconds regardless "
                     "of drift (default: off)")
    det.add_argument("--min-new-rows", type=int, default=1,
                     help="suppress non-staleness refreshes until this "
                     "many rows appended (default 1)")
    det.add_argument("--jitter-frac", type=float, default=0.0,
                     help="seeded +/- threshold jitter fraction (the "
                     "fleet de-synchronizer; default 0 = exact)")
    det.add_argument("--seed", type=int, default=0,
                     help="decision seed (reports are byte-reproducible "
                     "per seed)")
    gate = ap.add_argument_group("retrain gating")
    gate.add_argument("--hysteresis", type=int, default=1,
                      help="consecutive triggered ticks required "
                      "(default 1)")
    gate.add_argument("--cooldown-s", type=float, default=0.0,
                      help="post-refresh quiet period (default 0)")
    gate.add_argument("--breaker-threshold", type=int, default=3,
                      help="consecutive refresh failures that trip the "
                      "refresh breaker into degraded-watch mode "
                      "(default 3)")
    gate.add_argument("--breaker-cooldown-s", type=float, default=60.0,
                      help="open-breaker cooldown before a half-open "
                      "refresh probe (default 60)")
    fit = ap.add_argument_group("refresh fit")
    fit.add_argument("--cold", action="store_true",
                     help="cold refits (skip the warm seed)")
    fit.add_argument("--checkpoint", metavar="NPZ", default=None,
                     help="crash-safe refit checkpoints (binary "
                     "artifacts; enables --deadline-s)")
    fit.add_argument("--checkpoint-every", type=int, default=64,
                     metavar="K")
    fit.add_argument("--deadline-s", type=float, default=None,
                     help="fit watchdog: stop a too-slow refit at a "
                     "checkpointed segment boundary and resume it on a "
                     "later tick (requires --checkpoint)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: ingest, deploy, append, run the "
                    "supervisor in-process against a live server under "
                    "any active fault plan; asserts a refresh lands, "
                    "the swap serves the refreshed bytes, and drift "
                    "reports are byte-reproducible")
    ap.add_argument("--smoke-ticks", type=int, default=6,
                    help="smoke tick budget (default 6)")
    ap.add_argument("--trace", metavar="PATH",
                    help="write drift decisions + refresh lifecycle "
                    "events + metric snapshots to a JSONL trace")
    ap.add_argument("--trace-max-bytes", type=int, default=None,
                    metavar="N")
    ap.add_argument("-q", "--quiet", action="store_true")

    tn = sub.add_parser(
        "tenants", parents=[common],
        help="multi-tenant platform tier (tpusvm.tenants): thousands of "
        "per-tenant closed loops over ONE shared append-grown corpus — "
        "per-tenant drift detection, drifted tenants coalesced into "
        "power-of-two fleet refresh launches with warm seeds, staggered "
        "hot-swap roll-out")
    tn.add_argument("--data", metavar="DIR",
                    help="the shared sharded dataset every tenant views "
                    "(grown by stream appends; required unless --smoke)")
    tn.add_argument("--store", metavar="JSON", default=None,
                    help="crash-safe tenant registry + supervisor state "
                    "(atomic, versioned, CRC-fingerprinted; default: "
                    "DATA/tenants_store.json); --resume replays it")
    tn.add_argument("--artifacts", metavar="DIR", default=None,
                    help="refreshed per-tenant artifacts land here as "
                    "<tenant_id>.npz (atomic replace; default: "
                    "DATA/tenant_models) — point a serve --watch dir "
                    "here for zero-coordination deploys")
    tn.add_argument("--resume", action="store_true",
                    help="resume a killed supervisor from --store: "
                    "per-tenant decisions replay identically and an "
                    "in-flight coalesced launch continues from its "
                    "fleet checkpoint bit-identically")
    tn.add_argument("--swap", metavar="URL", dest="swap_url",
                    help="POST /admin/swap per tenant on this running "
                    "serve frontend after each refresh (omit for "
                    "artifact-drop mode)")
    tn.add_argument("--interval-s", type=float, default=30.0,
                    help="tick period (default 30)")
    tn.add_argument("--max-ticks", type=int, default=None,
                    help="stop after N ticks (default: run forever)")
    tdet = tn.add_argument_group("drift detectors (per tenant; "
                                 "None/off when unset)")
    tdet.add_argument("--growth-threshold", type=float, default=0.25,
                      help="refresh a tenant when appended rows exceed "
                      "this fraction of its rows at last refresh "
                      "(default 0.25; -1 disables)")
    tdet.add_argument("--feature-threshold", type=float, default=0.10,
                      help="refresh when appended shards' min/max "
                      "escapes the tenant artifact's fitted range by "
                      "this relative fraction (default 0.10; -1 "
                      "disables)")
    tdet.add_argument("--staleness-s", type=float, default=None,
                      help="refresh a tenant after this many seconds "
                      "regardless of drift (default: off)")
    tdet.add_argument("--min-new-rows", type=int, default=1,
                      help="suppress non-staleness refreshes until this "
                      "many rows appended (default 1)")
    tdet.add_argument("--jitter-frac", type=float, default=0.0,
                      help="seeded +/- threshold jitter fraction; each "
                      "tenant jitters with its own derived seed, so a "
                      "nonzero value de-synchronises the fleet "
                      "(default 0 = exact)")
    tdet.add_argument("--seed", type=int, default=0,
                      help="base decision seed (per-tenant seeds derive "
                      "from it; decisions replay per seed)")
    tgate = tn.add_argument_group("refresh gating + coalescing")
    tgate.add_argument("--hysteresis", type=int, default=1,
                       help="consecutive triggered ticks required per "
                       "tenant (default 1)")
    tgate.add_argument("--cooldown-s", type=float, default=0.0,
                       help="per-tenant post-refresh quiet period "
                       "(default 0)")
    tgate.add_argument("--min-fleet", type=int, default=2,
                       help="smallest drifted group coalesced into a "
                       "fleet launch; smaller groups refresh solo "
                       "(default 2)")
    tgate.add_argument("--stagger-s", type=float, default=0.0,
                       help="delay between per-tenant swaps of one "
                       "generation roll-out (default 0)")
    tgate.add_argument("--breaker-threshold", type=int, default=3,
                       help="consecutive all-failed refresh rounds that "
                       "trip the fleet refresh breaker (default 3)")
    tgate.add_argument("--breaker-cooldown-s", type=float, default=60.0,
                       help="open-breaker cooldown before a half-open "
                       "refresh probe (default 60)")
    tfit = tn.add_argument_group("refresh fit")
    tfit.add_argument("--cold", action="store_true",
                      help="cold refits (skip the deployed warm seeds)")
    tfit.add_argument("--checkpoint-every", type=int, default=64,
                      metavar="K",
                      help="fleet-checkpoint segment length in outer "
                      "rounds (default 64)")
    tn.add_argument("--smoke", action="store_true",
                    help="CI gate: provision a small tenant fleet over "
                    "one ingested corpus, grow it, run the supervisor "
                    "in-process against a live server under any active "
                    "fault plan; asserts a coalesced refresh lands, "
                    "every tenant's swap serves its refreshed bytes, "
                    "and the store resumes consistently")
    tn.add_argument("--smoke-tenants", type=int, default=8,
                    help="smoke fleet size (default 8)")
    tn.add_argument("--smoke-ticks", type=int, default=6,
                    help="smoke tick budget (default 6)")
    tn.add_argument("--trace", metavar="PATH",
                    help="write drift + refresh lifecycle events + "
                    "metric snapshots to a JSONL trace")
    tn.add_argument("--trace-max-bytes", type=int, default=None,
                    metavar="N")
    tn.add_argument("-q", "--quiet", action="store_true")

    ro = sub.add_parser(
        "router", parents=[common],
        help="multi-replica routing tier (tpusvm.router): an HTTP front "
        "door over N `tpusvm serve` replicas — HRW placement, burn-aware "
        "admission, failover on connection failure/503, staggered "
        "rollouts with skew holds")
    ro.add_argument("--replica", action="append", default=[],
                    metavar="URL", dest="replicas",
                    help="replica base URL (http://host:port), "
                    "repeatable; the initial membership — /admin/join "
                    "and /admin/leave mutate it live")
    ro.add_argument("--host", default="127.0.0.1")
    ro.add_argument("--port", type=int, default=8470,
                    help="router HTTP port (0 = ephemeral; default 8470)")
    ro.add_argument("--replication", type=int, default=2, metavar="K",
                    help="HRW replication factor: a model's requests "
                    "prefer its K placed replicas (default 2)")
    ro.add_argument("--seed", type=int, default=0,
                    help="placement seed (tables are byte-reproducible "
                    "per seed)")
    ro.add_argument("--poll-interval-s", type=float, default=1.0,
                    help="replica /healthz poll period (default 1.0)")
    ro.add_argument("--down-after", type=int, default=2,
                    help="consecutive failed polls that mark a replica "
                    "down (default 2; one blip keeps its state)")
    ro.add_argument("--health-timeout-s", type=float, default=2.0,
                    help="per-poll fetch timeout (default 2.0)")
    ro.add_argument("--forward-timeout-s", type=float, default=10.0,
                    help="per-attempt forward timeout (default 10.0)")
    ro.add_argument("--skew-window", type=int, default=1,
                    help="rollout generation-skew hold threshold "
                    "(default 1: the steady staggered state)")
    ro.add_argument("--smoke", action="store_true",
                    help="CI gate: an in-process two-replica fleet "
                    "behind the router — concurrent clients, a replica "
                    "outage mid-run (failover must absorb it), a "
                    "staggered rollout; asserts zero lost responses, "
                    "every score bitwise one of the two generations, "
                    "and a skew-free final vector")
    ro.add_argument("--smoke-threads", type=int, default=4)
    ro.add_argument("--smoke-requests", type=int, default=40,
                    help="requests per smoke thread")
    ro.add_argument("--trace", metavar="PATH",
                    help="write router.forward spans (one per proxied "
                    "request, carrying the minted trace context that "
                    "replicas honor) to a JSONL trace")
    ro.add_argument("--trace-max-bytes", type=int, default=None,
                    metavar="N")

    tu = sub.add_parser(
        "tune", parents=[common],
        help="cross-validated (C, gamma) search with warm-started fits "
        "(tpusvm.tune); trains the winner on the full data")
    add_data_source(tu)
    tu.set_defaults(multiclass=False)  # _load_train_data reads it

    space = tu.add_argument_group("search space")
    space.add_argument("--kernels", metavar="LIST", default=None,
                       help="comma-separated kernel families to search "
                       "alongside (C, gamma), e.g. rbf,linear,poly "
                       "(default: rbf only); each family runs the full "
                       "schedule over shared fold caches and the winner "
                       "is the global CV argmax")
    space.add_argument("--degree", type=int, default=3,
                       help="polynomial degree for the poly family")
    space.add_argument("--coef0", type=float, default=1.0,
                       help="polynomial additive term for the poly family "
                       "(default 1.0 — coef0=0 with an odd degree cannot "
                       "shift the decision surface)")
    space.add_argument("--C-grid", metavar="LIST", dest="C_grid",
                       help="comma-separated C values (overrides "
                       "--center-C/--span/--step)")
    space.add_argument("--gamma-grid", metavar="LIST",
                       help="comma-separated gamma values")
    space.add_argument("--center-C", type=float, default=10.0,
                       help="log-grid center C when --C-grid is absent "
                       "(default: the reference's MNIST constant)")
    space.add_argument("--center-gamma", type=float, default=0.00125,
                       help="log-grid center gamma when --gamma-grid is "
                       "absent")
    space.add_argument("--span", type=int, default=2,
                       help="log grid: steps each side of the center "
                       "(grid edge = 2*span+1)")
    space.add_argument("--step", type=float, default=4.0,
                       help="log grid: multiplicative step per cell")

    sched = tu.add_argument_group("schedule")
    sched.add_argument("--folds", type=int, default=3,
                       help="stratified CV folds (default 3)")
    sched.add_argument("--fold-seed", type=int, default=0,
                       help="fold split / rung subset shuffle seed")
    sched.add_argument("--schedule", choices=["grid", "halving"],
                       default="grid")
    sched.add_argument("--eta", type=int, default=3,
                       help="halving: rung growth factor and survivor "
                       "fraction denominator")
    sched.add_argument("--min-rung", type=int, default=256,
                       help="halving: smallest rung subset size")
    sched.add_argument("--no-warm-start", action="store_true",
                       help="fit every point cold (the benchmark's "
                       "control arm)")
    sched.add_argument("--patience", type=int, default=None,
                       help="grid: stop after this many consecutive "
                       "non-improving points")
    sched.add_argument("--plateau-tol", type=float, default=0.0,
                       help="minimum CV-accuracy gain that resets "
                       "--patience")
    sched.add_argument("--fleet", action="store_true", dest="fleet",
                       help="dispatch each rung's point population as "
                       "ONE batched fleet launch per fold (tpusvm.fleet) "
                       "— the points share the fold's scaled rows and "
                       "norms and differ only in (C, gamma)")
    sched.add_argument("--no-fleet", action="store_false", dest="fleet",
                       help="per-point sequential dispatch (the default; "
                       "explicit form of not passing --fleet)")
    sched.set_defaults(fleet=False)
    sched.add_argument("--fleet-compact", type=int, default=0,
                       metavar="R",
                       help="--fleet: compact converged points out of "
                       "the batch every R outer rounds (0 = monolithic "
                       "launch per fold x rung)")

    hp2 = tu.add_argument_group("numerics (defaults = reference constants)")
    hp2.add_argument("--tau", type=float, default=1e-5)
    hp2.add_argument("--eps", type=float, default=1e-12)
    hp2.add_argument("--sv-tol", type=float, default=1e-8)
    hp2.add_argument("--max-iter", type=int, default=100000)
    hp2.add_argument("--dtype", choices=["float32", "bfloat16", "float64"],
                     default="float32")
    hp2.add_argument(
        "--accum", choices=["none", "float64"], default="float64",
        help="solver accumulator dtype (see train --accum)")
    hp2.add_argument("--no-scale", action="store_true",
                     help="skip min-max feature scaling")
    hp2.add_argument(
        "--solver-opt", action="append", default=[], metavar="KEY=VALUE",
        help="extra static blocked-solver knob, repeatable "
        "(e.g. --solver-opt q=256)")

    out2 = tu.add_argument_group("output")
    out2.add_argument("--results", metavar="JSON",
                      help="write the versioned TuneResult table here")
    out2.add_argument("--trace", metavar="PATH",
                      help="write search phase spans + per-point "
                      "tune.point events to a JSONL telemetry trace")
    out2.add_argument("--profile", "--xprof", metavar="DIR",
                      dest="profile",
                      help="capture a jax.profiler trace of the search "
                      "phase (view in TensorBoard/Perfetto)")
    out2.add_argument("--save", metavar="NPZ",
                      help="save the winner model trained on the full data")
    out2.add_argument("--smoke", action="store_true",
                      help="CI gate: tiny grid, 2 folds, synthetic rings, "
                      "then assert every fit converged, warm seeding "
                      "engaged, and the winner model beats chance")
    out2.add_argument("-q", "--quiet", action="store_true")

    po = sub.add_parser(
        "pod", parents=[common],
        help="self-contained pod-cascade run (tpusvm.pod): coordinator "
        "+ worker processes train out-of-core from a sharded dataset, "
        "each leaf streaming only its manifest shards; gates SV-set/b "
        "parity against the in-memory cascade on the same rows")
    po.add_argument("--data", metavar="DIR", default=None,
                    help="existing sharded dataset dir to train on "
                    "(default: ingest a synthetic rings set into a "
                    "temp dir, which enables the in-memory parity gate)")
    po.add_argument("--workers", type=int, default=4, metavar="P",
                    help="worker process count = cascade leaf count "
                    "(default 4)")
    po.add_argument("--topology", choices=["tree", "star", "both"],
                    default="both",
                    help="merge topology to run (default both: the "
                    "tree and star rounds share leaf results only "
                    "through the wire protocol, so running both is the "
                    "transport-parity check)")
    po.add_argument("--n", type=int, default=192,
                    help="synthetic row count (ignored with --data)")
    po.add_argument("--rows-per-shard", type=int, default=24,
                    help="synthetic ingest shard size (ignored with "
                    "--data)")
    po.add_argument("--sv-capacity", type=int, default=128,
                    help="padded SV buffer capacity per leaf")
    po.add_argument("--C", type=float, default=10.0)
    po.add_argument("--gamma", type=float, default=10.0)
    po.add_argument("--max-rounds", type=int, default=12)
    po.add_argument("--smoke", action="store_true",
                    help="CI gate: non-zero exit unless every topology "
                    "converges, matches the in-memory cascade's SV-ID "
                    "set / alpha bytes / b exactly, conserves every row "
                    "across the workers, and keeps per-worker shard "
                    "residency within the prefetch bound")
    po.add_argument("--trace-dir", metavar="DIR", default=None,
                    help="distributed trace directory: the coordinator "
                    "writes coordinator.jsonl and every worker process "
                    "its own worker<id>.p<pid>.jsonl, stitched by "
                    "propagated trace contexts — `tpusvm report DIR` "
                    "renders the fleet as ONE timeline")
    po.add_argument("--trace-max-bytes", type=int, default=None,
                    metavar="N", help="per-file trace rotation bound")
    po.add_argument("-q", "--quiet", action="store_true")

    inf = sub.add_parser("info", parents=[common],
                         help="print device / backend information, or "
                         "describe a model / tune-results artifact")
    inf.add_argument("path", nargs="?", default=None,
                     help="optional artifact: a model .npz or a tune "
                     "results .json (auto-detected)")

    rep = sub.add_parser(
        "report", parents=[common],
        help="render --trace JSONL telemetry: phase summary (the "
        "reference's three-line timing contract), compile/cost table, "
        "convergence-gap table, and non-zero counters; several files or "
        "a directory merge into one wall-clock-interleaved report")
    rep.add_argument("path", metavar="TRACE", nargs="+",
                     help="trace file(s) written by --trace on "
                     "train/tune/serve/ingest, or a directory of them "
                     "(cascade leaves / tune workers collate into one "
                     "report; rotated trace.jsonl.1 sets are folded in)")
    rep.add_argument("--max-rows", type=int, default=40,
                     help="convergence table rows before middle elision")
    rep.add_argument("--smoke", action="store_true",
                     help="CI gate: non-zero exit unless the trace(s) "
                     "parse at the current schema version and carry "
                     "at least one phase span and one convergence record")

    def add_fleet_sources(p):
        p.add_argument("--router", metavar="URL", default=None,
                       help="router base URL: adopts its /fleet/"
                       "metrics.json (the router scrapes its replicas)")
        p.add_argument("--replica", action="append", default=[],
                       metavar="URL", dest="replicas",
                       help="serve replica base URL (scrapes "
                       "/metrics.json), repeatable")
        p.add_argument("--snapshot-file", action="append", default=[],
                       metavar="PATH", dest="snapshot_files",
                       help="on-disk snapshot payload (e.g. an "
                       "autopilot metrics_snapshot_path drop), "
                       "repeatable")
        p.add_argument("--timeout-s", type=float, default=2.0,
                       help="per-scrape fetch timeout (default 2.0)")

    fm = sub.add_parser(
        "fleet-metrics", parents=[common],
        help="scrape every fleet process (serve replicas' "
        "/metrics.json, a router's /fleet/metrics.json, on-disk "
        "snapshot drops) and print ONE merged, (role, instance)-"
        "labelled metrics view (obs.fleet.merge_fleet: counters sum, "
        "gauges max, histograms add)")
    add_fleet_sources(fm)
    fm.add_argument("--format", choices=["text", "json"], default="text")
    fm.add_argument("--smoke", action="store_true",
                    help="CI gate: an in-process two-replica fleet "
                    "behind a router; non-zero exit unless the merged "
                    "fleet view equals merge_fleet() of the per-process "
                    "snapshots scraped directly (exact counter totals, "
                    "label-tagged)")
    fm.add_argument("-q", "--quiet", action="store_true")

    tp = sub.add_parser(
        "top", parents=[common],
        help="live fleet table (one row per process: role, instance, "
        "pid, generation, request totals, qps, p99, burn, breaker, "
        "live shards) refreshed from the same sources as "
        "fleet-metrics")
    add_fleet_sources(tp)
    tp.add_argument("--interval-s", type=float, default=2.0,
                    help="refresh period (default 2.0)")
    tp.add_argument("--once", action="store_true",
                    help="scrape once, print one table, exit (scripts "
                    "and CI; no screen clearing)")
    tp.add_argument("--iterations", type=int, default=0, metavar="N",
                    help="exit after N refreshes (0 = until Ctrl-C)")
    tp.add_argument("--no-clear", action="store_true",
                    help="append tables instead of clearing the screen")

    bd = sub.add_parser(
        "benchdiff", parents=[common],
        help="schema-aware comparison of two benchmark JSONL artifacts "
        "(tpusvm.obs.benchdiff): per-metric direction/tolerance rules, "
        "backend-provenance check, non-zero exit on any regression")
    bd.add_argument("old", metavar="OLD.jsonl",
                    help="baseline artifact (e.g. a committed "
                    "benchmarks/results file)")
    bd.add_argument("new", metavar="NEW.jsonl",
                    help="candidate artifact to gate")
    bd.add_argument("--level", choices=["full", "smoke"], default="full",
                    help="full = every rule; smoke = direction-only "
                    "(wall-clock rules skipped — the CI gate, where the "
                    "runner is not the baseline's machine)")
    bd.add_argument("--format", choices=["text", "json", "markdown"],
                    default="text", help="verdict rendering")
    bd.add_argument("--allow-cross-backend", action="store_true",
                    help="annotate instead of refusing when the two "
                    "artifacts ran on different backends (default: "
                    "refuse — cross-backend numbers are not comparable)")
    return p


def _load_train_data(args) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]:
    """Returns (X_train, Y_train, X_test, Y_test); test side may be None."""
    from tpusvm.data import blobs, mnist_like, rings
    from tpusvm.data.native_io import read_csv_fast
    from tpusvm.data.synthetic import mnist_like_multiclass

    n_sources = sum(s is not None for s in
                    (args.train, args.synthetic, getattr(args, "data", None)))
    if n_sources != 1:
        raise SystemExit(
            "pass exactly one of --train / --synthetic / --data"
        )
    if args.train:
        if getattr(args, "task", "svc") == "svr":
            # regression: the last CSV column is a CONTINUOUS target
            from tpusvm.data.csv_reader import read_csv_regression

            X, Y = read_csv_regression(args.train, n_limit=args.n_limit)
            Xt = Yt = None
            if args.test:
                Xt, Yt = read_csv_regression(args.test)
            return X, Y, Xt, Yt
        binary = not args.multiclass
        X, Y = read_csv_fast(args.train, n_limit=args.n_limit,
                             binary_labels=binary,
                             positive_label=args.positive_label)
        Xt = Yt = None
        if args.test:
            Xt, Yt = read_csv_fast(args.test, binary_labels=binary,
                                   positive_label=args.positive_label)
        return X, Y, Xt, Yt

    n_total = args.n + args.n_test
    if args.synthetic == "mnist-like":
        if args.multiclass:
            from tpusvm.data.synthetic import BENCH_NOISE_MULTICLASS

            X, Y = mnist_like_multiclass(n=n_total, d=args.d, seed=args.seed,
                                         noise=BENCH_NOISE_MULTICLASS)
        else:
            from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE

            X, Y = mnist_like(n=n_total, d=args.d, seed=args.seed,
                              noise=BENCH_NOISE,
                              label_noise=BENCH_LABEL_NOISE)
    elif args.synthetic == "blobs":
        X, Y = blobs(n=n_total, d=args.d, seed=args.seed)
    elif args.synthetic == "sine":
        # continuous regression targets (--task svr); d=2 recommended
        from tpusvm.data.synthetic import svr_sine

        X, Y = svr_sine(n=n_total, d=args.d, seed=args.seed)
    else:
        X, Y = rings(n=n_total, seed=args.seed)
    if args.n_limit is not None:
        args.n = min(args.n, args.n_limit)
    # test slice anchored at the end so --n-limit shrinks the train set
    # without changing the test set
    return (X[: args.n], Y[: args.n],
            X[n_total - args.n_test :], Y[n_total - args.n_test :])


def _parse_solver_opts(items) -> dict:
    """KEY=VALUE --solver-opt strings -> typed knob dict.

    Values convert bool -> int -> float -> string in that order, so
    warm_start=false is a real False (not a truthy str) and refine=1e4 a
    number, while knobs like matmul_precision=default stay strings.
    """
    opts = {}
    for item in items:
        key, sep, value = item.partition("=")
        if not sep or not key:
            raise SystemExit(
                f"--solver-opt expects KEY=VALUE, got {item!r}"
            )
        if value.lower() in ("true", "false"):
            opts[key] = value.lower() == "true"
            continue
        for conv in (int, float):
            try:
                opts[key] = conv(value)
                break
            except ValueError:
                continue
        else:
            opts[key] = value
    return opts


def _make_tracer(args, command: str, role=None):
    """The shared --trace plumbing (train/tune/serve/ingest): one Tracer
    receiving fault/retry/breaker lifecycle events AND the compile
    observatory's prof.compile records (lower/compile wall time, XLA
    cost analysis — tpusvm.obs.prof), plus a profile.capture event when
    --profile/--xprof is also set so the trace names the capture dir.

    role= makes the tracer a trace-context origin (serve/router): its
    spans can be the resolved parents of other processes' spans when
    trace files merge in `tpusvm report`."""
    if not getattr(args, "trace", None):
        return None
    from tpusvm import faults
    from tpusvm.obs import Tracer, prof

    tracer = Tracer(args.trace, argv=[command], role=role,
                    max_bytes=getattr(args, "trace_max_bytes", None))
    faults.set_event_sink(tracer.event)
    prof.enable_profiling(event_sink=tracer.event)
    if getattr(args, "profile", None):
        tracer.event("profile.capture", dir=args.profile)
    return tracer


def _close_tracer(tracer) -> None:
    from tpusvm.obs import prof

    prof.disable_profiling()
    if tracer is not None:
        tracer.close()


def _cmd_train(args) -> int:
    import jax
    import jax.numpy as jnp

    from tpusvm.config import (
        CascadeConfig,
        SVMConfig,
        preset,
        resolve_accum_dtype,
    )
    from tpusvm.models import BinarySVC, OneVsRestSVC
    from tpusvm.utils import PhaseTimer, RunLogger, trace

    # --task ovr is the one-vs-rest synonym for --multiclass (the fleet's
    # natural task name); normalise BEFORE the smoke shape is chosen
    if args.task == "ovr":
        args.multiclass = True
    if args.fleet:
        if not args.multiclass:
            raise SystemExit("--fleet trains one-vs-rest heads as one "
                             "batched program; it requires "
                             "--multiclass/--task ovr")
        if args.solver not in (None, "fleet"):
            raise SystemExit(f"--fleet and --solver {args.solver} "
                             "conflict (--fleet means --solver fleet)")
        args.solver = "fleet"
    if args.solver == "fleet" and not args.multiclass:
        raise SystemExit("--solver fleet requires --multiclass/--task "
                         "ovr (the fleet batches the one-vs-rest heads)")
    if args.fleet_compact:
        if args.fleet_compact < 0:
            raise SystemExit("--fleet-compact must be >= 0")
        if args.solver != "fleet":
            raise SystemExit("--fleet-compact needs --fleet/--solver "
                             "fleet")

    if args.smoke:
        # the CI gate shape: tiny, CPU-friendly, deterministic, with the
        # convergence ring ON so the trace carries a real gap trajectory.
        # The workload matches the (kernel, task) cell under test: rings
        # NEED the RBF kernel (linear fails on them by construction), so
        # linear/poly smoke runs separable blobs, and --task svr runs the
        # sine regression problem with an R^2 gate.
        if args.task == "ovr":
            # the multiclass cell: a 10-class mnist-shaped problem small
            # enough for CI, accuracy-gated against chance (0.1); the
            # binary branches below force multiclass OFF, so this one
            # keeps its own shape and skips the binary-only ring gate
            args.synthetic, args.d = "mnist-like", 64
            args.C, args.gamma = 10.0, 1.0 / 64
            args.train = args.data = None
            args.test = None
            args.n, args.n_test, args.n_limit = 1024, 256, None
            args.mode = "single"
            args.solver = args.solver or "blocked"
        elif args.task == "svr":
            args.synthetic, args.d = "sine", 2
            args.C, args.gamma, args.epsilon = 10.0, 20.0, 0.1
        elif args.kernel in ("rbf", "rff", "nystrom"):
            # the approx families are rbf approximators: they get the
            # SAME rings workload the exact rbf smoke gates (linear
            # fails rings by construction, so passing it proves the map
            # carries the rbf geometry); map widths sized to the tiny
            # smoke problem (landmarks must be <= n = 240)
            args.synthetic = "rings"
            args.C, args.gamma = 10.0, 10.0
            if args.kernel == "rff":
                args.rff_dim = min(args.rff_dim, 512)
            if args.kernel == "nystrom":
                args.landmarks = min(args.landmarks, 128)
        elif args.kernel == "sigmoid":
            # tanh needs the negative offset to carve a margin on blobs
            # (coef0=0 saturates into a linear-at-origin surface whose
            # eta degenerates); measured CONVERGED at 1.0 accuracy
            args.synthetic, args.d = "blobs", 6
            args.C, args.gamma = 10.0, 0.25
            if args.coef0 == 0.0:
                args.coef0 = -1.0
        else:
            args.synthetic, args.d = "blobs", 6
            args.C, args.gamma = 1.0, 1.0
            if args.kernel == "poly" and args.coef0 == 0.0:
                args.coef0 = 1.0  # odd-degree poly needs the affine term
        if args.task != "ovr":
            args.train = args.data = None
            args.test = None
            args.n, args.n_test, args.n_limit = 240, 60, None
            args.mode, args.multiclass = "single", False
            args.solver = args.solver or "blocked"
            if args.convergence == 0:
                # the ring is a binary blocked-solver surface; the ovr
                # smoke gates statuses/accuracy instead
                args.convergence = 32

    # "float64" (the default) = the library's "auto" resolution: f64
    # accumulators + x64 enabled — one source of truth for that rule. The
    # library's enabling-x64 warning is suppressed here: its remediation
    # (accum_dtype=None) is Python-API advice, and the CLI has its own
    # explicit knob for this (--accum none).
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        accum_dtype = resolve_accum_dtype(
            "auto" if args.accum == "float64" else None
        )
    dtype = getattr(jnp, args.dtype)
    if args.dtype == "float64":
        jax.config.update("jax_enable_x64", True)

    kernel_kw = dict(kernel=args.kernel, degree=args.degree,
                     coef0=args.coef0, epsilon=args.epsilon,
                     rff_dim=args.rff_dim, map_seed=args.rff_seed,
                     landmarks=args.landmarks)
    try:
        if args.preset:
            cfg = preset(args.preset, tau=args.tau, eps=args.eps,
                         sv_tol=args.sv_tol, max_iter=args.max_iter,
                         max_rounds=args.max_rounds, **kernel_kw)
        else:
            cfg = SVMConfig(C=args.C, gamma=args.gamma, tau=args.tau,
                            eps=args.eps, sv_tol=args.sv_tol,
                            max_iter=args.max_iter,
                            max_rounds=args.max_rounds, **kernel_kw)
    except ValueError as e:
        # e.g. a tile-misaligned --rff-dim/--landmarks: the config
        # validator rejects it up front (the JXIR104 rationale), before
        # any data is loaded
        raise SystemExit(f"train: {e}")

    solver_opts = _parse_solver_opts(args.solver_opt)

    if args.fleet_compact:
        if "compact_every" in solver_opts:
            raise SystemExit("--fleet-compact and --solver-opt "
                             "compact_every= are the same knob; pass one")
        solver_opts["compact_every"] = args.fleet_compact
    if args.smoke and args.task == "ovr":
        # CI-sized working set for the 10 small heads (q=1024 would
        # clamp to the whole smoke training set)
        solver_opts.setdefault("q", 128)

    # dedicated ladder flags fold into the same solver_opts the models
    # consume; passing both spellings is a conflict, not a silent override
    if args.precision != "f32":
        if "matmul_precision" in solver_opts:
            raise SystemExit("--precision and --solver-opt "
                             "matmul_precision= are the same knob; "
                             "pass one")
        solver_opts["matmul_precision"] = args.precision
    if args.shrink_every:
        if args.shrink_every < 1:
            raise SystemExit("--shrink-every must be >= 1")
        if "shrink_every" in solver_opts:
            raise SystemExit("--shrink-every and --solver-opt "
                             "shrink_every= are the same knob; pass one")
        solver_opts["shrink_every"] = args.shrink_every
        solver_opts.setdefault("shrink_stable", args.shrink_stable)

    # pure flag-consistency checks, before the (possibly long) data load
    if solver_opts:
        if args.mode == "oracle":
            raise SystemExit(
                "--solver-opt/--precision/--shrink-every have no effect "
                "on --mode oracle (the NumPy oracle has no static "
                "solver knobs)"
            )
        # validate knob names against the selected solver's signature now,
        # not minutes later from inside fit
        import inspect

        from tpusvm.solver import smo_solve
        from tpusvm.solver.blocked import blocked_smo_solve
        from tpusvm.solver.shrink import shrinking_blocked_solve

        solver_name = args.solver or ("pair" if args.multiclass else "blocked")
        if solver_name == "fleet":
            from tpusvm.fleet.solve import fleet_smo_solve
            fn = fleet_smo_solve
        else:
            fn = blocked_smo_solve if solver_name == "blocked" else smo_solve
        # arrays and the hyperparameters with dedicated CLI flags are not
        # --solver-opt material (passing them twice would TypeError in fit)
        flagged = {"C", "gamma", "eps", "tau", "max_iter", "accum_dtype",
                   "kernel", "degree", "coef0"}
        reserved = {"X", "Y", "valid", "alpha0", "sn", "targets",
                    # the fleet launch's batched surface (driven by
                    # fleet_train, not --solver-opt)
                    "Ys", "Cs", "gammas", "valids", "alpha0s",
                    "resume_states",
                    # the checkpoint driver's internal resume surface
                    "resume_state", "pause_at", "return_state",
                    # the shrink driver's internal surfaces
                    "return_history", "kw"} | flagged
        known = set(inspect.signature(fn).parameters) - reserved
        if solver_name == "blocked":
            # the blocked solver's opts include the shrinking driver's
            # knobs (models route to solver/shrink.py on shrink_every)
            known |= set(inspect.signature(
                shrinking_blocked_solve).parameters) - reserved
        elif solver_name == "fleet":
            # the packing/compaction knobs of the fleet driver
            known |= {"bucket", "compact_every"}
        if args.data and args.kernel in ("rff", "nystrom"):
            # streamed approx training runs the primal epoch schedule
            # (tpusvm.approx.primal), whose knobs replace the blocked
            # solver's — fit_stream rejects blocked knobs by name there
            known |= {"primal_batch", "primal_epochs", "primal_tol",
                      "prefetch_depth"}
        bad = sorted(set(solver_opts) - known)
        if bad:
            hint = [k for k in bad if k in flagged]
            raise SystemExit(
                f"--solver-opt: unknown {solver_name!r}-solver knob(s) "
                f"{bad}; known: {sorted(known)}"
                + (f" (use the dedicated flags for {hint})" if hint else "")
            )
        if "matmul_precision" in solver_opts and solver_name != "blocked":
            raise SystemExit("--precision/matmul_precision is a blocked-"
                             "solver ladder knob; the pair solver has no "
                             "laddered contraction")
        if "shrink_every" in solver_opts:
            if solver_name != "blocked":
                raise SystemExit("--shrink-every needs the blocked "
                                 "solver (working-set rounds are what "
                                 "gets compacted)")
            if args.mode not in ("single", "pod"):
                raise SystemExit(
                    "--shrink-every needs --mode single or --mode pod: "
                    "the shrinking driver segments the solve host-side, "
                    "which the cascade's shard_map leaves cannot do (pod "
                    "leaves are host processes, so they can)"
                )
            if args.checkpoint and args.mode == "single":
                raise SystemExit(
                    "--shrink-every and --checkpoint both segment the "
                    "outer loop and cannot be combined yet (--mode pod "
                    "checkpoints per ROUND, which composes); crash-safe "
                    "single-mode shrinking is a future PR"
                )
            if args.multiclass:
                raise SystemExit("--shrink-every supports binary/svr "
                                 "--mode single/pod training for now")
    if args.kernel in ("rff", "nystrom"):
        if args.mode == "oracle":
            raise SystemExit(
                "--mode oracle has no approximate kernels: the NumPy "
                "oracle is the EXACT rbf anchor the approx families are "
                "gated against (benchmarks/fuzz_parity.py mode rff); "
                "train --kernel rbf --mode oracle instead"
            )
        if args.mode in ("cascade", "pod") and args.data:
            raise SystemExit(
                f"--mode {args.mode} --data with an approximate kernel "
                "is not supported yet (leaf partitions carry RAW rows; "
                "the mapped width would change every buffer shape): "
                f"drop --mode {args.mode} for the streaming primal "
                "path, or load the data in-memory for a mapped cascade"
            )
        if args.data and args.convergence:
            raise SystemExit(
                "--convergence rides the blocked solver's outer loop; "
                "streamed approximate training runs the primal epoch "
                "schedule (tpusvm.approx.primal), which has no "
                "convergence ring yet"
            )
    if args.task == "svr":
        if args.mode != "single":
            raise SystemExit("--task svr requires --mode single (the "
                             "doubled-variable solve; cascade/oracle SVR "
                             "is a future PR)")
        if args.multiclass:
            raise SystemExit("--task svr is a regression task; "
                             "--multiclass does not apply")
        if args.data:
            raise SystemExit("--task svr reads CSVs (--train, continuous "
                             "last column) or --synthetic sine; sharded "
                             "--data datasets carry class labels")
        if args.calibrate:
            raise SystemExit("--calibrate fits class probabilities; it "
                             "requires --task svc")
    elif args.synthetic == "sine":
        raise SystemExit("--synthetic sine generates continuous targets; "
                         "it requires --task svr")
    if args.calibrate:
        if args.calibrate < 2:
            raise SystemExit("--calibrate needs >= 2 folds")
        if args.multiclass or args.mode != "single":
            raise SystemExit("--calibrate applies to binary --mode single "
                             "training (Platt scaling of the binary "
                             "decision function)")
    if args.class_parallel and not args.multiclass:
        raise SystemExit("--class-parallel requires --multiclass (it "
                         "shards the one-vs-rest class axis)")
    if args.resume and not args.checkpoint:
        raise SystemExit("--resume requires --checkpoint")
    if args.checkpoint:
        if args.mode == "oracle":
            raise SystemExit("--checkpoint applies to --mode single "
                             "(solver-state checkpoints) or cascade "
                             "(per-round state); the NumPy oracle has no "
                             "checkpointable structure")
        if args.mode == "single":
            solver_name = args.solver or ("pair" if args.multiclass
                                          else "blocked")
            if args.multiclass or args.task == "svr" \
                    or solver_name != "blocked":
                raise SystemExit(
                    "--checkpoint with --mode single needs the binary "
                    "blocked solver (the outer-loop carry is what gets "
                    "persisted); multiclass/svr checkpointing is a "
                    "future PR"
                )
        if args.checkpoint_every < 1:
            raise SystemExit("--checkpoint-every must be >= 1")
    if args.stratify and args.mode not in ("cascade", "pod"):
        raise SystemExit("--stratify only applies to --mode cascade/pod "
                         "(it changes how rows are dealt over the leaves)")
    if args.mode == "pod":
        # pod leaves stream their manifest shards from disk — there is
        # no in-memory source to hand them
        if not args.data:
            raise SystemExit(
                "--mode pod trains out-of-core from a sharded dataset "
                "dir: pass --data DIR (`tpusvm ingest` builds one)"
            )
        if (args.solver or "blocked") not in ("blocked", "pair"):
            raise SystemExit("--mode pod leaves run the blocked or pair "
                             "solver")
    if args.convergence:
        if args.convergence < 0:
            raise SystemExit("--convergence must be >= 0")
        solver_name = args.solver or ("pair" if args.multiclass
                                      else "blocked")
        if args.mode != "single" or args.multiclass \
                or solver_name != "blocked":
            raise SystemExit(
                "--convergence needs --mode single with the blocked "
                "solver (the ring is carried through "
                "blocked_smo_solve's outer loop)"
            )
        if "telemetry" in solver_opts:
            raise SystemExit("--convergence and --solver-opt telemetry= "
                             "are the same knob; pass one")
        solver_opts["telemetry"] = args.convergence

    tracer = _make_tracer(args, "train")
    log = RunLogger(jsonl_path=args.jsonl,
                    primary=(jax.process_index() == 0) and not args.quiet)
    timer = PhaseTimer(tracer=tracer)

    dataset = None
    if args.data:
        # streamed source: scaler from manifest stats, shard-at-a-time
        # loading — trains the identical model to the in-memory path
        if args.train or args.synthetic:
            raise SystemExit(
                "pass exactly one of --train / --synthetic / --data"
            )
        if args.multiclass:
            raise SystemExit("--data supports binary training (one-vs-rest "
                             "over shards is a future PR); ingest was "
                             "binary-mapped or use --train")
        if args.mode == "oracle":
            raise SystemExit("--mode oracle reads CSVs (--train); --data "
                             "is the streaming path")
        if args.n_limit is not None:
            raise SystemExit("--n-limit does not apply to --data (the "
                             "manifest defines the rows; re-ingest with "
                             "--n-limit instead)")
        from tpusvm.data.native_io import read_csv_fast
        from tpusvm.stream import open_dataset

        Xt = Yt = None
        with timer.phase("data"):
            dataset = open_dataset(args.data)
            if args.test:
                Xt, Yt = read_csv_fast(args.test, binary_labels=True,
                                       positive_label=args.positive_label)
        n, n_features = dataset.n_rows, dataset.n_features
        X = Y = None
    else:
        with timer.phase("data"):
            X, Y, Xt, Yt = _load_train_data(args)
        n, n_features = X.shape
    log.info("n = %d, n_features = %d", n, n_features)
    log.event("data", n=n, n_features=n_features, mode=args.mode,
              streamed=dataset is not None)
    if args.task == "svr":
        from tpusvm.models import EpsilonSVR

        model = EpsilonSVR(config=cfg, dtype=dtype,
                           scale=not args.no_scale,
                           accum_dtype=accum_dtype,
                           solver=args.solver or "blocked",
                           solver_opts=solver_opts)
        with timer.phase("training"), trace(args.profile):
            model.fit(X, Y)
    elif args.multiclass:
        if args.mode != "single":
            raise SystemExit("--multiclass currently supports --mode single")
        if args.class_parallel and args.solver in ("blocked", "fleet"):
            raise SystemExit(
                "--class-parallel shards the vmapped pair solver over the "
                "mesh; --solver blocked trains classes sequentially and "
                "--solver fleet is already one batched launch"
            )
        model = OneVsRestSVC(config=cfg, dtype=dtype, scale=not args.no_scale,
                             accum_dtype=accum_dtype,
                             solver=args.solver or "pair",
                             solver_opts=solver_opts,
                             class_parallel=args.class_parallel)
        with timer.phase("training"), trace(args.profile):
            model.fit(X, Y)
        log.info("classes = %s", list(model.classes_))
    elif args.mode == "oracle":
        model = _fit_oracle(X, Y, cfg, timer, log)
    else:
        model = BinarySVC(config=cfg, dtype=dtype, scale=not args.no_scale,
                          accum_dtype=accum_dtype,
                          solver=args.solver or "blocked",
                          solver_opts=solver_opts)
        with timer.phase("training"), trace(args.profile):
            if args.mode == "pod":
                # worker PROCESSES, not mesh devices: the default count
                # is a small multiprocess pod, not the device count
                shards = args.shards or 4
                cc = CascadeConfig(n_shards=shards,
                                   sv_capacity=args.sv_capacity,
                                   topology=args.topology)
                model.fit_pod(args.data, cc, verbose=not args.quiet,
                              checkpoint_path=args.checkpoint,
                              resume=args.resume,
                              stratified=args.stratify,
                              tracer=tracer)
                log.info("pod: %d workers, %d rounds, converged = %s",
                         shards, model.cascade_rounds_,
                         model.status_.name == "CONVERGED")
            elif args.mode == "cascade":
                shards = args.shards or len(jax.devices())
                cc = CascadeConfig(n_shards=shards,
                                   sv_capacity=args.sv_capacity,
                                   topology=args.topology)
                if dataset is not None:
                    model.fit_cascade_stream(
                        dataset, cc, verbose=not args.quiet,
                        checkpoint_path=args.checkpoint,
                        resume=args.resume, stratified=args.stratify,
                        tracer=tracer)
                else:
                    model.fit_cascade(X, Y, cc, verbose=not args.quiet,
                                      checkpoint_path=args.checkpoint,
                                      resume=args.resume,
                                      stratified=args.stratify,
                                      tracer=tracer)
                log.info("cascade: %d rounds, converged = %s",
                         model.cascade_rounds_,
                         model.status_.name == "CONVERGED")
            elif dataset is not None:
                model.fit_stream(dataset,
                                 checkpoint_path=args.checkpoint,
                                 checkpoint_every=args.checkpoint_every,
                                 resume=args.resume)
            else:
                model.fit(X, Y,
                          checkpoint_path=args.checkpoint,
                          checkpoint_every=args.checkpoint_every,
                          resume=args.resume)

    if not args.multiclass:
        log.info("iterations = %d", model.n_iter_)
        log.info("b = %.15f", model.b_)
        if np.isfinite(model.b_high_):
            gap = (model.b_high_ - model.b_low_) / 2.0
            log.info("(b_high - b_low)/2 * 1e10 = %.6f", gap * 1e10)
        log.info("SV count = %d", model.n_support_)
        log.event("train", n_iter=model.n_iter_, b=model.b_,
                  sv_count=model.n_support_, status=model.status_.name,
                  train_time_s=timer["training"])

    if args.calibrate:
        # held-out-fold Platt scaling; the saved model then carries
        # (platt_a, platt_b) and serve adds a proba field
        with timer.phase("calibration"):
            model.calibrate(X, Y, folds=args.calibrate)
        log.info("calibrated: Platt A=%.6f B=%.6f", *model.platt_)
        log.event("calibrate", folds=args.calibrate,
                  platt_a=model.platt_[0], platt_b=model.platt_[1])

    acc = None
    if Xt is not None and len(Xt):
        with timer.phase("prediction"):
            acc = model.score(Xt, Yt)
        m = len(Yt)
        if args.task == "svr":
            # score() is R^2 for the regression task
            rmse = float(np.sqrt(np.mean(
                (model.predict(Xt) - np.asarray(Yt, np.float64)) ** 2)))
            log.info("r2 = %.4f  rmse = %.4f (%d rows)", acc, rmse, m)
            log.event("eval", r2=acc, rmse=rmse, m=m)
        else:
            log.info("accuracy = %.4f (%d/%d)", acc, round(acc * m), m)
            log.event("eval", accuracy=acc, m=m)

    if args.save:
        model.save(args.save)
        log.info("model saved to %s", args.save)

    conv = getattr(model, "convergence_", None)
    if conv is not None and not args.quiet:
        from tpusvm.obs import format_gap_table

        log.info("convergence (b_low - b_high per outer round):")
        log.info("%s", format_gap_table(conv))
    if tracer is not None:
        if conv is not None:
            from tpusvm.obs import to_trace_events

            to_trace_events(tracer, conv)
        from tpusvm.obs import default_registry

        tracer.metrics_snapshot(default_registry().snapshot())

    log.info("%s", timer.report())
    log.event("timing", **timer.asdict())
    log.close()
    _close_tracer(tracer)

    if args.smoke and args.task == "ovr":
        # the multiclass cell's gates: every head terminated CONVERGED,
        # and the 10-class argmax beats chance (0.1) with margin — the
        # fleet and loop paths share these gates, so `--fleet` smoke
        # failing while the loop passes is a fleet regression
        from tpusvm.status import Status as _Status

        failures = []
        bad = [
            (int(c), _Status(int(s)).name)
            for c, s in zip(model.classes_, model.statuses_)
            if int(s) != _Status.CONVERGED
        ]
        if bad:
            failures.append(f"heads did not converge: {bad}")
        if acc is None or acc <= 0.25:
            failures.append(f"held-out accuracy gate failed ({acc!r})")
        if failures:
            for f in failures:
                print(f"TRAIN SMOKE FAILED: {f}")
            return 1
        print(f"train smoke ok [ovr/{args.solver}]: "
              f"{len(model.classes_)} heads, SV union "
              f"{model.X_sv_.shape[0]}, accuracy {acc:.4f}")
        return 0

    if args.smoke:
        gate_name = "r2" if args.task == "svr" else "accuracy"
        failures = []
        if model.status_.name != "CONVERGED":
            failures.append(f"solver ended {model.status_.name}")
        if acc is None or acc <= 0.8:
            failures.append(f"held-out {gate_name} gate failed ({acc!r})")
        if conv is None or len(conv["gap"]) == 0:
            failures.append("no convergence telemetry recorded")
        elif conv["gap"][-1] > 2.0 * args.tau * (1 + 1e-9):
            failures.append(
                f"final recorded gap {conv['gap'][-1]:g} exceeds the "
                f"2*tau stopping criterion ({2 * args.tau:g})")
        if args.trace:
            from tpusvm.obs import read_trace
            from tpusvm.obs.report import convergence_rows, phase_summary

            try:
                records = read_trace(args.trace)
                phases, total = phase_summary(records)
                if not phases:
                    failures.append("trace carries no phase spans")
                if not convergence_rows(records):
                    failures.append("trace carries no convergence records")
            except ValueError as e:
                failures.append(f"trace unreadable: {e}")
        if failures:
            for f in failures:
                print(f"TRAIN SMOKE FAILED: {f}")
            return 1
        print(f"train smoke ok [{args.kernel}/{args.task}]: "
              f"{model.n_support_} SVs, {gate_name} {acc:.4f}, "
              f"{conv['rounds_recorded']} convergence rounds recorded")
    return 0


def _fit_oracle(X, Y, cfg, timer, log):
    """Serial NumPy SMO (main3.cpp capability) behind the BinarySVC surface."""
    from tpusvm.data import MinMaxScaler
    from tpusvm.models import BinarySVC
    from tpusvm.oracle.smo import get_sv_indices, smo_train

    model = BinarySVC(config=cfg)
    with timer.phase("training"):
        model.scaler_ = MinMaxScaler().fit(X)
        Xs = model.scaler_.transform(X)
        res = smo_train(Xs, Y, cfg)
    sv = get_sv_indices(res.alpha, cfg.sv_tol)
    model.sv_X_ = Xs[sv]
    model.sv_Y_ = np.asarray(Y)[sv].astype(np.int32)
    model.sv_alpha_ = res.alpha[sv]
    model.sv_ids_ = sv.astype(np.int32)
    model.b_ = res.b
    model.b_high_ = res.b_high
    model.b_low_ = res.b_low
    model.n_iter_ = res.n_iter
    model.status_ = res.status
    return model


def _cmd_pod(args) -> int:
    """Self-contained pod-cascade run: out-of-core multiprocess training
    with a bit-level parity gate against the in-memory cascade."""
    import tempfile
    import warnings

    from tpusvm.config import (
        CascadeConfig,
        SVMConfig,
        resolve_accum_dtype,
    )
    from tpusvm.pod import pod_fit
    from tpusvm.stream import open_dataset

    topologies = (["tree", "star"] if args.topology == "both"
                  else [args.topology])
    cfg = SVMConfig(C=args.C, gamma=args.gamma, max_rounds=args.max_rounds)
    with warnings.catch_warnings():
        # the enabling-x64 advice warning; the pod command always runs
        # the library's "auto" f64-accumulator resolution
        warnings.simplefilter("ignore", UserWarning)
        accum = resolve_accum_dtype("auto")
    tracer = None
    if getattr(args, "trace_dir", None):
        import os as _os

        from tpusvm import faults
        from tpusvm.obs import Tracer

        _os.makedirs(args.trace_dir, exist_ok=True)
        # the coordinator's own trace file; workers open theirs at spawn
        # (pod_fit hands them the dir + this tracer's context) so the
        # whole fleet stitches into one `tpusvm report` timeline
        tracer = Tracer(_os.path.join(args.trace_dir, "coordinator.jsonl"),
                        role="pod-coordinator", argv=["pod"],
                        max_bytes=args.trace_max_bytes)
        faults.set_event_sink(tracer.event)
    failures = []
    summaries = []
    with tempfile.TemporaryDirectory() as td:
        if args.data:
            data, X, Y = args.data, None, None
        else:
            import os as _os

            from tpusvm.data.synthetic import rings
            from tpusvm.stream import ingest_arrays

            X, Y = rings(n=args.n, seed=3)
            data = _os.path.join(td, "ds")
            ingest_arrays(data, X, Y,
                          rows_per_shard=args.rows_per_shard)
        n_rows = open_dataset(data).n_rows
        for topo in topologies:
            cc = CascadeConfig(n_shards=args.workers,
                               sv_capacity=args.sv_capacity,
                               topology=topo)
            res = pod_fit(data, cfg, cc, accum_dtype=accum,
                          verbose=not args.quiet,
                          tracer=tracer,
                          trace_dir=getattr(args, "trace_dir", None),
                          trace_max_bytes=getattr(
                              args, "trace_max_bytes", None))
            if not args.quiet:
                print(f"pod[{topo}]: {res.rounds} rounds, "
                      f"{len(res.sv_ids)} SVs, b = {res.b:.12f}, "
                      f"rows {list(res.worker_rows)}, "
                      f"live shards {list(res.worker_max_live_shards)}, "
                      f"revives {res.revives}")
            if not res.converged:
                failures.append(f"[{topo}] pod did not converge in "
                                f"{res.rounds} rounds")
            if sum(res.worker_rows) != n_rows:
                failures.append(
                    f"[{topo}] rows lost: workers hold "
                    f"{sum(res.worker_rows)} of {n_rows}")
            summaries.append((topo, res.rounds, len(res.sv_ids)))
            if X is None:
                continue
            # parity gate: the in-memory cascade on the identically
            # scaled rows must be BIT-identical — same SV-ID set, same
            # alpha bytes, same b (the pod moves leaf results over the
            # wire protocol; any serialization loss shows up here)
            from tpusvm.data import MinMaxScaler
            from tpusvm.parallel.cascade import cascade_fit

            ctrl = cascade_fit(MinMaxScaler().fit_transform(X), Y,
                               cfg, cc, accum_dtype=accum)
            if set(res.sv_ids.tolist()) != set(
                    np.asarray(ctrl.sv_ids).tolist()):
                failures.append(f"[{topo}] SV-ID set diverges from the "
                                "in-memory cascade")
            elif np.asarray(res.sv_alpha).tobytes() != np.asarray(
                    ctrl.sv_alpha).tobytes():
                failures.append(f"[{topo}] alpha bytes diverge from "
                                "the in-memory cascade")
            if res.b != ctrl.b:
                failures.append(f"[{topo}] b diverges: pod {res.b!r} "
                                f"vs in-memory {ctrl.b!r}")
    if tracer is not None:
        from tpusvm import faults

        faults.set_event_sink(None)
        tracer.close()
        if not args.quiet:
            print(f"trace: {args.trace_dir} "
                  f"(render with `tpusvm report {args.trace_dir}`)")
    if failures:
        for f in failures:
            print(f"POD{' SMOKE' if args.smoke else ''} FAILED: {f}")
        return 1
    parity = "bit-identical to in-memory cascade" if X is not None \
        else "parity gate skipped (--data)"
    print("pod ok: " + "; ".join(
        f"{t} {r} rounds/{s} SVs" for t, r, s in summaries)
        + f", {args.workers} workers, {parity}")
    return 0


def _cmd_ingest(args) -> int:
    """Convert a CSV / synthetic generator into a sharded dataset dir."""
    from tpusvm.status import StreamStatus
    from tpusvm.stream import ingest_arrays, ingest_csv, open_dataset
    from tpusvm.utils import PhaseTimer, trace

    say = (lambda msg: None) if args.quiet else print

    if getattr(args, "kernel", "raw") != "raw":
        # explicit interop decision, not a silent pass-through: shards
        # hold raw features by design — pre-mapping at ingest would pin
        # the dataset to one (D, seed) AND break the scale-then-map
        # order (the scaler comes from manifest stats at train time)
        raise SystemExit(
            f"ingest --kernel {args.kernel}: shards store RAW features; "
            "the approximate map is applied stream-side during training "
            "prefetch (tpusvm.approx) so one ingested dataset serves "
            f"every map — run `tpusvm train --data OUT --kernel "
            f"{args.kernel} --rff-dim D --rff-seed S` instead"
        )
    if args.smoke:
        return _ingest_smoke(args, say)
    if not args.out:
        raise SystemExit("ingest: --out DIR is required (or --smoke)")
    if (args.train is None) == (args.synthetic is None):
        raise SystemExit("ingest: pass exactly one of --train / --synthetic")
    if args.synthetic == "sine":
        raise SystemExit("ingest shards labelled datasets; --synthetic "
                         "sine generates continuous SVR targets "
                         "(train --task svr reads it directly)")

    tracer = _make_tracer(args, "ingest")
    timer = PhaseTimer(tracer=tracer)

    with timer.phase("ingest"), trace(args.profile):
        if args.train:
            manifest = ingest_csv(
                args.out, args.train, rows_per_shard=args.rows_per_shard,
                n_limit=args.n_limit, binary=not args.multiclass,
                positive_label=args.positive_label,
                block_rows=args.block_rows,
                resume=args.resume,
            )
        else:
            # synthetic generators are in-memory anyway; shard their output
            args.n_test = 0
            X, Y, _, _ = _load_train_data(args)
            manifest = ingest_arrays(
                args.out, X, Y, rows_per_shard=args.rows_per_shard,
                binary=not args.multiclass,
                positive_label=(None if args.multiclass
                                else args.positive_label),
                resume=args.resume,
            )

    with timer.phase("validate"):
        bad = [(manifest.shards[i].filename, s.name)
               for i, s in enumerate(open_dataset(args.out).validate())
               if s != StreamStatus.OK]
    if tracer is not None:
        from tpusvm.obs import default_registry

        tracer.event("ingest.manifest", n_rows=manifest.n_rows,
                     n_features=manifest.n_features,
                     n_shards=len(manifest.shards), out=args.out,
                     valid=not bad)
        tracer.metrics_snapshot(default_registry().snapshot())
    _close_tracer(tracer)
    if bad:
        print(f"ingest: wrote shards that FAIL validation: {bad}")
        return 1
    stats = manifest.global_stats()
    say(f"ingested {manifest.n_rows} rows x {manifest.n_features} features "
        f"into {len(manifest.shards)} shards at {args.out}")
    say(f"class counts: {dict(sorted(stats.class_counts.items()))}")
    say(timer.report())
    return 0


def _ingest_smoke(args, say) -> int:
    """CI gate: ingest a tiny synthetic dataset and assert every claim the
    stream layer makes — manifest integrity, reader round-trip parity,
    scaler-from-stats bit-parity, the prefetch residency bound."""
    import tempfile

    import numpy as np

    from tpusvm.data import MinMaxScaler, rings
    from tpusvm.status import StreamStatus
    from tpusvm.stream import ShardReader, ingest_arrays, open_dataset

    X, Y = rings(n=301, seed=11)
    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        manifest = ingest_arrays(tmp, X, Y, rows_per_shard=64)
        ds = open_dataset(tmp)
        statuses = ds.validate()
        if not all(s == StreamStatus.OK for s in statuses):
            failures.append(f"validate: {[s.name for s in statuses]}")
        reader = ShardReader(ds, prefetch_depth=2)
        blocks = list(reader)
        Xr = np.concatenate([b[0] for b in blocks])
        Yr = np.concatenate([b[1] for b in blocks])
        if not (np.array_equal(Xr, X) and np.array_equal(Yr, Y)):
            failures.append("reader round-trip diverged from the generator")
        if reader.max_live_shards > 3:
            failures.append(
                f"residency bound violated: {reader.max_live_shards} live "
                "shards > prefetch_depth + 1 = 3")
        sc = ds.scaler()
        sf = MinMaxScaler().fit(X)
        if not (np.array_equal(sc.min_val, sf.min_val)
                and np.array_equal(sc.max_val, sf.max_val)):
            failures.append("manifest scaler != full-array fit")
    if failures:
        for f in failures:
            print(f"INGEST SMOKE FAILED: {f}")
        return 1
    say(f"ingest smoke ok: {manifest.n_rows} rows, "
        f"{len(manifest.shards)} shards, scaler/round-trip/residency "
        "parity held")
    return 0


def _cmd_predict(args) -> int:
    from tpusvm.data.native_io import read_csv_fast
    from tpusvm.models import load_any
    from tpusvm.models.serialization import model_task
    from tpusvm.stream import is_dataset_dir
    from tpusvm.utils import PhaseTimer

    timer = PhaseTimer()
    # dispatch on the saved state (binary/OVR/SVR); multiclass labels
    # stay raw instead of the reference's binary != 1 -> -1 mapping
    task = model_task(args.model)
    multiclass = task == "ovr"
    model = load_any(args.model)
    if args.proba:
        if task != "svc" or getattr(model, "platt_", None) is None:
            raise SystemExit(
                "--proba needs a calibrated binary model (train with "
                "--calibrate); this artifact carries no Platt coefficients"
            )
    if task == "svr" and is_dataset_dir(args.data):
        raise SystemExit("svr models read CSV test data (--data CSV with "
                         "a continuous last column)")
    if is_dataset_dir(args.data):
        # streamed scoring off the shards: peak memory is the reader's
        # prefetch bound + one batch, regardless of dataset size
        from tpusvm.stream import evaluate_stream, open_dataset, predict_stream

        dataset = open_dataset(args.data)
        if args.mesh_predict:
            raise SystemExit("--mesh-predict applies to CSV input; the "
                             "streamed path batches over shards instead")
        if args.scores:
            n_out = 0
            for scores, _ in predict_stream(dataset=dataset, model=model,
                                            batch_size=args.batch_size):
                if args.n_limit is not None:
                    scores = scores[: max(0, args.n_limit - n_out)]
                n_out += len(scores)
                for row in scores.reshape(len(scores), -1):
                    print(" ".join(f"{s:.15f}" for s in row))
                if args.n_limit is not None and n_out >= args.n_limit:
                    break
            return 0
        with timer.phase("prediction"):
            acc, m = evaluate_stream(model, dataset,
                                     batch_size=args.batch_size,
                                     n_limit=args.n_limit)
        print(f"accuracy = {acc:.4f} ({round(acc * m)}/{m})")
        print(timer.report())
        return 0
    with timer.phase("data"):
        if task == "svr":
            from tpusvm.data.csv_reader import read_csv_regression

            X, Y = read_csv_regression(args.data, n_limit=args.n_limit)
        else:
            X, Y = read_csv_fast(args.data, n_limit=args.n_limit,
                                 binary_labels=not multiclass,
                                 positive_label=args.positive_label)
    mesh = None
    if args.mesh_predict:
        import jax

        from tpusvm.parallel.mesh import make_mesh

        devs = jax.local_devices()
        mesh = make_mesh(len(devs), devices=devs)
    if args.proba:
        proba = model.predict_proba(X, mesh=mesh)[:, 1]
        for p in proba:
            print(f"{p:.15f}")
        return 0
    if args.scores:
        kw = {} if task == "svr" else {"mesh": mesh}
        scores = np.asarray(model.decision_function(X, **kw))
        if len(scores):  # reshape(n, -1) is ambiguous on 0 rows;
            # an empty CSV must print nothing, as the old loop did
            for row in scores.reshape(len(scores), -1):
                print(" ".join(f"{s:.15f}" for s in row))
        return 0
    if task == "svr":
        with timer.phase("prediction"):
            r2 = model.score(X, Y)
        rmse = float(np.sqrt(np.mean(
            (model.predict(X) - np.asarray(Y, np.float64)) ** 2)))
        print(f"r2 = {r2:.4f}  rmse = {rmse:.4f} ({len(Y)} rows)")
        print(timer.report())
        return 0
    with timer.phase("prediction"):
        acc = model.score(X, Y, mesh=mesh)
    m = len(Y)
    print(f"accuracy = {acc:.4f} ({round(acc * m)}/{m})")
    print(timer.report())
    return 0


def _cmd_serve(args) -> int:
    import contextlib
    import json
    import os

    import jax.numpy as jnp

    from tpusvm.serve import ServeConfig, Server

    cfg = ServeConfig(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        queue_size=args.queue_size,
        timeout_ms=args.timeout_ms,
        shed_threshold=args.shed_threshold,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        slo_p99_ms=args.slo_p99_ms,
        slo_error_budget=args.slo_error_budget,
        slo_window_s=args.slo_window_s,
        slo_shed=args.slo_shed,
    )
    tracer = _make_tracer(args, "serve", role="serve")

    def _trace_final_metrics():
        if tracer is not None:
            for name in server.registry.names():
                tracer.event("serve.metrics", model=name,
                             snapshot=server.metrics(name))
                tracer.metrics_snapshot(
                    server._worker(name).metrics.registry_snapshot())
        _close_tracer(tracer)

    from tpusvm.serve import ModelLoadError

    cache_dir = args.cache_dir or os.environ.get("TPUSVM_CACHE_DIR")
    if args.assert_cached and not cache_dir:
        raise SystemExit("serve: --assert-cached needs --cache-dir (or "
                         "TPUSVM_CACHE_DIR) — there is no persistent "
                         "cache to have hit")
    if not (args.models or args.state or args.watch):
        raise SystemExit("serve: nothing to host — pass --model, "
                         "--state MANIFEST, or --watch DIR")

    server = Server(cfg, dtype=getattr(jnp, args.dtype))
    if cache_dir:
        manifest = server.configure_cache(cache_dir)
        known = len(manifest.get("signatures", {}))
        print(f"persistent compile cache: {cache_dir} "
              f"({known} known bucket signatures"
              f"{' — expecting a warm start' if known else ''})")
    if args.state:
        try:
            restored = server.restore_state(args.state)
        except FileNotFoundError:
            print(f"serve state {args.state}: absent (fresh start); "
                  "will be written after the first load")
        except ValueError as e:
            raise SystemExit(f"serve: --state: {e}")
        else:
            for n in restored["restored"]:
                gen = server.registry.generation(n)
                print(f"restored {n} (generation {gen}) from "
                      f"{args.state}")
            for n in restored["skipped"]:
                print(f"NOT restored (no source path recorded): {n}")
        server.enable_state(args.state)
    try:
        for spec in args.models:
            name, sep, path = spec.partition("=")
            if not sep:
                name, path = "", spec
            if not name:
                name = os.path.splitext(os.path.basename(path))[0]
            entry = server.load_model(name, path)
            print(f"loaded {name}: {entry.kind}, {entry.n_sv} SVs, "
                  f"{entry.n_features} features")
    except ModelLoadError as e:
        # the classified load failure (ServeStatus.LOAD_FAILED): the
        # offending path and cause, never a raw numpy/zipfile traceback
        raise SystemExit(f"serve: {e}")
    if not args.no_warmup:
        warm_span = (tracer.span("warmup", phase=True) if tracer
                     else contextlib.nullcontext())
        with warm_span:
            for name, n in server.warmup().items():
                print(f"warmed {name}: {n} bucket executables compiled")
    if cache_dir:
        from tpusvm.serve.cache import persistent_cache_stats

        stats = persistent_cache_stats()
        print(f"persistent cache: {stats['hits']} hits, "
              f"{stats['misses']} misses")

    watcher = None
    if args.watch:
        from tpusvm.serve.watch import ModelWatcher

        watcher = ModelWatcher(server, args.watch,
                               interval_s=args.watch_interval_s,
                               log_fn=print)
        watcher.poll_once()  # pick up anything already there
        if not args.smoke:
            watcher.start()
        print(f"watching {args.watch} every {args.watch_interval_s:g}s")

    from tpusvm.utils import trace as _profile_trace

    if args.smoke:
        smoke_span = (tracer.span("smoke", phase=True) if tracer
                      else contextlib.nullcontext())
        with smoke_span, _profile_trace(args.profile):
            rc = _serve_smoke(server, args.smoke_threads,
                              args.smoke_requests)
        if args.assert_cached:
            from tpusvm.serve.cache import persistent_cache_stats

            misses = persistent_cache_stats()["misses"]
            if misses:
                print(f"SMOKE FAILED --assert-cached: {misses} compile "
                      "cache misses (expected every executable to come "
                      "off the persistent cache)")
                rc = rc or 1
            else:
                print("assert-cached ok: 0 fresh compiles — warm "
                      "restart reached serving entirely from the "
                      "persistent cache")
        print(server.metrics_text(), end="")
        _trace_final_metrics()
        server.close()
        return rc

    from tpusvm.serve.http import make_http_server

    httpd = make_http_server(server, host=args.host, port=args.port)
    # per-request serve.request spans honoring propagated X-Tpusvm-Trace
    # contexts (a router in front re-parents them under its forwards)
    httpd.tpusvm_tracer = tracer
    # close() now owns the HTTP teardown: shutdown + server_close (the
    # bound port is released) + thread join — no leaked listener
    server.attach_http(httpd)
    host, port = httpd.server_address[:2]
    # with --port 0 the kernel chose the port just now: record the real
    # address into serve_state.json (when --state is on) and flush the
    # line, so a supervisor/chaos harness can discover where we bound
    server.set_bound_address(host, port)
    print(f"serving on http://{host}:{port} "
          f"(POST /v1/models/<name>:predict, POST /admin/swap, "
          f"GET /metrics)", flush=True)
    try:
        with _profile_trace(args.profile):
            httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if watcher is not None:
            watcher.stop()
        print(server.metrics_text(), end="")
        print(json.dumps(server.status()))
        _trace_final_metrics()
        server.close()
    return 0


def _serve_smoke(server, n_threads: int, n_requests: int) -> int:
    """Concurrent in-process exercise of every hosted model: the CI gate
    asserts zero errors and zero post-warm-up recompiles."""
    import threading

    import numpy as np

    failures = []
    for name in server.registry.names():
        entry = server.registry.get(name)
        rng = np.random.default_rng(0)
        rows = rng.random((n_threads * n_requests, entry.n_features))
        bad = []
        lock = threading.Lock()

        def run(t, name=name, rows=rows, bad=bad, lock=lock):
            for i in range(n_requests):
                r = server.submit(name, rows[t * n_requests + i])
                if not r.ok:
                    with lock:
                        bad.append(r.status)

        threads = [threading.Thread(target=run, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = server.metrics(name)
        if bad or snap["errors"] or snap["recompiles"]:
            failures.append((name, bad, snap["errors"], snap["recompiles"]))
        print(f"smoke {name}: {snap['ok']} ok, {snap['errors']} errors, "
              f"{snap['recompiles']} recompiles, mean batch rows "
              f"{snap['mean_batch_rows']:.2f}")
    if failures:
        for name, bad, errors, recompiles in failures:
            print(f"SMOKE FAILED {name}: statuses={bad} errors={errors} "
                  f"recompiles={recompiles}")
        return 1
    return 0


def _cmd_router(args) -> int:
    import json

    from tpusvm.router import Router, RouterConfig, make_router_http

    if args.smoke:
        return _router_smoke(args)
    if not args.replicas:
        raise SystemExit("router: no fleet — pass --replica URL "
                         "(repeatable) or --smoke")
    cfg = RouterConfig(
        replicas=tuple(args.replicas),
        replication=args.replication,
        seed=args.seed,
        poll_interval_s=args.poll_interval_s,
        down_after=args.down_after,
        health_timeout_s=args.health_timeout_s,
        forward_timeout_s=args.forward_timeout_s,
        skew_window=args.skew_window,
    )
    tracer = _make_tracer(args, "router", role="router")
    router = Router(cfg, tracer=tracer).start()
    httpd = make_router_http(router, host=args.host, port=args.port)
    router.attach_http(httpd)
    host, port = httpd.server_address[:2]
    print(f"routing on http://{host}:{port} over "
          f"{len(cfg.replicas)} replicas (k={cfg.replication}, "
          f"seed={cfg.seed}) — POST /v1/models/<name>:predict, "
          f"POST /admin/rollout|join|leave, GET /healthz /metrics "
          f"/v1/replicas", flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        print(router.metrics_text(), end="")
        print(json.dumps(router.health()))
        router.close()
        _close_tracer(tracer)
    return 0


def _router_smoke(args) -> int:
    """CI gate: an in-process two-replica fleet behind the router.

    Concurrent clients stream through Router.forward while one replica
    goes dark mid-run (its HTTP listener stops — failover must absorb
    it invisibly) and comes back for a staggered rollout. Asserts zero
    lost responses, every score bitwise one of the two generations, a
    skew-free final vector, and byte-reproducible placement tables."""
    import json
    import os
    import tempfile
    import threading
    import time

    import jax.numpy as jnp
    import numpy as np

    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.router import (
        Router,
        RouterConfig,
        placement_table,
        table_bytes,
    )
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.http import (
        make_http_server,
        start_http_thread,
        stop_http_server,
    )
    from tpusvm.status import RouterStatus

    failures = []
    Xa, Ya = rings(n=240, seed=2)
    Xb, Yb = rings(n=240, seed=9)
    A = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                  dtype=jnp.float32).fit(Xa, Ya)
    B = BinarySVC(SVMConfig(C=10.0, gamma=5.0),
                  dtype=jnp.float32).fit(Xb, Yb)
    Xq, _ = rings(n=16, seed=3)

    with tempfile.TemporaryDirectory() as td:
        pa = os.path.join(td, "v1.npz")
        pb = os.path.join(td, "v2.npz")
        A.save(pa)
        B.save(pb)
        replicas, frontends = [], []
        try:
            for i in range(2):
                srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
                srv.load_model("m", pa)
                srv.warmup()
                httpd = make_http_server(srv, port=0)
                srv.attach_http(httpd, start_http_thread(httpd))
                host, port = httpd.server_address[:2]
                replicas.append(srv)
                frontends.append((httpd, host, port))
            urls = [f"http://{h}:{p}" for _, h, p in frontends]
            refA, _ = replicas[0].predict_direct("m", Xq)
            refA = [float(v) for v in np.asarray(refA).ravel()]
            with Server(ServeConfig(max_batch=8),
                        dtype=jnp.float32) as orc:
                orc.load_model("m", pb)
                rb, _ = orc.predict_direct("m", Xq)
            refB = [float(v) for v in np.asarray(rb).ravel()]
            if refA == refB:
                print("ROUTER SMOKE FAILED: generations are not "
                      "distinguishable — the bitwise gate is vacuous")
                return 1

            keys = ["m", "m-shadow", "m-canary"]
            if table_bytes(placement_table(keys, urls, k=2, seed=3)) \
                    != table_bytes(placement_table(list(keys),
                                                   tuple(urls),
                                                   k=2, seed=3)):
                failures.append("placement tables for one seed are "
                                "not byte-identical")

            # the poller is deliberately SLOW to mark replicas down
            # (0.9s grace): the outage below must be discovered by
            # forward failures, i.e. the failover path, not admission
            router = Router(RouterConfig(
                replicas=tuple(urls), replication=2, seed=3,
                poll_interval_s=0.3, down_after=3,
                forward_timeout_s=15.0), log_fn=lambda m: None)
            router.start()
            bad = []
            bad_lock = threading.Lock()
            phase2 = threading.Event()  # set once the rollout finished

            def client(t):
                for i in range(args.smoke_requests):
                    idx = (t + i) % len(Xq)
                    body = json.dumps(
                        {"instances":
                         [np.asarray(Xq[idx], float).tolist()]}).encode()
                    code, data, _ra = router.forward("m", body)
                    if code == 429:
                        time.sleep(0.05)
                        continue
                    if code != 200:
                        with bad_lock:
                            bad.append(("code", code, data[:120]))
                        continue
                    s = json.loads(data)["scores"][0]
                    if isinstance(s, list):
                        s = s[0]
                    allowed = ([refB[idx]] if phase2.is_set()
                               else [refA[idx], refB[idx]])
                    if s not in allowed:
                        with bad_lock:
                            bad.append(("torn", idx, s))

            # phase 1: concurrent load while the replica every "m"
            # request PREFERS (first in placement order) goes DARK —
            # so the outage is guaranteed to be met by forwards and
            # must be absorbed by failover to the second placement
            dark = urls.index(router.replica_set.placement("m")[0])

            def metric(name):
                return sum(m["value"] for m
                           in router._registry.snapshot()["metrics"]
                           if m["name"] == name)

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(args.smoke_threads)]
            for t in threads:
                t.start()
            # cut the cord only once a quarter of the load is through —
            # wall-clock sleeps race 2ms in-process forwards
            target = (args.smoke_threads * args.smoke_requests) // 4
            deadline = time.monotonic() + 30.0
            while metric("router.requests") < target \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            httpd0, host0, port0 = frontends[dark]
            stop_http_server(httpd0)  # the outage: connection refused
            for t in threads:
                t.join(60.0)
            if bad:
                failures.append(f"lost/torn responses during the "
                                f"outage: {bad[:5]} ({len(bad)} total)")
            failovers = metric("router.failovers")
            if not failovers:
                failures.append("the outage never exercised failover "
                                "(router.failovers == 0)")

            # phase 2: the dark replica returns on ITS port; rollout
            httpd0b = make_http_server(replicas[dark], host=host0,
                                       port=port0)
            replicas[dark].attach_http(httpd0b,
                                       start_http_thread(httpd0b))
            frontends[dark] = (httpd0b, host0, port0)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                router.poller.poll_once()
                if all(s == "ok"
                       for s in router.poller.states().values()):
                    break
                time.sleep(0.1)
            out = router.rollout("m", pb)
            rep = out["report"]
            gens = set(rep["vector"].values())
            if out["status"] != RouterStatus.OK.name or out["failed"] \
                    or len(out["swapped"]) != 2 or rep["skew"] != 0 \
                    or rep["unknown"] or len(gens) != 1:
                failures.append(f"rollout not clean/skew-free: {out}")
            phase2.set()
            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(args.smoke_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60.0)
            if bad:
                failures.append(f"post-rollout responses off the new "
                                f"generation: {bad[:5]}")
            h = router.health()
            if h["router"] != RouterStatus.OK.name:
                failures.append(f"router health not OK at the end: {h}")
            router.close()
        finally:
            for srv in replicas:
                srv.close()

    if failures:
        for f in failures:
            print(f"ROUTER SMOKE FAILED: {f}")
        return 1
    total = args.smoke_threads * args.smoke_requests * 2
    print(f"router smoke ok: {total} requests over 2 replicas, 0 "
          f"lost/torn (failovers {int(failovers)} absorbed the outage), "
          f"rollout skew-free, placement bytes reproducible")
    return 0


def _cmd_refresh(args) -> int:
    """Warm-started crash-safe refit of a deployed model + hot-swap."""
    import os

    from tpusvm.serve.refresh import refresh_fit, swap_via_http
    from tpusvm.utils import PhaseTimer

    if args.smoke:
        return _refresh_smoke(args)
    if not args.model or not args.save:
        raise SystemExit("refresh: --model (the deployed artifact) and "
                         "--save (the refreshed output) are required "
                         "(or --smoke)")
    if args.resume and not args.checkpoint:
        raise SystemExit("refresh: --resume requires --checkpoint")

    say = (lambda msg: None) if args.quiet else print
    timer = PhaseTimer()
    # the data loader needs to know the TASK the artifact was trained
    # for (OvR keeps raw labels, SVR reads continuous targets) — sniff
    # it from the deployed state instead of asking the operator
    from tpusvm.models import model_task

    try:
        task = model_task(args.model)
    except (OSError, ValueError) as e:
        raise SystemExit(f"refresh: {e}")
    args.multiclass = task == "ovr"
    args.task = "svr" if task == "svr" else "svc"
    with timer.phase("data"):
        if getattr(args, "data", None):
            # the append-grown sharded dataset (stream.open_append):
            # refresh consumes the manifest's global row order, whose
            # prefix is exactly the deployed run's rows
            from tpusvm.stream import open_dataset

            if task == "svr":
                raise SystemExit("refresh: svr artifacts read CSV/"
                                 "synthetic continuous targets; sharded "
                                 "datasets store integer labels")
            try:
                X, Y = open_dataset(args.data).load_arrays()
            except (OSError, ValueError) as e:
                raise SystemExit(f"refresh: --data: {e}")
            Xt = Yt = None
        else:
            X, Y, Xt, Yt = _load_train_data(args)
    say(f"refresh: {X.shape[0]} rows x {X.shape[1]} features "
        f"({task} deployed: {args.model})")
    try:
        with timer.phase("training"):
            model = refresh_fit(
                args.model, X, Y, out_path=args.save,
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                resume=args.resume, warm=not args.cold,
            )
    except (OSError, ValueError) as e:
        raise SystemExit(f"refresh: {e}")
    n_iter = (int(np.sum(model.n_iter_))
              if np.ndim(model.n_iter_) else model.n_iter_)
    status = (model.status_.name if hasattr(model, "status_")
              else "per-head")
    n_sv = (model.n_support_ if hasattr(model, "n_support_")
            else len(model.X_sv_))
    say(f"refreshed model: {n_sv} SVs, "
        f"{n_iter} updates, status {status}, "
        f"saved to {args.save}")
    if Xt is not None and len(Xt):
        with timer.phase("prediction"):
            acc = model.score(Xt, Yt)
        say(f"held-out {'r2' if task == 'svr' else 'accuracy'}"
            f" = {acc:.4f}")
    if args.swap_url:
        name = args.swap_name or os.path.splitext(
            os.path.basename(args.save))[0]
        try:
            out = swap_via_http(args.swap_url, name,
                                os.path.abspath(args.save))
        except (RuntimeError, OSError) as e:
            raise SystemExit(f"refresh: {e}")
        say(f"swapped {name} -> generation {out['generation']} "
            f"({out['latency_s'] * 1e3:.1f} ms; the artifact is live)")
    say(timer.report())
    return 0


def _refresh_smoke(args) -> int:
    """CI gate for the refresh loop: deploy tiny, grow, refresh warm +
    cold control, hot-swap in-process; gates convergence, warm update
    savings, and bit-identical served scores post-swap."""
    import tempfile

    import jax.numpy as jnp

    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.refresh import refresh_fit

    failures = []
    X, Y = rings(n=360, seed=11)
    with tempfile.TemporaryDirectory() as td:
        import os as _os

        deployed = _os.path.join(td, "deployed.npz")
        refreshed = _os.path.join(td, "refreshed.npz")
        cfg = SVMConfig(C=10.0, gamma=10.0)
        # the deployed model: trained on the data's prefix
        BinarySVC(cfg).fit(X[:240], Y[:240]).save(deployed)
        warm = refresh_fit(deployed, X, Y, out_path=refreshed)
        cold = refresh_fit(deployed, X, Y,
                           out_path=_os.path.join(td, "cold.npz"),
                           warm=False)
        if warm.status_.name != "CONVERGED":
            failures.append(f"warm refresh ended {warm.status_.name}")
        if cold.status_.name != "CONVERGED":
            failures.append(f"cold control ended {cold.status_.name}")
        if warm.n_iter_ >= cold.n_iter_:
            failures.append(
                f"warm seed saved nothing: {warm.n_iter_} updates warm "
                f"vs {cold.n_iter_} cold")
        acc = warm.score(X, Y)
        if acc <= 0.8:
            failures.append(f"refreshed accuracy gate failed ({acc:.4f})")
        # the hot-swap leg: deployed serves, the refresh swaps in, and
        # the served scores ARE the refreshed model's offline scores
        with Server(ServeConfig(max_batch=8)) as srv:
            srv.load_model("m", deployed)
            srv.warmup()
            out = srv.swap("m", refreshed)
            scores, _ = srv.predict_direct("m", X[:16])
            ref = srv.registry.get("m")
            offline = BinarySVC.load(refreshed, dtype=jnp.float32)
            import numpy as _np

            want = _np.asarray(offline.decision_function(X[:16]))
            if not _np.array_equal(scores, want):
                failures.append("served scores after swap are not "
                                "bit-identical to the refreshed model")
            if out["generation"] != 2 or ref.generation != 2:
                failures.append(
                    f"swap generation bookkeeping off: {out}")
            h = srv.health()
            if h["status"] != "ok" or h["swap"]["m"]["staleness_s"] < 0:
                failures.append(f"health after swap: {h['status']}")
    if failures:
        for f in failures:
            print(f"REFRESH SMOKE FAILED: {f}")
        return 1
    print(f"refresh smoke ok: warm {warm.n_iter_} vs cold "
          f"{cold.n_iter_} updates "
          f"({1 - warm.n_iter_ / cold.n_iter_:.1%} saved), accuracy "
          f"{acc:.4f}, swap generation 2, served scores bit-identical")
    return 0


def _cmd_autopilot(args) -> int:
    """The closed-loop online-learning supervisor (tpusvm.autopilot)."""
    from tpusvm.autopilot import Autopilot, AutopilotConfig, DriftThresholds

    tracer = _make_tracer(args, "autopilot")

    def _finish(rc: int) -> int:
        if tracer is not None:
            from tpusvm.obs import default_registry

            tracer.metrics_snapshot(default_registry().snapshot())
        _close_tracer(tracer)
        return rc

    if args.smoke:
        return _finish(_autopilot_smoke(args))
    if not args.data or not args.model:
        raise SystemExit("autopilot: --data DIR and --model NPZ are "
                         "required (or --smoke)")
    say = (lambda msg: None) if args.quiet else print

    def thr(v):
        return None if v is not None and v < 0 else v

    cfg = AutopilotConfig(
        data_dir=args.data,
        model_path=args.model,
        out_path=args.save,
        state_path=args.state,
        name=args.name,
        interval_s=args.interval_s,
        thresholds=DriftThresholds(
            feature=thr(args.feature_threshold),
            growth=thr(args.growth_threshold),
            score=thr(args.score_threshold),
            staleness_s=args.staleness_s,
            min_new_rows=args.min_new_rows,
            jitter_frac=args.jitter_frac,
        ),
        hysteresis=args.hysteresis,
        cooldown_s=args.cooldown_s,
        warm=not args.cold,
        checkpoint_path=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        deadline_s=args.deadline_s,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        seed=args.seed,
    )
    try:
        pilot = Autopilot(cfg, swap_url=args.swap_url,
                          resume=args.resume, log_fn=say)
    except (OSError, ValueError) as e:
        raise SystemExit(f"autopilot: {e}")
    say(f"autopilot: watching {args.data} every {cfg.interval_s:g}s "
        f"(state {pilot.cfg.state_path}, out {pilot.cfg.out_path})")
    try:
        out = pilot.run(max_ticks=args.max_ticks)
    except KeyboardInterrupt:
        out = {"ticks": pilot.state.tick,
               "generation": pilot.state.generation,
               "refreshes": pilot.state.refreshes,
               "failures": pilot.state.failures}
    say(f"autopilot: {out['ticks']} ticks, {out['refreshes']} "
        f"refreshes ({out['failures']} failures), generation "
        f"{out['generation']}")
    return _finish(0)


def _autopilot_smoke(args) -> int:
    """CI gate: the whole closed loop in-process — ingest, deploy,
    serve, append, supervise — tolerant of an active fault plan (the
    chaos CI step runs it under tests/fixtures/chaos_plan.json, whose
    autopilot rules inject a transient refresh failure the breaker
    machinery must absorb and retry)."""
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from tpusvm.autopilot import (
        Autopilot,
        AutopilotConfig,
        DriftThresholds,
        evaluate,
    )
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.status import AutopilotStatus
    from tpusvm.stream import ShardWriter, ingest_arrays, open_dataset

    failures = []
    X, Y = rings(n=400, seed=11)
    with tempfile.TemporaryDirectory() as td:
        import os as _os

        data = _os.path.join(td, "data")
        ingest_arrays(data, X[:240], Y[:240], rows_per_shard=64)
        deployed = _os.path.join(td, "deployed.npz")
        BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                  dtype=jnp.float32).fit(X[:240], Y[:240]).save(deployed)
        thresholds = DriftThresholds(growth=0.5, feature=0.10,
                                     score=None, jitter_frac=0.0)
        with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
            srv.load_model("m", deployed)
            srv.warmup()
            ref_old, _ = srv.predict_direct("m", X[:16])
            cfg = AutopilotConfig(
                data_dir=data, model_path=deployed,
                out_path=_os.path.join(td, "m.refresh.npz"),
                name="m", thresholds=thresholds, hysteresis=1,
                checkpoint_path=_os.path.join(td, "refresh_ck.npz"),
                checkpoint_every=8,
                breaker_threshold=3, breaker_cooldown_s=0.1,
                seed=20260805,
            )
            pilot = Autopilot(cfg, server=srv,
                              log_fn=(lambda m: None) if args.quiet
                              else print)
            first = pilot.tick()
            if first["status"] != AutopilotStatus.WATCHING:
                failures.append(
                    f"tick on unchanged data: {first['status'].name}")
            # grow the dataset past the growth threshold (the appends
            # run through the crash-safe tail writer)
            w = ShardWriter.open_append(data)
            for s in range(240, 400, 40):
                w.append(X[s:s + 40], Y[s:s + 40])
            w.close()
            statuses = []
            for _ in range(args.smoke_ticks):
                statuses.append(pilot.tick()["status"])
                if statuses[-1] == AutopilotStatus.REFRESHED:
                    break
            if AutopilotStatus.REFRESHED not in statuses:
                failures.append(
                    "no refresh landed in "
                    f"{args.smoke_ticks} ticks: "
                    f"{[s.name for s in statuses]}")
            else:
                scores, _ = srv.predict_direct("m", X[:16])
                offline = BinarySVC.load(cfg.out_path, dtype=jnp.float32)
                want = np.asarray(offline.decision_function(X[:16]))
                if not np.array_equal(scores, want):
                    failures.append("served scores after the autopilot "
                                    "swap are not bit-identical to the "
                                    "refreshed artifact")
                if np.array_equal(scores, ref_old):
                    failures.append("swap was a no-op (old == new "
                                    "scores — the gate is vacuous)")
                if srv.registry.generation("m") < 2:
                    failures.append("registry generation did not "
                                    "advance")
            # determinism: same inputs + seed => byte-identical report
            ds = open_dataset(data)
            kw = dict(manifest=ds.manifest,
                      fitted_min=np.zeros(2), fitted_max=np.ones(2),
                      rows_at_refresh=240, since_refresh_s=1.0,
                      score_baseline=None, score_current=None,
                      thresholds=thresholds, seed=7, tick=3)
            if evaluate(**kw).to_json_bytes() != \
                    evaluate(**kw).to_json_bytes():
                failures.append("drift report is not byte-reproducible")
            # resumed supervisor must replay to the same state
            pilot2 = Autopilot(cfg, server=srv, resume=True,
                               log_fn=lambda m: None)
            if pilot2.state.generation != pilot.state.generation \
                    or pilot2.state.rows_at_refresh \
                    != pilot.state.rows_at_refresh:
                failures.append("resumed state diverged: "
                                f"{pilot2.state} vs {pilot.state}")
    if failures:
        for f in failures:
            print(f"AUTOPILOT SMOKE FAILED: {f}")
        return 1
    print(f"autopilot smoke ok: refresh landed in "
          f"{len(statuses)} ticks "
          f"({pilot.state.failures} absorbed failures), generation "
          f"{pilot.state.generation}, served scores bit-identical, "
          "drift reports byte-reproducible")
    return 0


def _cmd_tenants(args) -> int:
    """The multi-tenant coalescing supervisor (tpusvm.tenants)."""
    from tpusvm.autopilot import DriftThresholds
    from tpusvm.tenants import TenantsConfig, TenantsSupervisor

    tracer = _make_tracer(args, "tenants")

    def _finish(rc: int) -> int:
        if tracer is not None:
            from tpusvm.obs import default_registry

            tracer.metrics_snapshot(default_registry().snapshot())
        _close_tracer(tracer)
        return rc

    if args.smoke:
        return _finish(_tenants_smoke(args))
    if not args.data:
        raise SystemExit("tenants: --data DIR is required (or --smoke)")
    say = (lambda msg: None) if args.quiet else print

    def thr(v):
        return None if v is not None and v < 0 else v

    cfg = TenantsConfig(
        data_dir=args.data,
        store_path=args.store,
        artifacts_dir=args.artifacts,
        interval_s=args.interval_s,
        thresholds=DriftThresholds(
            feature=thr(args.feature_threshold),
            growth=thr(args.growth_threshold),
            score=None,
            staleness_s=args.staleness_s,
            min_new_rows=args.min_new_rows,
            jitter_frac=args.jitter_frac,
        ),
        hysteresis=args.hysteresis,
        cooldown_s=args.cooldown_s,
        warm=not args.cold,
        checkpoint_every=args.checkpoint_every,
        min_fleet=args.min_fleet,
        stagger_s=args.stagger_s,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        seed=args.seed,
    )
    try:
        sup = TenantsSupervisor(cfg, swap_url=args.swap_url,
                                resume=args.resume, log_fn=say)
    except (OSError, ValueError) as e:
        raise SystemExit(f"tenants: {e}")
    if not sup.state.tenants and not args.resume:
        raise SystemExit(
            "tenants: the store has no registered tenants — register "
            "them programmatically (TenantsSupervisor.register) or "
            "--resume an existing store"
        )
    say(f"tenants: supervising {len(sup.state.tenants)} tenants over "
        f"{args.data} every {cfg.interval_s:g}s (store "
        f"{sup.cfg.store_path}, artifacts {sup.cfg.artifacts_dir})")
    try:
        out = sup.run(max_ticks=args.max_ticks)
    except KeyboardInterrupt:
        out = {"ticks": sup.state.tick,
               "generation": sup.state.generation,
               "refreshes": sup.state.refreshes,
               "failures": sup.state.failures}
    say(f"tenants: {out['ticks']} ticks, {out['refreshes']} per-tenant "
        f"refreshes ({out['failures']} failures), generation "
        f"{out['generation']}")
    return _finish(0)


def _tenants_smoke(args) -> int:
    """CI gate: the whole multi-tenant loop in-process — ingest one
    shared corpus, provision a tenant fleet, serve every tenant, grow
    the corpus, supervise — tolerant of an active fault plan (the chaos
    CI step runs it under tests/fixtures/chaos_plan.json, whose tenants
    rules inject tick latency and a transient store-write failure the
    retry/breaker machinery must absorb)."""
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from tpusvm.autopilot import DriftThresholds
    from tpusvm.config import SVMConfig
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.status import TenantsStatus
    from tpusvm.stream import ShardWriter, ingest_arrays
    from tpusvm.tenants import (
        TenantRecord,
        TenantsConfig,
        TenantsSupervisor,
        load_store,
        tenant_labels,
    )

    say = (lambda m: None) if args.quiet else print
    failures = []
    n_tenants = max(2, args.smoke_tenants)
    K = 4
    rng = np.random.default_rng(20260806)
    n0, n1, d = 240, 120, 6
    X = rng.normal(size=(n0 + n1, d))
    labels = rng.integers(0, K, size=n0 + n1).astype(np.int32)
    for k in range(K):
        X[labels == k] += 0.8 * k
    with tempfile.TemporaryDirectory() as td:
        import os as _os

        data = _os.path.join(td, "data")
        ingest_arrays(data, X[:n0], labels[:n0], rows_per_shard=64)
        recs = []
        for i in range(n_tenants):
            recs.append(TenantRecord(
                tenant_id=f"tenant{i:03d}", positive_label=i % K,
                C=1.0 + 0.5 * (i % 3), gamma=0.3 + 0.1 * (i % 2),
                row_mod=(2 if i % 5 == 4 else None),
            ))
        with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
            for rec in recs:
                Y, valid = tenant_labels(labels[:n0], rec)
                opts = {} if valid is None else {"valid": valid}
                path = _os.path.join(td, rec.tenant_id + ".donor.npz")
                BinarySVC(SVMConfig(C=rec.C, gamma=rec.gamma),
                          dtype=jnp.float32,
                          solver_opts=opts).fit(X[:n0], Y).save(path)
                rec.model_path = path
                rec.rows_at_refresh = n0
                srv.load_model(rec.tenant_id, path)
            srv.warmup()
            cfg = TenantsConfig(
                data_dir=data,
                thresholds=DriftThresholds(growth=0.25, feature=0.10,
                                           score=None, jitter_frac=0.0),
                hysteresis=1, checkpoint_every=8,
                breaker_threshold=3, breaker_cooldown_s=0.1,
                seed=20260806,
            )
            sup = TenantsSupervisor(cfg, server=srv, log_fn=say)
            for rec in recs:
                sup.register(rec)
            first = sup.tick()
            if first["status"] != TenantsStatus.WATCHING:
                failures.append(
                    f"tick on unchanged data: {first['status'].name}")
            w = ShardWriter.open_append(data)
            w.append(X[n0:], labels[n0:])
            w.close()
            statuses = []
            for _ in range(args.smoke_ticks):
                statuses.append(sup.tick()["status"])
                if statuses[-1] in (TenantsStatus.REFRESHED,
                                    TenantsStatus.PARTIAL):
                    break
            if TenantsStatus.REFRESHED not in statuses:
                failures.append(
                    f"no coalesced refresh landed in {args.smoke_ticks} "
                    f"ticks: {[s.name for s in statuses]}")
            else:
                for rec in recs:
                    st_rec = sup.state.tenants[rec.tenant_id]
                    if st_rec.generation != 1:
                        failures.append(
                            f"{rec.tenant_id}: generation "
                            f"{st_rec.generation} != 1")
                        continue
                    scores, _ = srv.predict_direct(rec.tenant_id, X[:16])
                    offline = BinarySVC.load(st_rec.model_path,
                                             dtype=jnp.float32)
                    want = np.asarray(offline.decision_function(X[:16]))
                    if not np.array_equal(scores, want):
                        failures.append(
                            f"{rec.tenant_id}: served scores after the "
                            "swap are not bit-identical to its "
                            "refreshed artifact")
                    if srv.registry.generation(rec.tenant_id) < 2:
                        failures.append(
                            f"{rec.tenant_id}: registry generation did "
                            "not advance")
            # the store must resume to the same fleet state
            sup2 = TenantsSupervisor(cfg, server=srv, resume=True,
                                     log_fn=lambda m: None)
            if sup2.state.generation != sup.state.generation or \
                    len(sup2.state.tenants) != len(sup.state.tenants):
                failures.append("resumed store diverged: "
                                f"gen {sup2.state.generation} vs "
                                f"{sup.state.generation}")
            persisted = load_store(sup.cfg.store_path)
            if persisted.stage != "idle":
                failures.append(
                    f"store left stage {persisted.stage!r} after a "
                    "completed round")
    if failures:
        for f in failures:
            print(f"TENANTS SMOKE FAILED: {f}")
        return 1
    print(f"tenants smoke ok: {n_tenants} tenants refreshed in one "
          f"coalesced generation ({sup.state.failures} absorbed "
          "failures), every tenant served its refreshed bytes, store "
          "resumes consistently")
    return 0


def _cmd_tune(args) -> int:
    import dataclasses

    import jax.numpy as jnp

    from tpusvm.config import SVMConfig
    from tpusvm.models import BinarySVC
    from tpusvm.status import TuneStatus
    from tpusvm.tune import (
        TuneConfig,
        format_table,
        log_grid,
        make_grid,
        save_tune_result,
        tune,
    )
    from tpusvm.utils import PhaseTimer

    if args.smoke:
        # the CI gate shape: tiny, CPU-friendly, deterministic — 2 folds,
        # a 2x2 grid, so the whole run (including the winner's full-data
        # retrain) is seconds. Single-family smoke keeps the historical
        # rings problem; a --kernels family sweep runs separable blobs
        # instead (rings structurally fail the linear family, and the
        # smoke gates every family's points)
        multi_family = args.kernels and "," in args.kernels
        args.synthetic = "blobs" if multi_family else "rings"
        args.d = 6
        args.train, args.test, args.data = None, None, None
        args.n, args.n_test, args.n_limit = 240, 60, None
        args.folds, args.fold_seed = 2, 0
        args.C_grid, args.gamma_grid = "1,8", "1,8"
        args.schedule = "grid"
    if args.synthetic == "sine":
        raise SystemExit("tune is a classification search; --synthetic "
                         "sine is --task svr training data")

    if args.C_grid or args.gamma_grid:
        if not (args.C_grid and args.gamma_grid):
            raise SystemExit("tune: pass both --C-grid and --gamma-grid "
                             "(or neither, for the log grid around "
                             "--center-C/--center-gamma)")
        grid = make_grid([float(v) for v in args.C_grid.split(",")],
                         [float(v) for v in args.gamma_grid.split(",")])
    else:
        grid = log_grid(args.center_C, args.center_gamma,
                        span=args.span, step=args.step)

    base = SVMConfig(tau=args.tau, eps=args.eps, sv_tol=args.sv_tol,
                     max_iter=args.max_iter, degree=args.degree,
                     coef0=args.coef0)
    kernel_specs = (None if not args.kernels
                    else [k.strip() for k in args.kernels.split(",")])
    if kernel_specs is not None:
        # fail fast (before the data load): unknown names, duplicates,
        # and the approximate families' explicit rejection (gamma is
        # baked into their feature map — tune.normalize_kernel_specs)
        from tpusvm.tune.search import normalize_kernel_specs

        try:
            normalize_kernel_specs(kernel_specs, base)
        except ValueError as e:
            raise SystemExit(f"tune: {e}")
    try:
        config = TuneConfig(
            folds=args.folds, seed=args.fold_seed, schedule=args.schedule,
            eta=args.eta, min_rung=args.min_rung,
            warm_start=not args.no_warm_start, patience=args.patience,
            plateau_tol=args.plateau_tol, fleet=args.fleet,
            fleet_compact=args.fleet_compact,
        )
    except ValueError as e:
        raise SystemExit(f"tune: {e}")

    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        from tpusvm.config import resolve_accum_dtype

        accum = resolve_accum_dtype(
            "auto" if args.accum == "float64" else None
        )

    tracer = _make_tracer(args, "tune")
    timer = PhaseTimer(tracer=tracer)
    dataset = None
    if args.data:
        # streamed source: folds come from a labels-only manifest pass,
        # fold caches gather only their own rows shard by shard
        if args.train or args.synthetic:
            raise SystemExit(
                "pass exactly one of --train / --synthetic / --data"
            )
        if args.n_limit is not None:
            raise SystemExit("--n-limit does not apply to --data "
                             "(re-ingest with --n-limit instead)")
        from tpusvm.stream import open_dataset

        with timer.phase("data"):
            dataset = open_dataset(args.data)
        X = Y = None
        Xt = Yt = None
        if args.test:
            from tpusvm.data.native_io import read_csv_fast

            Xt, Yt = read_csv_fast(args.test, binary_labels=True,
                                   positive_label=args.positive_label)
        n, n_features = dataset.n_rows, dataset.n_features
    else:
        with timer.phase("data"):
            X, Y, Xt, Yt = _load_train_data(args)
        n, n_features = X.shape
    say = (lambda msg: None) if args.quiet else print
    say(f"n = {n}, n_features = {n_features}, "
        f"grid = {grid.shape[0]}x{grid.shape[1]}, folds = {args.folds}, "
        f"schedule = {args.schedule}")

    from tpusvm.utils import trace as _profile_trace

    with timer.phase("search"), _profile_trace(args.profile):
        result = tune(
            X, Y, grid, config, base=base, dtype=getattr(jnp, args.dtype),
            accum_dtype=accum, scale=not args.no_scale,
            solver_opts=_parse_solver_opts(args.solver_opt),
            log_fn=(lambda msg: None) if args.quiet else print,
            dataset=dataset,
            tracer=tracer,
            kernels=kernel_specs,
        )
    print(format_table(result))
    if args.results:
        save_tune_result(args.results, result)
        say(f"results written to {args.results}")

    # the winner becomes a normal model: full-data fit with the winning
    # point (kernel family included), saved in the standard .npz format
    win_cfg = dataclasses.replace(base, C=result.winner["C"],
                                  gamma=result.winner["gamma"],
                                  kernel=result.winner["kernel"],
                                  degree=result.winner["degree"],
                                  coef0=result.winner["coef0"])
    model = BinarySVC(config=win_cfg, dtype=getattr(jnp, args.dtype),
                      scale=not args.no_scale)
    with timer.phase("final-train"):
        if dataset is not None:
            model.fit_stream(dataset)
        else:
            model.fit(X, Y)
    say(f"winner model: {model.n_support_} SVs, "
        f"status {model.status_.name}")
    test_acc = None
    if Xt is not None and len(Xt):
        test_acc = model.score(Xt, Yt)
        say(f"held-out accuracy = {test_acc:.4f}")
    if args.save:
        model.save(args.save)
        say(f"model saved to {args.save}")
    say(timer.report())
    if tracer is not None:
        from tpusvm.obs import default_registry

        tracer.metrics_snapshot(default_registry().snapshot())
    _close_tracer(tracer)

    if args.smoke:
        evaluated = [r for r in result.points
                     if r["status"] == TuneStatus.EVALUATED.name]
        # beyond each FAMILY's first point every fold fit must have found
        # a warm seed (warm stores are per-family — duals do not transfer
        # across kernel geometries); a regression that silently runs
        # everything cold would still "pass" on accuracy alone
        warm_ok = True
        for fam in {r["kernel"] for r in evaluated}:
            fam_rows = [r for r in evaluated if r["kernel"] == fam]
            if args.fleet:
                # a fleet grid schedule fits the whole population in one
                # concurrent launch — there is no already-solved
                # neighbour to seed from, so the warm gate is vacuous
                # (halving fleets warm across rungs instead)
                continue
            warm_ok &= all(r["warm_seeded"] == args.folds
                           for r in fam_rows[1:])
        acc_ok = all(r["cv_accuracy"] is not None
                     and r["cv_accuracy"] > 0.5 for r in evaluated)
        final_ok = test_acc is not None and test_acc > 0.8
        if not (warm_ok and acc_ok and final_ok):
            print(f"TUNE SMOKE FAILED: warm_ok={warm_ok} acc_ok={acc_ok} "
                  f"final_ok={final_ok} (test_acc={test_acc})")
            return 1
        print(f"tune smoke ok: {len(evaluated)} points over "
              f"{len(result.kernels)} kernel famil"
              f"{'ies' if len(result.kernels) > 1 else 'y'}, "
              f"winner kernel={result.winner['kernel']} "
              f"C={result.winner['C']:g} "
              f"gamma={result.winner['gamma']:g}, "
              f"test_acc={test_acc:.4f}")
    return 0


def _info_artifact(path: str) -> int:
    """`tpusvm info <path>`: describe a sharded dataset dir, a tune-results
    JSON, or a model .npz."""
    from tpusvm.stream import is_dataset_dir
    from tpusvm.tune import format_table, is_tune_result, load_tune_result

    if is_dataset_dir(path):
        return _info_dataset(path)
    if is_tune_result(path):
        print(format_table(load_tune_result(path)))
        return 0
    from tpusvm.tenants import is_tenant_store

    if is_tenant_store(path):
        return _info_tenant_store(path)
    from tpusvm.models.serialization import load_model, model_task

    try:
        task = model_task(path)
    except (OSError, ValueError) as e:
        raise SystemExit(
            f"info: {path!r} is neither a tune-results JSON nor a "
            f"readable model artifact ({e})"
        )
    state, config = load_model(path)
    kind = {"ovr": "multiclass (one-vs-rest)", "svr": "epsilon-SVR"}.get(
        task, "binary")
    print(f"model: {kind}")
    from tpusvm.config import APPROX_FAMILIES

    approx = config.kernel in APPROX_FAMILIES
    # approx states: sv_X holds MAPPED rows — the request-row width is
    # the map provenance field, the mapped width the sv_X trailing dim
    n_feat = (int(state["map_n_features_in"]) if approx
              and "map_n_features_in" in state
              else state["sv_X"].shape[1])
    if task == "ovr":
        print(f"classes: {state['classes'].tolist()}")
        print(f"SV union: {state['sv_X'].shape[0]}")
        print(f"n_features: {n_feat}")
    else:
        sv_key = "sv_coef" if task == "svr" else "sv_alpha"
        print(f"SV count: {len(state[sv_key])}")
        print(f"n_features: {n_feat}")
        print(f"b = {float(state['b']):.15f}")
    kern = f"kernel: {config.kernel}"
    if config.kernel == "poly":
        kern += f" (degree={config.degree} coef0={config.coef0:g})"
    if config.kernel == "sigmoid":
        kern += f" (coef0={config.coef0:g})"
    print(kern)
    if approx:
        # approx provenance (serialization v4): which map produced the
        # mapped SV rows, and what regenerates/reads it at load
        dim = int(state["sv_X"].shape[1])
        if config.kernel == "rff":
            print(f"approx map: rff D={config.rff_dim} "
                  f"seed={config.map_seed} "
                  f"({n_feat} raw -> {dim} mapped features; omega "
                  "regenerates from config)")
        else:
            n_lm = (int(state["map_landmarks"].shape[0])
                    if "map_landmarks" in state else config.landmarks)
            print(f"approx map: nystrom landmarks={n_lm} "
                  f"seed={config.map_seed} "
                  f"({n_feat} raw -> {dim} mapped features; landmark "
                  "rows stored in the artifact)")
    print(f"config: C={config.C:g} gamma={config.gamma:g} "
          f"tau={config.tau:g} sv_tol={config.sv_tol:g}"
          + (f" epsilon={config.epsilon:g}" if task == "svr" else ""))
    print(f"scaled: {bool(state.get('scale', False))}")
    if task in ("svc", "svr"):
        # training provenance (format v3): which solver-ladder rung and
        # shrinking cadence produced this artifact; older files load
        # with the f32/no-shrink defaults
        prec = (str(state["train_precision"])
                if "train_precision" in state else "f32")
        se = int(state["shrink_every"]) if "shrink_every" in state else 0
        shrink = (f"every {se} rounds "
                  f"(stable {int(state['shrink_stable'])})"
                  if se else "off")
        print(f"trained: precision={prec} shrinking={shrink}")
        if "cascade_topology" in state:
            # distributed-training provenance (v4-additive keys):
            # cascade/pod-trained artifacts record which merge topology
            # and leaf count produced them, and how many rounds the
            # SV-ID fixed point took
            print(f"cascade: topology={str(state['cascade_topology'])} "
                  f"leaves={int(state['cascade_leaves'])} "
                  f"rounds={int(state['cascade_rounds'])}")
    if task == "svc":
        if "platt_a" in state:
            print(f"calibrated: yes (Platt A={float(state['platt_a']):.6f} "
                  f"B={float(state['platt_b']):.6f})")
        else:
            print("calibrated: no")
    return 0


def _info_tenant_store(path: str) -> int:
    """Describe a multi-tenant registry/store file."""
    from tpusvm.tenants import load_store

    try:
        st = load_store(path)
    except ValueError as e:
        raise SystemExit(f"info: {e}")
    print(f"tenant store: {len(st.tenants)} tenants, generation "
          f"{st.generation} (tick {st.tick})")
    print(f"stage: {st.stage}"
          + (f" — in-flight launch over "
             f"{len(st.inflight.get('tenant_ids', []))} tenants at "
             f"{st.inflight.get('stage_rows')} rows"
             if st.inflight else ""))
    print(f"refreshes landed: {st.refreshes} ({st.failures} failures)")
    if st.tenants:
        gens = [r.generation for r in st.tenants.values()]
        subset = sum(1 for r in st.tenants.values()
                     if r.row_mod is not None)
        armed = sum(1 for r in st.tenants.values()
                    if r.consecutive_triggered > 0)
        print(f"tenant generations: min {min(gens)} max {max(gens)}")
        print(f"views: {subset} row-subset, "
              f"{len(st.tenants) - subset} full-corpus; {armed} "
              "drift-armed")
    return 0


def _info_dataset(path: str) -> int:
    """Describe + verify an ingested sharded dataset directory."""
    from tpusvm.status import StreamStatus
    from tpusvm.stream import open_dataset

    ds = open_dataset(path)
    m = ds.manifest
    stats = m.global_stats()
    kind = "binary" if m.binary else "multiclass (raw labels)"
    print(f"sharded dataset: {m.n_rows} rows x {m.n_features} features, "
          f"{len(m.shards)} shards")
    print(f"labels: {kind}"
          + (f" (positive_label={m.positive_label})"
             if m.positive_label is not None else ""))
    print(f"class counts: {dict(sorted(stats.class_counts.items()))}")
    print(f"feature range: [{stats.min_val.min():g}, "
          f"{stats.max_val.max():g}]")
    statuses = ds.validate()
    bad = [(m.shards[i].filename, s.name)
           for i, s in enumerate(statuses) if s != StreamStatus.OK]
    if bad:
        print(f"validation FAILED on {len(bad)}/{len(statuses)} shards:")
        for name, status in bad:
            print(f"  {name}: {status}")
        return 1
    print(f"validation: all {len(statuses)} shards OK "
          "(checksums, row counts, stats)")
    return 0


def _report_paths(raw_paths) -> list:
    """Expand the report positionals: directories become their sorted
    *.jsonl members (rotated .jsonl.N backups are folded in by
    read_trace, so they are not listed separately)."""
    import glob
    import os

    paths = []
    for p in raw_paths:
        if os.path.isdir(p):
            members = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not members:
                raise SystemExit(
                    f"report: directory {p!r} holds no *.jsonl trace files"
                )
            paths.extend(members)
        else:
            paths.append(p)
    return paths


def _cmd_report(args) -> int:
    """Render --trace JSONL telemetry back into the reference's
    human-readable contracts (phase timing block + convergence table),
    plus the compile observatory's cost table. Several files (or a
    directory) merge into one wall-clock-interleaved report: registry
    snapshots merge exactly, phase durations accumulate, and the total
    is the cross-process wall envelope."""
    from tpusvm.obs import read_trace
    from tpusvm.obs.report import (
        autopilot_rows,
        compile_rows,
        convergence_rows,
        cross_process_spans,
        format_autopilot_table,
        format_compile_table,
        format_convergence_table,
        format_round_gantt,
        format_timeline,
        merge_trace_files,
        nonzero_counters,
        phase_summary,
        render_phase_lines,
        reparent_stats,
    )

    paths = _report_paths(args.path)
    try:
        if len(paths) == 1:
            records = read_trace(paths[0])
        else:
            records = merge_trace_files(paths)
    except OSError as e:
        raise SystemExit(f"report: cannot read trace ({e})")
    except ValueError as e:
        if args.smoke:
            print(f"REPORT SMOKE FAILED: {e}")
            return 1
        raise SystemExit(f"report: {e}")

    phases, total = phase_summary(records)
    conv = convergence_rows(records)
    spans = sum(1 for r in records if r["kind"] == "span")
    events = sum(1 for r in records if r["kind"] == "event")
    label = paths[0] if len(paths) == 1 else f"{len(paths)} files"
    print(f"trace: {label} ({spans} spans, {events} events)")
    if len(paths) > 1:
        for p in paths:
            print(f"  {p}")
    print()
    comp = compile_rows(records)
    if comp:
        print("compiles (lower/compile wall time, XLA cost analysis):")
        print(format_compile_table(comp))
        print()
    print("convergence (b_low - b_high per outer round):")
    print(format_convergence_table(conv, max_rows=args.max_rows))
    print()
    auto = autopilot_rows(records)
    if auto:
        print("autopilot (drift decisions per tick):")
        print(format_autopilot_table(auto, max_rows=args.max_rows))
        print()
    counters = nonzero_counters(records)
    if counters:
        print("counters:")
        for line in counters:
            print(f"  {line}")
        print()
    _, roles = cross_process_spans(records)
    stats = None
    if len(roles) > 1:
        # a merged multi-process trace: the distributed-observability
        # payoff — ONE timeline across the fleet, spans re-parented by
        # the trace contexts propagated over frames/headers
        stats = reparent_stats(records)
        print(f"cross-process timeline ({stats['files']} files, "
              f"roles: {', '.join(roles)}; "
              f"{stats['reparented']} spans re-parented, "
              f"{stats['unresolved']} unresolved):")
        print(format_timeline(records, max_rows=args.max_rows))
        print()
        gantt = format_round_gantt(records)
        if gantt:
            print("pod rounds (gantt over the fit wall window):")
            print(gantt)
            print()
    print(render_phase_lines(phases, total))

    if args.smoke:
        failures = []
        if not phases:
            failures.append("no phase spans in the trace")
        if not conv:
            failures.append("no convergence records in the trace")
        if stats is not None and stats["unresolved"]:
            # every ctx-carrying file's root spans must have found their
            # origin span — a propagation break would silently flatten
            # the timeline otherwise
            failures.append(
                f"{stats['unresolved']} cross-process root span(s) "
                "failed to re-parent under their propagated context")
        if failures:
            for f in failures:
                print(f"REPORT SMOKE FAILED: {f}")
            return 1
        extra = ""
        if stats is not None:
            extra = (f", {stats['files']} files/"
                     f"{len(stats['roles'])} roles stitched "
                     f"({stats['reparented']} re-parented)")
        print(f"report smoke ok: {len(phases)} phases, "
              f"{len(conv)} convergence rounds" + extra)
    return 0


def _fleet_collector(args):
    """Build a FleetCollector from the shared --router/--replica/
    --snapshot-file source flags (fleet-metrics and top)."""
    from tpusvm.obs.fleet import FleetCollector

    c = FleetCollector(timeout_s=args.timeout_s)
    n = 0
    if args.router:
        c.add_router(args.router)
        n += 1
    for url in args.replicas:
        c.add_replica(url)
        n += 1
    for path in args.snapshot_files:
        c.add_file(path)
        n += 1
    if not n:
        raise SystemExit(
            f"{args.command}: no fleet sources — pass --router URL, "
            "--replica URL (repeatable), and/or --snapshot-file PATH"
            + (" (or --smoke)" if args.command == "fleet-metrics" else ""))
    return c


def _fleet_metrics_smoke(args) -> int:
    """CI gate: an in-process two-replica fleet behind a router; the
    merged fleet view must equal merge_fleet() of the per-process
    payloads it scraped (exact), and the merged serve.ok total must
    conserve the request count across the replicas (label-tagged)."""
    import json as _json
    import os
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.obs.fleet import (
        FleetCollector,
        merge_fleet,
        render_fleet_text,
    )
    from tpusvm.obs.registry import render_snapshot_text
    from tpusvm.router import Router, RouterConfig
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.http import make_http_server, start_http_thread

    failures = []
    X, Y = rings(n=96, seed=2)
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float32).fit(X, Y)
    Xq = np.asarray(X[:8], float)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "m.npz")
        model.save(path)
        replicas, router = [], None
        try:
            urls = []
            for _ in range(2):
                srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
                srv.load_model("m", path)
                srv.warmup()
                httpd = make_http_server(srv, port=0)
                srv.attach_http(httpd, start_http_thread(httpd))
                host, port = httpd.server_address[:2]
                urls.append(f"http://{host}:{port}")
                replicas.append(srv)
            router = Router(RouterConfig(
                replicas=tuple(urls), replication=2, seed=3,
                poll_interval_s=0.2, forward_timeout_s=10.0),
                log_fn=lambda m: None)
            router.start()
            n_req, ok = 12, 0
            for i in range(n_req):
                body = _json.dumps(
                    {"instances":
                     [Xq[i % len(Xq)].tolist()]}).encode()
                code, _, _ra = router.forward("m", body)
                ok += int(code == 200)
            if ok != n_req:
                failures.append(f"only {ok}/{n_req} requests scored "
                                "through the router")

            # scrape the fleet the way `tpusvm fleet-metrics` does:
            # every replica directly, plus the router's own payload
            coll = FleetCollector(timeout_s=2.0)
            for url in urls:
                coll.add_replica(url)
            coll.add_callable(router.fleet_payload, name="router")
            view = coll.scrape_once()
            if view.errors:
                failures.append(f"scrape errors: {view.errors}")

            # THE machine check: the published merged view is exactly
            # merge_fleet() of the per-process payloads it scraped —
            # byte-identical in rendered form
            expect = merge_fleet(view.processes)
            if render_snapshot_text(view.merged) \
                    != render_snapshot_text(expect):
                failures.append("merged view != merge_fleet() of the "
                                "scraped per-process snapshots")

            # conservation: the label-tagged per-replica serve.ok
            # counters must sum to the routed request count in the
            # SAME merged snapshot (no double count, no loss)
            per_replica, total = {}, 0.0
            for m in view.merged["metrics"]:
                if m["name"] == "serve.ok" and m["type"] == "counter":
                    inst = m["labels"].get("instance", "?")
                    per_replica[inst] = per_replica.get(inst, 0.0) \
                        + m["value"]
                    total += m["value"]
            if total != float(ok):
                failures.append(
                    f"merged serve.ok total {total} != {ok} routed "
                    f"requests (per replica: {per_replica})")
            if len(per_replica) != 2:
                failures.append(
                    f"expected 2 labelled replica instances, got "
                    f"{sorted(per_replica)}")
            if not args.quiet:
                print(render_fleet_text(view))
        finally:
            if router is not None:
                router.close()
            for srv in replicas:
                srv.close()
    if failures:
        for f in failures:
            print(f"FLEET-METRICS SMOKE FAILED: {f}")
        return 1
    print(f"fleet-metrics smoke ok: 2 replicas + router merged "
          f"exactly; serve.ok conserved at {n_req} across "
          f"{sorted(per_replica)}")
    return 0


def _cmd_fleet_metrics(args) -> int:
    """One merged, (role, instance)-labelled metrics view of a fleet."""
    import json as _json

    from tpusvm.obs.fleet import fleet_json, render_fleet_text

    if args.smoke:
        return _fleet_metrics_smoke(args)
    coll = _fleet_collector(args)
    view = coll.scrape_once()
    if args.format == "json":
        print(_json.dumps(fleet_json(view), sort_keys=True))
    else:
        print(render_fleet_text(view), end="")
    # partial scrapes still print (ops reality: half a fleet view beats
    # none), but a fleet that is ENTIRELY unreachable is an error
    return 1 if view.errors and not view.processes else 0


def _cmd_top(args) -> int:
    """Live fleet table over the fleet-metrics sources."""
    import time

    from tpusvm.obs.fleet import format_top, top_rows

    coll = _fleet_collector(args)
    if args.once:
        view = coll.scrape_once()
        print(format_top(top_rows(view, coll.rates()),
                         errors=view.errors), end="")
        return 1 if view.errors and not view.processes else 0
    t0 = time.monotonic()
    i = 0
    with coll:  # starts the scrape thread; stop() joins it on the way out
        coll.start(interval_s=args.interval_s)
        try:
            while True:
                view = coll.view()
                out = format_top(top_rows(view, coll.rates()),
                                 errors=view.errors,
                                 clock_s=time.monotonic() - t0)
                if not args.no_clear:
                    print("\x1b[2J\x1b[H", end="")
                print(out, end="", flush=True)
                i += 1
                if args.iterations and i >= args.iterations:
                    break
                time.sleep(args.interval_s)
        except KeyboardInterrupt:
            pass
    return 0


def _cmd_benchdiff(args) -> int:
    """Schema-aware regression gate over two benchmark JSONL artifacts."""
    from tpusvm.obs.benchdiff import run_benchdiff

    return run_benchdiff(args)


def _cmd_info(args) -> int:
    if args.path:
        return _info_artifact(args.path)
    import jax

    print(f"jax {jax.__version__}")
    print(f"backend: {jax.default_backend()}")
    print(f"process {jax.process_index()}/{jax.process_count()}")
    for d in jax.devices():
        print(f"  {d}")
    return 0


def main(argv=None) -> int:
    import os

    parser = _build_parser()
    args = parser.parse_args(argv)
    plan_path = args.faults or os.environ.get("TPUSVM_FAULTS")
    if plan_path:
        # chaos mode: activate the seeded fault plan before any subsystem
        # touches its injection points, so hit counting starts at 0
        from tpusvm import faults

        try:
            plan = faults.load_plan(plan_path)
        except (OSError, ValueError) as e:
            parser.error(f"--faults: {e}")
        faults.activate(plan)
        print(f"fault plan active: {plan_path} "
              f"(seed {plan.seed}, {len(plan.rules)} rules)")
    if not args.distributed and (
        args.coordinator_address
        or args.num_processes is not None
        or args.process_id is not None
    ):
        # geometry without --distributed would silently train standalone
        # on each host instead of joining the mesh
        parser.error(
            "--coordinator-address/--num-processes/--process-id require "
            "--distributed"
        )
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.distributed:
        # The MPI_Init equivalent (mpi_svm_main3.cpp:416-419): must run
        # before any backend use so every host joins one global mesh and
        # jax.devices() spans the pod. On TPU the geometry is auto-detected
        # from the TPU metadata; the explicit flags cover other clusters.
        import jax

        kw = {}
        if args.coordinator_address:
            kw["coordinator_address"] = args.coordinator_address
        if args.num_processes is not None:
            kw["num_processes"] = args.num_processes
        if args.process_id is not None:
            kw["process_id"] = args.process_id
        jax.distributed.initialize(**kw)
    return {"train": _cmd_train, "pod": _cmd_pod, "ingest": _cmd_ingest,
            "predict": _cmd_predict, "serve": _cmd_serve,
            "refresh": _cmd_refresh, "autopilot": _cmd_autopilot,
            "tenants": _cmd_tenants, "router": _cmd_router,
            "tune": _cmd_tune, "info": _cmd_info,
            "report": _cmd_report,
            "fleet-metrics": _cmd_fleet_metrics, "top": _cmd_top,
            "benchdiff": _cmd_benchdiff}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
