"""Solver termination status codes.

The reference signals these conditions with cerr prints + `break`, leaving
partial state (SURVEY.md §5.3); here they are explicit status codes shared by
the NumPy oracle and the on-device JAX solver so tests can assert on them.

Reference exit paths in SMO_train (main3.cpp:200-288):
  - CONVERGED:      b_low <= b_high + 2*tau            (main3.cpp:213)
  - NO_WORKING_SET: i_high or i_low not found          (main3.cpp:205-209)
  - INFEASIBLE_UV:  U > V + 1e-12                      (main3.cpp:246-250)
  - NONPOS_ETA:     eta <= 1e-12                       (main3.cpp:253-257)
  - MAX_ITER:       more than max_iter updates         (main3.cpp:283-287)

One addition beyond the reference:
  - STALLED: the selected pair's update rounded to exactly zero change
    (alpha and f unchanged), so the deterministic selection would pick the
    same pair forever — the reference would spin to max_iter in this state
    (possible in float32, or with a pair pinned at its box bound). Both the
    oracle and the on-device solver terminate immediately instead; b is
    still (b_high + b_low)/2 of the final iteration.
"""

import enum


class Status(enum.IntEnum):
    RUNNING = 0
    CONVERGED = 1
    NO_WORKING_SET = 2
    INFEASIBLE_UV = 3
    NONPOS_ETA = 4
    MAX_ITER = 5
    STALLED = 6


class ServeStatus(enum.IntEnum):
    """Per-request outcome codes for the online-serving path (tpusvm.serve).

    Mirrors the solver's explicit-status philosophy above: the serving
    frontend never raises for load-induced conditions — a request comes
    back with a code the caller (and the metrics layer) can branch on.

      OK          scored; result carries scores/label
      TIMEOUT     missed its deadline (client wait or queue residency)
      QUEUE_FULL  fast-failed by backpressure; never entered the queue
      ERROR       the scoring path raised (bad input caught pre-queue
                  raises ValueError instead — that is a caller bug)
      SHUTDOWN    the server closed while the request was in flight
      OVERLOADED  shed by the load-shedding threshold (the queue passed
                  ServeConfig.shed_threshold of its capacity) — the
                  degraded-mode "come back later" answer, distinct from
                  the hard QUEUE_FULL bound so dashboards can tell
                  deliberate shedding from a mis-sized queue
      UNAVAILABLE the model's circuit breaker is OPEN (consecutive
                  scoring failures tripped it); requests fail fast
                  without paying kernel time until a half-open probe
                  recovers the model (tpusvm.faults.breaker)
      DRAINING    the server is draining (Server.drain()): in-flight
                  requests complete, new ones are refused
      LOAD_FAILED a model artifact could not be loaded/staged (missing,
                  truncated or corrupted .npz, or transient I/O that
                  survived the retry budget) — carried by
                  serve.ModelLoadError so `tpusvm serve`, /admin/swap
                  and the --watch loop report the offending path
                  instead of a raw traceback; a failed hot-swap stage
                  rolls back and the previous generation keeps serving
    """

    OK = 0
    TIMEOUT = 1
    QUEUE_FULL = 2
    ERROR = 3
    SHUTDOWN = 4
    OVERLOADED = 5
    UNAVAILABLE = 6
    DRAINING = 7
    LOAD_FAILED = 8


class StreamStatus(enum.IntEnum):
    """Per-shard integrity codes for the out-of-core data layer
    (tpusvm.stream).

    A sharded dataset is a directory of packed .npz shards plus a JSON
    manifest recording per-shard row counts, feature min/max, class counts
    and content checksums. `ShardedDataset.validate()` re-derives those
    facts from the bytes on disk and reports one of these per shard —
    `tpusvm info <dir>` and the ingest smoke gate branch on the codes
    instead of guessing from exceptions:

      OK                  bytes match the manifest's claims
      MISSING_FILE        the shard file named by the manifest is absent
      CHECKSUM_MISMATCH   content hash differs — the shard was modified
                          (or corrupted) after ingest
      ROW_COUNT_MISMATCH  the shard's arrays disagree with the manifest's
                          n_rows / n_features (a truncated or swapped file
                          that happens to parse)
      STATS_MISMATCH      per-shard min/max or class counts don't re-derive
                          from the rows — the manifest-fitted scaler and
                          the stratified assignment would silently diverge
                          from a full-array fit
      READ_FAILED         the shard could not be read even after the
                          reader's retry/backoff budget was exhausted
                          (tpusvm.faults.retry) — transient I/O that
                          never became readable, as opposed to bytes
                          that read fine but fail their checksum
    """

    OK = 0
    MISSING_FILE = 1
    CHECKSUM_MISMATCH = 2
    ROW_COUNT_MISMATCH = 3
    STATS_MISMATCH = 4
    READ_FAILED = 5


class AutopilotStatus(enum.IntEnum):
    """Per-tick outcome codes for the online-learning supervisor
    (tpusvm.autopilot). Every tick ends in exactly one of these, so
    "why did (or didn't) the autopilot retrain" is always an explicit
    code the tests, the obs counters and `tpusvm report` branch on:

      WATCHING             no detector triggered; nothing to do
      TRIGGERED_HYSTERESIS a detector triggered but fewer than
                           `hysteresis` consecutive ticks have — a noisy
                           detector can't thrash retrains
      SUPPRESSED_COOLDOWN  triggered, but the post-refresh cooldown has
                           not elapsed
      SUPPRESSED_BREAKER   triggered, but the refresh circuit breaker is
                           OPEN (repeated refresh failures tripped it) —
                           degraded-watch mode instead of hot-looping a
                           poisoned batch
      REFRESHED            refresh fit + save + swap all succeeded; the
                           new generation is live
      REFRESH_FAILED       the refresh stage raised (fit error, swap
                           rollback, injected fault); counted by the
                           breaker, retried on a later tick
      REFRESH_TIMEOUT      the watchdog deadline stopped the fit at a
                           checkpointed segment boundary; the next
                           eligible tick resumes it from its checkpoint
    """

    WATCHING = 0
    TRIGGERED_HYSTERESIS = 1
    SUPPRESSED_COOLDOWN = 2
    SUPPRESSED_BREAKER = 3
    REFRESHED = 4
    REFRESH_FAILED = 5
    REFRESH_TIMEOUT = 6


class RouterStatus(enum.IntEnum):
    """Fleet-level outcome codes for the routing tier (tpusvm.router).

    The single-replica conditions already have ServeStatus codes; these
    are the conditions that only EXIST once there is a fleet, reported
    on the router's /healthz and by `tpusvm router`'s rollout driver:

      OK          replicas are admissible and rollouts are skew-free
      NO_REPLICA  placement produced no candidate at all — the replica
                  set is empty, or every member is unknown to the
                  health poller (never successfully polled); nothing
                  was forwarded
      ALL_DOWN    candidates existed but every one was down, draining
                  or failed the forward — the whole placement (and the
                  fallback tier) was exhausted
      SKEW_HOLD   a staggered rollout's generation vector spread beyond
                  the skew window (a replica's swap failed and rolled
                  back while the rollout advanced elsewhere); the
                  rollout is held — no further swap is issued — until
                  the laggard is resolved (tpusvm.router.rollout)
    """

    OK = 0
    NO_REPLICA = 1
    ALL_DOWN = 2
    SKEW_HOLD = 3


class TenantsStatus(enum.IntEnum):
    """Per-tick outcome codes for the multi-tenant coalescing supervisor
    (tpusvm.tenants). One supervisor owns THOUSANDS of per-tenant closed
    loops, so the tick outcome is fleet-level — "what did this tick do
    with the currently-drifted tenant set":

      WATCHING            no tenant's detectors triggered past its
                          hysteresis; nothing refreshed
      TRIGGERED_HYSTERESIS at least one tenant triggered but none has
                          accumulated `hysteresis` consecutive ticks —
                          noisy per-tenant detectors cannot thrash the
                          fleet into refresh storms
      SUPPRESSED_BREAKER  drifted tenants exist but the fleet refresh
                          circuit breaker is OPEN (repeated coalesced-
                          refresh failures); degraded-watch mode
      REFRESHED           the drifted set was coalesced into fleet
                          launches (+ solo fallbacks), every artifact
                          saved and its swap rolled out — the tenants'
                          new generations are live
      PARTIAL             the coalesced launches finished but at least
                          one tenant's save/swap failed (its previous
                          generation keeps serving; its drift state
                          stays armed so a later tick retries it)
      REFRESH_FAILED      the coalesced refresh stage raised before any
                          tenant completed (fit error, injected fault);
                          breaker-counted, retried on a later tick —
                          an in-flight fleet checkpoint resumes
                          bit-identically
    """

    WATCHING = 0
    TRIGGERED_HYSTERESIS = 1
    SUPPRESSED_BREAKER = 2
    REFRESHED = 3
    PARTIAL = 4
    REFRESH_FAILED = 5


class TuneStatus(enum.IntEnum):
    """Per-grid-point outcome codes for hyperparameter search (tpusvm.tune).

    A tune run's result table records every point of the search space with
    one of these, so "this point has no CV accuracy" is always explained
    by the schedule that produced it rather than left as a null to guess
    about:

      EVALUATED  fit and scored on every fold at the FINAL rung (grid
                 schedule: all points; halving: the last survivors —
                 the winner is always one of these)
      PRUNED     successive halving dropped it after a smaller-rung
                 evaluation; its recorded metrics are from that rung
      SKIPPED    plateau early-stopping ended the sweep before this point
                 was ever fit; no metrics recorded
    """

    EVALUATED = 0
    PRUNED = 1
    SKIPPED = 2
