"""Serial SMO oracle in NumPy — the in-tree correctness anchor.

This mirrors the reference's serial solver (SMO_train, main3.cpp:162-294) with
the Keerthi first-order working-set heuristic, including every numerical
constant and tie-breaking rule, but written as the golden model for the JAX
on-device solver rather than as a performance path:

  - f initialised to -y (main3.cpp:171-172); optional warm start reconstructs
    f_i = sum_j alpha_j y_j K(x_j, x_i) - y_i like the cascade's
    SMO_train(init=false) (mpi_svm_main3.cpp:156-186).
  - i_high = argmin f over I_high = {y=+1, a<C-eps} u {y=-1, a>eps};
    i_low = argmax f over I_low (mirror sets); first-occurrence tie-break,
    identical to the reference's strict-improvement scan (main3.cpp:107-142).
  - stop when b_low <= b_high + 2*tau (main3.cpp:213).
  - kernel rows cached and recomputed only when the selected index changes
    (main3.cpp:191-232).
  - analytic 2-variable update with box [U, V] from s = y_h*y_l
    (calculate_U_V, main3.cpp:145-159), eta = K11+K22-2*K12 with
    eta <= eps bail-out, clip, paired alpha_high update (main3.cpp:234-279).
  - f update f_i += da_h y_h K_h[i] + da_l y_l K_l[i] (main3.cpp:271-275).
  - b = (b_high + b_low)/2 on exit (main3.cpp:291).

The iteration counter matches the reference exactly: it starts at 1 and
counts successful updates + 1 (main3.cpp:197, :281).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from tpusvm.config import SVMConfig
from tpusvm.status import Status


class OracleResult(NamedTuple):
    alpha: np.ndarray
    b: float
    b_high: float
    b_low: float
    n_iter: int
    status: Status


def rbf_row(X: np.ndarray, x: np.ndarray, gamma: float) -> np.ndarray:
    """K(x, X[j]) for all j: exp(-gamma * ||x - X[j]||^2) (main3.cpp:92-104)."""
    diff = X - x
    return np.exp(-gamma * np.einsum("ij,ij->i", diff, diff))


def kernel_row(X: np.ndarray, x: np.ndarray, config: SVMConfig) -> np.ndarray:
    """K(x, X[j]) for all j under the config's kernel family.

    The oracle's single kernel touchpoint, mirroring tpusvm.kernels:
    "rbf" keeps the reference's per-pair formulation byte-for-byte;
    "linear"/"poly"/"sigmoid" are the dot forms in f64. The approximate
    families have no oracle kernel by design — their parity anchor is
    the EXACT rbf oracle on the same instance (the accuracy-delta gate
    of benchmarks/fuzz_parity.py mode 'rff'), so an approx family name
    reaching this function is a harness bug, not a fallback case.
    """
    if config.kernel == "linear":
        return X @ x
    if config.kernel == "poly":
        return (config.gamma * (X @ x) + config.coef0) ** config.degree
    if config.kernel == "sigmoid":
        return np.tanh(config.gamma * (X @ x) + config.coef0)
    if config.kernel != "rbf":
        raise ValueError(
            f"the NumPy oracle has no kernel {config.kernel!r} "
            "(approximate families are gated against the exact rbf "
            "oracle, not re-implemented here)"
        )
    return rbf_row(X, x, config.gamma)


def _masked_argmin(f: np.ndarray, mask: np.ndarray) -> int:
    """First index of the minimum of f over mask; -1 if mask empty.

    Equivalent to the reference's strict-improvement scan (main3.cpp:113-121):
    both take the FIRST occurrence of the minimum.
    """
    if not mask.any():
        return -1
    vals = np.where(mask, f, np.inf)
    return int(np.argmin(vals))


def _masked_argmax(f: np.ndarray, mask: np.ndarray) -> int:
    if not mask.any():
        return -1
    vals = np.where(mask, f, -np.inf)
    return int(np.argmax(vals))


def smo_train(
    X: np.ndarray,
    Y: np.ndarray,
    config: SVMConfig = SVMConfig(),
    alpha0: Optional[np.ndarray] = None,
    warm_start: bool = False,
    targets: Optional[np.ndarray] = None,
) -> OracleResult:
    """Train a binary SVM with serial SMO. Returns (alpha, b, ...).

    Args:
      X: (n, d) float64 scaled features.
      Y: (n,) labels in {+1, -1}.
      config: hyperparameters (defaults = reference constants); the kernel
        family/params come from config.kernel/degree/coef0 (kernel_row).
      alpha0: initial dual variables; zeros if None.
      warm_start: if True, reconstruct f from alpha0 (cascade semantics,
        mpi_svm_main3.cpp:156-186); if False alpha0 must be zeros and f = -y.
      targets: optional pseudo-target vector z replacing the labels in
        f_i = sum_j a_j y_j K_ij - z_i (the epsilon-SVR doubling,
        tpusvm.kernels.svr; None = z = Y, classification).
    """
    X = np.asarray(X, np.float64)
    Y = np.asarray(Y)
    n = len(Y)
    C, eps, tau = config.C, config.eps, config.tau
    z = (Y.astype(np.float64) if targets is None
         else np.asarray(targets, np.float64))

    if alpha0 is None:
        alpha = np.zeros(n, np.float64)
    else:
        alpha = np.array(alpha0, np.float64, copy=True)

    if warm_start:
        # f_i = sum_j alpha_j y_j K(x_j, x_i) - z_i; only alpha != 0 contribute
        # (mpi_svm_main3.cpp:160-186 skips alpha_j == 0 as an optimisation —
        # algebraically identical to the full sum).
        f = np.empty(n, np.float64)
        nz = np.nonzero(alpha)[0]
        coef = alpha[nz] * Y[nz]
        for i in range(n):
            if len(nz):
                k = kernel_row(X[nz], X[i], config)
                f[i] = float(coef @ k) - float(z[i])
            else:
                f[i] = -float(z[i])
    else:
        f = -z.copy()

    pos = Y == 1
    i_high_prev = -1
    i_low_prev = -1
    k_high = np.zeros(n, np.float64)
    k_low = np.zeros(n, np.float64)
    b_high = np.nan
    b_low = np.nan

    n_iter = 1
    status = Status.RUNNING
    while status == Status.RUNNING:
        in_high = np.where(pos, alpha < C - eps, alpha > eps)
        in_low = np.where(pos, alpha > eps, alpha < C - eps)
        i_high = _masked_argmin(f, in_high)
        i_low = _masked_argmax(f, in_low)
        if i_high < 0 or i_low < 0:
            status = Status.NO_WORKING_SET
            break
        b_high = float(f[i_high])
        b_low = float(f[i_low])
        if b_low <= b_high + 2.0 * tau:
            status = Status.CONVERGED
            break

        if i_high != i_high_prev:
            i_high_prev = i_high
            k_high = kernel_row(X, X[i_high], config)
        if i_low != i_low_prev:
            i_low_prev = i_low
            k_low = kernel_row(X, X[i_low], config)

        s = int(Y[i_high]) * int(Y[i_low])
        K11 = k_high[i_high]
        K22 = k_low[i_low]
        K12 = k_high[i_low]
        eta = K11 + K22 - 2.0 * K12

        if s == -1:
            U = max(0.0, alpha[i_low] - alpha[i_high])
            V = min(C, C + alpha[i_low] - alpha[i_high])
        else:
            U = max(0.0, alpha[i_low] + alpha[i_high] - C)
            V = min(C, alpha[i_low] + alpha[i_high])
        if U > V + 1e-12:
            status = Status.INFEASIBLE_UV
            break
        if eta <= eps:
            status = Status.NONPOS_ETA
            break

        a_low_new = alpha[i_low] + Y[i_low] * (b_high - b_low) / eta
        # reference clip order: cap at V first, then floor at U (main3.cpp:261-264)
        a_low_new = max(min(a_low_new, V), U)
        a_high_new = alpha[i_high] + s * (alpha[i_low] - a_low_new)

        da_high = a_high_new - alpha[i_high]
        da_low = a_low_new - alpha[i_low]
        f += da_high * Y[i_high] * k_high + da_low * Y[i_low] * k_low
        alpha[i_high] = a_high_new
        alpha[i_low] = a_low_new

        n_iter += 1
        if da_high == 0.0 and da_low == 0.0:
            # zero-change update: the same pair would be re-selected forever
            # (the reference would spin to max_iter here); see Status.STALLED
            status = Status.STALLED
            break
        if n_iter > config.max_iter:
            status = Status.MAX_ITER
            break

    b = (b_high + b_low) / 2.0
    return OracleResult(alpha, b, b_high, b_low, n_iter, status)


def svr_train(
    X: np.ndarray,
    t: np.ndarray,
    config: SVMConfig = SVMConfig(),
) -> OracleResult:
    """Serial epsilon-SVR oracle: the 2n-variable doubling through smo_train.

    Builds the doubled problem (tpusvm.kernels.svr.doubled_problem: labels
    [+1]*n + [-1]*n, pseudo-targets t -/+ config.epsilon) over [X; X] and
    runs the UNCHANGED classification SMO skeleton on it. The returned
    alpha is the raw 2n beta vector; collapse with
    kernels.svr.collapse_duals for the signed prediction coefficients
    alpha_i - alpha*_i.
    """
    from tpusvm.kernels.svr import doubled_problem

    X = np.asarray(X, np.float64)
    Y2, z = doubled_problem(t, config.epsilon)
    return smo_train(np.concatenate([X, X]), Y2, config, targets=z)


def get_sv_indices(alpha: np.ndarray, tol: float = 1e-8) -> np.ndarray:
    """Indices with alpha > tol (main3.cpp:297-304)."""
    return np.nonzero(alpha > tol)[0]


def predict(
    X_test: np.ndarray,
    X_train: np.ndarray,
    Y_train: np.ndarray,
    alpha: np.ndarray,
    b: float,
    gamma: float,
    sv_tol: float = 1e-8,
) -> np.ndarray:
    """sign(sum_{k in SV} a_k y_k K(x, x_k) - b), strict >0 -> +1 (main3.cpp:391-402).

    Vectorised blockwise (VERDICT r3 #6: the per-row Python loop made
    mid-scale parity runs needlessly slow): squared distances via the
    norms+dot identity ||x-z||^2 = ||x||^2 + ||z||^2 - 2 x.z in float64,
    clamped at 0 — the same formulation as the framework's device kernels
    (ops/rbf.py), here with f64 accumulation so cancellation stays at the
    1e-12 level. The decision rule (strict >0 -> +1) is unchanged; scores
    can move by ~1ulp vs the old per-row diff loop, which only matters on
    an exactly-zero margin (measure zero on real data). Memory is bounded
    by blocking the test rows (~2e7 kernel entries per block)."""
    sv = get_sv_indices(alpha, sv_tol)
    # select SV rows first, THEN cast: avoids a full-size f64 copy of a
    # large f32 training matrix when only the m SV rows are needed
    Xsv = np.asarray(X_train)[sv].astype(np.float64)
    coef = np.asarray(alpha, np.float64)[sv] * np.asarray(Y_train)[sv]
    preds = np.empty(len(X_test), np.int32)
    m = len(sv)
    if m == 0:
        preds[:] = 1 if -b > 0 else -1  # empty SV sum: score = -b
        return preds
    sv_sq = np.einsum("kj,kj->k", Xsv, Xsv)
    block = max(1, int(2e7) // m)
    for s0 in range(0, len(X_test), block):
        # cast per block so a huge f32 test set is never duplicated whole
        B = np.asarray(X_test[s0:s0 + block], np.float64)
        d2 = (
            np.einsum("ij,ij->i", B, B)[:, None]
            + sv_sq[None, :]
            - 2.0 * (B @ Xsv.T)
        )
        scores = np.exp(-gamma * np.maximum(d2, 0.0)) @ coef - b
        preds[s0:s0 + block] = np.where(scores > 0, 1, -1)
    return preds
