from tpusvm.oracle.smo import OracleResult, get_sv_indices, predict, smo_train

__all__ = ["OracleResult", "smo_train", "get_sv_indices", "predict"]
