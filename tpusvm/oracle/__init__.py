from tpusvm.oracle.smo import (
    OracleResult,
    get_sv_indices,
    kernel_row,
    predict,
    smo_train,
    svr_train,
)

__all__ = ["OracleResult", "smo_train", "svr_train", "get_sv_indices",
           "kernel_row", "predict"]
