"""Epsilon-SVR estimator: the regression task over the same solvers.

Sibling of BinarySVC built on the variable-doubling reduction
(tpusvm.kernels.svr): fit stacks [X; X] with labels [+1]*n + [-1]*n and
pseudo-targets t -/+ epsilon, runs the UNCHANGED blocked (or pairwise)
SMO solver on it via the `targets=` operand, and collapses the 2n betas
to signed coefficients coef_i = alpha_i - alpha*_i. Prediction is then
the same sum the classifiers score with —

    y(x) = sum_i coef_i K(x, x_i) - b

— so solver/predict.decision_function, serve's bucket executables, and
the .npz layout are shared; an SVR state differs from a classifier state
only in carrying `sv_coef` (signed) instead of (sv_Y, sv_alpha), plus a
`task` marker for loader dispatch. The kernel family comes from
config.kernel like everywhere else; epsilon (the tube half-width) from
config.epsilon.
"""

from __future__ import annotations

import time
import warnings
from typing import Optional

import jax.numpy as jnp
import numpy as np

from tpusvm.config import SVMConfig, resolve_accum_dtype
from tpusvm.data.scaler import MinMaxScaler
from tpusvm.kernels.svr import collapse_duals, doubled_problem
from tpusvm.models.serialization import load_model, save_model
from tpusvm.solver.blocked import blocked_smo_solve
from tpusvm.solver.predict import decision_function as _decision
from tpusvm.solver.smo import smo_solve
from tpusvm.status import Status


class EpsilonSVR:
    """Epsilon-insensitive support vector regression via doubled SMO.

    Attributes after fit: sv_X_, sv_coef_ (signed alpha - alpha*),
    sv_ids_, b_, n_iter_, status_, train_time_s_, scaler_.
    """

    def __init__(
        self,
        config: SVMConfig = SVMConfig(),
        dtype=jnp.float32,
        scale: bool = True,
        accum_dtype="auto",
        solver: str = "blocked",
        solver_opts: Optional[dict] = None,
    ):
        if solver not in ("blocked", "pair"):
            raise ValueError(f"unknown solver {solver!r}")
        self.config = config
        self.dtype = dtype
        self.scale = scale
        self.accum_dtype = accum_dtype
        self.solver = solver
        self.solver_opts = dict(solver_opts or {})
        self.scaler_: Optional[MinMaxScaler] = None
        # approximate-kernel state: fitted map + raw input width
        # (sv_X_ holds MAPPED rows for the approx families)
        self.fmap_ = None
        self.n_features_in_: Optional[int] = None
        self.sv_X_: Optional[np.ndarray] = None
        self.sv_coef_: Optional[np.ndarray] = None
        self.sv_ids_: Optional[np.ndarray] = None
        self.b_: float = 0.0
        self.b_high_: float = float("nan")
        self.b_low_: float = float("nan")
        self.n_iter_: int = 0
        self.status_: Status = Status.RUNNING
        self.train_time_s_: float = 0.0
        self.convergence_: Optional[dict] = None

    # ------------------------------------------------------------------ fit
    def fit(self, X: np.ndarray, t: np.ndarray) -> "EpsilonSVR":
        """Fit on features X and CONTINUOUS targets t (not labels)."""
        t0 = time.perf_counter()
        cfg = self.config
        X = np.asarray(X)
        t = np.asarray(t, np.float64)
        n = len(t)
        if self.scale:
            self.scaler_ = MinMaxScaler().fit(X)
            Xs = self.scaler_.transform(X)
        else:
            Xs = X
        # approx families: the doubled problem solves over Phi(X) — the
        # map is fitted on the SINGLE set of rows (the doubling shares
        # it), and sv_X_ below holds mapped rows
        from tpusvm import kernels as _kernels

        if _kernels.is_approx(cfg.kernel):
            from tpusvm.approx import build_map

            self.n_features_in_ = int(np.asarray(Xs).shape[1])
            self.fmap_ = build_map(cfg, X_scaled=np.asarray(Xs))
            Xs = self.fmap_.transform_np(
                np.asarray(Xs), np.dtype(jnp.dtype(self.dtype)))
        Y2, z = doubled_problem(t, cfg.epsilon)
        opts = dict(self.solver_opts)
        shrink_every = opts.pop("shrink_every", 0)
        driver_kw = {k: opts.pop(k) for k in
                     ("shrink_min", "shrink_gap_factor", "max_unshrinks")
                     if k in opts}
        kw = dict(
            C=cfg.C,
            gamma=cfg.gamma,
            eps=cfg.eps,
            tau=cfg.tau,
            max_iter=cfg.max_iter,
            kernel=cfg.kernel,
            degree=cfg.degree,
            coef0=cfg.coef0,
            accum_dtype=resolve_accum_dtype(self.accum_dtype),
            **opts,
        )
        X2 = jnp.concatenate([jnp.asarray(Xs, self.dtype)] * 2)
        if shrink_every:
            # the doubled problem is a plain blocked solve with targets=,
            # exactly what the shrinking driver segments (a frozen beta
            # is a frozen beta; the twin rows are independent duals)
            if self.solver != "blocked":
                raise ValueError(
                    "shrink_every requires the blocked solver"
                )
            from tpusvm.solver.shrink import shrinking_blocked_solve

            res = shrinking_blocked_solve(
                X2, jnp.asarray(Y2), targets=jnp.asarray(z),
                shrink_every=shrink_every,
                shrink_stable=kw.pop("shrink_stable", 3),
                **driver_kw, **kw,
            )
        else:
            solve = (blocked_smo_solve if self.solver == "blocked"
                     else smo_solve)
            res = solve(
                X2,
                jnp.asarray(Y2),
                targets=jnp.asarray(z),
                **kw,
            )
        beta = np.asarray(res.alpha)  # device->host copy = completion barrier
        self.train_time_s_ = time.perf_counter() - t0
        tele = getattr(res, "telemetry", None)
        if tele is not None:
            from tpusvm.obs.convergence import materialize

            self.convergence_ = materialize(tele)
        coef = collapse_duals(beta)
        sv = np.nonzero(np.abs(coef) > cfg.sv_tol)[0]
        self.sv_X_ = Xs[sv]
        self.sv_coef_ = coef[sv]
        self.sv_ids_ = sv.astype(np.int32)
        self.b_ = float(res.b)
        self.b_high_ = float(res.b_high)
        self.b_low_ = float(res.b_low)
        self.n_iter_ = int(res.n_iter)
        self.status_ = Status(int(res.status))
        if self.status_ != Status.CONVERGED:
            warnings.warn(
                f"SVR SMO terminated with {self.status_.name} after "
                f"{self.n_iter_} iterations; the model may be partially "
                "optimised",
                RuntimeWarning,
                stacklevel=2,
            )
        return self

    # -------------------------------------------------------------- predict
    def _check_fitted(self):
        if self.sv_X_ is None:
            raise RuntimeError("model is not fitted")

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Regressed values y(x) = sum_i coef_i K(x, x_i) - b. Shape (m,)."""
        self._check_fitted()
        Xs = (self.scaler_.transform(np.asarray(X)) if self.scale
              else np.asarray(X))
        cfg = self.config
        if self.fmap_ is not None:
            # the fused map+decision program serve's bucket cache lowers
            # (approx families; bit-identical served scores)
            from tpusvm.approx import approx_decision_function

            params = tuple(jnp.asarray(a) for a in self.fmap_.arrays)
            scores = approx_decision_function(
                jnp.asarray(Xs, self.dtype), params,
                jnp.asarray(self.sv_X_, self.dtype),
                jnp.asarray(self.sv_coef_, self.dtype),
                jnp.asarray(self.b_, self.dtype),
                family=cfg.kernel,
            )
            return np.asarray(scores)
        scores = _decision(
            jnp.asarray(Xs, self.dtype),
            jnp.asarray(self.sv_X_, self.dtype),
            jnp.asarray(self.sv_coef_, self.dtype),
            jnp.asarray(self.b_, self.dtype),
            gamma=cfg.gamma, kernel=cfg.kernel, degree=cfg.degree,
            coef0=cfg.coef0,
        )
        return np.asarray(scores)

    # decision_function aliases predict: serve/tests treat "the scored
    # value" uniformly across tasks (for SVR the score IS the prediction)
    decision_function = predict

    def score(self, X: np.ndarray, t: np.ndarray) -> float:
        """Coefficient of determination R^2 (1 = perfect regression)."""
        t = np.asarray(t, np.float64)
        resid = t - self.predict(X)
        ss_tot = float(((t - t.mean()) ** 2).sum())
        if ss_tot == 0.0:
            return 1.0 if float((resid ** 2).sum()) == 0.0 else 0.0
        return 1.0 - float((resid ** 2).sum()) / ss_tot

    @property
    def n_support_(self) -> int:
        self._check_fitted()
        return len(self.sv_coef_)

    # ---------------------------------------------------------- persistence
    def save(self, path: str) -> None:
        self._check_fitted()
        state = {
            "task": "svr",
            "sv_X": self.sv_X_,
            "sv_coef": self.sv_coef_,
            "sv_ids": self.sv_ids_,
            "b": self.b_,
            "scale": self.scale,
        }
        if self.scale:
            state["scaler_min"] = self.scaler_.min_val
            state["scaler_max"] = self.scaler_.max_val
        if self.fmap_ is not None:
            # approximate-map provenance (serialization format v4)
            state.update(self.fmap_.state_entries())
        save_model(path, state, self.config)

    @classmethod
    def load(cls, path: str, dtype=jnp.float32) -> "EpsilonSVR":
        state, config = load_model(path)
        if "sv_coef" not in state:
            raise ValueError(
                f"{path!r} is not an EpsilonSVR artifact (no sv_coef "
                "state); load it with BinarySVC/OneVsRestSVC"
            )
        model = cls(config=config, dtype=dtype, scale=bool(state["scale"]))
        model.sv_X_ = state["sv_X"]
        model.sv_coef_ = state["sv_coef"]
        model.sv_ids_ = state["sv_ids"]
        model.b_ = float(state["b"])
        if model.scale:
            model.scaler_ = MinMaxScaler(
                min_val=state["scaler_min"], max_val=state["scaler_max"]
            )
        from tpusvm import kernels as _kernels

        if _kernels.is_approx(config.kernel):
            from tpusvm.approx import map_from_state

            model.fmap_ = map_from_state(state, config)
            model.n_features_in_ = model.fmap_.n_features_in
        model.status_ = Status.CONVERGED
        return model
