from tpusvm.models.ovr import OneVsRestSVC
from tpusvm.models.serialization import load_model, model_task, save_model
from tpusvm.models.svm import BinarySVC
from tpusvm.models.svr import EpsilonSVR


def load_any(path: str, dtype=None):
    """Load any saved model artifact with the right estimator class.

    Dispatches on the state layout (serialization.model_task): OvR states
    carry `classes`, SVR states a `task` marker, everything else — every
    v1 file included — is a BinarySVC. The single loader `tpusvm predict`
    and serve's ModelEntry.from_path share.
    """
    import jax.numpy as jnp

    dtype = jnp.float32 if dtype is None else dtype
    kind = model_task(path)
    cls = {"ovr": OneVsRestSVC, "svr": EpsilonSVR}.get(kind, BinarySVC)
    return cls.load(path, dtype=dtype)


__all__ = ["BinarySVC", "OneVsRestSVC", "EpsilonSVR", "save_model",
           "load_model", "load_any", "model_task"]
