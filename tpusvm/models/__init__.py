from tpusvm.models.ovr import OneVsRestSVC
from tpusvm.models.serialization import load_model, save_model
from tpusvm.models.svm import BinarySVC

__all__ = ["BinarySVC", "OneVsRestSVC", "save_model", "load_model"]
