"""One-vs-rest multi-class SVM over the class axis.

The reference trains a single one-vs-rest digit ("1" vs. rest); full 10-class
MNIST is its natural extension (BASELINE.json config 5: "10 SVMs vmapped over
chips"). TPU-native design:

  - training, solver="pair": `jax.vmap` of the on-device pairwise SMO solver
    over the class axis — one compiled program runs all K binary problems in
    lockstep (the batched while_loop keeps stepping until every class has
    terminated; finished classes are masked no-ops). X is shared, only the
    +/-1 label vectors differ. Right for small/medium n.
  - training, solver="blocked": per-class blocked working-set solves
    sharing one compiled executable — each class's FLOPs ride the MXU, so
    on big problems (MNIST-60k scale) this is orders of magnitude faster
    than lockstep pairwise, whose vmapped while_loop streams all of X once
    per class per 2-alpha update. The scaled X and its row norms are
    computed ONCE and shared by every head's solve (sn=).
  - training, solver="fleet": ALL K one-vs-rest heads as ONE batched
    blocked-solver program (tpusvm.fleet) — the K problems share X and
    differ only in their +/-1 label vectors, so they pack into one
    power-of-two bucket launch with per-class convergence masking in the
    carry. One compile, one X residency, every head's contraction batched
    onto the MXU together; each head converges to the same optimum as its
    solver="blocked" loop fit (exact SV-set parity, b within the
    cross-engine band — tests/test_fleet.py). The right mode when heads
    are individually too small to saturate the hardware.
  - training, class_parallel=True: the BASELINE config-5 design verbatim
    ("10 SVMs vmapped over chips") — the class axis is sharded over a 1-D
    device mesh via shard_map, each device running the vmapped pair solver
    on its slice of the one-vs-rest label matrix with X replicated
    (classes share the data; only the +/-1 labels differ, so the class
    axis is embarrassingly parallel — no collectives in the hot path; one
    end-of-solve all_gather replicates the results). The class count is
    padded to a device multiple with all-negative dummy label vectors,
    which terminate NO_WORKING_SET after one masked iteration (free in
    the lockstep batched while_loop). MULTI-HOST capable (round 4): under
    jax.distributed the default mesh spans all global devices and every
    process passes the same host data (the multi-controller contract,
    like cascade_fit) — the class axis then shards across hosts the way
    the reference's MPI ranks split work across nodes.
  - prediction: ONE kernel matrix K(test, train) feeds all classes:
    scores = K @ coef^T with coef (K, n) = alpha * y per class — a single
    MXU matmul batched over classes instead of K separate predict passes.
    Class = argmax_k score_k (standard OvR decision).
"""

from __future__ import annotations

import functools
import time
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm.config import SVMConfig, resolve_accum_dtype
from tpusvm.data.scaler import MinMaxScaler
from tpusvm.models.serialization import load_model, save_model
from tpusvm.obs import prof
from tpusvm.ops.rbf import coef_matvec, sq_norms
from tpusvm.solver.smo import smo_solve
from tpusvm.status import Status


class OneVsRestSVC:
    """K-class SVM as K one-vs-rest binary RBF SVMs.

    solver="pair" (default) trains all classes in one vmap (batched=True)
    or sequentially (batched=False); solver="blocked" always trains
    per-class with the blocked working-set solver sharing one compiled
    executable (see module docstring for when each wins).
    """

    def __init__(
        self,
        config: SVMConfig = SVMConfig(),
        dtype=jnp.float32,
        scale: bool = True,
        batched: Optional[bool] = None,
        accum_dtype="auto",
        solver: str = "pair",
        solver_opts: Optional[dict] = None,
        class_parallel: bool = False,
        mesh=None,
    ):
        if solver not in ("pair", "blocked", "fleet"):
            raise ValueError(
                f"solver must be pair|blocked|fleet, got {solver!r}"
            )
        if solver == "blocked" and batched:
            warnings.warn(
                "batched=True has no effect with solver='blocked' "
                "(per-class sequential solves sharing one executable)",
                UserWarning,
                stacklevel=2,
            )
        if class_parallel and solver != "pair":
            # the class axis is parallelised by vmapping the solver and
            # sharding the batch; only the pair solver has a vmap-clean
            # body (the blocked solver's fused Pallas subproblem has no
            # batching rule)
            raise ValueError(
                "class_parallel=True requires solver='pair' (the vmapped "
                "lockstep solver BASELINE config 5 names); the blocked "
                "solver trains classes sequentially and the fleet solver "
                "is already one single-launch batched program (sharding "
                "a fleet over the mesh is a future PR)"
            )
        self.config = config
        self.dtype = dtype
        self.scale = scale
        # None = auto: vmap-batch the pair solver (blocked is per-class)
        self.batched = batched if batched is not None else (solver == "pair")
        self.accum_dtype = accum_dtype
        self.solver = solver
        self.class_parallel = class_parallel
        self.mesh = mesh  # class_parallel: 1-D mesh (default: all devices)
        # extra static solver knobs forwarded to the per-class solve calls
        # (blocked: q, max_outer, max_inner, wss, refine, matmul_precision)
        self.solver_opts = dict(solver_opts or {})
        self.scaler_: Optional[MinMaxScaler] = None
        self.classes_: Optional[np.ndarray] = None
        # approximate-kernel state (config.kernel in APPROX_FAMILIES):
        # the fitted feature map + raw input width — X_sv_ then holds
        # MAPPED rows and every predict path applies the map first
        self.fmap_ = None
        self.n_features_in_: Optional[int] = None
        self.X_sv_: Optional[np.ndarray] = None   # union of SVs across classes
        self.coef_: Optional[np.ndarray] = None   # (K, n_sv_union) alpha*y
        self.sv_ids_: Optional[np.ndarray] = None  # union SV row ids
        self.b_: Optional[np.ndarray] = None      # (K,)
        self.n_iter_: Optional[np.ndarray] = None
        self.statuses_: Optional[np.ndarray] = None
        self.train_time_s_: float = 0.0
        # class_parallel only: the mesh fit() actually trained over
        # ({"axes": (...), "shape": {...}}) — the user-supplied mesh or the
        # auto-built local-device one; benchmark rows record it so a result
        # states its effective process geometry (VERDICT r3 weak #1)
        self.class_mesh_: Optional[dict] = None

    def fit(self, X: np.ndarray, labels: np.ndarray,
            warm_seeds: Optional[np.ndarray] = None) -> "OneVsRestSVC":
        """warm_seeds: optional (K, n) per-head alpha0 seeds (already
        projected feasible per head — tune.warm.deployed_seed_ovr), the
        OvR refresh warm start. Blocked solver only: the pair solver's
        vmapped lockstep and the fleet's batched launch have no per-head
        seed surface yet."""
        cfg = self.config
        t0 = time.perf_counter()
        if warm_seeds is not None and self.solver != "blocked":
            raise ValueError(
                "warm_seeds requires solver='blocked' (per-head "
                f"sequential solves); got solver={self.solver!r}"
            )
        # "auto" -> f64 accumulators (enables x64); see config.resolve_accum_dtype
        accum_dtype = resolve_accum_dtype(self.accum_dtype)
        X = np.asarray(X)
        labels = np.asarray(labels)
        self.classes_ = np.unique(labels)
        Ys = np.stack(
            [np.where(labels == c, 1, -1).astype(np.int32) for c in self.classes_]
        )  # (K, n)

        if self.scale:
            self.scaler_ = MinMaxScaler().fit(X)
            Xs = self.scaler_.transform(X)
        else:
            Xs = X
        # approx families map ONCE for all heads: the K one-vs-rest
        # problems share Phi(X) exactly as they share X (only the +/-1
        # labels differ), so the fleet/blocked/pair paths below all run
        # the linear primal geometry over one mapped matrix
        from tpusvm import kernels as _kernels

        if _kernels.is_approx(cfg.kernel):
            from tpusvm.approx import build_map

            self.n_features_in_ = int(np.asarray(Xs).shape[1])
            self.fmap_ = build_map(cfg, X_scaled=np.asarray(Xs))
            Xs = self.fmap_.transform_np(
                np.asarray(Xs), np.dtype(jnp.dtype(self.dtype)))
        # the class_parallel path feeds X in as a mesh-replicated global
        # array instead, so only the single-controller branches pay the
        # plain device transfer
        if not self.class_parallel:
            Xd = jnp.asarray(Xs, self.dtype)

        if self.solver in ("blocked", "fleet"):
            # both blocked-core modes share one hoisted row-norms
            # precompute: the K heads train on the SAME rows, so the
            # O(n*d) sq_norms stream is paid once for the whole model
            # instead of once per head's solve (rbf only — no norms
            # exist for the other families)
            from tpusvm import kernels as _kernels

            sn_shared = (sq_norms(Xd)
                         if _kernels.needs_norms(cfg.kernel) else None)
        if self.solver == "blocked":
            # per-class blocked working-set solves, sequentially: every
            # class reuses ONE compiled executable (identical shapes), each
            # solve keeps its FLOPs on the MXU via the q-sized subproblem
            # machinery — on big problems this beats the lockstep-vmapped
            # pairwise solver by orders of magnitude (the vmapped
            # while_loop streams X once per class per 2-alpha update)
            from tpusvm.solver.blocked import blocked_smo_solve

            def solve_one(y, **warm_kw):
                return blocked_smo_solve(
                    Xd, y, sn=sn_shared, C=cfg.C, gamma=cfg.gamma,
                    eps=cfg.eps, tau=cfg.tau, max_iter=cfg.max_iter,
                    kernel=cfg.kernel, degree=cfg.degree, coef0=cfg.coef0,
                    accum_dtype=accum_dtype, **warm_kw,
                    **self.solver_opts,
                )
        elif self.solver == "fleet":
            pass  # one batched launch below — no per-class solve_one
        else:
            def solve_pair(Xarr, y):
                return smo_solve(
                    Xarr, y, C=cfg.C, gamma=cfg.gamma, eps=cfg.eps,
                    tau=cfg.tau, max_iter=cfg.max_iter,
                    kernel=cfg.kernel, degree=cfg.degree, coef0=cfg.coef0,
                    accum_dtype=accum_dtype, **self.solver_opts,
                )

            if not self.class_parallel:
                # class_parallel feeds X explicitly (no Xd exists there)
                def solve_one(y):
                    return solve_pair(Xd, y)

        if self.class_parallel:
            # BASELINE config 5 verbatim: the K one-vs-rest problems
            # sharded over the device mesh, the vmapped pair solver
            # running each device's class slice with X replicated; classes
            # share no state, so the hot path has zero collectives.
            # Multi-host capable (round 4): under jax.distributed the
            # default mesh spans ALL global devices, inputs are built as
            # global arrays (label matrix class-sharded, X replicated),
            # and the outputs are all_gathered inside the shard_map so
            # every PROCESS holds the full replicated result — the same
            # treatment that makes the cascade multi-host
            # (parallel/cascade.py:_replicate_outputs): sharded outputs
            # are not process-addressable, and the host-side SV-union /
            # save / score steps need the whole model everywhere.
            from jax import lax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from tpusvm.parallel.mesh import make_mesh, require_1d_mesh

            K = Ys.shape[0]
            mesh = self.mesh
            if mesh is None:
                if jax.process_count() > 1:
                    # every process must run the same SPMD program, and a
                    # mesh over ALL devices keeps every process holding
                    # addressable (replicated) output shards; surplus
                    # devices just train dummy padding classes
                    devs = jax.devices()
                else:
                    devs = jax.local_devices()
                    devs = devs[: min(K, len(devs))]
                mesh = make_mesh(len(devs), devices=devs, axis="classes")
            require_1d_mesh(mesh, "class_parallel")
            self.class_mesh_ = {
                "axes": tuple(mesh.axis_names),
                "shape": dict(mesh.shape),
                "processes": jax.process_count(),
                "devices": [str(d) for d in mesh.devices.flat],
            }
            axis = mesh.axis_names[0]
            n_use = mesh.devices.size
            pad = (-K) % n_use
            # all-negative dummy labels: I_high is empty, so the padded
            # problems end NO_WORKING_SET after one masked lockstep
            # iteration — effectively free
            Ys_p = np.concatenate(
                [Ys, -np.ones((pad, Ys.shape[1]), np.int32)]
            )
            # global input arrays: every process passes the SAME host data
            # (the multi-controller contract, as for cascade_fit) and
            # materialises its addressable shards — works identically
            # single-host
            Xs_f = np.asarray(Xs, self.dtype)
            Xg = jax.make_array_from_callback(
                Xs_f.shape, NamedSharding(mesh, P()),
                lambda idx: Xs_f[idx])
            Ysg = jax.make_array_from_callback(
                Ys_p.shape, NamedSharding(mesh, P(axis)),
                lambda idx: Ys_p[idx])

            def device_fn(Xr, ys):
                res = jax.vmap(lambda y: solve_pair(Xr, y))(ys)
                # K_padded-sized end-of-solve gather — noise next to the
                # per-class solves, and what makes the result replicated
                return jax.tree.map(
                    lambda x: lax.all_gather(x, axis, tiled=True), res
                )

            # check_vma=False for the same reason as parallel/cascade.py:
            # the solver's while_loop/cond carries start from unvarying
            # constants, which the varying-manual-axes checker rejects on
            # every carry; no cross-device communication happens inside
            # the solver, so correctness is unaffected
            fn = jax.jit(jax.shard_map(
                device_fn, mesh=mesh,
                in_specs=(P(), P(axis)), out_specs=P(),
                check_vma=False,
            ))
            res = fn(Xg, Ysg)
            alphas = np.asarray(res.alpha)[:K]       # (K, n)
            bs = np.asarray(res.b)[:K]
            iters = np.asarray(res.n_iter)[:K]
            statuses = np.asarray(res.status)[:K]
        elif self.solver == "fleet":
            # ONE batched launch trains every head: the K one-vs-rest
            # problems share X (and the hoisted norms) and differ only
            # in labels, so they pack into a power-of-two bucket with
            # inert padding lanes; per-class convergence masking lives
            # in the batched while-loop carry (tpusvm.fleet)
            from tpusvm.fleet import fleet_train

            K = Ys.shape[0]
            outs = fleet_train(
                Xd, list(Ys), [cfg.C] * K, [cfg.gamma] * K,
                sn=sn_shared, eps=cfg.eps, tau=cfg.tau,
                max_iter=cfg.max_iter, kernel=cfg.kernel,
                degree=cfg.degree, coef0=cfg.coef0,
                accum_dtype=accum_dtype, **self.solver_opts,
            )
            alphas = np.stack([np.asarray(o.alpha) for o in outs])
            bs = np.asarray([float(o.b) for o in outs])
            iters = np.asarray([int(o.n_iter) for o in outs])
            statuses = np.asarray([int(o.status) for o in outs])
        elif self.batched and self.solver == "pair":
            res = jax.vmap(solve_one)(jnp.asarray(Ys))
            alphas = np.asarray(res.alpha)           # (K, n)
            bs = np.asarray(res.b)
            iters = np.asarray(res.n_iter)
            statuses = np.asarray(res.status)
        else:
            if warm_seeds is not None:
                warm_seeds = np.asarray(warm_seeds, np.float64)
                if warm_seeds.shape != Ys.shape:
                    raise ValueError(
                        f"warm_seeds shape {warm_seeds.shape} != "
                        f"(K, n) = {Ys.shape}"
                    )
            outs = []
            for k, y in enumerate(Ys):
                kw = {}
                if warm_seeds is not None and warm_seeds[k].any():
                    # an all-zero seed is a cold start — skip the
                    # warm-start f reconstruction for it
                    kw = {"alpha0": jnp.asarray(warm_seeds[k]),
                          "warm_start": True}
                outs.append(solve_one(jnp.asarray(y), **kw))
            alphas = np.stack([np.asarray(o.alpha) for o in outs])
            bs = np.asarray([float(o.b) for o in outs])
            iters = np.asarray([int(o.n_iter) for o in outs])
            statuses = np.asarray([int(o.status) for o in outs])
        self.train_time_s_ = time.perf_counter() - t0

        # keep only the union of support vectors across classes
        is_sv = (alphas > cfg.sv_tol).any(axis=0)
        sv_idx = np.nonzero(is_sv)[0]
        alphas_sv = np.where(
            alphas[:, sv_idx] > cfg.sv_tol, alphas[:, sv_idx], 0.0
        )
        self.X_sv_ = Xs[sv_idx]
        self.coef_ = alphas_sv * Ys[:, sv_idx]
        self.sv_ids_ = sv_idx.astype(np.int32)
        self.b_ = bs
        self.n_iter_ = iters
        self.statuses_ = statuses
        not_conv = [
            (int(c), Status(int(s)).name)
            for c, s in zip(self.classes_, statuses)
            if s != Status.CONVERGED
        ]
        if not_conv:
            warnings.warn(
                f"per-class SMO did not converge for {not_conv}; those "
                "classifiers may be partially optimised",
                RuntimeWarning,
                stacklevel=2,
            )
        return self

    def decision_function(self, X: np.ndarray, mesh=None) -> np.ndarray:
        """(m, K) OvR scores via one batched kernel matmul.

        mesh: optional 1-D mesh — shards the test-row axis over local
        devices (SV set / coef replicated), same semantics as
        BinarySVC.decision_function."""
        if self.X_sv_ is None:
            raise RuntimeError("model is not fitted")
        from tpusvm.parallel.mesh import shard_rows_padded

        Xq = self.scaler_.transform(np.asarray(X)) if self.scale else np.asarray(X)
        Xd, m = shard_rows_padded(mesh, jnp.asarray(Xq, self.dtype))
        if self.fmap_ is not None:
            # the FUSED map+gemm program — the exact executable serve's
            # ovr bucket cache AOT-compiles, so served scores match this
            # path bit-for-bit; the gemm is flat, so the row sharding of
            # a mesh call partitions cleanly through the map too
            from tpusvm.approx import approx_ovr_scores

            params = tuple(jnp.asarray(a) for a in self.fmap_.arrays)
            scores = approx_ovr_scores(
                Xd, params,
                jnp.asarray(self.X_sv_, self.dtype),
                jnp.asarray(self.coef_, self.dtype),
                jnp.asarray(self.b_, self.dtype),
                family=self.config.kernel,
            )
            return np.asarray(scores[:m])
        scores = _ovr_scores(
            Xd,
            jnp.asarray(self.X_sv_, self.dtype),
            jnp.asarray(self.coef_, self.dtype),
            jnp.asarray(self.b_, self.dtype),
            self.config.gamma,
            self.config.coef0,
            kernel=self.config.kernel,
            degree=self.config.degree,
        )
        return np.asarray(scores[:m])

    def predict(self, X: np.ndarray, mesh=None) -> np.ndarray:
        scores = self.decision_function(X, mesh=mesh)
        return self.classes_[np.argmax(scores, axis=1)]

    def score(self, X: np.ndarray, labels: np.ndarray, mesh=None) -> float:
        return float((self.predict(X, mesh=mesh) == np.asarray(labels)).mean())

    def save(self, path: str) -> None:
        if self.X_sv_ is None:
            raise RuntimeError("model is not fitted")
        state = {
            "classes": self.classes_,
            "sv_X": self.X_sv_,
            "coef": self.coef_,
            "b": self.b_,
            "scale": self.scale,
        }
        if self.sv_ids_ is not None:
            # union SV row ids (absent in pre-0.18 artifacts, and in
            # re-saves of models loaded from them): the OvR refresh warm
            # seed scatters per-head duals back to these positions
            state["sv_ids"] = self.sv_ids_
        if self.scale:
            state["scaler_min"] = self.scaler_.min_val
            state["scaler_max"] = self.scaler_.max_val
        if self.fmap_ is not None:
            # approximate-map provenance (serialization format v4)
            state.update(self.fmap_.state_entries())
        save_model(path, state, self.config)

    @classmethod
    def load(cls, path: str, dtype=jnp.float32) -> "OneVsRestSVC":
        state, config = load_model(path)
        model = cls(config=config, dtype=dtype, scale=bool(state["scale"]))
        model.classes_ = state["classes"]
        model.X_sv_ = state["sv_X"]
        model.coef_ = state["coef"]
        model.sv_ids_ = state["sv_ids"] if "sv_ids" in state else None
        model.b_ = state["b"]
        if model.scale:
            model.scaler_ = MinMaxScaler(
                min_val=state["scaler_min"], max_val=state["scaler_max"]
            )
        from tpusvm import kernels as _kernels

        if _kernels.is_approx(config.kernel):
            from tpusvm.approx import map_from_state

            model.fmap_ = map_from_state(state, config)
            model.n_features_in_ = model.fmap_.n_features_in
        return model


_OVR_SCORES_STATIC = ("kernel", "degree")


@functools.partial(jax.jit, static_argnames=_OVR_SCORES_STATIC)
def _ovr_scores_jit(Xq, X_sv, coef, b, gamma, coef0=0.0, *, kernel="rbf",
                    degree=3):
    from tpusvm import kernels

    snB = sq_norms(X_sv) if kernels.needs_norms(kernel) else None
    K = kernels.cross(kernel, Xq, X_sv, gamma=gamma, coef0=coef0,
                      degree=degree, snB=snB)  # (m, n_sv)
    return coef_matvec(K, coef.T) - b[None, :]


# compile-observatory wrapper (tpusvm.obs.prof); serve's bucket cache
# uses the preserved `.lower` surface
_ovr_scores = prof.profiled_jit("predict.ovr_scores", _ovr_scores_jit,
                                static=_OVR_SCORES_STATIC)
