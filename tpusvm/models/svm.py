"""User-facing binary SVM estimator.

The reference's user interface is "edit the hardcoded dataset string and
constants in main(), recompile" (main3.cpp:306-347, SURVEY.md §5.6). This
class is the framework replacement: scikit-learn-flavoured fit/predict over
the TPU-native solver, with both single-chip (gpu_svm_main3.cu capability)
and distributed-cascade (mpi_svm_main*.cpp capability) training paths, and
proper model persistence.

Pipeline parity with the reference (main3.cpp:335-405):
  fit:      min-max scale on TRAIN data -> SMO -> extract SVs
  predict:  scale with TRAIN min/max -> sign(sum_sv a_k y_k K(x,x_k) - b)
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm import kernels as _kernels
from tpusvm.config import CascadeConfig, SVMConfig, resolve_accum_dtype
from tpusvm.data.scaler import MinMaxScaler
from tpusvm.models.serialization import load_model, save_model
from tpusvm.oracle.smo import get_sv_indices
from tpusvm.parallel.cascade import cascade_fit
from tpusvm.solver.blocked import blocked_smo_solve
from tpusvm.solver.predict import (
    decision_function as _decision,
    decision_function_flat as _decision_flat,
)
from tpusvm.solver.smo import smo_solve
from tpusvm.status import Status


class BinarySVC:
    """Binary SVM trained with on-device SMO (kernel from config.kernel:
    rbf, linear, or poly — tpusvm.kernels).

    Attributes after fit: sv_X_, sv_Y_, sv_alpha_, sv_ids_, b_, n_iter_,
    status_, train_time_s_, scaler_; after calibrate(): platt_ (A, B) and
    predict_proba becomes available.
    """

    def __init__(
        self,
        config: SVMConfig = SVMConfig(),
        dtype=jnp.float32,
        scale: bool = True,
        accum_dtype="auto",
        solver: str = "blocked",
        solver_opts: Optional[dict] = None,
    ):
        """accum_dtype: solver accumulator dtype (see smo_solve). The
        default "auto" resolves to float64 at fit time (enabling jax x64
        mode if needed) — the mixed-precision mode that matches the f64
        reference's convergence behaviour at f32 speed, and the same
        default as the CLI's --accum. Pass None for same-as-features
        accumulators (f32 alone can STALL near convergence).

        solver: "blocked" (default — the TPU-first working-set solver,
        solver/blocked.py) or "pair" (the reference-faithful one-pair-per-
        iteration solver, solver/smo.py). SVMConfig.max_iter bounds total
        alpha updates in both.

        solver_opts: extra static solver knobs forwarded to the solve call
        (blocked: q, max_outer, max_inner)."""
        if solver not in ("blocked", "pair"):
            raise ValueError(f"unknown solver {solver!r}")
        self.config = config
        self.dtype = dtype
        self.scale = scale
        self.accum_dtype = accum_dtype
        self.solver = solver
        self.solver_opts = dict(solver_opts or {})
        self.scaler_: Optional[MinMaxScaler] = None
        self.sv_X_: Optional[np.ndarray] = None
        self.sv_Y_: Optional[np.ndarray] = None
        self.sv_alpha_: Optional[np.ndarray] = None
        self.sv_ids_: Optional[np.ndarray] = None
        self.b_: float = 0.0
        self.b_high_: float = float("nan")
        self.b_low_: float = float("nan")
        self.n_iter_: int = 0
        self.status_: Status = Status.RUNNING
        self.train_time_s_: float = 0.0
        # materialized convergence telemetry (obs.convergence.materialize
        # output) when the blocked solver ran with telemetry=T > 0
        self.convergence_: Optional[dict] = None
        # training provenance (round 9): precision rung + shrink cadence
        # the fit ran under; persisted in the .npz (format v3) so
        # `tpusvm info` can answer "which ladder rung trained this"
        self.train_precision_: str = "f32"
        self.shrink_every_: int = 0
        self.shrink_stable_: int = 0
        # Platt sigmoid (A, B) after calibrate(); enables predict_proba
        self.platt_: Optional[tuple] = None
        # approximate-kernel state (config.kernel in APPROX_FAMILIES):
        # the fitted feature map and the RAW input width — sv_X_ then
        # holds MAPPED rows, and every predict path applies the map
        self.fmap_ = None
        self.n_features_in_: Optional[int] = None
        # streamed approx fits record the reader residency high-water
        # mark (the prefetch_depth + 1 bound the tests audit)
        self.stream_max_live_shards_: Optional[int] = None
        # cascade/pod training provenance (v4-additive serialization
        # keys): merge topology, leaf count and rounds-to-stabilize of a
        # cascade- or pod-trained artifact — `tpusvm info` prints them;
        # None/0 for single-solver fits and older files
        self.cascade_topology_: Optional[str] = None
        self.cascade_leaves_: Optional[int] = None
        self.cascade_rounds_: int = 0
        self.cascade_history_: Optional[list] = None
        # pod fits keep the per-worker leaf row counts so callers can
        # audit that the partition conserved every ingested row
        self.pod_worker_rows_: Optional[tuple] = None

    # ------------------------------------------------------------------ fit
    def _scale_fit(self, X: np.ndarray) -> np.ndarray:
        if self.scale:
            self.scaler_ = MinMaxScaler().fit(X)
            return self.scaler_.transform(X)
        return X

    def _map_fit(self, Xs: np.ndarray) -> np.ndarray:
        """Fit + apply the approximate feature map (identity for exact
        families): everything downstream — solver, SV extraction,
        cascade buffers — then lives in the mapped space."""
        if not _kernels.is_approx(self.config.kernel):
            return Xs
        from tpusvm.approx import build_map

        self.n_features_in_ = int(Xs.shape[1])
        self.fmap_ = build_map(self.config, X_scaled=Xs)
        return self.fmap_.transform_np(
            Xs, np.dtype(jnp.dtype(self.dtype)))

    def fit(self, X: np.ndarray, Y: np.ndarray,
            checkpoint_path: Optional[str] = None,
            checkpoint_every: int = 64,
            resume: bool = False) -> "BinarySVC":
        """Single-chip on-device SMO training (gpu_svm_main3.cu capability).

        checkpoint_path: crash-safe training (blocked solver only) — the
        solver's outer-loop carry is snapshotted atomically every
        `checkpoint_every` outer rounds, and resume=True restarts from
        the file, BIT-IDENTICAL to an uninterrupted fit
        (solver/checkpoint.py; a missing file means a fresh start)."""
        t0 = time.perf_counter()
        Xs = self._scale_fit(np.asarray(X))
        return self._fit_scaled(Xs, Y, t0, checkpoint_path=checkpoint_path,
                                checkpoint_every=checkpoint_every,
                                resume=resume)

    def fit_stream(self, dataset,
                   checkpoint_path: Optional[str] = None,
                   checkpoint_every: int = 64,
                   resume: bool = False) -> "BinarySVC":
        """Single-chip fit from a sharded dataset (tpusvm.stream).

        The scaler is fitted from MANIFEST statistics (bit-identical to a
        full-array fit — stream.stats) and shards are scaled as they
        stream in, so the raw array is never materialised. For the EXACT
        families the SCALED matrix is — single-chip SMO needs every row
        on device; use fit_cascade_stream when per-leaf loading is the
        point. The APPROXIMATE families (kernel="rff"/"nystrom") instead
        run the streaming primal solver (tpusvm.approx.primal): shards
        are mapped per-block inside the reader's prefetch hook and
        consumed batch-by-batch, so NEITHER the raw nor the mapped
        (n, D) matrix is ever materialised — peak residency stays the
        reader's prefetch_depth + 1 bound (stream_max_live_shards_
        records the audited high-water mark).
        checkpoint_path/resume: see fit() (exact families only).
        """
        from tpusvm.stream.reader import ShardReader

        t0 = time.perf_counter()
        scaler = None
        if self.scale:
            self.scaler_ = scaler = dataset.scaler()
        if _kernels.is_approx(self.config.kernel):
            if checkpoint_path is not None or resume:
                raise ValueError(
                    "checkpoint_path/resume is a blocked-solver outer-"
                    "loop surface; the streamed approximate fit runs "
                    "the tpusvm.approx.primal epoch schedule instead — "
                    "checkpointing it is a future PR"
                )
            return self._fit_stream_approx(dataset, scaler, t0)
        parts = [X for X, _ in ShardReader(dataset, scaler=scaler)]
        Xs = np.concatenate(parts)
        del parts
        return self._fit_scaled(Xs, dataset.load_labels(), t0,
                                checkpoint_path=checkpoint_path,
                                checkpoint_every=checkpoint_every,
                                resume=resume)

    def _fit_stream_approx(self, dataset, scaler, t0: float) -> "BinarySVC":
        """Out-of-core approx training: per-shard mapping in the reader's
        prefetch hook + the streaming mini-batch primal solver.

        The result is embedded as a ONE-support-vector linear model over
        mapped features (sv_X_ = w, alpha*y = 1, b = -bias): exactly the
        layout every predict/serve/serialization path already speaks, so
        the primal regime rides the standard machinery unchanged.
        solver_opts: primal_batch (default 1024), primal_epochs (64),
        primal_tol (0.05 — the relative per-epoch improvement below
        which the 1/t SGD tail is diminishing returns), prefetch_depth
        (2); anything else is a blocked-solver knob and is rejected by
        name.
        """
        from tpusvm.approx import build_map, streaming_primal_fit
        from tpusvm.approx.features import nystrom_landmark_indices
        from tpusvm.stream.reader import ShardReader

        cfg = self.config
        opts = dict(self.solver_opts)
        batch = int(opts.pop("primal_batch", 1024))
        epochs = int(opts.pop("primal_epochs", 64))
        tol = float(opts.pop("primal_tol", 0.05))
        prefetch_depth = int(opts.pop("prefetch_depth", 2))
        if opts:
            raise ValueError(
                f"streamed approximate fits take only the primal knobs "
                f"(primal_batch, primal_epochs, primal_tol, "
                f"prefetch_depth); got blocked-solver opts "
                f"{sorted(opts)}"
            )
        n, d = dataset.n_rows, dataset.n_features
        self.n_features_in_ = int(d)
        if cfg.kernel == "nystrom":
            # the SAME seeded landmark rows the in-memory path would
            # draw, gathered from the manifest without loading the rest
            from tpusvm.stream.assign import gather_rows

            idx = nystrom_landmark_indices(n, cfg.landmarks, cfg.map_seed)
            rows = gather_rows(dataset, idx)
            if scaler is not None:
                rows = scaler.transform(rows)
            fmap = build_map(cfg, landmark_rows=rows)
        else:
            fmap = build_map(cfg, n_features=d)
        self.fmap_ = fmap
        dt = np.dtype(jnp.dtype(self.dtype))
        readers = []

        def make_reader(epoch):
            r = ShardReader(
                dataset, prefetch_depth=prefetch_depth, scaler=scaler,
                transform=lambda X: fmap.transform_np(X, dt))
            readers.append(r)
            return r

        res = streaming_primal_fit(
            make_reader, fmap.dim, C=cfg.C, n_rows=n, batch=batch,
            epochs=epochs, tol=tol, dtype=dt)
        self.stream_max_live_shards_ = max(
            r.max_live_shards for r in readers)
        self.train_time_s_ = time.perf_counter() - t0
        self.sv_X_ = res.w[None, :].astype(dt)
        self.sv_Y_ = np.array([1], np.int32)
        self.sv_alpha_ = np.array([1.0], dt)
        # the primal weight vector is not a training row: sentinel id
        self.sv_ids_ = np.array([-1], np.int32)
        # decision_function computes Phi(x).sv_coef - b_, and the primal
        # model is f = w.Phi(x) - bias: same sign, b_ IS the bias
        self.b_ = res.bias
        self.n_iter_ = int(res.n_steps)
        self.status_ = res.status
        if self.status_ != Status.CONVERGED:
            warnings.warn(
                f"streaming primal fit ended {self.status_.name} after "
                f"{res.epochs_run} epochs (objective {res.objective:g}); "
                "raise primal_epochs or loosen primal_tol",
                RuntimeWarning,
                stacklevel=2,
            )
        return self

    def _fit_scaled(self, Xs: np.ndarray, Y: np.ndarray, t0: float,
                    checkpoint_path: Optional[str] = None,
                    checkpoint_every: int = 64,
                    resume: bool = False) -> "BinarySVC":
        """Shared solve + SV extraction on an already-scaled matrix."""
        cfg = self.config
        # approx families: map first — the solver then runs the linear
        # primal fast path over Phi(X) (kernels.dispatch routes the
        # family name through kernels/linear.py), and the extracted
        # sv_X_ rows are MAPPED rows
        Xs = self._map_fit(Xs)
        opts = dict(self.solver_opts)
        shrink_every = opts.pop("shrink_every", 0)
        driver_kw = {k: opts.pop(k) for k in
                     ("shrink_min", "shrink_gap_factor", "max_unshrinks")
                     if k in opts}
        kw = dict(
            C=cfg.C,
            gamma=cfg.gamma,
            eps=cfg.eps,
            tau=cfg.tau,
            max_iter=cfg.max_iter,
            kernel=cfg.kernel,
            degree=cfg.degree,
            coef0=cfg.coef0,
            accum_dtype=resolve_accum_dtype(self.accum_dtype),
            **opts,
        )
        if shrink_every and self.solver != "blocked":
            raise ValueError(
                "shrink_every drives the blocked solver's outer loop in "
                "compacted segments (tpusvm.solver.shrink); the pair "
                "solver has no working-set rounds to shrink"
            )
        if checkpoint_path is not None:
            if self.solver != "blocked":
                raise ValueError(
                    "checkpoint_path requires the blocked solver (the "
                    "outer-loop carry is what gets persisted); the pair "
                    "solver has no checkpointable round structure"
                )
            if shrink_every:
                raise ValueError(
                    "checkpoint_path and shrink_every both segment the "
                    "outer loop and cannot be combined yet (the "
                    "checkpoint carry would span changing compaction "
                    "buckets); crash-safe shrinking is a future PR"
                )
            from tpusvm.solver.checkpoint import checkpointed_blocked_solve

            res = checkpointed_blocked_solve(
                jnp.asarray(Xs, self.dtype), jnp.asarray(Y),
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every, resume=resume, **kw,
            )
        elif shrink_every:
            from tpusvm.solver.shrink import shrinking_blocked_solve

            kw.setdefault("shrink_stable", 3)
            res = shrinking_blocked_solve(
                jnp.asarray(Xs, self.dtype), jnp.asarray(Y),
                shrink_every=shrink_every,
                shrink_stable=kw.pop("shrink_stable"),
                **driver_kw, **kw,
            )
        else:
            solve = (blocked_smo_solve if self.solver == "blocked"
                     else smo_solve)
            res = solve(jnp.asarray(Xs, self.dtype), jnp.asarray(Y), **kw)
        alpha = np.asarray(res.alpha)  # device->host copy = completion barrier
        self.train_time_s_ = time.perf_counter() - t0
        # training provenance persisted with the model (round 9): the
        # precision rung and shrinking cadence it was trained under —
        # scoring is unaffected, but `tpusvm info` must be able to answer
        # "which ladder rung produced this artifact"
        self.train_precision_ = opts.get("matmul_precision") or "f32"
        self.shrink_every_ = int(shrink_every)
        self.shrink_stable_ = int(self.solver_opts.get(
            "shrink_stable", 3 if shrink_every else 0))
        if getattr(res, "cache_hits", None) is not None:
            from tpusvm.obs import default_registry

            reg = default_registry()
            reg.counter("solver.krow_cache.rows_hit").inc(
                int(res.cache_hits))
            reg.counter("solver.krow_cache.rows_miss").inc(
                int(res.cache_misses))
        tele = getattr(res, "telemetry", None)
        if tele is not None:
            from tpusvm.obs.convergence import materialize

            self.convergence_ = materialize(tele)
        sv = get_sv_indices(alpha, cfg.sv_tol)
        self.sv_X_ = Xs[sv]
        self.sv_Y_ = np.asarray(Y)[sv].astype(np.int32)
        self.sv_alpha_ = alpha[sv]
        self.sv_ids_ = sv.astype(np.int32)
        self.b_ = float(res.b)
        self.b_high_ = float(res.b_high)
        self.b_low_ = float(res.b_low)
        self.n_iter_ = int(res.n_iter)
        self.status_ = Status(int(res.status))
        if self.status_ != Status.CONVERGED:
            warnings.warn(
                f"SMO terminated with {self.status_.name} after "
                f"{self.n_iter_} iterations; the model may be partially "
                "optimised (for STALLED in float32, try "
                "accum_dtype=jnp.float64)",
                RuntimeWarning,
                stacklevel=2,
            )
        return self

    def fit_cascade(
        self,
        X: np.ndarray,
        Y: np.ndarray,
        cascade_config: CascadeConfig = CascadeConfig(),
        mesh=None,
        verbose: bool = False,
        checkpoint_path: Optional[str] = None,
        resume: bool = False,
        stratified: bool = False,
        tracer=None,
    ) -> "BinarySVC":
        """Distributed cascade training over a device mesh (MPI capability).

        Each shard runs this estimator's configured solver ("blocked" by
        default — the accelerated-solver-per-mesh-member hybrid; "pair" for
        the reference-faithful trajectory).

        checkpoint_path/resume: persist per-round cascade state and restart
        from it (parallel.cascade.cascade_fit).

        stratified: per-class round-robin sharding instead of the
        reference's contiguous scatter — safe on label-sorted input
        (parallel.cascade.cascade_fit).

        Approximate families (kernel="rff"/"nystrom") cascade over the
        MAPPED features: the map is fitted once on the full scaled data,
        every leaf solve then runs the linear primal fast path, and the
        merged SV buffers hold mapped rows — cascade machinery applies
        unchanged on top of the linear-cost solver."""
        t0 = time.perf_counter()
        Xs = self._map_fit(self._scale_fit(np.asarray(X)))
        res = cascade_fit(
            Xs, Y, self.config, cascade_config, mesh=mesh, dtype=self.dtype,
            # cascade_fit resolves the "auto" sentinel itself
            accum_dtype=self.accum_dtype, verbose=verbose,
            checkpoint_path=checkpoint_path, resume=resume,
            solver=self.solver, solver_opts=self.solver_opts,
            stratified=stratified, tracer=tracer,
        )
        return self._finish_cascade(res, t0, cascade_config)

    def fit_cascade_stream(
        self,
        dataset,
        cascade_config: CascadeConfig = CascadeConfig(),
        mesh=None,
        verbose: bool = False,
        checkpoint_path: Optional[str] = None,
        resume: bool = False,
        stratified: bool = False,
        tracer=None,
    ) -> "BinarySVC":
        """Cascade training from a sharded dataset (tpusvm.stream).

        The out-of-core twin of fit_cascade: the scaler comes from
        MANIFEST statistics (the reference's rank-0 global min/max
        broadcast, computed without holding X — mpi_svm_main3.cpp:529-539),
        and each cascade leaf is filled by streaming dataset shards into a
        prebuilt partition (stream.partition_from_dataset), so no
        monolithic (n, d) array ever exists. Trains the IDENTICAL model to
        fit_cascade on the equivalent array: same SV-ID set, same b, same
        accuracy (the partition is bit-identical and everything downstream
        consumes only the partition)."""
        if _kernels.is_approx(self.config.kernel):
            raise ValueError(
                "fit_cascade_stream does not support the approximate "
                f"families yet (kernel={self.config.kernel!r}): leaf "
                "partitions are filled with RAW rows and the mapped "
                "width would change every buffer shape; use fit_stream "
                "(the streaming primal path) or in-memory fit_cascade "
                "over mapped features"
            )
        t0 = time.perf_counter()
        from tpusvm.stream.assign import partition_from_dataset

        scaler = None
        if self.scale:
            self.scaler_ = scaler = dataset.scaler()
        part = partition_from_dataset(
            dataset, cascade_config.n_shards, stratified=stratified,
            scaler=scaler,
        )
        res = cascade_fit(
            None, None, self.config, cascade_config, mesh=mesh,
            dtype=self.dtype, accum_dtype=self.accum_dtype, verbose=verbose,
            checkpoint_path=checkpoint_path, resume=resume,
            solver=self.solver, solver_opts=self.solver_opts,
            partition=part, tracer=tracer,
        )
        return self._finish_cascade(res, t0, cascade_config)

    def fit_pod(
        self,
        data: str,
        cascade_config: CascadeConfig = CascadeConfig(),
        verbose: bool = False,
        checkpoint_path: Optional[str] = None,
        resume: bool = False,
        stratified: bool = False,
        prefetch_depth: int = 2,
        tracer=None,
    ) -> "BinarySVC":
        """Pod (multi-process) cascade training from a sharded dataset.

        Each cascade leaf runs in its OWN worker process that streams
        only its manifest shards (tpusvm.pod) — the out-of-core,
        shard_map-free sibling of fit_cascade_stream, with the same
        SV-ID fixed point and the same manifest-fitted scaler. Because
        leaves are host processes, the full solver ladder applies:
        shrink_every and friends in solver_opts run the shrinking
        driver per leaf (solver="blocked"), which the shard_map cascade
        rejects.

        checkpoint_path/resume: crash-safe per-round coordinator
        checkpoints (pod/state.py, fsync_replace); a killed coordinator
        resumes bit-identically, a killed worker is revived mid-round.
        """
        if _kernels.is_approx(self.config.kernel):
            raise ValueError(
                "fit_pod does not support the approximate families yet "
                f"(kernel={self.config.kernel!r}): leaf partitions are "
                "filled with RAW rows and the mapped width would change "
                "every buffer shape; use fit_stream (the streaming "
                "primal path) or in-memory fit_cascade over mapped "
                "features"
            )
        t0 = time.perf_counter()
        from tpusvm.pod import pod_fit
        from tpusvm.stream.format import open_dataset

        if self.scale:
            self.scaler_ = open_dataset(data).scaler()
        res = pod_fit(
            data, self.config, cascade_config, dtype=self.dtype,
            accum_dtype=self.accum_dtype, verbose=verbose,
            checkpoint_path=checkpoint_path, resume=resume,
            solver=self.solver, solver_opts=self.solver_opts,
            stratified=stratified, prefetch_depth=prefetch_depth,
            scale=self.scale, tracer=tracer,
        )
        self.stream_max_live_shards_ = int(
            max(res.worker_max_live_shards))
        self.pod_worker_rows_ = tuple(int(r) for r in res.worker_rows)
        # ladder provenance, as fit() records it: pod leaves run the
        # shrinking driver and precision rungs for real
        self.train_precision_ = (
            self.solver_opts.get("matmul_precision") or "f32")
        self.shrink_every_ = int(
            self.solver_opts.get("shrink_every", 0) or 0)
        self.shrink_stable_ = int(self.solver_opts.get(
            "shrink_stable", 3 if self.shrink_every_ else 0))
        return self._finish_cascade(res, t0, cascade_config)

    def _finish_cascade(self, res, t0: float,
                        cascade_config: CascadeConfig) -> "BinarySVC":
        self.train_time_s_ = time.perf_counter() - t0
        self.sv_X_ = res.sv_X
        self.sv_Y_ = res.sv_Y
        self.sv_alpha_ = res.sv_alpha
        self.sv_ids_ = res.sv_ids
        self.b_ = res.b
        self.n_iter_ = int(sum(h["iters"].sum() for h in res.history))
        self.status_ = (
            Status.CONVERGED if res.converged else Status.MAX_ITER
        )
        self.cascade_history_ = res.history
        self.cascade_rounds_ = res.rounds
        self.cascade_topology_ = cascade_config.topology
        self.cascade_leaves_ = int(cascade_config.n_shards)
        return self

    # -------------------------------------------------------------- predict
    def _check_fitted(self):
        if self.sv_X_ is None:
            raise RuntimeError("model is not fitted")

    def decision_function(self, X: np.ndarray, mesh=None) -> np.ndarray:
        """Decision scores f(x) = sum_k alpha_k y_k K(x, x_k) - b.

        mesh: optional 1-D jax.sharding.Mesh — shards the TEST-ROW axis
        over the mesh's devices (SV set and b replicated) so serving a
        large batch uses every chip; XLA partitions the K(test, SV)
        matmul along the sharded rows with no collectives in the forward
        pass (each row's score depends only on that row). Scores match
        the single-device path to fp-summation-order noise (~1 ULP: the
        partitioned matmul may tile the contraction differently). Single-controller
        (local devices); rows are zero-padded to a device multiple for
        the even NamedSharding split and the padding is sliced off the
        returned scores (a zero row's score is garbage but independent —
        it cannot contaminate real rows).
        """
        self._check_fitted()
        from tpusvm.parallel.mesh import shard_rows_padded

        Xs = self.scaler_.transform(np.asarray(X)) if self.scale else np.asarray(X)
        Xd, m = shard_rows_padded(mesh, jnp.asarray(Xs, self.dtype))
        coef = jnp.asarray(self.sv_alpha_ * self.sv_Y_, self.dtype)
        sv = jnp.asarray(self.sv_X_, self.dtype)
        b = jnp.asarray(self.b_, self.dtype)
        if self.fmap_ is not None:
            # approx families: sv_X_ is MAPPED rows, Xd is raw scaled
            # rows. Single-device scoring runs the FUSED map+decision
            # program (approx_decision_function) — the exact executable
            # serve's bucket cache AOT-compiles, so served scores are
            # bit-identical to this path by construction. The mesh path
            # maps first and uses the flat matmul (the fused program's
            # blocked scan would destroy row sharding).
            if mesh is not None:
                Z = self.fmap_.transform(Xd)
                scores = _decision_flat(Z, sv, coef, b, gamma=0.0,
                                        kernel=self.config.kernel)
            else:
                from tpusvm.approx import approx_decision_function

                params = tuple(jnp.asarray(a) for a in self.fmap_.arrays)
                scores = approx_decision_function(
                    Xd, params, sv, coef, b, family=self.config.kernel)
            return np.asarray(scores[:m])
        args = (Xd, sv, coef, b)
        kern = dict(gamma=self.config.gamma, kernel=self.config.kernel,
                    degree=self.config.degree, coef0=self.config.coef0)
        if mesh is not None:
            # the FLAT matmul: the blocked variant's reshape+scan destroys
            # row sharding (XLA all-gathers the test set onto every
            # device); flat partitions cleanly — see decision_function_flat
            scores = _decision_flat(*args, **kern)
        else:
            scores = _decision(*args, **kern)
        return np.asarray(scores[:m])

    def predict(self, X: np.ndarray, mesh=None) -> np.ndarray:
        # strict > 0 -> +1, the oracle convention (main3.cpp:399)
        return np.where(
            self.decision_function(X, mesh=mesh) > 0, 1, -1
        ).astype(np.int32)

    def score(self, X: np.ndarray, Y: np.ndarray, mesh=None) -> float:
        return float((self.predict(X, mesh=mesh) == np.asarray(Y)).mean())

    # ---------------------------------------------------------- calibration
    def calibrate(self, X: np.ndarray, Y: np.ndarray, folds: int = 3,
                  seed: int = 0) -> "BinarySVC":
        """Fit Platt-scaled predict_proba on held-out fold scores.

        Fits `folds` clones on stratified train splits (the same
        deterministic tune/folds splits the CV search uses), pools their
        OUT-OF-FOLD decision scores, and fits the Platt sigmoid on that
        pool (tpusvm.kernels.platt — held-out scores are the calibration
        discipline Platt 1999 prescribes; in-sample scores of bound SVs
        would bias the sigmoid overconfident). The sigmoid then maps THIS
        model's decision_function; call after (or before) fit, with the
        same training rows.
        """
        from tpusvm.kernels.platt import fit_platt
        from tpusvm.tune.folds import stratified_kfold

        X = np.asarray(X)
        Y = np.asarray(Y)
        scores = np.empty(len(Y), np.float64)
        for fold in stratified_kfold(Y, folds, seed=seed):
            sub = BinarySVC(
                config=self.config, dtype=self.dtype, scale=self.scale,
                accum_dtype=self.accum_dtype, solver=self.solver,
                solver_opts=self.solver_opts,
            )
            sub.fit(X[fold.train_idx], Y[fold.train_idx])
            scores[fold.val_idx] = sub.decision_function(X[fold.val_idx])
        self.platt_ = fit_platt(scores, Y)
        return self

    def predict_proba(self, X: np.ndarray, mesh=None) -> np.ndarray:
        """(m, 2) class probabilities [P(y=-1), P(y=+1)], Platt-scaled.

        Monotone in decision_function (the fitted A is negative on any
        informative score set). Requires calibrate() first — an
        uncalibrated model has no probability semantics to offer.
        """
        if self.platt_ is None:
            raise RuntimeError(
                "model is not calibrated; call calibrate(X, Y) (or train "
                "with --calibrate) before predict_proba"
            )
        from tpusvm.kernels.platt import platt_proba

        p = platt_proba(self.decision_function(X, mesh=mesh), *self.platt_)
        return np.stack([1.0 - p, p], axis=1)

    @property
    def n_support_(self) -> int:
        self._check_fitted()
        return len(self.sv_alpha_)

    # ---------------------------------------------------------- persistence
    def save(self, path: str) -> None:
        self._check_fitted()
        state = {
            "sv_X": self.sv_X_,
            "sv_Y": self.sv_Y_,
            "sv_alpha": self.sv_alpha_,
            "sv_ids": self.sv_ids_,
            "b": self.b_,
            "scale": self.scale,
        }
        if self.scale:
            state["scaler_min"] = self.scaler_.min_val
            state["scaler_max"] = self.scaler_.max_val
        if self.platt_ is not None:
            state["platt_a"], state["platt_b"] = self.platt_
        # training provenance (format v3): absent in older files, which
        # load with the f32/no-shrink defaults — scoring ignores these
        state["train_precision"] = self.train_precision_
        state["shrink_every"] = self.shrink_every_
        state["shrink_stable"] = self.shrink_stable_
        # cascade/pod provenance (format v4, additive): topology, leaf
        # count, rounds-to-stabilize — absent for single-solver fits
        # and in older files, which load bit-identically without them
        if self.cascade_topology_ is not None:
            state["cascade_topology"] = self.cascade_topology_
            state["cascade_leaves"] = int(self.cascade_leaves_ or 0)
            state["cascade_rounds"] = int(self.cascade_rounds_)
        # approximate-map provenance (format v4): the raw input width
        # for both families, landmark rows + inverse-root weights for
        # nystrom; rff's omega regenerates from the config alone
        if self.fmap_ is not None:
            state.update(self.fmap_.state_entries())
        save_model(path, state, self.config)

    @classmethod
    def load(cls, path: str, dtype=jnp.float32) -> "BinarySVC":
        state, config = load_model(path)
        model = cls(config=config, dtype=dtype, scale=bool(state["scale"]))
        model.sv_X_ = state["sv_X"]
        model.sv_Y_ = state["sv_Y"]
        model.sv_alpha_ = state["sv_alpha"]
        model.sv_ids_ = state["sv_ids"]
        model.b_ = float(state["b"])
        if model.scale:
            model.scaler_ = MinMaxScaler(
                min_val=state["scaler_min"], max_val=state["scaler_max"]
            )
        if "platt_a" in state:
            model.platt_ = (float(state["platt_a"]),
                            float(state["platt_b"]))
        # v1/v2 files predate the training-provenance fields: f32 /
        # no-shrink defaults, bit-identical scoring either way
        if "train_precision" in state:
            model.train_precision_ = str(state["train_precision"])
        if "shrink_every" in state:
            model.shrink_every_ = int(state["shrink_every"])
            model.shrink_stable_ = int(state["shrink_stable"])
        # cascade/pod provenance is optional at every version: absent
        # keys leave the single-solver defaults (None/0)
        if "cascade_topology" in state:
            model.cascade_topology_ = str(state["cascade_topology"])
            model.cascade_leaves_ = int(state["cascade_leaves"])
            model.cascade_rounds_ = int(state["cascade_rounds"])
        if _kernels.is_approx(config.kernel):
            # v4: rebuild the fitted map (rff regenerates omega from the
            # config; nystrom reads its stored landmark/weight arrays) —
            # the loaded model predicts without retraining the map
            from tpusvm.approx import map_from_state

            model.fmap_ = map_from_state(state, config)
            model.n_features_in_ = model.fmap_.n_features_in
        model.status_ = Status.CONVERGED
        return model
