"""Model persistence: save/load = (SV X, Y/coef, alpha, ids, b, scaler, config).

The reference intended but never enabled model persistence — the final-model
dump is commented out (mpi_svm_main3.cpp:754-770: final_sv_ids/labels/
alphas/b.txt). This implements that intent properly as a single .npz
(SURVEY.md §5.4): everything needed to predict — support vectors, duals,
bias, the train-set min/max of the scaler, and the hyperparameters.

Format history:
  v1  binary/OvR RBF classifiers; config carries only numeric fields.
  v2  the kernel/task matrix: config gains the kernel family +
      degree/coef0/epsilon, state may carry a `task` marker ("svr" for
      EpsilonSVR; absent = classification), SVR states store signed
      `sv_coef` instead of (sv_Y, sv_alpha), and calibrated classifiers
      add `platt_a`/`platt_b`.
  v3  the solver speed ladder: state gains the training provenance
      fields `train_precision` ("f32" | "bf16_f32" | "bf16_f32c" |
      "default") and `shrink_every`/`shrink_stable` — which ladder rung
      and shrinking cadence produced the artifact. Scoring never reads
      them.
  v4  the approximate-kernel primal regime (this version): the config
      gains the map parameters `rff_dim`/`map_seed`/`landmarks`
      (tpusvm.approx), and approx-family states carry the map
      provenance — `map_n_features_in` (the RAW input width; sv_X is
      the MAPPED rows) for both families, plus the data-dependent
      `map_landmarks`/`map_weights` arrays for nystrom (rff's omega
      regenerates bit-identically from (d, rff_dim, gamma, map_seed),
      so a saved rff model predicts without retraining OR storing the
      map). Exact-family states are unchanged byte-for-byte.
      v4-ADDITIVE (no version bump — readers gate on key presence, so
      pre-existing v4 files load bit-identically with the keys absent):
      cascade/pod-trained artifacts carry the distributed-training
      provenance `cascade_topology` ("tree" | "star"),
      `cascade_leaves` (worker/leaf count) and `cascade_rounds`
      (rounds to SV-ID stabilization); `tpusvm info` prints them.
      Scoring never reads them.

Compatibility contract: v1/v2/v3 files LOAD — configs predating the
kernel fields default to the implicit RBF family, configs predating the
map fields to the (inert for exact families) map defaults, and states
predating the provenance fields load as f32/no-shrink; all are
bit-identical in scoring to the build that wrote them. Files with an
unknown kernel name fail with a specific error (written by a
newer/tampered tpusvm), never a downstream shape or math error.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict

import numpy as np

from tpusvm import faults
from tpusvm.config import KERNEL_FAMILIES, SVMConfig

_FORMAT_VERSION = 4
_SUPPORTED_VERSIONS = (1, 2, 3, 4)


def _norm(path) -> str:
    # np.savez appends ".npz" to suffix-less paths; normalise so save/load
    # agree on the actual filename
    return path if path.endswith(".npz") else path + ".npz"


def _open_npz(path_or_file):
    """np.load over a path OR a seekable file-like (rewound first).

    The file-like form is the serving registry's staged-load path: the
    artifact bytes are read once (through the ``registry.load`` fault
    point, where corrupt rules can mangle them) and parsed from memory —
    each np.load sniff/read pass rewinds the same buffer."""
    if hasattr(path_or_file, "seek"):
        path_or_file.seek(0)
        return np.load(path_or_file, allow_pickle=False)
    return np.load(_norm(path_or_file), allow_pickle=False)


def _name_of(path_or_file) -> str:
    return (_norm(path_or_file) if isinstance(path_or_file, str)
            else getattr(path_or_file, "name", "<bytes>"))


def is_multiclass_model(path) -> bool:
    """True if the saved model is a OneVsRestSVC state (carries the
    `classes` array; BinarySVC state has no such key). Reads only the zip
    directory — cheap enough to sniff before choosing which class to
    load."""
    with _open_npz(path) as z:
        return "classes" in z.files


def model_task(path) -> str:
    """Artifact kind sniff: "ovr" | "svr" | "svc".

    Dispatch key for loaders (`tpusvm predict`, serve's from_path): OvR
    states carry `classes`, SVR states a `task` marker; anything else is a
    binary classifier (including every v1 file, which predates the
    marker).
    """
    with _open_npz(path) as z:
        if "classes" in z.files:
            return "ovr"
        if "task" in z.files:
            return str(z["task"].item())
    return "svc"


def save_model(path: str, state: Dict[str, Any], config: SVMConfig) -> None:
    """Atomically persist a model artifact (temp file + os.replace).

    The house atomic-write discipline (stream shards, solver
    checkpoints) applied to models: a process killed mid-save — e.g. a
    `tpusvm refresh` dying while writing its output — leaves either the
    previous complete artifact or none, never a truncated .npz that a
    serve --watch loop would then try to stage."""
    out = _norm(path)
    faults.point("models.save", path=out)
    tmp = out + ".tmp.npz"
    np.savez_compressed(
        tmp,
        format_version=_FORMAT_VERSION,
        **state,
        **{f"config_{k}": v for k, v in dataclasses.asdict(config).items()},
    )
    os.replace(tmp, out)


def load_model(path: str):
    """Returns (state dict, SVMConfig).

    Version gate first: artifacts that will be served long after they were
    trained must fail loudly and specifically — a missing field means "not
    a tpusvm model" (or one predating versioning), an unknown version means
    "written by a different tpusvm"; neither may surface as a KeyError from
    whichever state field happens to be read first. The kernel family gets
    the same treatment: a v2 file naming a family this build does not
    implement fails HERE, not as a dispatch error mid-request.
    """
    with _open_npz(path) as z:
        if "format_version" not in z.files:
            raise ValueError(
                f"{_name_of(path)!r} has no format_version field — not a "
                "tpusvm model artifact (or written before format "
                "versioning; retrain and re-save it)"
            )
        version = int(z["format_version"])
        if version not in _SUPPORTED_VERSIONS:
            raise ValueError(
                f"unsupported model format version {version} in "
                f"{_name_of(path)!r}: this build reads versions "
                f"{list(_SUPPORTED_VERSIONS)}"
            )
        cfg_fields = SVMConfig.__dataclass_fields__
        cfg_kwargs = {}
        state = {}
        for key in z.files:
            if key == "format_version":
                continue
            if key.startswith("config_"):
                name = key[len("config_"):]
                if name in cfg_fields:
                    # host-side numpy .item() on an npz scalar, not a
                    # device sync  # tpusvm: disable=JX002
                    val = z[key].item()
                    ftype = cfg_fields[name].type
                    if ftype == "int":
                        cfg_kwargs[name] = int(val)
                    elif ftype == "float":
                        cfg_kwargs[name] = float(val)
                    else:
                        cfg_kwargs[name] = str(val)
            else:
                state[key] = z[key]
    # v1 files predate the kernel fields: absent keys fall through to the
    # SVMConfig defaults — the implicit RBF family they were trained with
    family = cfg_kwargs.get("kernel", "rbf")
    if family not in KERNEL_FAMILIES:
        raise ValueError(
            f"{_name_of(path)!r} names kernel family {family!r}, which this "
            f"build does not implement (supported: {list(KERNEL_FAMILIES)}"
            "); the artifact was written by a newer tpusvm or tampered with"
        )
    return state, SVMConfig(**cfg_kwargs)
