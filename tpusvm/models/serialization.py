"""Model persistence: save/load = (SV X, Y, alpha, ids, b, scaler, config).

The reference intended but never enabled model persistence — the final-model
dump is commented out (mpi_svm_main3.cpp:754-770: final_sv_ids/labels/
alphas/b.txt). This implements that intent properly as a single .npz
(SURVEY.md §5.4): everything needed to predict — support vectors, duals,
bias, the train-set min/max of the scaler, and the hyperparameters.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import numpy as np

from tpusvm.config import SVMConfig

_FORMAT_VERSION = 1


def _norm(path: str) -> str:
    # np.savez appends ".npz" to suffix-less paths; normalise so save/load
    # agree on the actual filename
    return path if path.endswith(".npz") else path + ".npz"


def is_multiclass_model(path: str) -> bool:
    """True if the saved model is a OneVsRestSVC state (carries the
    `classes` array; BinarySVC state has no such key). Reads only the zip
    directory — cheap enough to sniff before choosing which class to
    load."""
    with np.load(_norm(path), allow_pickle=False) as z:
        return "classes" in z.files


def save_model(path: str, state: Dict[str, Any], config: SVMConfig) -> None:
    np.savez_compressed(
        _norm(path),
        format_version=_FORMAT_VERSION,
        **state,
        **{f"config_{k}": v for k, v in dataclasses.asdict(config).items()},
    )


def load_model(path: str):
    """Returns (state dict, SVMConfig).

    Version gate first: artifacts that will be served long after they were
    trained must fail loudly and specifically — a missing field means "not
    a tpusvm model" (or one predating versioning), an unknown version means
    "written by a different tpusvm"; neither may surface as a KeyError from
    whichever state field happens to be read first.
    """
    with np.load(_norm(path), allow_pickle=False) as z:
        if "format_version" not in z.files:
            raise ValueError(
                f"{_norm(path)!r} has no format_version field — not a "
                "tpusvm model artifact (or written before format "
                "versioning; retrain and re-save it)"
            )
        version = int(z["format_version"])
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"unsupported model format version {version} in "
                f"{_norm(path)!r}: this build reads version "
                f"{_FORMAT_VERSION}"
            )
        cfg_fields = {f.name for f in dataclasses.fields(SVMConfig)}
        cfg_kwargs = {}
        state = {}
        for key in z.files:
            if key == "format_version":
                continue
            if key.startswith("config_"):
                name = key[len("config_"):]
                if name in cfg_fields:
                    # host-side numpy .item() on an npz scalar, not a
                    # device sync  # tpusvm: disable=JX002
                    val = z[key].item()
                    ftype = SVMConfig.__dataclass_fields__[name].type
                    cfg_kwargs[name] = int(val) if ftype == "int" else float(val)
            else:
                state[key] = z[key]
    return state, SVMConfig(**cfg_kwargs)
