"""Deterministic drift & staleness detectors over on-disk artifacts.

Every signal the autopilot's retrain decision consumes is computed from
facts already durable on disk or in the obs registry — no sampling, no
wall-clock reads inside the detectors — so the SAME inputs always
produce the SAME decision, and a `--resume`d supervisor replays to the
decisions the killed one would have made:

  feature_drift   appended shards' merged min/max vs the DEPLOYED
                  model's fitted scaler range (the manifest's per-shard
                  stats make "appended" = row_start >= baseline_rows a
                  pure manifest read; the scaler min/max in the artifact
                  IS the fitted stats snapshot). Score: the largest
                  per-feature range escape, relative to the fitted range.
  score_shift     served-score positive-rate of the traffic SINCE the
                  last refresh vs the baseline tallies recorded at swap
                  time (serve's serve.scores_pos/neg registry counters —
                  Server.score_stats). Score: |rate_now - rate_base|.
  row_growth      dataset rows vs the rows recorded at the last refresh
                  (the deployed model's provenance in autopilot_state).
                  Score: new_rows / rows_at_refresh.
  staleness       wall seconds since the last refresh (the clock value
                  is an INPUT, supplied by the supervisor's injectable
                  clock — the registry's staleness_s gauge in-process).
                  Score: seconds / threshold_s.

Each evaluation emits a schema-versioned DriftReport whose JSON is
byte-identical for identical (inputs, seed): detector thresholds get a
deterministic per-(seed, tick, detector) jitter — the thundering-herd
de-synchronizer — drawn from the FaultPlan's rng-derivation discipline
(`default_rng(seed ^ crc32(tick:name))`), so the jitter is reproducible
by seed, not time.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Dict, List, Optional

import numpy as np

DRIFT_SCHEMA_VERSION = 1


@dataclasses.dataclass
class DetectorResult:
    """One detector's verdict. `score` is normalised so that
    triggered == (score >= threshold); threshold carries the applied
    (jittered) value, base_threshold the configured one."""

    name: str
    score: float
    threshold: float
    base_threshold: float
    triggered: bool
    detail: Dict[str, float]

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "score": float(self.score),
            "threshold": float(self.threshold),
            "base_threshold": float(self.base_threshold),
            "triggered": bool(self.triggered),
            "detail": {k: (float(v) if isinstance(v, (int, float,
                                                      np.floating,
                                                      np.integer))
                           else v)
                       for k, v in sorted(self.detail.items())},
        }


@dataclasses.dataclass
class DriftReport:
    """The per-tick decision record: schema-versioned, reproducible by
    seed (same inputs + seed => byte-identical JSON)."""

    seed: int
    tick: int
    detectors: List[DetectorResult]
    decision: bool
    reason: str
    schema_version: int = DRIFT_SCHEMA_VERSION

    def to_json(self) -> dict:
        return {
            "schema_version": self.schema_version,
            "seed": int(self.seed),
            "tick": int(self.tick),
            "decision": bool(self.decision),
            "reason": self.reason,
            "detectors": [d.to_json() for d in self.detectors],
        }

    def to_json_bytes(self) -> bytes:
        """Canonical bytes: sorted keys, minimal separators. Python's
        repr-based float serialisation is shortest-round-trip, so equal
        float64 inputs serialise to equal bytes."""
        return json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":")).encode()


def _jitter(seed: int, tick: int, name: str, frac: float) -> float:
    """Deterministic threshold jitter in [-frac, +frac] — the FaultPlan
    rng-derivation discipline, so adding a detector never perturbs
    another's draw."""
    if frac <= 0.0:
        return 0.0
    rng = np.random.default_rng(
        (int(seed) ^ zlib.crc32(f"{tick}:{name}".encode())) & 0xFFFFFFFF
    )
    return float(rng.uniform(-frac, frac))


def _result(name: str, score: float, base_thr: float, seed: int,
            tick: int, jitter_frac: float,
            detail: Dict[str, float]) -> DetectorResult:
    thr = base_thr * (1.0 + _jitter(seed, tick, name, jitter_frac))
    return DetectorResult(
        name=name, score=float(score), threshold=float(thr),
        base_threshold=float(base_thr),
        triggered=bool(score >= thr), detail=detail,
    )


def feature_drift(manifest, fitted_min: np.ndarray,
                  fitted_max: np.ndarray, baseline_rows: int) -> dict:
    """Raw feature-range drift facts of the shards appended since
    `baseline_rows` vs the fitted [min, max] (the deployed scaler).

    Pure manifest arithmetic — no shard bytes are read. Returns
    {"score", "frac_escaped", "appended_rows"}; score is the largest
    per-feature escape relative to the fitted range (a degenerate fitted
    range compares absolutely)."""
    fitted_min = np.asarray(fitted_min, np.float64)
    fitted_max = np.asarray(fitted_max, np.float64)
    appended = [s for s in manifest.shards
                if s.row_start >= baseline_rows]
    if not appended:
        return {"score": 0.0, "frac_escaped": 0.0, "appended_rows": 0}
    from tpusvm.stream.stats import merge_stats

    st = merge_stats([s.stats for s in appended])
    rng = fitted_max - fitted_min
    rng = np.where(rng > 0.0, rng, 1.0)
    below = np.maximum(0.0, (fitted_min - st.min_val) / rng)
    above = np.maximum(0.0, (st.max_val - fitted_max) / rng)
    esc = np.maximum(below, above)
    return {
        "score": float(esc.max()),
        "frac_escaped": float(np.mean(esc > 0.0)),
        "appended_rows": int(st.n_rows),
    }


def score_shift(baseline: Dict[str, int], current: Dict[str, int]) -> dict:
    """Positive-rate shift of served scores SINCE the baseline tallies.

    Both inputs are cumulative {pos, neg} counters (Server.score_stats);
    the detector differences them so only post-refresh traffic counts.
    Returns {"score", "window", "rate_now", "rate_base"}."""
    dp = max(0, int(current.get("pos", 0)) - int(baseline.get("pos", 0)))
    dn = max(0, int(current.get("neg", 0)) - int(baseline.get("neg", 0)))
    window = dp + dn
    bp = int(baseline.get("pos", 0))
    bn = int(baseline.get("neg", 0))
    base_total = bp + bn
    rate_base = (bp / base_total) if base_total else 0.5
    rate_now = (dp / window) if window else rate_base
    return {
        "score": abs(rate_now - rate_base),
        "window": window,
        "rate_now": rate_now,
        "rate_base": rate_base,
    }


@dataclasses.dataclass(frozen=True)
class DriftThresholds:
    """The decision surface (autopilot config slice). A None threshold
    disables its detector. jitter_frac spreads each threshold by a
    seeded ±fraction (0 = exact thresholds, the chaos-gate setting)."""

    feature: Optional[float] = 0.10
    growth: Optional[float] = 0.25
    score: Optional[float] = 0.20
    staleness_s: Optional[float] = None
    min_new_rows: int = 1
    min_score_window: int = 32
    jitter_frac: float = 0.0


def evaluate(*, manifest, fitted_min, fitted_max, rows_at_refresh: int,
             since_refresh_s: float,
             score_baseline: Optional[Dict[str, int]],
             score_current: Optional[Dict[str, int]],
             thresholds: DriftThresholds, seed: int,
             tick: int) -> DriftReport:
    """Run every enabled detector and fold them into one DriftReport.

    Decision rule: refresh when ANY detector triggers AND at least
    min_new_rows rows have been appended (a refresh on unchanged data
    would re-fit the identical problem — suppressed with its own
    reason, staleness excepted)."""
    t = thresholds
    dets: List[DetectorResult] = []
    new_rows = max(0, manifest.n_rows - rows_at_refresh)
    if t.feature is not None:
        fd = feature_drift(manifest, fitted_min, fitted_max,
                           rows_at_refresh)
        score = fd.pop("score")
        dets.append(_result("feature_drift", score, t.feature, seed,
                            tick, t.jitter_frac, fd))
    if t.growth is not None:
        growth = new_rows / max(1, rows_at_refresh)
        dets.append(_result(
            "row_growth", growth, t.growth, seed, tick, t.jitter_frac,
            {"new_rows": new_rows, "rows_at_refresh": rows_at_refresh},
        ))
    if t.score is not None and score_baseline is not None \
            and score_current is not None:
        ss = score_shift(score_baseline, score_current)
        score = ss.pop("score")
        if ss["window"] < t.min_score_window:
            # too little post-refresh traffic for the rate to mean
            # anything: report the facts, never trigger
            dets.append(DetectorResult(
                "score_shift", float(score), float("inf"),
                float(t.score), False,
                {**ss, "below_min_window": 1},
            ))
        else:
            dets.append(_result("score_shift", score, t.score, seed,
                                tick, t.jitter_frac, ss))
    if t.staleness_s is not None:
        dets.append(_result(
            "staleness", since_refresh_s / t.staleness_s, 1.0, seed,
            tick, t.jitter_frac,
            {"since_refresh_s": since_refresh_s,
             "threshold_s": t.staleness_s},
        ))
    fired = [d.name for d in dets if d.triggered]
    if not fired:
        decision, reason = False, "no detector triggered"
    elif new_rows < t.min_new_rows and fired != ["staleness"]:
        decision, reason = False, (
            f"suppressed: {new_rows} new rows < min_new_rows="
            f"{t.min_new_rows} (triggered: {', '.join(fired)})"
        )
    else:
        decision, reason = True, f"triggered: {', '.join(fired)}"
    return DriftReport(seed=int(seed), tick=int(tick), detectors=dets,
                       decision=decision, reason=reason)
