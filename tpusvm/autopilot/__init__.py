"""tpusvm.autopilot — the closed-loop online-learning supervisor.

Ties the stream-side half (crash-safe tail-shard appends,
stream/append.py) to the serving-side half PR 14 landed (warm-started
checkpointed refresh + atomic hot-swap) with a supervised daemon:

  drift.py   deterministic, mergeable drift/staleness detectors over
             on-disk artifacts; schema-versioned DriftReport whose JSON
             is byte-identical for identical (inputs, seed)
  state.py   crash-safe autopilot_state.json (atomic, format-versioned,
             CRC-fingerprinted) — decisions and the in-flight refresh
             stage replay across kills
  loop.py    the tick loop: ingest-watch -> drift decision ->
             refresh_fit -> atomic save -> swap, hardened with
             hysteresis, cooldown, a refresh CircuitBreaker and a
             checkpointed fit watchdog

CLI: `tpusvm autopilot`; chaos gate: `python -m tpusvm.faults
autopilot-chaos-smoke`.
"""

from tpusvm.autopilot.drift import (
    DRIFT_SCHEMA_VERSION,
    DetectorResult,
    DriftReport,
    DriftThresholds,
    evaluate,
    feature_drift,
    score_shift,
)
from tpusvm.autopilot.loop import Autopilot, AutopilotConfig
from tpusvm.autopilot.state import (
    STATE_VERSION,
    AutopilotState,
    load_state,
    save_state,
)

__all__ = [
    "DRIFT_SCHEMA_VERSION",
    "STATE_VERSION",
    "Autopilot",
    "AutopilotConfig",
    "AutopilotState",
    "DetectorResult",
    "DriftReport",
    "DriftThresholds",
    "evaluate",
    "feature_drift",
    "load_state",
    "save_state",
    "score_shift",
]
