"""The autopilot supervisor: ingest-watch -> drift decision -> refresh.

The serving-era analogue of the cascade's iterate-until-stable outer
loop: a tick loop that watches the (append-grown) dataset and the
serving metrics, decides via the deterministic drift detectors whether
the deployed model went stale, and drives the existing crash-safe
refresh machinery — warm-started checkpointed fit, atomic save, staged
hot-swap — unattended, surviving every failure along the way with the
PR 7/14 toolbox:

  * hysteresis + cooldown: a noisy detector must trigger `hysteresis`
    consecutive ticks, and a fresh refresh starts a cooldown window —
    retrains cannot thrash;
  * a refresh CircuitBreaker: repeated refresh failures trip it and the
    supervisor degrades to watch-only mode (SUPPRESSED_BREAKER) instead
    of hot-looping a poisoned batch; the half-open probe retries after
    the cooldown (the `watch.py` per-(path,mtime) failure-memory
    discipline, applied to retraining);
  * a watchdog deadline: a too-slow fit is stopped at a checkpointed
    segment boundary (solver.checkpoint.WatchdogTimeout) and RESUMED
    from its own checkpoint on a later tick;
  * retry/backoff (faults.retry) on the dataset-open I/O edge;
  * crash-safe state (autopilot/state.py): every decision input and the
    in-flight refresh stage persist atomically, so a `--resume`d
    supervisor replays to the same decisions and — via the solver
    checkpoint — a bit-identical refit. Chaos-gated by
    `python -m tpusvm.faults autopilot-chaos-smoke`.

Fault points: `autopilot.tick` (per-tick entry), `autopilot.refresh`
(the whole fit/save/swap stage). Obs: autopilot.* counters and gauges
in the process default registry; drift decisions flow to the trace as
`autopilot.drift` events through the faults event sink.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional

from tpusvm import faults
from tpusvm.autopilot.drift import DriftThresholds, evaluate
from tpusvm.autopilot.state import AutopilotState, load_state, save_state
from tpusvm.status import AutopilotStatus


def _registry():
    from tpusvm.obs.registry import default_registry

    return default_registry()


@dataclasses.dataclass
class AutopilotConfig:
    """The supervisor's knobs. Paths: `model_path` is the deployed
    artifact the FIRST refresh warm-starts from (successive refreshes
    chain from the last successfully swapped artifact); `out_path` is
    where refreshed artifacts land (atomic replace — point a
    `serve --watch` directory at it for zero-coordination deploys)."""

    data_dir: str
    model_path: str
    out_path: Optional[str] = None          # default: <model>.refresh.npz
    state_path: Optional[str] = None        # default: data_dir/autopilot_state.json
    name: Optional[str] = None              # hosted model name for swaps
    interval_s: float = 30.0
    thresholds: DriftThresholds = dataclasses.field(
        default_factory=DriftThresholds)
    hysteresis: int = 1
    cooldown_s: float = 0.0
    warm: bool = True
    checkpoint_path: Optional[str] = None
    checkpoint_every: int = 64
    deadline_s: Optional[float] = None      # watchdog (needs checkpoint)
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 60.0
    seed: int = 0
    # fleet observability for a process with no HTTP listener: when set,
    # every tick publishes this process's obs.fleet snapshot payload
    # here (staged + fsync_replace — never torn), and the fleet
    # collector picks it up as a file source
    metrics_snapshot_path: Optional[str] = None

    def resolved(self) -> "AutopilotConfig":
        if self.hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got "
                             f"{self.hysteresis}")
        if self.deadline_s is not None and self.checkpoint_path is None:
            raise ValueError(
                "deadline_s (the fit watchdog) needs checkpoint_path: "
                "the deadline stops the fit at a checkpointed segment "
                "boundary so a later tick can resume it"
            )
        out = self.out_path
        if out is None:
            stem = self.model_path
            if stem.endswith(".npz"):
                stem = stem[:-4]
            out = stem + ".refresh.npz"
        return dataclasses.replace(
            self,
            out_path=out,
            state_path=(self.state_path
                        or os.path.join(self.data_dir,
                                        "autopilot_state.json")),
            name=(self.name
                  or os.path.splitext(os.path.basename(out))[0]),
        )


class Autopilot:
    """The tick loop. Deploy targets, pick exactly one:

      server=    an in-process serve.Server (swaps via Server.swap);
      swap_url=  a running `tpusvm serve` frontend (POST /admin/swap);
      neither    artifact-drop mode — the refreshed .npz lands at
                 out_path and a `serve --watch` loop picks it up.

    `clock` is injectable (tests pin cooldown/staleness/watchdog/breaker
    arithmetic with a fake clock); it must be the same clock domain
    across resumes for cooldowns to replay — the default wall clock is.
    """

    def __init__(self, config: AutopilotConfig, server=None,
                 swap_url: Optional[str] = None,
                 resume: bool = False,
                 clock=time.time,
                 log_fn=print):
        self.cfg = config.resolved()
        self.server = server
        self.swap_url = swap_url
        self._clock = clock
        self.log = log_fn or (lambda msg: None)
        self._io_retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                                      op="autopilot.tick")
        self._scaler_cache = {}
        if resume and os.path.exists(self.cfg.state_path):
            self.state = load_state(self.cfg.state_path)
            if self.state.seed != self.cfg.seed:
                raise ValueError(
                    f"autopilot state {self.cfg.state_path!r} was "
                    f"written with seed {self.state.seed}, this run "
                    f"passes {self.cfg.seed}; decisions would not "
                    "replay — resume with the original seed"
                )
        else:
            ds = self._open_dataset()
            self.state = AutopilotState(
                seed=self.cfg.seed,
                rows_at_refresh=ds.n_rows,
                last_refresh_t=float(self._clock()),
                model_path=self.cfg.model_path,
                score_baseline=self._score_stats(),
            )
        self.breaker = faults.CircuitBreaker(
            threshold=self.cfg.breaker_threshold,
            cooldown_s=self.cfg.breaker_cooldown_s,
            name="autopilot.refresh",
            clock=clock,
        )
        if self.state.breaker is not None:
            self.breaker.restore(self.state.breaker)
        # persist the deployment-time baseline IMMEDIATELY: a supervisor
        # killed before its first tick must not let a resumed
        # incarnation re-baseline on data that grew in between (the
        # drift decision would silently never fire)
        self._save()

    # ------------------------------------------------------------ helpers
    def _open_dataset(self):
        from tpusvm.stream import open_dataset

        return self._io_retry(open_dataset, self.cfg.data_dir)

    def _score_stats(self) -> Optional[dict]:
        if self.server is None or self.cfg.name is None:
            return None
        try:
            return self.server.score_stats(self.cfg.name)
        except KeyError:
            return None  # not hosted (yet): no score-shift signal

    def _fitted_range(self):
        """(min, max) the current donor artifact was scaled with, or
        None for an unscaled model (feature drift then has no fitted
        range to compare against)."""
        path = self.state.model_path
        cached = self._scaler_cache.get(path)
        if cached is not None:
            return cached
        from tpusvm.models.serialization import load_model

        st, _ = load_model(path)
        rng = (None if "scaler_min" not in st
               else (st["scaler_min"], st["scaler_max"]))
        self._scaler_cache[path] = rng
        return rng

    def _save(self) -> None:
        self.state.breaker = self.breaker.snapshot()
        save_state(self.cfg.state_path, self.state)

    # --------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One supervisor step; returns {"status": AutopilotStatus,
        "report": DriftReport, ...}. Refresh failures come back as
        status codes (breaker-counted), never exceptions; what CAN
        propagate is SimulatedKill and tick-edge I/O the run() loop's
        retry-next-tick policy owns (injected tick transients, an
        unreadable dataset)."""
        st = self.state
        st.tick += 1
        faults.point("autopilot.tick", tick=st.tick)
        reg = _registry()
        reg.counter("autopilot.ticks").inc()
        dataset = self._open_dataset()
        rng = self._fitted_range()
        thresholds = self.cfg.thresholds
        if rng is None and thresholds.feature is not None:
            thresholds = dataclasses.replace(thresholds, feature=None)
        now = float(self._clock())
        report = evaluate(
            manifest=dataset.manifest,
            fitted_min=rng[0] if rng else None,
            fitted_max=rng[1] if rng else None,
            rows_at_refresh=st.rows_at_refresh,
            since_refresh_s=max(0.0, now - st.last_refresh_t),
            score_baseline=st.score_baseline,
            score_current=self._score_stats(),
            thresholds=thresholds,
            seed=st.seed,
            tick=st.tick,
        )
        for d in report.detectors:
            reg.gauge("autopilot.drift_score", detector=d.name).set(d.score)
        reg.gauge("autopilot.data_staleness_rows").set(
            float(max(0, dataset.n_rows - st.rows_at_refresh)))
        reg.gauge("autopilot.breaker_open").set(
            0.0 if self.breaker.state == "closed" else 1.0)
        faults.emit("autopilot.drift", tick=st.tick,
                    decision=report.decision, reason=report.reason,
                    report=report.to_json())

        st.consecutive_triggered = (st.consecutive_triggered + 1
                                    if report.decision else 0)
        pending = st.stage != "idle"
        status = AutopilotStatus.WATCHING
        if pending or (report.decision
                       and st.consecutive_triggered >= self.cfg.hysteresis):
            if not pending and now < st.cooldown_until:
                status = AutopilotStatus.SUPPRESSED_COOLDOWN
                reg.counter("autopilot.refreshes_suppressed",
                            reason="cooldown").inc()
            elif not self.breaker.allow():
                status = AutopilotStatus.SUPPRESSED_BREAKER
                reg.counter("autopilot.refreshes_suppressed",
                            reason="breaker").inc()
            else:
                status = self._refresh(dataset)
        elif report.decision:
            status = AutopilotStatus.TRIGGERED_HYSTERESIS
            reg.counter("autopilot.refreshes_suppressed",
                        reason="hysteresis").inc()
        self._save()
        if self.cfg.metrics_snapshot_path is not None:
            self._drop_fleet_snapshot(status)
        return {"status": status, "report": report,
                "tick": st.tick, "rows": dataset.n_rows,
                "generation": st.generation}

    def _drop_fleet_snapshot(self, status: AutopilotStatus) -> None:
        """Publish the on-disk fleet payload (best-effort: telemetry
        must never fail a tick — a full disk loses one drop, not the
        supervisor)."""
        from tpusvm.obs.fleet import snapshot_payload, write_snapshot_file

        try:
            write_snapshot_file(
                self.cfg.metrics_snapshot_path,
                snapshot_payload(
                    "autopilot", self.cfg.name, _registry().snapshot(),
                    status={"stage": self.state.stage,
                            "tick": self.state.tick,
                            "status": status.name,
                            "generation": self.state.generation}))
        except OSError as e:
            self.log(f"autopilot: fleet snapshot drop failed: {e}")

    # ------------------------------------------------------------ refresh
    def _refresh(self, dataset) -> AutopilotStatus:
        from tpusvm.solver.checkpoint import WatchdogTimeout

        st, cfg = self.state, self.cfg
        reg = _registry()
        try:
            faults.point("autopilot.refresh", tick=st.tick)
            if st.stage != "swapping":
                # record the row count the refit consumes BEFORE fitting:
                # a kill between save and swap must not let later appends
                # inflate the provenance
                st.stage = "fitting"
                st.stage_rows = dataset.n_rows
                self._save()
                from tpusvm.serve.refresh import refresh_fit

                X, Y = dataset.load_arrays()
                watchdog = None
                if cfg.deadline_s is not None:
                    deadline = float(self._clock()) + cfg.deadline_s
                    watchdog = lambda: float(self._clock()) >= deadline  # noqa: E731
                refresh_fit(
                    st.model_path, X, Y, out_path=cfg.out_path,
                    checkpoint_path=cfg.checkpoint_path,
                    checkpoint_every=cfg.checkpoint_every,
                    resume=cfg.checkpoint_path is not None,
                    warm=cfg.warm, watchdog=watchdog,
                )
                st.stage = "swapping"
                self._save()
            self._swap()
        except faults.SimulatedKill:
            raise
        except WatchdogTimeout as e:
            # deadline hit between solve segments: the checkpoint is
            # durable, stage stays "fitting", a later eligible tick
            # resumes the SAME fit bit-identically
            self.breaker.record_failure()
            st.failures += 1
            reg.counter("autopilot.refreshes_failed",
                        kind="timeout").inc()
            self.log(f"autopilot: refresh watchdog timeout ({e}); will "
                     "resume from its checkpoint")
            self._save()
            return AutopilotStatus.REFRESH_TIMEOUT
        except Exception as e:  # noqa: BLE001 — every refresh failure is
            # a counted, breaker-fed outcome, never a dead supervisor
            self.breaker.record_failure()
            st.failures += 1
            reg.counter("autopilot.refreshes_failed", kind="error").inc()
            self.log(f"autopilot: refresh FAILED ({type(e).__name__}: "
                     f"{e}); previous generation keeps serving")
            faults.emit("autopilot.refresh_failed", tick=st.tick,
                        error=f"{type(e).__name__}: {e}")
            self._save()
            return AutopilotStatus.REFRESH_FAILED
        self.breaker.record_success()
        now = float(self._clock())
        st.stage = "idle"
        st.refreshes += 1
        st.generation += 1
        st.rows_at_refresh = int(st.stage_rows)
        st.last_refresh_t = now
        st.cooldown_until = now + cfg.cooldown_s
        st.consecutive_triggered = 0
        st.model_path = cfg.out_path   # the refresh chain's new donor
        st.score_baseline = self._score_stats()
        self._scaler_cache.pop(cfg.out_path, None)
        reg.counter("autopilot.refreshes_triggered").inc()
        reg.gauge("autopilot.generation").set(float(st.generation))
        self._save()
        self.log(f"autopilot: refreshed -> generation {st.generation} "
                 f"({st.rows_at_refresh} rows)")
        return AutopilotStatus.REFRESHED

    def _swap(self) -> None:
        cfg = self.cfg
        if self.server is not None:
            self.server.swap(cfg.name, cfg.out_path)
        elif self.swap_url:
            from tpusvm.serve.refresh import swap_via_http

            swap_via_http(self.swap_url, cfg.name,
                          os.path.abspath(cfg.out_path))
        # else: artifact-drop mode — the atomic save already published
        # the new artifact for a `serve --watch` poller

    # ---------------------------------------------------------------- run
    def run(self, max_ticks: Optional[int] = None,
            stop: Optional[threading.Event] = None) -> dict:
        """Tick until stopped (or max_ticks). Unexpected tick errors are
        logged and retried next tick — the supervisor is the component
        that must NOT die quietly."""
        stop = stop or threading.Event()
        done = 0
        last = {}
        while not stop.is_set():
            try:
                last = self.tick()
                self.log(f"autopilot tick {last['tick']}: "
                         f"{last['status'].name} "
                         f"(rows {last['rows']}, generation "
                         f"{last['generation']})")
            except (faults.SimulatedKill, KeyboardInterrupt):
                raise
            except Exception as e:  # noqa: BLE001 — keep supervising
                self.log(f"autopilot: tick error "
                         f"{type(e).__name__}: {e}")
                last = {"status": AutopilotStatus.REFRESH_FAILED,
                        "error": str(e)}
            done += 1
            if max_ticks is not None and done >= max_ticks:
                break
            stop.wait(self.cfg.interval_s)
        return {"ticks": done, "generation": self.state.generation,
                "refreshes": self.state.refreshes,
                "failures": self.state.failures, "last": last}
