"""Crash-safe supervisor state: `autopilot_state.json`.

The autopilot's whole decision memory lives in one atomic,
format-versioned, CRC-fingerprinted JSON file — the solver-checkpoint
discipline applied to the control loop. A `--resume`d supervisor
restores this file and replays to the same decisions (drift evaluation
is a pure function of dataset + state + seed + tick) and, via the
persisted refresh stage marker, finishes an interrupted refresh from
its own checkpoint instead of restarting or double-swapping:

  stage "idle"      no refresh in flight;
  stage "fitting"   a refresh fit was started (its solver checkpoint —
                    if configured — resumes bit-identically);
  stage "swapping"  the refreshed artifact is SAVED and complete
                    (save_model is atomic); only the swap remains.

The CRC covers the canonical JSON payload, so a torn or hand-edited
state file is a named error, never a silently wrong decision replay.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib

from tpusvm import faults
from tpusvm.utils.durable import fsync_replace
from typing import Dict, Optional

STATE_VERSION = 1

STAGES = ("idle", "fitting", "swapping")


@dataclasses.dataclass
class AutopilotState:
    """Everything a tick decision depends on (plus progress counters)."""

    seed: int
    tick: int = 0
    consecutive_triggered: int = 0
    rows_at_refresh: int = 0
    last_refresh_t: float = 0.0       # supervisor clock domain
    cooldown_until: float = 0.0       # supervisor clock domain
    generation: int = 0               # successful refreshes survived
    refreshes: int = 0
    failures: int = 0
    stage: str = "idle"
    stage_rows: int = 0               # rows the in-flight refit consumes
    model_path: str = ""              # current warm-start donor artifact
    score_baseline: Optional[Dict[str, int]] = None
    breaker: Optional[dict] = None    # faults.CircuitBreaker.snapshot()

    def to_json(self) -> dict:
        return {
            "state_version": STATE_VERSION,
            **{f.name: getattr(self, f.name)
               for f in dataclasses.fields(self)},
        }


def _canonical(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()


def save_state(path: str, state: AutopilotState) -> None:
    """Atomic write (temp + os.replace) with a CRC32 fingerprint of the
    canonical payload — a kill mid-write leaves the previous state."""
    if state.stage not in STAGES:
        raise ValueError(f"unknown autopilot stage {state.stage!r}")
    payload = state.to_json()
    obj = {"crc32": zlib.crc32(_canonical(payload)) & 0xFFFFFFFF,
           **payload}
    faults.point("autopilot.state", path=path, stage=state.stage)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write("\n")
    fsync_replace(tmp, path)


def load_state(path: str) -> AutopilotState:
    """Version gate + CRC verification first; corruption and version
    skew are named errors, not wrong replays."""
    with open(path) as f:
        try:
            obj = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"autopilot state {path!r} is not valid JSON ({e}); "
                "delete it to start fresh"
            ) from e
    if "state_version" not in obj:
        raise ValueError(
            f"{path!r} is not a tpusvm autopilot state (no state_version)"
        )
    v = obj["state_version"]
    if v != STATE_VERSION:
        raise ValueError(
            f"unsupported autopilot state version {v!r} in {path!r} "
            f"(this build reads version {STATE_VERSION})"
        )
    crc = obj.pop("crc32", None)
    want = zlib.crc32(_canonical(obj)) & 0xFFFFFFFF
    if crc != want:
        raise ValueError(
            f"autopilot state {path!r} fails its CRC fingerprint "
            f"(stored {crc!r}, computed {want}) — torn write or manual "
            "edit; delete it to start fresh"
        )
    obj.pop("state_version")
    fields = {f.name for f in dataclasses.fields(AutopilotState)}
    unknown = set(obj) - fields
    if unknown:
        raise ValueError(
            f"autopilot state {path!r} carries unknown fields "
            f"{sorted(unknown)} (written by a newer tpusvm?)"
        )
    st = AutopilotState(**obj)
    if st.stage not in STAGES:
        raise ValueError(
            f"autopilot state {path!r} names unknown stage {st.stage!r}"
        )
    return st
