"""Masked working-set selection (Keerthi first-order heuristic) as XLA ops.

TPU-native replacement for the reference's two-phase GPU selection
(gpu_svm_main3.cu:166-239): the mask kernels that write f or +/-INF
(calc_f_in_I_high/low) become a jnp.where, and the multi-launch index-array
tree reductions (calc_i_high/low) become a single jnp.argmin/argmax — XLA
lowers these to native tree reductions on the VPU, so the whole cascade of
kernel launches collapses into one fused op.

Tie-breaking: jnp.argmin/argmax return the FIRST occurrence of the extremum,
which matches the serial oracle's strict-improvement scan (main3.cpp:113-121)
— this is the deterministic tie-break SURVEY.md §7.3 calls for. (The
reference's GPU reduction has launch-order-dependent ties; we standardise on
the serial behaviour.)
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def i_high_mask(alpha, y, C, eps, valid=None):
    """I_high = {y=+1, a < C-eps} u {y=-1, a > eps} (main3.cpp:115)."""
    m = jnp.where(y == 1, alpha < C - eps, (y == -1) & (alpha > eps))
    if valid is not None:
        m = m & valid
    return m


def i_low_mask(alpha, y, C, eps, valid=None):
    """I_low = {y=+1, a > eps} u {y=-1, a < C-eps} (main3.cpp:134)."""
    m = jnp.where(y == 1, alpha > eps, (y == -1) & (alpha < C - eps))
    if valid is not None:
        m = m & valid
    return m


def masked_argmin(f, mask) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(first argmin of f over mask, any(mask))."""
    vals = jnp.where(mask, f, jnp.inf)
    return jnp.argmin(vals), jnp.any(mask)


def masked_argmax(f, mask) -> Tuple[jnp.ndarray, jnp.ndarray]:
    vals = jnp.where(mask, f, -jnp.inf)
    return jnp.argmax(vals), jnp.any(mask)
