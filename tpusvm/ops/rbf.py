"""RBF kernel primitives as XLA-friendly JAX ops.

TPU-native replacements for the reference's CUDA kernel computations:
  - `rbf_row` / `rbf_rows_at` <- calc_kernel_matrix with n1=1
    (gpu_svm_main3.cu:137-147, launched per SMO iteration at :400/:409);
  - `rbf_cross` <- the general K(X1, X2) tile kernel, used for prediction
    (gpu_svm_main3.cu:277-296) — expressed as one big matmul so XLA tiles it
    onto the MXU;
  - `rbf_matvec` <- the warm-start f reconstruction
    sum_j coef_j K(x_j, x_i) (mpi_svm_main3.cpp:160-186), blocked so the
    (n, n) kernel matrix is never materialised.

Two formulations are provided:
  - direct:  exp(-g * sum((X - x)^2))             — elementwise, VPU-bound,
    numerically closest to the reference's per-pair loop;
  - dot:     exp(-g * (|X|^2 + |x|^2 - 2 X @ x))  — one matmul on the MXU,
    used whenever there is a batch dimension to amortise it over.

The dot form can produce tiny negative squared distances in low precision;
they are clamped at 0 before the exp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusvm.config import RAW_BF16, resolve_matmul_precision

# Matmul precision for the distance dot-products. TPU MXUs compute f32
# matmuls in bfloat16 passes when asked for jax precision="default"
# (~1e-2 absolute error on [0,1]^d Gram entries) — enough to perturb the
# SMO trajectory and break SV-set parity with the f64 oracle (the
# reference's correctness criterion, SURVEY.md §4). "float32" forces
# full-f32-equivalent MXU passes. FOOTGUN, now closed: precision=
# "default" READS like "no preference" but REQUESTS raw bf16 — every
# precision knob in this module therefore routes through
# tpusvm.config.resolve_matmul_precision, which raises on the "default"
# spelling and admits raw bf16 only as the unmistakable
# config.RAW_BF16 token (the blocked solver emits it after validating
# its refine drift guard). CPU/GPU backends ignore the precision= hint
# (always true f32); the bf16_f32* rungs ROUND OPERANDS and so behave
# identically on every backend.
DEFAULT_PRECISION = "float32"


def _prec(precision):
    """Resolved token -> the jax `precision=` argument for plain matmuls.

    The bf16_f32* rungs are not expressible as a precision hint (they
    cast operands); contractions that support them go through matmul_p.
    """
    p = resolve_matmul_precision(precision)
    if p in ("bf16_f32", "bf16_f32c"):
        raise ValueError(
            f"precision={p!r} casts operands to bfloat16 and is only "
            "implemented for the laddered contractions (ops.rbf.matmul_p "
            "call sites: the solver f-update / K-row refresh); this "
            "computation runs at the trust-anchor tiers only"
        )
    return "default" if p == RAW_BF16 else p


def matmul_p(A: jax.Array, B: jax.Array, precision=None) -> jax.Array:
    """A @ B at the requested precision rung — the laddered contraction.

    The solver's dominant cost (the (n, d) x (d, q) f-update distance
    dot and the K-row refresh) routes through here so every rung of the
    speed ladder is requested the same explicit way:

      "float32"/"highest": plain matmul at the full-f32 trust tier.
      "bf16_f32":  operands ROUNDED to bfloat16, accumulated in f32
        (preferred_element_type) — single-pass MXU throughput; the only
        loss is the ~2^-9 relative operand rounding. Backend-independent
        semantics: CPU runs round the same operands, so cross-precision
        parity harnesses exercise the real arithmetic off-TPU.
      "bf16_f32c": compensated — adds (A - bf16(A)) @ bf16(B), the
        residual of the LEFT operand (the streamed X block, which
        dominates the rounding error budget; B is the q-sized working
        set). ~2x the matmul cost, still under full-f32 emulation's ~3x.
      RAW_BF16: raw single-pass bf16 (jax precision="default").

    Output dtype is f32 for the bf16 rungs (the f32 accumulator),
    A's promotion otherwise — callers cast to their accumulator dtype,
    exactly as they do for the plain matmul.
    """
    p = resolve_matmul_precision(precision)
    if p in ("bf16_f32", "bf16_f32c"):
        Ab = A.astype(jnp.bfloat16)
        Bb = B.astype(jnp.bfloat16)
        out = jnp.matmul(Ab, Bb, preferred_element_type=jnp.float32)
        if p == "bf16_f32c":
            resid = (A.astype(jnp.float32)
                     - Ab.astype(jnp.float32)).astype(jnp.bfloat16)
            out = out + jnp.matmul(resid, Bb,
                                   preferred_element_type=jnp.float32)
        return out
    return jnp.matmul(A, B, precision=_prec(p))


def coef_matvec(K: jax.Array, coef: jax.Array, precision=None) -> jax.Array:
    """K @ coef at the trust tier — the coefficient epilogue of every
    kernel contraction (f updates, prediction scores, warm-start sums).

    The ladder rungs apply to the STREAMED distance/dot contraction
    (matmul_p): that is where the FLOPs and HBM traffic live. The
    coefficient matvec that follows is O(rows * q) — noise next to the
    O(rows * d * q) main contraction — so rounding it buys nothing and
    costs accuracy; it runs at full f32 on every rung except an explicit
    RAW_BF16 request. Routing it here (instead of a bare `K @ coef`,
    whose dot_general carries jax's DEFAULT precision = raw single-pass
    bf16 on TPU MXUs) is what the JXIR101 IR audit and the JX010 lint
    rule enforce: no contraction reaches the MXU without an explicit
    precision.
    """
    return jnp.matmul(K, coef, precision=_prec(_norm_prec(precision)))


def _norm_prec(precision):
    """Precision for the row-norm prologues of a laddered contraction:
    the bf16 rungs keep their norms at the trust anchor (norms feed the
    distance formula's cancellation — rounding them costs accuracy for
    no bandwidth win; they are O(n*d) once, not per-round)."""
    p = resolve_matmul_precision(precision)
    return None if p in ("bf16_f32", "bf16_f32c") else p


def sq_norms(X: jax.Array, precision=None) -> jax.Array:
    """Per-row squared norms |x_i|^2, shape (n,)."""
    return jnp.einsum("nd,nd->n", X, X, precision=_prec(precision))


def rbf_row(X: jax.Array, x: jax.Array, gamma, precision=None) -> jax.Array:
    """K(x, X[j]) for all j via the direct formulation. Shape (n,)."""
    diff = X - x[None, :]
    return jnp.exp(-gamma * jnp.einsum("nd,nd->n", diff, diff,
                                       precision=_prec(precision)))


def rbf_rows_at(X: jax.Array, idx: jax.Array, gamma,
                sn: jax.Array | None = None, precision=None) -> jax.Array:
    """K(X[idx[k]], X[j]) for a small static-size index vector idx.

    The SMO hot loop needs the i_high and i_low rows together; this computes
    them as ONE (n, d) x (d, k) MXU matmul via the dot formulation
    |x_i|^2 + |x_j|^2 - 2 x_i.x_j, so X is streamed from HBM exactly once
    per refresh — half the traffic of two independent row computations and
    of the broadcast-subtract formulation. Shape (len(idx), n).

    Precision: f32 cancellation in the dot form contributes ~1e-7 relative
    error on squared distances — at the reference's gamma=0.00125 that is
    ~1e-8 absolute on the exp argument, far below the solver's tau=1e-5.
    Negative rounding artifacts are clamped at 0. Pass precomputed sq_norms
    to avoid re-reading X.
    """
    Xi = X[idx]  # (k, d)
    if sn is None:
        sn = sq_norms(X, _norm_prec(precision))
    d2 = (sn[idx][:, None] + sn[None, :]
          - 2.0 * matmul_p(Xi, X.T, precision))
    d2 = jnp.maximum(d2, 0.0)
    return jnp.exp(-gamma * d2)


def rbf_rows_at_direct(X: jax.Array, idx: jax.Array, gamma,
                       precision=None) -> jax.Array:
    """rbf_rows_at via the broadcast (X - x)^2 formulation.

    Numerically identical to the serial oracle's per-pair loop (no dot-trick
    cancellation); ~2x the HBM traffic. Used when trajectory-level closeness
    to the f64 oracle matters more than speed.
    """
    Xi = X[idx]  # (k, d)
    diff = X[None, :, :] - Xi[:, None, :]  # (k, n, d)
    d2 = jnp.einsum("knd,knd->kn", diff, diff, precision=_prec(precision))
    return jnp.exp(-gamma * d2)


def rbf_cross(XA: jax.Array, XB: jax.Array, gamma,
              snA: jax.Array | None = None, snB: jax.Array | None = None,
              precision=None) -> jax.Array:
    """Full K(XA, XB) kernel matrix, shape (nA, nB). MXU matmul."""
    if snA is None:
        snA = sq_norms(XA, precision)
    if snB is None:
        snB = sq_norms(XB, precision)
    d2 = (snA[:, None] + snB[None, :]
          - 2.0 * jnp.matmul(XA, XB.T, precision=_prec(precision)))
    d2 = jnp.maximum(d2, 0.0)
    return jnp.exp(-gamma * d2)


def rbf_cross_matvec(
    X: jax.Array, XB: jax.Array, coef: jax.Array, gamma,
    sn: jax.Array | None = None, block: int = 8192, precision=None,
) -> jax.Array:
    """sum_k coef_k K(x_i, xb_k) for all i, blocked over i. Shape (n,).

    The blocked SMO solver's global error-vector update: after a working-set
    subproblem changes q alphas, f moves by K(X, X_B) @ (dalpha * y_B) — one
    (n, d) x (d, q) MXU contraction streamed in n-blocks so the (n, q)
    kernel slab is never materialised. This is where the blocked solver's
    FLOPs live, and it is exactly the shape the MXU wants.

    Pass precomputed sn = sq_norms(X) when calling in a loop. Blocks are
    taken with dynamic slices (no padded copy of X); when block does not
    divide n, the final block's start is clamped so it re-reads trailing
    rows, and the overlapping writes carry identical values.
    """
    n, d = X.shape
    block = min(block, n)
    nb = -(-n // block)
    if sn is None:
        sn = sq_norms(X, _norm_prec(precision))
    snB = sq_norms(XB, _norm_prec(precision))
    coef = coef.astype(X.dtype)

    def step(_, start):
        zero = jnp.zeros((), start.dtype)
        Xblk = jax.lax.dynamic_slice(X, (start, zero), (block, d))
        snblk = jax.lax.dynamic_slice(sn, (start,), (block,))
        d2 = (snblk[:, None] + snB[None, :]
              - 2.0 * matmul_p(Xblk, XB.T, precision))
        d2 = jnp.maximum(d2, 0.0)
        return None, coef_matvec(jnp.exp(-gamma * d2), coef, precision)

    starts = jnp.minimum(
        jnp.arange(nb, dtype=jnp.int32) * block, max(n - block, 0)
    )
    _, chunks = jax.lax.scan(step, None, starts)

    # Reassemble with static slices, not an (n,)-sized scatter (scatters
    # lower poorly on TPU and this runs once per outer solver round): every
    # block but the last is contiguous at start i*block; the clamped last
    # block covers [n-block, n), whose first nb*block-n rows duplicate
    # values already written by the body and are dropped.
    body = chunks[:-1].reshape(-1)
    tail = chunks[-1, (nb * block - n):]
    return jnp.concatenate([body, tail]).astype(X.dtype)


def rbf_matvec(X: jax.Array, coef: jax.Array, gamma, block: int = 1024,
               precision=None) -> jax.Array:
    """sum_j coef_j K(x_j, x_i) for all i, without materialising K.

    Scans over j-blocks: each step is an (n, block) MXU matmul + exp + matvec.
    Used for the cascade's warm-start f reconstruction. Shape (n,).
    """
    n, d = X.shape
    nb = -(-n // block)
    pad = nb * block - n
    Xp = jnp.pad(X, ((0, pad), (0, 0)))
    cp = jnp.pad(coef, (0, pad))  # padded rows have coef 0 -> no contribution
    sn = sq_norms(X, precision)

    Xb = Xp.reshape(nb, block, d)
    cb = cp.reshape(nb, block)
    snb = sq_norms(Xp, precision).reshape(nb, block)

    def step(acc, args):
        Xj, cj, snj = args
        d2 = (sn[:, None] + snj[None, :]
              - 2.0 * jnp.matmul(X, Xj.T, precision=_prec(precision)))
        d2 = jnp.maximum(d2, 0.0)
        return acc + coef_matvec(jnp.exp(-gamma * d2), cj, precision), None

    acc0 = jnp.zeros((n,), X.dtype)
    acc, _ = jax.lax.scan(step, acc0, (Xb, cb, snb))
    return acc
