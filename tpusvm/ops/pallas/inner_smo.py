"""Pallas TPU kernel: the ENTIRE blocked-SMO inner subproblem in one launch.

The blocked solver (solver/blocked.py) spends ~85% of its wall-clock in the
inner working-set subproblem: up to max_inner sequential 2-variable SMO
updates over a VMEM-sized K_BB. Expressed as an XLA `lax.while_loop`, each
tiny O(q) iteration costs ~36us of fixed per-op dispatch overhead on this
TPU runtime (measured with benchmarks/probe_split.py: 84k updates = 3.4s of
a 4.1s MNIST-60k solve). This kernel fuses the whole subproblem — working
-set selection, analytic pair update, f/alpha updates, and the termination
cascade — into ONE kernel launch with K_BB resident in VMEM, so each inner
iteration is a handful of VPU ops on sublane-packed (q//128, 128) vectors
instead of a dispatched XLA op graph.

This is the TPU-native analogue of how GPU SVM solvers run the subproblem in
a single thread block against shared-memory K (the design the reference's
own literature uses — SURVEY.md §2 papers list); the reference itself pays a
host round-trip per update (gpu_svm_main3.cu:363-467, 9 memcpys/iter), which
SURVEY.md §3.2 flags as the structural inefficiency to eliminate.

Semantics match solver/blocked.py's `_inner_smo` (same selection rule, same
shared `pair_update` scalar step from solver/analytic.py) with two
deviations:
  - float32 compute (TPU VPU/Mosaic has no f64). The outer loop re-derives
    the global f in the accum dtype each round, so inner f32 drift is
    bounded by one subproblem and reset every outer round; convergence is
    still judged on the accum-dtype global f.
  - SHRINKING instead of bail-out: where `_inner_smo` ends the subproblem
    on a zero-progress pair (box-pinned, infeasible [U,V], or eta <= eps —
    deterministic re-selection would spin), this kernel deactivates that
    pair's i_low for the rest of the subproblem and keeps going, so the
    possible end reasons are only CONVERGED / NO_WORKING_SET / MAX_ITER.
    f32 hits zero-progress pairs mid-optimisation (measured: a box-pinned
    pair 12 rounds into MNIST-60k with b-gap still 0.42) where f64 happens
    to take a different trajectory; shrinking makes the subproblem finish
    its violator budget regardless.

Alignment: q % 128 == 0 (lane width). Callers fall back to the XLA inner
loop for small/unaligned working sets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusvm.solver.analytic import pair_update
from tpusvm.status import Status

LANE = 128

# the only statuses this kernel can end with (shrinking replaces the
# INFEASIBLE_UV / NONPOS_ETA / STALLED bail-outs — see module docstring)
_RUNNING = int(Status.RUNNING)
_CONVERGED = int(Status.CONVERGED)
_NO_WS = int(Status.NO_WORKING_SET)
_MAX_ITER = int(Status.MAX_ITER)


def _make_kernel(q: int, max_inner: int, wss: int, R: int, L: int):
    # Working vectors are laid out (R, L): the "packed" layout uses
    # (q//128, 128) so a vector occupies full 8-sublane vregs instead of
    # 1 of 8 as the original "flat" (1, q) layout did — every elementwise
    # op stops wasting 7/8 of VPU throughput. The row-major layout keeps
    # the global index of element (r, c) at r*L + c, preserving the
    # (1, q) ordering, so first-occurrence tie-breaks (and hence the
    # whole iteration trajectory) are identical between layouts. The
    # flat layout (R=1, L=q) is retained as the fallback lowering proven
    # on hardware in round 1.

    def kernel(scal_ref, K_ref, diag_ref, y_ref, a0_ref, f0_ref, act_ref,
               diag_s_ref, y_s_ref, a0_s_ref, aout_ref, stat_ref, a_s_ref):
        iota = (lax.broadcasted_iota(jnp.int32, (R, L), 0) * L
                + lax.broadcasted_iota(jnp.int32, (R, L), 1))

        def pick(v, i):
            """v at global index i for a traced scalar i, as a masked
            reduction (no dynamic scalar addressing into loop-carried
            values on the VPU). Used only where the value lives in vector
            registers (a freshly loaded K row, the current f); everything
            with a static home (y, diag) or a maintained mirror (alpha)
            reads from SMEM in O(1) instead — each pick is a full
            cross-lane reduction, and they dominated the original
            kernel's 8.2us/update."""
            return jnp.sum(jnp.where(iota == i, v, 0.0))

        C = scal_ref[0]
        eps = scal_ref[1]
        tau = scal_ref[2]
        y = y_ref[:]                      # (R, L) float32, +/-1 (0 on pads)
        diag = diag_ref[:]                # (R, L) K_BB diagonal
        pos = y > 0.0

        # SMEM alpha mirror: scalar reads (a[i_h], a[i_l]) and the two
        # per-iteration writes are O(1) on the scalar core, replacing
        # masked-sum reductions over the whole working set. The vector
        # alpha stays loop-carried for the mask computations; both are
        # updated with the same f32 deltas, so they cannot drift.
        def copy(i, _):
            a_s_ref[i] = a0_s_ref[i]
            return 0

        lax.fori_loop(0, q, copy, 0)

        def cond(st):
            return st[5] == _RUNNING

        def body(st):
            # act carried as a f32 mask: Mosaic can't lay out i1 vector
            # carries in scf.while
            a, f, act_f, n_upd, progress, _ = st
            act = act_f > 0.5
            # boolean algebra, not jnp.where over bools: Mosaic can't lower
            # i8->i1 vector select operands
            lo = a > eps
            hi = a < C - eps
            m_h = act & ((pos & hi) | (~pos & lo))
            m_l = act & ((pos & lo) | (~pos & hi))

            vh = jnp.where(m_h, f, jnp.inf)
            b_h = jnp.min(vh)
            i_h = jnp.min(jnp.where(vh == b_h, iota, jnp.int32(q)))
            vl = jnp.where(m_l, f, -jnp.inf)
            b_l = jnp.max(vl)
            if wss == 1:
                i_l = jnp.min(jnp.where(vl == b_l, iota, jnp.int32(q)))
                i_l = jnp.minimum(i_l, jnp.int32(q - 1))

            # emptiness check without jnp.any (whose Mosaic lowering goes
            # through an f64 squeeze under x64): masked-out lanes are +/-inf,
            # and live f values are always finite
            found = (b_h < jnp.inf) & (b_l > -jnp.inf)
            converged = found & (b_l <= b_h + 2.0 * tau)
            proceed = found & ~converged

            # clamp so the row loads stay in bounds when not found (i == q)
            i_h = jnp.minimum(i_h, jnp.int32(q - 1))

            row_h = K_ref[pl.ds(i_h, 1)].reshape(R, L)
            K11 = diag_s_ref[i_h]

            if wss == 2:
                # second-order partner choice (the maximal-gain heuristic of
                # LIBSVM's WSS2, free here because row_h is already in
                # VMEM): among violating I_low members, maximise
                # (f_j - b_h)^2 / eta_j. The Keerthi STOP check above stays
                # on the global (b_h, b_l) pair regardless. NOTE: a
                # degenerate partner (true eta <= eps; the clamp below
                # makes its gain huge) CAN win this argmax — the kernel
                # then self-heals by SHRINKING the dead pair (see the
                # zero-progress policy below), where the XLA loop instead
                # excludes such partners from selection up front
                # (solver/blocked.py _inner_smo, fuzz seed 4047). Same
                # optimum; folding the exclusion in here awaits a hardware
                # measurement (one more reduction in the hot loop).
                eta_vec = jnp.maximum(K11 + diag - 2.0 * row_h, 1e-12)
                viol = m_l & (f > b_h)
                vg = jnp.where(viol, (f - b_h) ** 2 / eta_vec, -jnp.inf)
                g = jnp.max(vg)
                i_l2 = jnp.min(jnp.where(vg == g, iota, jnp.int32(q)))
                # the second-order pick IS the i_low (no first-order
                # fallback reduction): whenever this iteration proceeds, a
                # violating partner exists — viol empty means no f in I_low
                # exceeds b_h, so b_l <= b_h < b_h + 2*tau and the
                # iteration exits as converged (or not-found) with zero
                # deltas, so the i_l=0 index that an all-(-inf) vg yields
                # is used only for in-bounds loads and zero-delta stores
                i_l = jnp.minimum(i_l2, jnp.int32(q - 1))

            row_l = K_ref[pl.ds(i_l, 1)].reshape(R, L)
            K22 = diag_s_ref[i_l]
            K12 = pick(row_h, i_l)   # row_h is in vector registers
            y_h = y_s_ref[i_h]
            y_l = y_s_ref[i_l]
            a_h = a_s_ref[i_h]
            a_l = a_s_ref[i_l]
            # the 2-variable step uses the SELECTED pair's f values; with
            # first-order selection f[i_l] == b_l exactly. For wss=2,
            # f[i_l] is reconstructed from the selected gain instead of a
            # cross-lane pick: g = (f[i_l]-b_h)^2/eta_clamped at exactly
            # this lane (eta_clamped recomputed below from the same K11/
            # K22/K12 scalars), so sqrt(g*eta_clamped) recovers
            # f[i_l]-b_h (> 0 for violators) to f32 rounding. When no
            # violator exists g==-inf, but then the iteration exits with
            # zero deltas, so the maximum(g, 0) placeholder is unused.
            if wss == 2:
                eta_l = jnp.maximum(K11 + K22 - 2.0 * K12, 1e-12)
                b_l_pair = b_h + jnp.sqrt(jnp.maximum(g, 0.0) * eta_l)
            else:
                b_l_pair = b_l

            upd = pair_update(K11, K22, K12, y_h, y_l, a_h, a_l, b_h,
                              b_l_pair, C, eps, proceed)

            f = f + upd.da_h * y_h * row_h + upd.da_l * y_l * row_l
            a = (a + jnp.where(iota == i_h, upd.da_h, 0.0)
                   + jnp.where(iota == i_l, upd.da_l, 0.0))
            # keep the SMEM mirror in lockstep (deltas are 0 when the
            # iteration did not update, so the stores are always safe; an
            # i_h == i_l coincidence implies eta == 0 -> zero deltas)
            a_s_ref[i_h] = a_h + upd.da_h
            a_s_ref[i_l] = a_l + upd.da_l
            ok = upd.do_update & ~upd.stalled
            n_upd = n_upd + ok.astype(jnp.int32)
            progress = jnp.maximum(progress, ok.astype(jnp.int32))

            # SHRINKING: a pair that yields zero progress (box-pinned pair,
            # U > V, or eta <= eps — all deterministic given (i_h, i_l), so
            # re-selecting it would spin forever, which is exactly how the
            # f32 subproblem stalls mid-optimisation) deactivates its i_low
            # for the REST OF THIS SUBPROBLEM ONLY; selection then moves to
            # the next violator. The outer round rebuilds the working set
            # with full masks, so nothing leaks out. Termination: every
            # iteration either updates (bounded by max_inner) or deactivates
            # one index (bounded by q).
            dead = proceed & (~upd.feasible | ~upd.eta_ok | upd.stalled)
            act_f = jnp.where(dead & (iota == i_l), 0.0, act_f)

            # explicit int32 constants: under jax_enable_x64 bare python ints
            # promote to int64, which Mosaic cannot lower
            reason = jnp.where(
                ~found,
                jnp.int32(_NO_WS),
                jnp.where(
                    converged,
                    jnp.int32(_CONVERGED),
                    jnp.where(
                        n_upd >= max_inner,
                        jnp.int32(_MAX_ITER),
                        jnp.int32(_RUNNING),
                    ),
                ),
            )
            return (a, f, act_f, n_upd, progress, reason)

        a, _f, _act, n_upd, progress, reason = lax.while_loop(
            cond, body,
            (a0_ref[:], f0_ref[:], act_ref[:], jnp.int32(0),
             jnp.int32(0), jnp.int32(_RUNNING)),
        )
        aout_ref[:] = a
        stat_ref[0] = n_upd
        stat_ref[1] = progress
        stat_ref[2] = reason

    return kernel


@functools.partial(
    jax.jit, static_argnames=("max_inner", "interpret", "wss", "layout")
)
def inner_smo_pallas(K_BB, y_B, a_B, f_B, active_B, C, eps, tau, *,
                     max_inner: int, interpret: bool = False, wss: int = 1,
                     layout: str = "packed"):
    """Run the inner working-set SMO subproblem as one fused TPU kernel.

    Same contract as solver/blocked.py `_inner_smo`: returns
    (a_B_new, n_updates, made_progress, end_reason). Inputs may be any float
    dtype; compute is float32 (see module docstring), and a_B_new comes back
    in a_B's dtype.

    wss=1 selects i_low by first-order Keerthi argmax-f (the reference's
    heuristic, main3.cpp:124-142); wss=2 selects the maximal-gain partner
    (second-order) while keeping the reference's stopping rule.
    """
    if wss not in (1, 2):
        raise ValueError(f"wss must be 1 or 2, got {wss}")
    if layout not in ("packed", "flat"):
        raise ValueError(f"layout must be packed|flat, got {layout!r}")
    q = y_B.shape[0]
    if q % LANE:
        raise ValueError(f"inner_smo_pallas needs q % {LANE} == 0, got {q}")
    # packed = full-vreg sublane utilisation; flat = the (1, q) layout
    # proven on hardware in round 1 (kept as a lowering fallback)
    R, L = (q // LANE, LANE) if layout == "packed" else (1, q)
    scal = jnp.stack([
        jnp.asarray(C, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(tau, jnp.float32),
    ])
    K32 = K_BB.astype(jnp.float32)
    diag32 = jnp.diagonal(K32)
    y32 = y_B.astype(jnp.float32)
    a32 = a_B.astype(jnp.float32)
    aout, stat = pl.pallas_call(
        _make_kernel(q, max_inner, wss, R, L),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            # (q,) SMEM copies of diag / y / a0 for O(1) scalar reads in
            # the hot loop (the VMEM copies above serve the vector math)
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, L), jnp.float32),
            jax.ShapeDtypeStruct((3,), jnp.int32),
        ],
        scratch_shapes=[pltpu.SMEM((q,), jnp.float32)],  # alpha mirror
        interpret=interpret,
    )(
        scal,
        K32.reshape(q, R, L),
        diag32.reshape(R, L),
        y32.reshape(R, L),
        a32.reshape(R, L),
        f_B.astype(jnp.float32).reshape(R, L),
        active_B.astype(jnp.float32).reshape(R, L),
        diag32,
        y32,
        a32,
    )
    return (aout.reshape(q).astype(a_B.dtype), stat[0], stat[1] > 0, stat[2])
