"""Pallas TPU kernel: the ENTIRE blocked-SMO inner subproblem in one launch.

The blocked solver (solver/blocked.py) spends ~85% of its wall-clock in the
inner working-set subproblem: up to max_inner sequential 2-variable SMO
updates over a VMEM-sized K_BB. Expressed as an XLA `lax.while_loop`, each
tiny O(q) iteration costs ~36us of fixed per-op dispatch overhead on this
TPU runtime (measured with benchmarks/probe_split.py: 84k updates = 3.4s of
a 4.1s MNIST-60k solve). This kernel fuses the whole subproblem — working
-set selection, analytic pair update, f/alpha updates, and the termination
cascade — into ONE kernel launch with K_BB resident in VMEM, so each inner
iteration is a handful of VPU ops on sublane-packed (q//128, 128) vectors
instead of a dispatched XLA op graph.

This is the TPU-native analogue of how GPU SVM solvers run the subproblem in
a single thread block against shared-memory K (the design the reference's
own literature uses — SURVEY.md §2 papers list); the reference itself pays a
host round-trip per update (gpu_svm_main3.cu:363-467, 9 memcpys/iter), which
SURVEY.md §3.2 flags as the structural inefficiency to eliminate.

Semantics match solver/blocked.py's `_inner_smo` (same selection rule, same
shared `pair_update` scalar step from solver/analytic.py) with two
deviations:
  - float32 compute (TPU VPU/Mosaic has no f64). The outer loop re-derives
    the global f in the accum dtype each round, so inner f32 drift is
    bounded by one subproblem and reset every outer round; convergence is
    still judged on the accum-dtype global f.
  - SHRINKING instead of bail-out: where `_inner_smo` ends the subproblem
    on a zero-progress pair (box-pinned, infeasible [U,V], or eta <= eps —
    deterministic re-selection would spin), this kernel deactivates that
    pair's i_low for the rest of the subproblem and keeps going, so the
    possible end reasons are only CONVERGED / NO_WORKING_SET / MAX_ITER.
    f32 hits zero-progress pairs mid-optimisation (measured: a box-pinned
    pair 12 rounds into MNIST-60k with b-gap still 0.42) where f64 happens
    to take a different trajectory; shrinking makes the subproblem finish
    its violator budget regardless.

Alignment: q % 128 == 0 (lane width). Callers fall back to the XLA inner
loop for small/unaligned working sets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusvm.solver.analytic import pair_update
from tpusvm.status import Status

LANE = 128

# the only statuses this kernel can end with (shrinking replaces the
# INFEASIBLE_UV / NONPOS_ETA / STALLED bail-outs — see module docstring)
_RUNNING = int(Status.RUNNING)
_CONVERGED = int(Status.CONVERGED)
_NO_WS = int(Status.NO_WORKING_SET)
_MAX_ITER = int(Status.MAX_ITER)


def _make_kernel(q: int, max_inner: int, wss: int, R: int, L: int,
                 eta_exclude: bool = False):
    # Working vectors are laid out (R, L): the "packed" layout uses
    # (q//128, 128) so a vector occupies full 8-sublane vregs instead of
    # 1 of 8 as the original "flat" (1, q) layout did — every elementwise
    # op stops wasting 7/8 of VPU throughput. The row-major layout keeps
    # the global index of element (r, c) at r*L + c, preserving the
    # (1, q) ordering, so first-occurrence tie-breaks (and hence the
    # whole iteration trajectory) are identical between layouts. The
    # flat layout (R=1, L=q) is retained as the fallback lowering proven
    # on hardware in round 1.

    def kernel(scal_ref, K_ref, diag_ref, y_ref, a0_ref, f0_ref, act_ref,
               diag_s_ref, y_s_ref, a0_s_ref, aout_ref, stat_ref, a_s_ref):
        iota = (lax.broadcasted_iota(jnp.int32, (R, L), 0) * L
                + lax.broadcasted_iota(jnp.int32, (R, L), 1))

        def pick(v, i):
            """v at global index i for a traced scalar i, as a masked
            reduction (no dynamic scalar addressing into loop-carried
            values on the VPU). Used only where the value lives in vector
            registers (a freshly loaded K row, the current f); everything
            with a static home (y, diag) or a maintained mirror (alpha)
            reads from SMEM in O(1) instead — each pick is a full
            cross-lane reduction, and they dominated the original
            kernel's 8.2us/update."""
            return jnp.sum(jnp.where(iota == i, v, 0.0))

        C = scal_ref[0]
        eps = scal_ref[1]
        tau = scal_ref[2]
        y = y_ref[:]                      # (R, L) float32, +/-1 (0 on pads)
        diag = diag_ref[:]                # (R, L) K_BB diagonal
        pos = y > 0.0

        # SMEM alpha mirror: scalar reads (a[i_h], a[i_l]) and the two
        # per-iteration writes are O(1) on the scalar core, replacing
        # masked-sum reductions over the whole working set. The vector
        # alpha stays loop-carried for the mask computations; both are
        # updated with the same f32 deltas, so they cannot drift.
        def copy(i, _):
            a_s_ref[i] = a0_s_ref[i]
            return 0

        lax.fori_loop(0, q, copy, 0)

        def cond(st):
            return st[5] == _RUNNING

        def body(st):
            # act carried as a f32 mask: Mosaic can't lay out i1 vector
            # carries in scf.while
            a, f, act_f, n_upd, progress, _ = st
            act = act_f > 0.5
            # boolean algebra, not jnp.where over bools: Mosaic can't lower
            # i8->i1 vector select operands
            lo = a > eps
            hi = a < C - eps
            m_h = act & ((pos & hi) | (~pos & lo))
            m_l = act & ((pos & lo) | (~pos & hi))

            vh = jnp.where(m_h, f, jnp.inf)
            b_h = jnp.min(vh)
            i_h = jnp.min(jnp.where(vh == b_h, iota, jnp.int32(q)))
            vl = jnp.where(m_l, f, -jnp.inf)
            b_l = jnp.max(vl)
            if wss == 1:
                i_l = jnp.min(jnp.where(vl == b_l, iota, jnp.int32(q)))
                i_l = jnp.minimum(i_l, jnp.int32(q - 1))

            # emptiness check without jnp.any (whose Mosaic lowering goes
            # through an f64 squeeze under x64): masked-out lanes are +/-inf,
            # and live f values are always finite
            found = (b_h < jnp.inf) & (b_l > -jnp.inf)
            converged = found & (b_l <= b_h + 2.0 * tau)
            proceed = found & ~converged

            # clamp so the row loads stay in bounds when not found (i == q)
            i_h = jnp.minimum(i_h, jnp.int32(q - 1))

            row_h = K_ref[pl.ds(i_h, 1)].reshape(R, L)
            K11 = diag_s_ref[i_h]

            if wss == 2:
                # second-order partner choice (the maximal-gain heuristic of
                # LIBSVM's WSS2, free here because row_h is already in
                # VMEM): among violating I_low members, maximise
                # (f_j - b_h)^2 / eta_j. The Keerthi STOP check above stays
                # on the global (b_h, b_l) pair regardless. NOTE on
                # degenerate partners (true eta <= eps; the clamp below
                # makes their gain huge): by default they CAN win this
                # argmax — the kernel then self-heals by SHRINKING the
                # dead pair (the zero-progress policy below), where the
                # XLA loop instead excludes them from selection up front
                # (solver/blocked.py _inner_smo, fuzz seed 4047).
                # eta_exclude=True folds the XLA loop's exclusion in here
                # (VERDICT r4 #5): degenerate partners drop out of the
                # gain mask, and when EVERY violator is degenerate the
                # pick falls back to the first-order argmax-f partner —
                # byte-identical selection semantics to _inner_smo, at
                # the cost of one extra cross-lane reduction per
                # iteration (the fallback index pick).
                eta_raw = K11 + diag - 2.0 * row_h
                eta_vec = jnp.maximum(eta_raw, 1e-12)
                viol = m_l & (f > b_h)
                if eta_exclude:
                    viol = viol & (eta_raw > eps)
                vg = jnp.where(viol, (f - b_h) ** 2 / eta_vec, -jnp.inf)
                g = jnp.max(vg)
                i_l2 = jnp.min(jnp.where(vg == g, iota, jnp.int32(q)))
                if eta_exclude:
                    # every violating partner degenerate w.r.t. i_h: use
                    # the first-order pick (identical failure semantics
                    # to wss=1 on such data — the XLA loop's rule). The
                    # dead pair then shrinks via the zero-progress policy
                    # below, never spinning.
                    i_l1 = jnp.min(jnp.where(vl == b_l, iota,
                                             jnp.int32(q)))
                    i_l2 = jnp.where(g > -jnp.inf, i_l2, i_l1)
                # without exclusion the second-order pick IS the i_low
                # (no fallback reduction): whenever this iteration
                # proceeds, a violating partner exists — viol empty means
                # no f in I_low exceeds b_h, so b_l <= b_h < b_h + 2*tau
                # and the iteration exits as converged (or not-found)
                # with zero deltas, so the i_l=0 index that an
                # all-(-inf) vg yields is used only for in-bounds loads
                # and zero-delta stores
                i_l = jnp.minimum(i_l2, jnp.int32(q - 1))

            row_l = K_ref[pl.ds(i_l, 1)].reshape(R, L)
            K22 = diag_s_ref[i_l]
            K12 = pick(row_h, i_l)   # row_h is in vector registers
            y_h = y_s_ref[i_h]
            y_l = y_s_ref[i_l]
            a_h = a_s_ref[i_h]
            a_l = a_s_ref[i_l]
            # the 2-variable step uses the SELECTED pair's f values; with
            # first-order selection f[i_l] == b_l exactly. For wss=2,
            # f[i_l] is reconstructed from the selected gain instead of a
            # cross-lane pick: g = (f[i_l]-b_h)^2/eta_clamped at exactly
            # this lane (eta_clamped recomputed below from the same K11/
            # K22/K12 scalars), so sqrt(g*eta_clamped) recovers
            # f[i_l]-b_h (> 0 for violators) to f32 rounding. When no
            # violator exists g==-inf, but then the iteration exits with
            # zero deltas, so the maximum(g, 0) placeholder is unused.
            if wss == 2:
                eta_l = jnp.maximum(K11 + K22 - 2.0 * K12, 1e-12)
                b_l_pair = b_h + jnp.sqrt(jnp.maximum(g, 0.0) * eta_l)
                if eta_exclude:
                    # fallback case (no non-degenerate violator): the
                    # first-order partner's f IS b_l exactly — the gain
                    # reconstruction doesn't apply to it
                    b_l_pair = jnp.where(g > -jnp.inf, b_l_pair, b_l)
            else:
                b_l_pair = b_l

            upd = pair_update(K11, K22, K12, y_h, y_l, a_h, a_l, b_h,
                              b_l_pair, C, eps, proceed)

            f = f + upd.da_h * y_h * row_h + upd.da_l * y_l * row_l
            a = (a + jnp.where(iota == i_h, upd.da_h, 0.0)
                   + jnp.where(iota == i_l, upd.da_l, 0.0))
            # keep the SMEM mirror in lockstep (deltas are 0 when the
            # iteration did not update, so the stores are always safe; an
            # i_h == i_l coincidence implies eta == 0 -> zero deltas)
            a_s_ref[i_h] = a_h + upd.da_h
            a_s_ref[i_l] = a_l + upd.da_l
            ok = upd.do_update & ~upd.stalled
            n_upd = n_upd + ok.astype(jnp.int32)
            progress = jnp.maximum(progress, ok.astype(jnp.int32))

            # SHRINKING: a pair that yields zero progress (box-pinned pair,
            # U > V, or eta <= eps — all deterministic given (i_h, i_l), so
            # re-selecting it would spin forever, which is exactly how the
            # f32 subproblem stalls mid-optimisation) deactivates its i_low
            # for the REST OF THIS SUBPROBLEM ONLY; selection then moves to
            # the next violator. The outer round rebuilds the working set
            # with full masks, so nothing leaks out. Termination: every
            # iteration either updates (bounded by max_inner) or deactivates
            # one index (bounded by q).
            dead = proceed & (~upd.feasible | ~upd.eta_ok | upd.stalled)
            act_f = jnp.where(dead & (iota == i_l), 0.0, act_f)

            # explicit int32 constants: under jax_enable_x64 bare python ints
            # promote to int64, which Mosaic cannot lower
            reason = jnp.where(
                ~found,
                jnp.int32(_NO_WS),
                jnp.where(
                    converged,
                    jnp.int32(_CONVERGED),
                    jnp.where(
                        n_upd >= max_inner,
                        jnp.int32(_MAX_ITER),
                        jnp.int32(_RUNNING),
                    ),
                ),
            )
            return (a, f, act_f, n_upd, progress, reason)

        a, _f, _act, n_upd, progress, reason = lax.while_loop(
            cond, body,
            (a0_ref[:], f0_ref[:], act_ref[:], jnp.int32(0),
             jnp.int32(0), jnp.int32(_RUNNING)),
        )
        aout_ref[:] = a
        stat_ref[0] = n_upd
        stat_ref[1] = progress
        stat_ref[2] = reason

    return kernel


def _make_multipair_kernel(q: int, max_inner: int, p: int, R: int, L: int):
    """p disjoint slot-pairs per iteration (VERDICT r4 #3 prototype).

    The single-pair kernel's ~8us/update is almost entirely the serialized
    latency of its per-update cross-lane reductions (selection, K12 pick) —
    at n=60k the solver streams ~1% of HBM peak (ROOFLINE.md), so updates
    per second, not bandwidth, bound the wall-clock. This kernel amortises
    that latency: the working set's high half (rows [0, R/2), the outer
    selection places the q/2 worst I_high violators there) and low half
    (rows [R/2, R)) are partitioned into p SLOTS of R/(2p) rows each, and
    each iteration runs ONE first-order analytic pair update per slot —
    slot s pairs the locally-worst I_high member of its high rows with the
    locally-worst I_low member of its low rows. The p selections are
    reductions over disjoint row slices (instruction-level parallel), the
    p scalar steps are exact per-pair analytic updates (solver/analytic.py)
    against the iteration-start f, and the 2p row FMAs apply jointly.

    Semantics vs the sequential kernel:
      - JACOBI across slots: all p pairs read the same pre-iteration f, so
        simultaneous application can overshoot where pairs interact
        (bounded by the box clips; each pair ALONE is a valid ascent
        step). Empirically convergence holds (fuzz + blocked-solver
        tests); the global Keerthi stop and the outer loop's accum-dtype
        f reconstruction judge convergence either way, so a noisy inner
        trajectory cannot corrupt the reported optimum.
      - slot-LOCAL selection: the globally-worst pair is examined only if
        both ends land in the same slot; other slots work on their own
        worst violators (a breadth-first schedule of the same violator
        set the outer selection already ranked).
      - role drift: a member whose alpha moves it from I_high to I_low
        mid-subproblem is only reachable by slots covering its row's
        half. Slots with no eligible member idle (zero deltas); if EVERY
        slot idles with the global gap still open, the kernel ends with
        NO_WORKING_SET and zero progress, which triggers the blocked
        solver's accum-dtype XLA retry hatch — never a silent spin.
    Stop check, shrinking, and status surface are the sequential
    kernel's; q/layout alignment: packed rows R must divide by 2p.
    """

    def kernel(scal_ref, K_ref, diag_ref, y_ref, a0_ref, f0_ref, act_ref,
               diag_s_ref, y_s_ref, a0_s_ref, aout_ref, stat_ref, a_s_ref):
        iota = (lax.broadcasted_iota(jnp.int32, (R, L), 0) * L
                + lax.broadcasted_iota(jnp.int32, (R, L), 1))
        Rh = R // (2 * p)  # rows per slot per half

        def pick(v, i):
            return jnp.sum(jnp.where(iota == i, v, 0.0))

        C = scal_ref[0]
        eps = scal_ref[1]
        tau = scal_ref[2]
        y = y_ref[:]
        pos = y > 0.0

        def copy(i, _):
            a_s_ref[i] = a0_s_ref[i]
            return 0

        lax.fori_loop(0, q, copy, 0)

        def cond(st):
            return st[5] == _RUNNING

        def body(st):
            a, f, act_f, n_upd, progress, _ = st
            act = act_f > 0.5
            lo = a > eps
            hi = a < C - eps
            m_h = act & ((pos & hi) | (~pos & lo))
            m_l = act & ((pos & lo) | (~pos & hi))

            vh = jnp.where(m_h, f, jnp.inf)
            vl = jnp.where(m_l, f, -jnp.inf)
            # the STOP decision stays on the globally-worst pair — exact
            # Keerthi criterion regardless of the slot partition
            b_h = jnp.min(vh)
            b_l = jnp.max(vl)
            # global pair INDICES too: the slot partition cannot reach a
            # pair whose ends live in different slots, and near the
            # subproblem optimum exactly that happens — every slot-local
            # gap closes while the global gap stays open (first prototype
            # exited NO_WORKING_SET at HALF the sequential kernel's dual
            # on the q=512 invariant test). The fallback step below
            # applies the globally-best update whenever all slots idle.
            i_hg = jnp.min(jnp.where(vh == b_h, iota, jnp.int32(q)))
            i_hg = jnp.minimum(i_hg, jnp.int32(q - 1))
            i_lg = jnp.min(jnp.where(vl == b_l, iota, jnp.int32(q)))
            i_lg = jnp.minimum(i_lg, jnp.int32(q - 1))
            found = (b_h < jnp.inf) & (b_l > -jnp.inf)
            converged = found & (b_l <= b_h + 2.0 * tau)
            proceed = found & ~converged

            # per-slot selections over DISJOINT static row slices: the 2p
            # reductions have no data dependence on each other
            slot = []
            for s in range(p):
                vh_s = vh[s * Rh:(s + 1) * Rh]
                io_h = iota[s * Rh:(s + 1) * Rh]
                bh_s = jnp.min(vh_s)
                ih_s = jnp.min(jnp.where(vh_s == bh_s, io_h, jnp.int32(q)))
                ih_s = jnp.minimum(ih_s, jnp.int32(q - 1))
                lo0 = R // 2 + s * Rh
                vl_s = vl[lo0:lo0 + Rh]
                io_l = iota[lo0:lo0 + Rh]
                bl_s = jnp.max(vl_s)
                il_s = jnp.min(jnp.where(vl_s == bl_s, io_l, jnp.int32(q)))
                il_s = jnp.minimum(il_s, jnp.int32(q - 1))
                # a slot updates only on a locally VIOLATING pair (local
                # gap open): bl_s <= bh_s would reverse the step's sign
                ok_s = (bh_s < jnp.inf) & (bl_s > -jnp.inf) \
                    & (bl_s > bh_s + 2.0 * tau)
                slot.append((ih_s, il_s, bh_s, bl_s, ok_s))

            df = jnp.zeros_like(f)
            da_vec = jnp.zeros_like(a)
            n_ok = jnp.int32(0)
            n_dead = jnp.int32(0)
            new_act = act_f
            glob_touched = jnp.bool_(False)
            for s in range(p):
                ih_s, il_s, bh_s, bl_s, ok_s = slot[s]
                row_h = K_ref[pl.ds(ih_s, 1)].reshape(R, L)
                row_l = K_ref[pl.ds(il_s, 1)].reshape(R, L)
                K11 = diag_s_ref[ih_s]
                K22 = diag_s_ref[il_s]
                K12 = pick(row_h, il_s)
                y_h = y_s_ref[ih_s]
                y_l = y_s_ref[il_s]
                a_h = a_s_ref[ih_s]
                a_l = a_s_ref[il_s]
                upd = pair_update(K11, K22, K12, y_h, y_l, a_h, a_l,
                                  bh_s, bl_s, C, eps, proceed & ok_s)
                df = df + upd.da_h * y_h * row_h + upd.da_l * y_l * row_l
                da_vec = (da_vec + jnp.where(iota == ih_s, upd.da_h, 0.0)
                          + jnp.where(iota == il_s, upd.da_l, 0.0))
                # slots cover disjoint index ranges, so the SMEM mirror
                # writes never collide
                a_s_ref[ih_s] = a_h + upd.da_h
                a_s_ref[il_s] = a_l + upd.da_l
                ok = upd.do_update & ~upd.stalled
                n_ok = n_ok + ok.astype(jnp.int32)
                # the global step below must not run against alphas a slot
                # moved THIS iteration (ADVICE r5 #4): its b_h/b_l are
                # iteration-start values, so
                #   - a slot that took exactly the global pair would see
                #     the SAME analytic delta re-applied — a_l walks to
                #     2*delta, the zero-gain point of the pair's dual
                #     parabola, and n_upd double-counts;
                #   - a slot that moved EITHER end (the cross-slot case:
                #     i_hg in one slot's rows, i_lg in another's) leaves
                #     the global step a box-clipped but potentially
                #     non-ascent step — transient dual decrease and
                #     inflated update counts on adversarial data.
                # Track any applied slot update overlapping a global end;
                # gate on ok (not do_update): a STALLED slot take leaves
                # alphas unmoved, and the global step must still
                # re-diagnose the pair so the fresh-f shrink below can
                # retire it
                glob_touched = glob_touched | (
                    ((ih_s == i_hg) | (il_s == i_hg)
                     | (ih_s == i_lg) | (il_s == i_lg)) & ok)
                # slots NEVER shrink: a slot's dead diagnosis is made
                # against intra-iteration-stale f (other slots' deltas
                # land simultaneously), and shrinking on it falsely
                # deactivates live members — measured as convergence to
                # a dual 1% BELOW the sequential optimum at q=1024/p=4
                # (global gap "closed" over the wrongly-shrunken active
                # set). All shrinking goes through the global pair below,
                # whose fresh-f diagnosis is exact and alone guarantees
                # termination; a persistently-dead slot pair just idles
                # (zero deltas) until the moving f unsticks it.

            # global-pair step, EVERY iteration: the slot partition alone
            # cannot close the global gap (pairs straddling slots are
            # unreachable — the first prototype exited at half the dual;
            # firing the global step only on all-idle then left p>=4 runs
            # circling at MAX_ITER, slots micro-updating while the gap
            # stayed open). Applying the sequential kernel's
            # globally-best move each iteration makes the batched kernel
            # at least as strong as the sequential one: its selection
            # reductions depend only on iteration-start f — independent
            # of the slot work, so they pipeline with it — and it runs
            # Gauss-Seidel after the slots (alpha mirror reads happen
            # post-slot-writes, so a coincidence with a slot index sees
            # the current value and the combined deltas stay box-clipped
            # and sum(y*a)-conserving). Skipped whenever a slot's APPLIED
            # update touched either global end this iteration
            # (glob_touched, ADVICE r5 #4): against post-slot alphas the
            # iteration-start b_h/b_l would make this a box-clipped but
            # potentially non-ascent step (transient dual decrease,
            # inflated update counts on adversarial data). Termination is
            # unaffected: if every slot idled nothing was touched and the
            # step (or the fresh-f shrink) still fires; if a slot
            # updated, the iteration already made progress.
            glob_go = proceed & ~glob_touched
            row_hg = K_ref[pl.ds(i_hg, 1)].reshape(R, L)
            row_lg = K_ref[pl.ds(i_lg, 1)].reshape(R, L)
            K12g = pick(row_hg, i_lg)
            y_hg = y_s_ref[i_hg]
            y_lg = y_s_ref[i_lg]
            a_hg = a_s_ref[i_hg]
            a_lg = a_s_ref[i_lg]
            updg = pair_update(diag_s_ref[i_hg], diag_s_ref[i_lg], K12g,
                               y_hg, y_lg, a_hg, a_lg, b_h, b_l, C, eps,
                               glob_go)
            df = df + updg.da_h * y_hg * row_hg + updg.da_l * y_lg * row_lg
            da_vec = (da_vec + jnp.where(iota == i_hg, updg.da_h, 0.0)
                      + jnp.where(iota == i_lg, updg.da_l, 0.0))
            a_s_ref[i_hg] = a_hg + updg.da_h
            a_s_ref[i_lg] = a_lg + updg.da_l
            okg = updg.do_update & ~updg.stalled
            # SHRINK the global pair only when the slots all idled: then
            # f was fresh for it and the dead diagnosis is exact (the
            # sequential kernel's situation). With slot updates in
            # flight its b_h/b_l are stale, and shrinking on a stale
            # diagnosis falsely deactivates live members (measured: the
            # q=512 invariant case converged 3% BELOW the sequential
            # dual before this guard). No spin: if slots keep updating,
            # state advances; once they idle, an exact dead pair shrinks.
            deadg = (glob_go & (n_ok == 0)
                     & (~updg.feasible | ~updg.eta_ok | updg.stalled))
            n_ok = n_ok + okg.astype(jnp.int32)
            n_dead = n_dead + deadg.astype(jnp.int32)
            new_act = jnp.where(deadg & (iota == i_lg), 0.0, new_act)

            f = f + df
            a = a + da_vec
            act_f = new_act
            n_upd = n_upd + n_ok
            progress = jnp.maximum(progress, (n_ok > 0).astype(jnp.int32))

            # all-idle guard (defensive; with the global fallback every
            # proceeding iteration either updates or shrinks, so this
            # should be unreachable — kept so a future regression ends
            # the subproblem instead of spinning)
            idle = proceed & (n_ok == 0) & (n_dead == 0)
            reason = jnp.where(
                ~found | idle,
                jnp.int32(_NO_WS),
                jnp.where(
                    converged,
                    jnp.int32(_CONVERGED),
                    jnp.where(
                        n_upd >= max_inner,
                        jnp.int32(_MAX_ITER),
                        jnp.int32(_RUNNING),
                    ),
                ),
            )
            return (a, f, act_f, n_upd, progress, reason)

        a, _f, _act, n_upd, progress, reason = lax.while_loop(
            cond, body,
            (a0_ref[:], f0_ref[:], act_ref[:], jnp.int32(0),
             jnp.int32(0), jnp.int32(_RUNNING)),
        )
        aout_ref[:] = a
        stat_ref[0] = n_upd
        stat_ref[1] = progress
        stat_ref[2] = reason

    return kernel


@functools.partial(
    jax.jit, static_argnames=("max_inner", "interpret", "wss", "layout",
                              "eta_exclude", "multipair")
)
def inner_smo_pallas(K_BB, y_B, a_B, f_B, active_B, C, eps, tau, *,
                     max_inner: int, interpret: bool = False, wss: int = 1,
                     layout: str = "packed", eta_exclude: bool = False,
                     multipair: int = 1):
    """Run the inner working-set SMO subproblem as one fused TPU kernel.

    Same contract as solver/blocked.py `_inner_smo`: returns
    (a_B_new, n_updates, made_progress, end_reason). Inputs may be any float
    dtype; compute is float32 (see module docstring), and a_B_new comes back
    in a_B's dtype.

    wss=1 selects i_low by first-order Keerthi argmax-f (the reference's
    heuristic, main3.cpp:124-142); wss=2 selects the maximal-gain partner
    (second-order) while keeping the reference's stopping rule.
    eta_exclude (wss=2 only) folds the XLA engine's degenerate-partner
    exclusion into the in-kernel gain selection (VERDICT r4 #5) — same
    selection rule as _inner_smo, one extra reduction per iteration;
    default False = the hardware-proven shrink-on-dead-pair policy.
    multipair=p > 1 selects the batched slot-pair kernel
    (_make_multipair_kernel: p first-order analytic updates per
    iteration over a disjoint slot partition of the working set) —
    requires the packed layout with (q//128) % (2p) == 0, first-order
    selection (wss=1), and n_updates then counts all per-slot updates.
    """
    if wss not in (1, 2):
        raise ValueError(f"wss must be 1 or 2, got {wss}")
    if eta_exclude and wss != 2:
        raise ValueError("eta_exclude only applies to wss=2")
    if multipair < 1:
        raise ValueError(f"multipair must be >= 1, got {multipair}")
    if multipair > 1:
        if wss != 1:
            raise ValueError("multipair requires wss=1 (slot pairing is "
                             "first-order)")
        if layout != "packed":
            raise ValueError("multipair requires layout='packed'")
    if layout not in ("packed", "flat"):
        raise ValueError(f"layout must be packed|flat, got {layout!r}")
    q = y_B.shape[0]
    if q % LANE:
        raise ValueError(f"inner_smo_pallas needs q % {LANE} == 0, got {q}")
    # packed = full-vreg sublane utilisation; flat = the (1, q) layout
    # proven on hardware in round 1 (kept as a lowering fallback)
    R, L = (q // LANE, LANE) if layout == "packed" else (1, q)
    if multipair > 1 and R % (2 * multipair):
        raise ValueError(
            f"multipair={multipair} needs (q//{LANE}) % {2 * multipair} == 0 "
            f"(rows per slot per half >= 1), got q={q} (R={R})"
        )
    scal = jnp.stack([
        jnp.asarray(C, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(tau, jnp.float32),
    ])
    K32 = K_BB.astype(jnp.float32)
    diag32 = jnp.diagonal(K32)
    y32 = y_B.astype(jnp.float32)
    a32 = a_B.astype(jnp.float32)
    kernel_fn = (
        _make_multipair_kernel(q, max_inner, multipair, R, L)
        if multipair > 1 else
        _make_kernel(q, max_inner, wss, R, L, eta_exclude)
    )
    aout, stat = pl.pallas_call(
        kernel_fn,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            # (q,) SMEM copies of diag / y / a0 for O(1) scalar reads in
            # the hot loop (the VMEM copies above serve the vector math)
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, L), jnp.float32),
            jax.ShapeDtypeStruct((3,), jnp.int32),
        ],
        scratch_shapes=[pltpu.SMEM((q,), jnp.float32)],  # alpha mirror
        interpret=interpret,
    )(
        scal,
        K32.reshape(q, R, L),
        diag32.reshape(R, L),
        y32.reshape(R, L),
        a32.reshape(R, L),
        f_B.astype(jnp.float32).reshape(R, L),
        active_B.astype(jnp.float32).reshape(R, L),
        diag32,
        y32,
        a32,
    )
    return (aout.reshape(q).astype(a_B.dtype), stat[0], stat[1] > 0, stat[2])
