"""Pallas TPU kernel: fused two-row RBF computation for the SMO hot loop.

This is the BASELINE-named Pallas target (SURVEY.md §7.1): the TPU-native
replacement for the reference's per-iteration calc_kernel_matrix launches
(gpu_svm_main3.cu:137-147, launched twice per iteration at :395-411).

One kernel produces BOTH needed rows K(x_{i_high}, .) and K(x_{i_low}, .) in
a single pass: the grid walks n in TILE_N-row blocks; each step streams one
(TILE_N, d) block of X from HBM into VMEM exactly once and
  - computes the block's row squared-norms on the VPU (no separate sq_norms
    array read),
  - does the two multiply-reduce contractions on the VPU (a (d, 2) MXU
    matmul would waste 126 of 128 output columns and become compute-bound),
  - fuses the -gamma * d^2 -> exp into the same block,
so HBM traffic is exactly one read of X per refresh.

STATUS: experimental, not wired into the solvers. On this environment's
TPU runtime it benchmarks at parity with the XLA dot-form rbf_rows_at
(~530 us for 60k x 896 f32 — both near the platform's observed practical
bandwidth), and the blocked working-set solver (solver/blocked.py) made the
per-iteration row refresh a non-bottleneck altogether. Kept, tested in
interpret mode (tests/test_pallas.py), as the starting point for future
kernel-level tuning (e.g. fusing the f-update and selection partials into
the same X pass for the pairwise solver).

Shapes must be aligned: n % TILE_N == 0 and d % 128 == 0 — callers pad
(MNIST's d=784 pads to 896).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 512
LANE = 128


def _rows_kernel(x_ref, xi_ref, gamma_ref, out_ref):
    # A (d, 2) contraction would waste 126 of the MXU's 128 output columns
    # and become compute-bound; the VPU does the two multiply-reduces at
    # full HBM bandwidth instead (the block is already in VMEM).
    xb = x_ref[:]                    # (TILE_N, d) block of X
    xi = xi_ref[:]                   # (2, d) gathered pair, replicated
    gamma = gamma_ref[0]
    dot0 = jnp.sum(xb * xi[0][None, :], axis=1)     # (TILE_N,)
    dot1 = jnp.sum(xb * xi[1][None, :], axis=1)
    snb = jnp.sum(xb * xb, axis=1)                  # (TILE_N,)
    sni = jnp.sum(xi * xi, axis=1)                  # (2,)
    d2 = jnp.stack(
        [snb + sni[0] - 2.0 * dot0, snb + sni[1] - 2.0 * dot1], axis=1
    )
    out_ref[:] = jnp.exp(-gamma * jnp.maximum(d2, 0.0))


@functools.partial(jax.jit, static_argnames=("interpret",))
def rbf_two_rows(
    X: jax.Array, Xi: jax.Array, gamma, *, interpret: bool = False
) -> jax.Array:
    """K(Xi[k], X[j]) for the 2 gathered rows Xi. Returns (n, 2) float32.

    Args:
      X: (n, d) float32, n % TILE_N == 0, d % 128 == 0.
      Xi: (2, d) float32 — the i_high/i_low rows (gathered outside; a 2-row
        gather is too small to matter next to the (n, d) stream).
      gamma: scalar RBF width (traced).
    """
    n, d = X.shape
    if n % TILE_N or d % LANE:
        raise ValueError(
            f"rbf_two_rows needs n % {TILE_N} == 0 and d % {LANE} == 0, "
            f"got {X.shape}; pad first"
        )
    gamma_arr = jnp.asarray([gamma], jnp.float32)
    return pl.pallas_call(
        _rows_kernel,
        grid=(n // TILE_N,),
        in_specs=[
            pl.BlockSpec((TILE_N, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((2, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (TILE_N, 2), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, 2), jnp.float32),
        interpret=interpret,
    )(X, Xi, gamma_arr)
