"""Pallas TPU kernel: the blocked solver's f-update contraction, fused.

The global error-vector update (solver/blocked.py step 4) is
f += K(X, X_B) @ (dalpha * y_B). As XLA ops (ops/rbf.py:rbf_cross_matvec)
each n-block materialises its (block, q) squared-distance slab and the
exp'd kernel slab in HBM between the distance matmul and the coefficient
matvec — ~1 GB of intermediate HBM traffic per outer round at the bench
shape (60000 x 2048), on top of the 188 MB X stream the contraction
fundamentally needs.

This kernel fuses distance matmul -> exp -> coefficient matvec per tile:
the slab lives in VMEM only, so HBM sees the X stream and the (n,) result
— the reference's update_f kernel (gpu_svm_main3.cu:262-272) reimagined as
one MXU pipeline instead of q separate row updates.

Parity note: the distance dot runs at precision=HIGHEST (full-f32
equivalent MXU passes), matching ops/rbf.py's DEFAULT_PRECISION="float32"
trust anchor — NOT raw single-pass bf16. Off TPU use interpret=True
(true f32 math).

Default on TPU since the round-4 hardware A/B (blocked_smo_solve's
fused_fupdate='auto' -> solver.blocked.resolve_fused_fupdate): at the
bench shape the fused kernel measured 0.476/0.478 s vs 0.497 s for the
XLA contraction in the same session (benchmarks/results/tpu_capture_r4/
fused_fixed_*.jsonl) — at-or-under the two-matmul path's time while
removing its (n, q) HBM slab traffic. Off TPU, at bf16 precision, or on
VMEM-infeasible / unaligned shapes, 'auto' keeps the XLA contraction.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _x64_off():
    """`jax.enable_x64(False)` where available, else a no-op context.

    The grid index maps' i64 promotion only breaks MOSAIC lowering (see
    the trace-time comment at the call sites); older jax builds without
    the context manager (0.4.3x) cannot hit that path off-TPU, where
    interpret mode runs true f32 math regardless."""
    try:
        return jax.enable_x64(False)
    except AttributeError:
        return contextlib.nullcontext()


def _kernel(gamma_ref, x_ref, sn_ref, xb_t_ref, snb_ref, coef_ref, out_ref):
    # (block, d) @ (d, q) distance dot on the MXU, full-f32 passes
    xdot = jax.lax.dot_general(
        x_ref[:], xb_t_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    d2 = sn_ref[:] + snb_ref[:] - 2.0 * xdot
    d2 = jnp.maximum(d2, 0.0)  # dot-form cancellation guard (rbf.py)
    k = jnp.exp(-gamma_ref[0] * d2)
    # (block, q) @ (q, 1) coefficient matvec, also on the MXU
    out_ref[:] = jax.lax.dot_general(
        k, coef_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _resident_bytes(q: int, d: int) -> int:
    """VMEM held for the whole grid: XB^T (4qd) + snB/coef (12q)."""
    return 4 * q * d + 12 * q


def _floor_block(n: int | None) -> int:
    """Smallest row block the grid can step (the final-block mask lets
    small n lower the 128-row floor)."""
    return 128 if n is None else max(8, min(128, n))


def _stack_bytes(block: int, q: int, d: int) -> int:
    """Scoped-stack cost of one grid step: double-buffered (block, q)
    f32 slab pair + the (block, d) X input block."""
    return block * (2 * q * 8 + d * 4)


_RESIDENT_BUDGET = 64_000_000  # half of v5e's ~128 MB VMEM
_STACK_BUDGET_FLOOR = 15_000_000  # 16 MB Mosaic scoped stack, with margin


def fused_feasible(q: int, d: int, n: int | None = None) -> bool:
    """True iff the kernel's VMEM cost model admits (q, d, n).

    The boolean face of _auto_block's two raise conditions (same helpers,
    same budgets) — lets fused_fupdate='auto' resolution fall back to the
    XLA contraction instead of raising on shapes the chip cannot hold.
    """
    return (_resident_bytes(q, d) <= _RESIDENT_BUDGET
            and _stack_bytes(_floor_block(n), q, d) <= _STACK_BUDGET_FLOOR)


def _auto_block(q: int, d: int, n: int | None = None) -> int:
    """Largest power-of-two row block whose per-step stack fits Mosaic's
    16 MB scoped-vmem limit, from the kernel's measured cost model:

      stack(block) = 2*block*q*8 + block*d*4   (double-buffered (block, q)
                      f32 slab pair + the (block, d) X input block)

    calibrated against q=2048/d=784 compile measurements: block=1024 ->
    model 36.7 MB vs 37.2 MB measured OOM, block=512 -> 18.4 vs 18.4 OOM,
    block=256 -> 9.2, compiles. The measured scoped figures match the
    stack-only model (no 4*q*d term), so the resident XB^T/snB/coef blocks
    are NOT charged against the scoped stack — they are bounded separately
    against total VMEM (~128 MB on v5e): huge q*d raises here, pointing at
    the XLA path, instead of failing as an inscrutable Mosaic compile OOM.
    """
    resident = _resident_bytes(q, d)
    if resident > _RESIDENT_BUDGET:
        # budget half the chip's ~128 MB VMEM for the resident blocks,
        # leaving the rest for the scoped stack + double-buffered X/out
        raise ValueError(
            f"fused f-update cannot fit VMEM at q={q}, d={d}: the resident "
            f"XB^T block is {resident / 1e6:.1f} MB, over the ~64 MB "
            "budgeted for resident blocks (half of the chip's ~128 MB "
            "VMEM). Use the XLA contraction (fused_fupdate=False)."
        )
    # the grid never steps more than n rows, so small n lowers the floor
    floor = _floor_block(n)
    if _stack_bytes(floor, q, d) > _STACK_BUDGET_FLOOR:
        # tall-skinny XB: even the floor block's slab pair busts the stack
        raise ValueError(
            f"fused f-update cannot fit VMEM at q={q}, d={d}: the minimum "
            f"{floor}-row step needs {_stack_bytes(floor, q, d) / 1e6:.1f} "
            "MB of the 16 MB scoped stack. Use the XLA contraction "
            "(fused_fupdate=False)."
        )
    block = floor
    while block < 1024 and _stack_bytes(2 * block, q, d) <= 12_000_000:
        block *= 2
    return block


@functools.partial(
    jax.jit, static_argnames=("block", "interpret")
)
def rbf_cross_matvec_pallas(
    X: jax.Array,
    XB: jax.Array,
    coef: jax.Array,
    gamma: float,
    sn: jax.Array | None = None,
    *,
    block: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """sum_k coef_k K(x_i, xb_k) for all i, fused in VMEM. Shape (n,).

    Drop-in for ops.rbf.rbf_cross_matvec at its default ("float32")
    precision. gamma may be traced (delivered to the kernel via SMEM).
    X rows are processed in `block`-row grid steps. n need not divide the
    block: Pallas masks the out-of-bounds portion of the final block's
    output write, and every output row depends only on its own input row,
    so the unspecified out-of-bounds input lanes cannot contaminate real
    rows — no padded copy of X is ever made (a per-call pad would re-read
    and re-write all of X inside the solver's round body, giving back a
    third of the HBM traffic this kernel exists to save).
    """
    from tpusvm.ops.rbf import sq_norms

    n, d = X.shape
    q = XB.shape[0]
    if sn is None:
        sn = sq_norms(X)
    snB = sq_norms(XB)

    if block is None:
        if interpret:
            # interpret mode has no VMEM: keep hardware's block when the
            # shape fits (so interpret tests exercise the same grid), but
            # fall back to the old flat default instead of raising on
            # shapes only the real chip cannot hold
            try:
                block = _auto_block(q, d, n)
            except ValueError:
                block = 1024
        else:
            block = _auto_block(q, d, n)
    block = min(block, max(n, 8))
    nb = -(-n // block)

    # Trace the pallas_call with x64 promotion OFF: under jax_enable_x64
    # the grid index maps' integer returns promote to i64, which Mosaic
    # cannot legalize ("func.return (i64)" — reproduced on TPU v5e with a
    # minimal grid kernel, so it is the platform's grid lowering, not this
    # kernel). Every operand here is explicitly f32, so disabling
    # promotion inside the call changes nothing semantically. The grid-less
    # inner_smo kernel never hits this (no index maps).
    with _x64_off():
        out = pl.pallas_call(
            _kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # gamma
                pl.BlockSpec((block, d), lambda i: (i, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
                # XB^T, snB, coef: whole-array blocks, identical every
                # step — the compiler keeps them resident in VMEM across
                # the grid
                pl.BlockSpec((d, q), lambda i: (0, 0)),
                pl.BlockSpec((1, q), lambda i: (0, 0)),
                pl.BlockSpec((q, 1), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block, 1), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
            interpret=interpret,
        )(
            jnp.asarray(gamma, jnp.float32).reshape(1),
            X.astype(jnp.float32),
            sn.astype(jnp.float32)[:, None],
            XB.astype(jnp.float32).T,
            snB.astype(jnp.float32)[None, :],
            coef.astype(jnp.float32)[:, None],
        )
    return out[:, 0].astype(X.dtype)


# --------------------------------------------------------------------------
# Fused f-update + working-set selection (round 9, ladder rung 3): the
# violator-mask + per-block top-k candidate selection runs in the SAME
# kernel epilogue that computes df, so the separate mask+top_k pass the
# solver used to make over all n rows disappears. Each grid step emits,
# besides its df block, the k best I_high candidates (smallest updated f)
# and k best I_low candidates (largest updated f) of its rows; the solver
# assembles the next working set from the (nb * k)-sized candidate pool.
# Selection quality is the per-block-top-k approximation (each block's
# extremes always survive — the same progress argument as
# selection='approx'); the Keerthi STOP decision stays outside on exact
# global reductions, so the convergence criterion is unchanged.
# --------------------------------------------------------------------------


def selection_shape(n: int, d: int, q: int, k_min: int = 8):
    """(block, nb, k_cand, ncand) the fused-selection kernel will use.

    One definition shared by the kernel wrapper and the solver (the
    candidate arrays live in the solver's loop carry, so their static
    shapes must agree with the kernel's grid). k_cand is sized so the
    candidate pool covers a full q/2 half (plus a k_min floor for
    selection quality on small grids); nb * k_cand <= n always holds
    because k_cand <= block (half <= n/2 <= nb*block/2).
    """
    try:
        block = _auto_block(q, d, n)
    except ValueError:
        block = 1024
    block = min(block, max(n, 8))
    nb = -(-n // block)
    half = max(q // 2, 1)
    k_cand = max(k_min, -(-half // nb))
    k_cand = min(k_cand, block)
    return block, nb, k_cand, nb * k_cand


def _make_select_kernel(block: int, k_cand: int):
    def kernel(fscal_ref, nscal_ref, x_ref, sn_ref, xb_t_ref, snb_ref,
               coef_ref, f_ref, a_ref, ye_ref,
               df_ref, upv_ref, upi_ref, lov_ref, loi_ref):
        gamma = fscal_ref[0]
        C = fscal_ref[1]
        eps = fscal_ref[2]
        n = nscal_ref[0]
        # --- the f-update contraction, exactly as _kernel ----------------
        xdot = jax.lax.dot_general(
            x_ref[:], xb_t_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        d2 = sn_ref[:] + snb_ref[:] - 2.0 * xdot
        d2 = jnp.maximum(d2, 0.0)
        k = jnp.exp(-gamma * d2)
        df = jax.lax.dot_general(
            k, coef_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        df_ref[:] = df
        # --- epilogue: violator masks + per-block top-k candidates -------
        f_new = f_ref[:] + df                    # (block, 1) f32
        a = a_ref[:]                             # (block, 1) f32
        ye = ye_ref[:]                           # (block, 1) i32; 0=invalid
        rows = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
        gidx = rows + pl.program_id(0) * block
        in_range = gidx < n
        m_h = jnp.where(ye == 1, a < C - eps, (ye == -1) & (a > eps))
        m_l = jnp.where(ye == 1, a > eps, (ye == -1) & (a < C - eps))
        key_up = jnp.where(m_h & in_range, f_new, jnp.inf)
        key_lo = jnp.where(m_l & in_range, f_new, -jnp.inf)

        def pick(key, chosen, largest):
            eff = jnp.where(chosen, -jnp.inf if largest else jnp.inf, key)
            v = jnp.max(eff) if largest else jnp.min(eff)
            cand = (eff == v) & ~chosen
            pos = jnp.max(jnp.where(cand, rows, -1))
            return v, pos, chosen | (rows == pos)

        up_v, up_i, lo_v, lo_i = [], [], [], []
        chosen_up = jnp.zeros((block, 1), bool)
        chosen_lo = jnp.zeros((block, 1), bool)
        base = pl.program_id(0) * block
        for _ in range(k_cand):  # static unroll: k_cand is small
            v, pos, chosen_up = pick(key_up, chosen_up, largest=False)
            up_v.append(v.reshape(1, 1))
            up_i.append((pos + base).reshape(1, 1))
            v, pos, chosen_lo = pick(key_lo, chosen_lo, largest=True)
            lo_v.append(v.reshape(1, 1))
            lo_i.append((pos + base).reshape(1, 1))
        upv_ref[:] = jnp.concatenate(up_v, axis=1)
        upi_ref[:] = jnp.concatenate(up_i, axis=1)
        lov_ref[:] = jnp.concatenate(lo_v, axis=1)
        loi_ref[:] = jnp.concatenate(lo_i, axis=1)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("k_cand", "block", "interpret")
)
def fused_fupdate_select_pallas(
    X: jax.Array,
    XB: jax.Array,
    coef: jax.Array,
    gamma,
    sn: jax.Array | None,
    f32_f: jax.Array,
    alpha32: jax.Array,
    y_eff: jax.Array,
    C,
    eps,
    *,
    k_cand: int,
    block: int | None = None,
    interpret: bool = False,
):
    """df + next-round working-set candidates, fused in VMEM.

    Returns (df (n,) f32, up_val (ncand,) f32, up_idx (ncand,) i32,
    low_val, low_idx) with ncand = nb * k_cand. f32_f is the CURRENT f's
    f32 face (candidate keys were already f32 in the two-pass path — the
    exact adt f stays with the solver for the stop decision); alpha32 the
    POST-round alphas (next round's masks); y_eff = y * valid, so invalid
    rows (y=0) belong to neither index set. Filler candidates carry
    +/-inf values; their indices may alias real rows (the solver clamps
    and first-occurrence-dedups them). The df face of this kernel is the
    same full-f32 pipeline as rbf_cross_matvec_pallas.
    """
    from tpusvm.ops.rbf import sq_norms

    n, d = X.shape
    q = XB.shape[0]
    if sn is None:
        sn = sq_norms(X)
    snB = sq_norms(XB)

    if block is None:
        try:
            block = _auto_block(q, d, n)
        except ValueError:
            if not interpret:
                raise
            block = 1024
    block = min(block, max(n, 8))
    nb = -(-n // block)

    kernel = _make_select_kernel(block, k_cand)
    with _x64_off():
        out = pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # gamma, C, eps
                pl.BlockSpec(memory_space=pltpu.SMEM),  # n
                pl.BlockSpec((block, d), lambda i: (i, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
                # XB^T, snB, coef: whole-array, VMEM-resident across grid
                pl.BlockSpec((d, q), lambda i: (0, 0)),
                pl.BlockSpec((1, q), lambda i: (0, 0)),
                pl.BlockSpec((q, 1), lambda i: (0, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),  # f32 f
                pl.BlockSpec((block, 1), lambda i: (i, 0)),  # alpha32
                pl.BlockSpec((block, 1), lambda i: (i, 0)),  # y_eff
            ],
            out_specs=[
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
                pl.BlockSpec((1, k_cand), lambda i: (i, 0)),
                pl.BlockSpec((1, k_cand), lambda i: (i, 0)),
                pl.BlockSpec((1, k_cand), lambda i: (i, 0)),
                pl.BlockSpec((1, k_cand), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n, 1), jnp.float32),
                jax.ShapeDtypeStruct((nb, k_cand), jnp.float32),
                jax.ShapeDtypeStruct((nb, k_cand), jnp.int32),
                jax.ShapeDtypeStruct((nb, k_cand), jnp.float32),
                jax.ShapeDtypeStruct((nb, k_cand), jnp.int32),
            ],
            interpret=interpret,
        )(
            jnp.stack([jnp.asarray(gamma, jnp.float32).reshape(()),
                       jnp.asarray(C, jnp.float32).reshape(()),
                       jnp.asarray(eps, jnp.float32).reshape(())]),
            jnp.asarray(n, jnp.int32).reshape(1),
            X.astype(jnp.float32),
            sn.astype(jnp.float32)[:, None],
            XB.astype(jnp.float32).T,
            snB.astype(jnp.float32)[None, :],
            coef.astype(jnp.float32)[:, None],
            f32_f.astype(jnp.float32)[:, None],
            alpha32.astype(jnp.float32)[:, None],
            y_eff.astype(jnp.int32)[:, None],
        )
    df, upv, upi, lov, loi = out
    return (df[:, 0].astype(X.dtype), upv.reshape(-1), upi.reshape(-1),
            lov.reshape(-1), loi.reshape(-1))
