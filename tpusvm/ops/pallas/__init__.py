from tpusvm.ops.pallas.inner_smo import inner_smo_pallas

__all__ = ["inner_smo_pallas"]
