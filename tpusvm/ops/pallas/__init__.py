from tpusvm.ops.pallas.rows import rbf_two_rows

__all__ = ["rbf_two_rows"]
