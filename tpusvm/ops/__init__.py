from tpusvm.ops.rbf import (
    rbf_cross,
    rbf_cross_matvec,
    rbf_matvec,
    rbf_row,
    rbf_rows_at,
    rbf_rows_at_direct,
    sq_norms,
)
from tpusvm.ops.selection import (
    i_high_mask,
    i_low_mask,
    masked_argmax,
    masked_argmin,
)

__all__ = [
    "rbf_cross",
    "rbf_cross_matvec",
    "rbf_matvec",
    "rbf_row",
    "rbf_rows_at",
    "rbf_rows_at_direct",
    "sq_norms",
    "i_high_mask",
    "i_low_mask",
    "masked_argmax",
    "masked_argmin",
]
