"""Min-max feature scaling with the reference's exact semantics.

Reference: find_min_max (main3.cpp:57-71) and scale_features (main3.cpp:74-89):
per-feature min-max scaling to [0,1], with degenerate ranges (< 1e-12) treated
as range 1.0 so constant features pass through shifted by their min. The test
set is always scaled with the TRAIN set's min/max (main3.cpp:338-339, 355).

In the distributed cascade, rank 0 computes min/max over the FULL dataset
before scattering and broadcasts it (mpi_svm_main3.cpp:529-539) — here the
scaler is simply fit on the full array before sharding, which is the same
computation without the broadcast.
"""

from __future__ import annotations

import dataclasses

import numpy as np

_DEGENERATE_RANGE = 1e-12


@dataclasses.dataclass
class MinMaxScaler:
    """Per-feature min-max scaler. fit() on train data only."""

    min_val: np.ndarray | None = None
    max_val: np.ndarray | None = None

    def fit(self, X: np.ndarray) -> "MinMaxScaler":
        self.min_val = np.min(X, axis=0)
        self.max_val = np.max(X, axis=0)
        return self

    @property
    def range_(self) -> np.ndarray:
        r = self.max_val - self.min_val
        return np.where(r < _DEGENERATE_RANGE, 1.0, r)

    def transform(self, X: np.ndarray) -> np.ndarray:
        if self.min_val is None:
            raise RuntimeError("scaler not fitted")
        return (X - self.min_val) / self.range_

    def fit_transform(self, X: np.ndarray) -> np.ndarray:
        return self.fit(X).transform(X)
