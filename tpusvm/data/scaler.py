"""Min-max feature scaling with the reference's exact semantics.

Reference: find_min_max (main3.cpp:57-71) and scale_features (main3.cpp:74-89):
per-feature min-max scaling to [0,1], with degenerate ranges (< 1e-12) treated
as range 1.0 so constant features pass through shifted by their min. The test
set is always scaled with the TRAIN set's min/max (main3.cpp:338-339, 355).

In the distributed cascade, rank 0 computes min/max over the FULL dataset
before scattering and broadcasts it (mpi_svm_main3.cpp:529-539) — here the
scaler is simply fit on the full array before sharding, which is the same
computation without the broadcast. For out-of-core datasets the same global
min/max is assembled WITHOUT ever holding X: per-shard partial min/max merge
exactly (min/max are selections, not accumulations, so elementwise
minimum/maximum over partials is bit-identical to a fit on the concatenated
array), and `MinMaxScaler.from_stats` builds the scaler from the merged
result (tpusvm.stream.stats is the manifest-side producer).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Tuple

import numpy as np

_DEGENERATE_RANGE = 1e-12


def merge_minmax(
    parts: Iterable[Tuple[np.ndarray, np.ndarray]],
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-shard (min_val, max_val) partials into global min/max.

    Bit-identical to np.min/np.max over the row-concatenated array: min and
    max are selections, so the reduction order cannot perturb the result
    (unlike a mean or a sum). Raises on an empty iterable — there is no
    identity element that would round-trip through the degenerate-range
    rule honestly.
    """
    lo = hi = None
    for p_lo, p_hi in parts:
        p_lo = np.asarray(p_lo)
        p_hi = np.asarray(p_hi)
        if lo is None:
            lo, hi = p_lo.copy(), p_hi.copy()
        else:
            np.minimum(lo, p_lo, out=lo)
            np.maximum(hi, p_hi, out=hi)
    if lo is None:
        raise ValueError("merge_minmax: no partial stats to merge")
    return lo, hi


@dataclasses.dataclass
class MinMaxScaler:
    """Per-feature min-max scaler. fit() on train data only."""

    min_val: np.ndarray | None = None
    max_val: np.ndarray | None = None

    def fit(self, X: np.ndarray) -> "MinMaxScaler":
        self.min_val = np.min(X, axis=0)
        self.max_val = np.max(X, axis=0)
        return self

    @classmethod
    def from_stats(cls, min_val: np.ndarray, max_val: np.ndarray) -> "MinMaxScaler":
        """Build a fitted scaler from precomputed per-feature min/max.

        The out-of-core constructor: pass manifest-recorded global stats
        (or a merge_minmax of per-shard partials) and transform() behaves
        exactly as after fit() on the full array — including the
        degenerate-range (< 1e-12) branch, which lives in `range_` and is
        therefore shared by both construction paths.
        """
        min_val = np.asarray(min_val)
        max_val = np.asarray(max_val)
        if min_val.shape != max_val.shape:
            raise ValueError(
                f"min/max shape mismatch: {min_val.shape} vs {max_val.shape}"
            )
        if np.any(max_val < min_val):
            raise ValueError("from_stats: max_val < min_val on some feature")
        return cls(min_val=min_val, max_val=max_val)

    @property
    def range_(self) -> np.ndarray:
        r = self.max_val - self.min_val
        return np.where(r < _DEGENERATE_RANGE, 1.0, r)

    def transform(self, X: np.ndarray) -> np.ndarray:
        if self.min_val is None:
            raise RuntimeError("scaler not fitted")
        return (X - self.min_val) / self.range_

    def fit_transform(self, X: np.ndarray) -> np.ndarray:
        return self.fit(X).transform(X)
