from tpusvm.data.csv_reader import read_csv, write_csv
from tpusvm.data.partition import Partition, partition
from tpusvm.data.scaler import MinMaxScaler
from tpusvm.data.synthetic import blobs, mnist_like, mnist_like_multiclass, rings

__all__ = [
    "read_csv",
    "write_csv",
    "Partition",
    "partition",
    "MinMaxScaler",
    "blobs",
    "rings",
    "mnist_like",
    "mnist_like_multiclass",
]
