from tpusvm.data.csv_reader import read_csv, read_csv_blocks, write_csv
from tpusvm.data.partition import Partition, partition
from tpusvm.data.scaler import MinMaxScaler, merge_minmax
from tpusvm.data.synthetic import (
    blobs,
    mnist_like,
    mnist_like_multiclass,
    rings,
    svr_sine,
)

__all__ = [
    "read_csv",
    "read_csv_blocks",
    "write_csv",
    "Partition",
    "partition",
    "MinMaxScaler",
    "merge_minmax",
    "blobs",
    "rings",
    "mnist_like",
    "mnist_like_multiclass",
    "svr_sine",
]
