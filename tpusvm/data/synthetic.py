"""Deterministic synthetic datasets.

The reference validates against out-of-repo CSVs (mnist3_train_data.csv etc.,
main3.cpp:314 — not present in the repo, SURVEY.md §4.3). This module replaces
them with deterministic in-tree generators:

  - `blobs`: two Gaussian clusters, linearly-ish separable — the "debug"-scale
    fixture.
  - `rings`: two concentric annuli — NOT linearly separable, exercises the RBF
    kernel properly (an SVM with a linear kernel fails on it).
  - `mnist_like`: an MNIST-shaped (n, 784) one-vs-rest problem with a low-rank
    "digit manifold" structure, for benchmarking at the reference's exact
    shapes (60k x 784) without network access.

All generators take an explicit seed and are reproducible across platforms
(numpy Generator with a fixed bit generator).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


# Benchmark difficulty calibration. Gaussian pixel noise 330 with NO label
# flips makes held-out accuracy land off the 1.0 ceiling and rise with n the
# way real MNIST does (measured on the TPU chip, one-vs-rest digit 1, C=10,
# gamma=0.00125: n=6k -> 0.9865, 12k -> 0.9922, 30k -> 0.9928,
# 60k -> 0.9955 with 2172 SVs / 43.7k iterations; real MNIST-60k: 0.9969 /
# 1548 SVs), so benchmark accuracy columns carry information about the
# learning problem. The previous recipe (noise=30, label_noise=0.005) pinned
# accuracy at the label-flip ceiling — flat 0.9932 at every n.
BENCH_NOISE = 330.0
BENCH_LABEL_NOISE = 0.0
# 10-class variant: all classes overlap each other, so the same noise is
# harsher under an argmax decision; 300 lands held-out 10-class accuracy at
# 0.987 (measured, n=8k train) — the band real-MNIST 10-class RBF SVMs
# occupy (~0.984) — instead of the old recipe's uninformative 1.0.
BENCH_NOISE_MULTICLASS = 300.0


def blobs(
    n: int = 200, d: int = 2, sep: float = 3.0, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Two Gaussian blobs at +/- sep/2 along each axis. Labels {+1,-1}."""
    rng = np.random.default_rng(seed)
    n_pos = n // 2
    n_neg = n - n_pos
    Xp = rng.normal(loc=+sep / 2, scale=1.0, size=(n_pos, d))
    Xn = rng.normal(loc=-sep / 2, scale=1.0, size=(n_neg, d))
    X = np.concatenate([Xp, Xn], axis=0)
    Y = np.concatenate([np.ones(n_pos, np.int32), -np.ones(n_neg, np.int32)])
    perm = rng.permutation(n)
    return X[perm], Y[perm]


def rings(
    n: int = 400, r_inner: float = 1.0, r_outer: float = 3.0, noise: float = 0.15,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Two concentric rings in 2-D. Inner ring = +1, outer ring = -1."""
    rng = np.random.default_rng(seed)
    n_pos = n // 2
    n_neg = n - n_pos
    theta = rng.uniform(0, 2 * np.pi, size=n)
    r = np.concatenate(
        [
            r_inner + rng.normal(0, noise, n_pos),
            r_outer + rng.normal(0, noise, n_neg),
        ]
    )
    X = np.stack([r * np.cos(theta), r * np.sin(theta)], axis=1)
    Y = np.concatenate([np.ones(n_pos, np.int32), -np.ones(n_neg, np.int32)])
    perm = rng.permutation(n)
    return X[perm], Y[perm]


def svr_sine(
    n: int = 400, d: int = 2, noise: float = 0.05, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Smooth regression problem for epsilon-SVR: continuous targets.

    X uniform on [-3, 3]^d; the target is a sine of the first coordinate
    plus small linear terms of the rest (so every feature carries signal
    but the problem stays dominated by a 1-D nonlinearity an RBF machine
    resolves easily), plus gaussian target noise. Returns (X, t) with t
    float64 — the labels column is a CONTINUOUS target, not a class.
    """
    rng = np.random.default_rng(seed)
    X = rng.uniform(-3.0, 3.0, size=(n, d))
    t = np.sin(X[:, 0])
    for j in range(1, d):
        t = t + 0.25 * X[:, j]
    if noise > 0:
        t = t + rng.normal(0, noise, size=n)
    return X, t


def mnist_like_multiclass(
    n: int = 60000, d: int = 784, n_classes: int = 10, rank: int = 32, seed: int = 587,
    noise: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped multi-class problem; returns raw class ids (0..n_classes-1).

    Each class lives on its own low-rank affine manifold in [0, 255]^d (like
    digit images: correlated pixels, bounded intensities), then values are
    clipped to [0, 255] and rounded to integers like pixel data. `noise` adds
    per-pixel gaussian noise (std in pixel units) to control the problem's
    difficulty: higher noise -> more overlap -> more support vectors and SMO
    iterations (used by bench.py to match real-MNIST difficulty).
    """
    rng = np.random.default_rng(seed)
    per = np.full(n_classes, n // n_classes)
    per[: n % n_classes] += 1
    xs = []
    for c in range(n_classes):
        basis = rng.normal(0, 1, size=(rank, d))
        center = rng.uniform(30, 225, size=(d,)) * (rng.random(d) < 0.25)
        coeff = rng.normal(0, 18.0, size=(per[c], rank))
        Xc = center + coeff @ basis
        if noise > 0:
            Xc += rng.normal(0, noise, size=Xc.shape)
        np.clip(Xc, 0, 255, out=Xc)
        np.rint(Xc, out=Xc)
        xs.append(Xc)
    X = np.concatenate(xs, axis=0)
    labels = np.concatenate(
        [np.full(per[c], c, np.int32) for c in range(n_classes)]
    )
    perm = rng.permutation(n)
    return X[perm], labels[perm]


def mnist_like(
    n: int = 60000, d: int = 784, n_classes: int = 10, rank: int = 32,
    positive_class: int = 1, seed: int = 587, noise: float = 0.0,
    label_noise: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped ONE-VS-REST problem: labels in {+1,-1}.

    One-vs-rest on `positive_class` exactly as the reference maps MNIST
    (label != 1 -> -1, main3.cpp:49-52). Returns (X, Y) with X float64 in
    [0, 255], Y in {+1,-1}.

    `label_noise` deterministically flips that fraction of labels (separate
    rng stream; X is unaffected). Flipped points become bound support
    vectors, pushing SV count and SMO iteration count into the range real
    MNIST exhibits (~1548 SVs / tens of thousands of iterations) — bench.py
    uses this to match the reference workload's difficulty.
    """
    X, labels = mnist_like_multiclass(n, d, n_classes, rank, seed, noise)
    Y = np.where(labels == positive_class, 1, -1).astype(np.int32)
    if label_noise > 0:
        flip_rng = np.random.default_rng(seed + 104729)
        idx = flip_rng.choice(n, int(label_noise * n), replace=False)
        Y[idx] = -Y[idx]
    return X, Y
