"""CSV dataset reader with the reference's exact semantics.

Reference: read_CSV in main3.cpp:13-54 (and the n_limit-capped variant in
gpu_svm_main4.cu:16-59):
  - the first line is a header and is discarded; the number of features is
    (number of header fields - 1) — the last column is the label;
  - data rows with fewer than 2 comma-separated fields are skipped;
  - the label is the last field, parsed as int, mapped `label != 1 -> -1`
    (one-vs-rest, digit "1" vs. rest);
  - optional `n_limit` caps the number of rows kept (gpu_svm_main4.cu:38-40).

One generalisation beyond the reference: `positive_label` parameterises the
one-vs-rest mapping (the reference hard-codes digit "1", main3.cpp:49-52) —
`binary=True, positive_label=k` maps `label != k -> -1`. The default k=1
reproduces the reference bit-for-bit.

Returns float64 row-major X and int32 Y, matching the reference's
vector<double>/vector<int>.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np


def _iter_rows(f, n_limit: Optional[int], binary: bool, positive_label: int):
    """Shared row loop: yields (features list, mapped label) per kept row."""
    kept = 0
    for line in f:
        if n_limit is not None and kept >= n_limit:
            break
        fields = line.rstrip("\n").split(",")
        if len(fields) < 2:  # must have at least one feature + label
            continue
        label = int(float(fields[-1]))
        if binary:
            label = 1 if label == positive_label else -1
        kept += 1
        yield [float(v) for v in fields[:-1]], label


def read_csv(
    filename: str,
    n_limit: Optional[int] = None,
    binary: bool = True,
    positive_label: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Read a labelled CSV the way the reference does.

    Args:
      filename: path to a CSV whose last column is an integer label.
      n_limit: if given, keep at most this many data rows (gpu_svm_main4.cu).
      binary: map labels `!= positive_label -> -1` (the reference's
        one-vs-rest mapping, main3.cpp:49-52); False keeps raw integer
        labels for multi-class use.
      positive_label: the class mapped to +1 in binary mode (default 1,
        the reference's hard-coded digit).

    Returns:
      (X, Y): X float64 of shape (n, n_features); Y int32 of shape (n,) with
      values in {+1, -1} when binary, raw labels otherwise.
    """
    xs = []
    ys = []
    with open(filename, "r") as f:
        header = f.readline()  # discarded; defines the column count
        n_features = len(header.rstrip("\n").split(",")) - 1
        for row, label in _iter_rows(f, n_limit, binary, positive_label):
            xs.append(row)
            ys.append(label)
    if not ys:
        return np.zeros((0, max(n_features, 0)), np.float64), np.zeros((0,), np.int32)
    X = np.asarray(xs, dtype=np.float64)
    Y = np.asarray(ys, dtype=np.int32)
    return X, Y


def read_csv_regression(
    filename: str,
    n_limit: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Read a CSV whose last column is a CONTINUOUS regression target.

    Same layout rules as read_csv (header discarded, <2-field rows
    skipped, n_limit cap) but the target keeps its float value instead of
    the reference's int-parse + one-vs-rest mapping — the epsilon-SVR
    input path. Returns (X float64, t float64).
    """
    xs = []
    ts = []
    kept = 0
    with open(filename, "r") as f:
        header = f.readline()
        n_features = len(header.rstrip("\n").split(",")) - 1
        for line in f:
            if n_limit is not None and kept >= n_limit:
                break
            fields = line.rstrip("\n").split(",")
            if len(fields) < 2:
                continue
            kept += 1
            xs.append([float(v) for v in fields[:-1]])
            ts.append(float(fields[-1]))
    if not ts:
        return (np.zeros((0, max(n_features, 0)), np.float64),
                np.zeros((0,), np.float64))
    return np.asarray(xs, np.float64), np.asarray(ts, np.float64)


def read_csv_blocks(
    filename: str,
    block_rows: int = 8192,
    n_limit: Optional[int] = None,
    binary: bool = True,
    positive_label: int = 1,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream a labelled CSV as (X, Y) blocks of at most block_rows rows.

    Identical row/label semantics to read_csv (the concatenation of all
    yielded blocks equals read_csv's output bit-for-bit) with peak memory
    bounded by one block — the ingest path for datasets that do not fit in
    RAM (tpusvm.stream.format.ingest_csv). Yields nothing for a header-only
    file.
    """
    if block_rows < 1:
        raise ValueError(f"block_rows must be >= 1, got {block_rows}")
    with open(filename, "r") as f:
        f.readline()  # header: discarded; column count checked row-wise
        xs, ys = [], []
        for row, label in _iter_rows(f, n_limit, binary, positive_label):
            xs.append(row)
            ys.append(label)
            if len(ys) == block_rows:
                yield (np.asarray(xs, np.float64), np.asarray(ys, np.int32))
                xs, ys = [], []
        if ys:
            yield (np.asarray(xs, np.float64), np.asarray(ys, np.int32))


def write_csv(filename: str, X: np.ndarray, Y: np.ndarray) -> None:
    """Write (X, Y) in the format read_csv expects (header + last-column label)."""
    n, d = X.shape
    tmp = filename + ".tmp"
    with open(tmp, "w") as f:
        f.write(",".join([f"f{j}" for j in range(d)] + ["label"]) + "\n")
        for i in range(n):
            f.write(",".join(repr(float(v)) for v in X[i]) + f",{int(Y[i])}\n")
    os.replace(tmp, filename)
