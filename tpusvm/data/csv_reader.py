"""CSV dataset reader with the reference's exact semantics.

Reference: read_CSV in main3.cpp:13-54 (and the n_limit-capped variant in
gpu_svm_main4.cu:16-59):
  - the first line is a header and is discarded; the number of features is
    (number of header fields - 1) — the last column is the label;
  - data rows with fewer than 2 comma-separated fields are skipped;
  - the label is the last field, parsed as int, mapped `label != 1 -> -1`
    (one-vs-rest, digit "1" vs. rest);
  - optional `n_limit` caps the number of rows kept (gpu_svm_main4.cu:38-40).

Returns float64 row-major X and int32 Y, matching the reference's
vector<double>/vector<int>.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def read_csv(
    filename: str, n_limit: Optional[int] = None, binary: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """Read a labelled CSV the way the reference does.

    Args:
      filename: path to a CSV whose last column is an integer label.
      n_limit: if given, keep at most this many data rows (gpu_svm_main4.cu).
      binary: map labels `!= 1 -> -1` (the reference's one-vs-rest mapping,
        main3.cpp:49-52); False keeps raw integer labels for multi-class use.

    Returns:
      (X, Y): X float64 of shape (n, n_features); Y int32 of shape (n,) with
      values in {+1, -1} when binary, raw labels otherwise.
    """
    xs = []
    ys = []
    with open(filename, "r") as f:
        header = f.readline()  # discarded; defines the column count
        n_features = len(header.rstrip("\n").split(",")) - 1
        for line in f:
            if n_limit is not None and len(ys) >= n_limit:
                break
            fields = line.rstrip("\n").split(",")
            if len(fields) < 2:  # must have at least one feature + label
                continue
            xs.append([float(v) for v in fields[:-1]])
            label = int(float(fields[-1]))
            ys.append((1 if label == 1 else -1) if binary else label)
    if not ys:
        return np.zeros((0, max(n_features, 0)), np.float64), np.zeros((0,), np.int32)
    X = np.asarray(xs, dtype=np.float64)
    Y = np.asarray(ys, dtype=np.int32)
    return X, Y


def write_csv(filename: str, X: np.ndarray, Y: np.ndarray) -> None:
    """Write (X, Y) in the format read_csv expects (header + last-column label)."""
    n, d = X.shape
    with open(filename, "w") as f:
        f.write(",".join([f"f{j}" for j in range(d)] + ["label"]) + "\n")
        for i in range(n):
            f.write(",".join(repr(float(v)) for v in X[i]) + f",{int(Y[i])}\n")
