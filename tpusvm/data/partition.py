"""Contiguous data partitioning with global IDs.

Reference: the MPI scatter (mpi_svm_main3.cpp:463-518) splits the dataset into
P contiguous chunks of ceil(n/P) rows each (the last chunk may be short) and
assigns each row its original index as a global ID; the cascade's dedup-by-ID
union builder (C21) and ID-set convergence test (C24) both key on these IDs.

On TPU there is no scatter: the partition is expressed as a padded (P, cap, d)
array + validity mask, which is then laid out over the mesh with a
NamedSharding so each mesh member holds exactly one chunk. Padding keeps
shapes static for XLA (SURVEY.md §7.3 "Dynamic shapes").
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Partition(NamedTuple):
    """P padded chunks. Arrays are host-side numpy; sharding happens later.

    X:     (P, cap, d) float  — rows beyond `count[p]` are zero padding
    Y:     (P, cap) int32     — padded entries are 0 (neither +1 nor -1)
    ids:   (P, cap) int32     — global row index; padded entries are -1
    valid: (P, cap) bool
    count: (P,) int32
    """

    X: np.ndarray
    Y: np.ndarray
    ids: np.ndarray
    valid: np.ndarray
    count: np.ndarray


def partition(X: np.ndarray, Y: np.ndarray, n_shards: int) -> Partition:
    """Split (X, Y) into n_shards contiguous ceil(n/P)-row padded chunks.

    Like the reference's scatter, trailing shards can be short — or entirely
    empty when n < n_shards * ceil(n/n_shards) by a full chunk. Empty shards
    solve to NO_WORKING_SET with an empty SV set; the cascade layer masks
    them out of merges, so they are harmless there, but callers running
    per-shard solves directly should check `count` first.
    """
    n, d = X.shape
    cap = -(-n // n_shards)  # ceil
    Xp = np.zeros((n_shards, cap, d), X.dtype)
    Yp = np.zeros((n_shards, cap), np.int32)
    ids = np.full((n_shards, cap), -1, np.int32)
    valid = np.zeros((n_shards, cap), bool)
    count = np.zeros((n_shards,), np.int32)
    for p in range(n_shards):
        lo = p * cap
        hi = min(lo + cap, n)
        c = max(hi - lo, 0)
        if c:
            Xp[p, :c] = X[lo:hi]
            Yp[p, :c] = Y[lo:hi]
            ids[p, :c] = np.arange(lo, hi, dtype=np.int32)
            valid[p, :c] = True
        count[p] = c
    return Partition(Xp, Yp, ids, valid, count)
