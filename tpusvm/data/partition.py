"""Data partitioning with global IDs: contiguous (reference) or stratified.

Reference: the MPI scatter (mpi_svm_main3.cpp:463-518) splits the dataset into
P contiguous chunks of ceil(n/P) rows each (the last chunk may be short) and
assigns each row its original index as a global ID; the cascade's dedup-by-ID
union builder (C21) and ID-set convergence test (C24) both key on these IDs.

On TPU there is no scatter: the partition is expressed as a padded (P, cap, d)
array + validity mask, which is then laid out over the mesh with a
NamedSharding so each mesh member holds exactly one chunk. Padding keeps
shapes static for XLA (SURVEY.md §7.3 "Dynamic shapes").

The contiguous split is reference-faithful but class-blind: on label-sorted
input it hands cascade leaves single-class (or class-starved) shards, whose
solves die NO_WORKING_SET — the exact shape the `pallas-mp-adv` parity fuzz
constructs deliberately (block-sorted labels). `stratified=True` deals each
class's rows round-robin over the shards instead, so every shard carries
both classes at near the global ratio regardless of input order; global IDs
are unchanged (still the original row indices), so dedup-by-ID and the
convergence test are oblivious to which split produced the shards.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Partition(NamedTuple):
    """P padded chunks. Arrays are host-side numpy; sharding happens later.

    X:     (P, cap, d) float  — rows beyond `count[p]` are zero padding
    Y:     (P, cap) int32     — padded entries are 0 (neither +1 nor -1)
    ids:   (P, cap) int32     — global row index; padded entries are -1
    valid: (P, cap) bool
    count: (P,) int32
    """

    X: np.ndarray
    Y: np.ndarray
    ids: np.ndarray
    valid: np.ndarray
    count: np.ndarray


def _fill(X: np.ndarray, Y: np.ndarray, n_shards: int, cap: int,
          shard_rows) -> Partition:
    n, d = X.shape
    Xp = np.zeros((n_shards, cap, d), X.dtype)
    Yp = np.zeros((n_shards, cap), np.int32)
    ids = np.full((n_shards, cap), -1, np.int32)
    valid = np.zeros((n_shards, cap), bool)
    count = np.zeros((n_shards,), np.int32)
    for p, rows in enumerate(shard_rows):
        c = len(rows)
        if c:
            idx = np.asarray(rows, np.int32)
            Xp[p, :c] = X[idx]
            Yp[p, :c] = Y[idx]
            ids[p, :c] = idx
            valid[p, :c] = True
        count[p] = c
    return Partition(Xp, Yp, ids, valid, count)


def partition(X: np.ndarray, Y: np.ndarray, n_shards: int,
              stratified: bool = False) -> Partition:
    """Split (X, Y) into n_shards padded chunks with global IDs.

    stratified=False (default): the reference's contiguous ceil(n/P)-row
    scatter — trailing shards can be short, or entirely empty when
    n < n_shards * ceil(n/n_shards) by a full chunk. Empty shards solve to
    NO_WORKING_SET with an empty SV set; the cascade layer masks them out
    of merges, so they are harmless there, but callers running per-shard
    solves directly should check `count` first.

    stratified=True: per-class round-robin — class c's rows (in original
    order) are dealt one at a time over the shards, with the starting
    shard staggered per class so the "one extra row" remainders of
    different classes don't all pile onto shard 0. Shard sizes stay within
    one row per class of each other; cap is the realised maximum, so the
    padded width can differ from the contiguous split's ceil(n/P) by at
    most (n_classes - 1). Row order within a shard interleaves classes —
    irrelevant to the solver, which is order-free over the validity mask.
    """
    n, d = X.shape
    if not stratified:
        cap = -(-n // n_shards)  # ceil
        shard_rows = [range(p * cap, min(p * cap + cap, n))
                      if p * cap < n else range(0)
                      for p in range(n_shards)]
        return _fill(X, Y, n_shards, cap, shard_rows)

    shard_rows = [[] for _ in range(n_shards)]
    for ci, c in enumerate(np.unique(Y)):
        for j, i in enumerate(np.flatnonzero(Y == c)):
            shard_rows[(ci + j) % n_shards].append(int(i))
    cap = max(1, max(len(rows) for rows in shard_rows))
    return _fill(X, Y, n_shards, cap, shard_rows)
