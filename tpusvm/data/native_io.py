"""ctypes bridge to the native CSV loader (native/csv_reader.cpp).

The reference's data layer is C++ (read_CSV, main3.cpp:13-54); this is the
framework's native equivalent — a multi-threaded C++ parser behind a C ABI,
loaded with ctypes (no pybind11 in this environment). `read_csv_fast`
transparently falls back to the pure-Python reference-faithful reader
(csv_reader.read_csv) when the shared library hasn't been built
(scripts/build_native.sh) — the native path is a fast path, never a
requirement.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from tpusvm.data.csv_reader import read_csv as _py_read_csv

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "_native",
    "libtpusvm_io.so",
)


class _CsvData(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int64),
        ("d", ctypes.c_int64),
        ("X", ctypes.POINTER(ctypes.c_double)),
        ("Y", ctypes.POINTER(ctypes.c_int32)),
        ("error", ctypes.c_int64),
    ]


_lib = None
_lib_checked = False


def _load_lib():
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.tpusvm_read_csv.restype = ctypes.POINTER(_CsvData)
    lib.tpusvm_read_csv.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.tpusvm_free_csv.restype = None
    lib.tpusvm_free_csv.argtypes = [ctypes.POINTER(_CsvData)]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load_lib() is not None


def read_csv_fast(
    filename: str,
    n_limit: Optional[int] = None,
    binary_labels: bool = True,
    n_threads: int = 0,
    positive_label: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """read_csv with the native multi-threaded parser when available.

    Same contract as data.read_csv (header skipped, last column = label,
    binary mode maps label != positive_label -> -1, rows with < 2 fields
    skipped, n_limit caps rows); binary_labels=False keeps raw integer
    labels for multi-class use. n_threads=0 = one per hardware thread.

    positive_label: the class mapped to +1 in binary mode. The C ABI only
    knows the reference's hard-coded `1 vs rest` mapping, so a non-default
    positive_label reads RAW labels through the native parser and remaps
    them vectorised on the host — same bytes out as the pure-Python
    reader, still one native parse of the file.
    """
    lib = _load_lib()
    if lib is None:
        return _py_read_csv(filename, n_limit, binary=binary_labels,
                            positive_label=positive_label)

    remap = binary_labels and positive_label != 1
    ptr = lib.tpusvm_read_csv(
        os.fsencode(filename),
        -1 if n_limit is None else int(n_limit),
        0 if remap else (1 if binary_labels else 0),
        int(n_threads),
    )
    if not ptr:
        raise OSError(f"native CSV reader failed to open {filename!r}")
    try:
        data = ptr.contents
        if int(data.error) == 2:
            raise MemoryError(
                f"{filename!r}: native CSV reader ran out of memory"
            )
        if int(data.error):
            # mirror the pure-Python reader, which raises ValueError on
            # unparsable fields / ragged rows
            raise ValueError(
                f"{filename!r}: malformed CSV (unparsable field or row "
                "whose field count differs from the header)"
            )
        n, d = int(data.n), int(data.d)
        if n == 0:
            return (np.zeros((0, max(d, 0)), np.float64),
                    np.zeros((0,), np.int32))
        X = np.ctypeslib.as_array(data.X, shape=(n, d)).copy()
        Y = np.ctypeslib.as_array(data.Y, shape=(n,)).copy()
        if remap:
            Y = np.where(Y == positive_label, 1, -1).astype(np.int32)
        return X, Y
    finally:
        lib.tpusvm_free_csv(ptr)
