"""Batched many-model SMO: train a fleet of SVMs as ONE XLA program.

The cascade parallelises one problem across workers; this module
parallelises PROBLEMS across one device program. B optimisation problems
sharing X but with distinct (y, C, gamma) — the 10 OvR heads, a tune
rung's (C, gamma) population, per-tenant heads — vmap over the blocked
solver's core (solver/blocked.py `blocked_smo_core`, the "Fleet vmap
contract" refactor): one jit launch, one X residency, every problem's
FLOPs batched into the same MXU contractions. Problems individually too
small to saturate the hardware ride together.

Per-problem convergence masking is structural, not bolted on: the core's
ENTIRE solve state lives in its while-loop carry, so JAX's while/cond
batching rules turn the batched stop into "loop while any problem still
RUNNING" and freeze a terminated problem's carry with a per-lane select.
A converged problem no-ops its alpha/f updates; the Keerthi stop is the
batched all-problems reduction; the per-problem update/round counters and
the telemetry ring simply gain the leading problem axis. A problem's
result is therefore BIT-IDENTICAL no matter which companions share its
bucket program (tests/test_fleet.py pins this bitwise — the hard
no-crosstalk gate). Against a separately-compiled solo program the
convergence point matches at the solution level (identical SV sets, b
within the cross-engine band): XLA emits different fma/fusion patterns
for batched vs unbatched programs, so cross-PROGRAM bitwise equality is
not a property any XLA rewrite preserves — parity gates compare SV
identity and accuracy exactly, b/alpha at the oracle band, and reserve
bitwise assertions for same-program lane invariance.

Launch economics: power-of-two problem buckets (fleet/batch.py) bound
jit signatures, and C/gamma enter as ARRAYS — their values cannot bake
into the trace, so a whole (C, gamma) sweep at one bucket is ONE compile
(the weak-scalar discipline obs/prof.py keys caches by, here enforced by
construction; benchmarks/fleet_train.py gates recompiles == 0 across a
sweep). One kernel-family bucket per launch: the family and every other
jit-static knob are shared by the whole fleet — per-problem statics are
a contradiction in terms, validated at the boundary
(batch.fleet_opt_errors).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm import kernels
from tpusvm.fleet.batch import bucket_for, fleet_opt_errors, pack_problems
from tpusvm.fleet.results import lane_result, unpack_results
from tpusvm.obs import prof
from tpusvm.ops.rbf import sq_norms
from tpusvm.solver.blocked import blocked_smo_core
from tpusvm.solver.smo import SMOResult
from tpusvm.status import Status

# the fleet launch's static surface: the vmap-clean subset of
# _BLOCKED_STATIC (solver/blocked.py) — everything Pallas/host-segmented
# is pinned off inside the vmapped call and rejected at the boundary
_FLEET_STATIC = (
    "q", "max_outer", "max_inner", "warm_start", "accum_dtype",
    "wss", "selection", "refine", "max_refines", "matmul_precision",
    "telemetry", "kernel", "degree", "kernel_fast", "return_state",
)


@functools.partial(jax.jit, static_argnames=_FLEET_STATIC)
def _fleet_smo_solve_jit(
    X: jax.Array,
    Ys: jax.Array,
    valids: Optional[jax.Array] = None,
    alpha0s: Optional[jax.Array] = None,
    *,
    Cs: jax.Array,
    gammas: jax.Array,
    sn: Optional[jax.Array] = None,
    eps: float = 1e-12,
    tau: float = 1e-5,
    max_iter: int = 100000,
    q: int = 1024,
    max_outer: int = 5000,
    max_inner: int = 1024,
    warm_start: bool = False,
    accum_dtype=None,
    wss: int = 1,
    selection: str = "auto",
    refine: int = 0,
    max_refines: int = 2,
    matmul_precision: Optional[str] = None,
    telemetry: int = 0,
    kernel: str = "rbf",
    degree: int = 3,
    coef0: float = 0.0,
    kernel_fast: bool = True,
    resume_states=None,
    pause_at: Optional[jax.Array] = None,
    return_state: bool = False,
) -> SMOResult:
    """Solve B problems sharing X as one batched program.

    Ys is (B, n) with per-problem +/-1 labels (0 = inert padding lane,
    fleet/batch.py); Cs/gammas are (B,) per-problem hyperparameters —
    ARRAYS, so a sweep over their values reuses one executable. valids
    (B, n) and alpha0s (B, n) are optional per-problem row masks and
    warm seeds. Every static knob is shared by the launch; the result
    is a batched SMOResult — every field (alpha, b, status, n_iter,
    n_outer, telemetry ring...) carries the leading problem axis.

    sn: optional precomputed sq_norms(X) — shared by every problem (X
    is shared), computed once here when omitted; rbf only.

    resume_states / pause_at / return_state: the problem-axis
    compaction surface (fleet_train's segment driver, mirroring the
    checkpoint/shrink segmenters): pause_at stops every lane once ITS
    n_outer reaches the bound (running lanes advance in lockstep, so
    this is a segment boundary), return_state=True also returns the
    batched carry, and resume_states re-enters from a carry whose
    problem axis the driver may have SLICED down to a smaller bucket —
    each lane's carry is independent, so dropping finished lanes and
    re-entering is exact per surviving lane.
    """
    if Ys.ndim != 2:
        raise ValueError(
            f"fleet_smo_solve wants Ys of shape (B, n), got {Ys.shape}; "
            "for a single problem use blocked_smo_solve"
        )
    B, n = Ys.shape
    if X.shape[0] != n:
        raise ValueError(
            f"fleet problems carry {n} rows but X has {X.shape[0]}"
        )
    for name, arr in (("Cs", Cs), ("gammas", gammas)):
        arr = jnp.asarray(arr)
        if arr.shape != (B,):
            raise ValueError(
                f"{name} must be one value per problem, shape ({B},), "
                f"got {arr.shape}"
            )
    adt = X.dtype if accum_dtype is None else accum_dtype
    if valids is None:
        valids = jnp.ones((B, n), bool)
    if alpha0s is None:
        alpha0s = jnp.zeros((B, n), adt)

    # one X stream for the WHOLE fleet (every problem shares the rows);
    # only the rbf family has row norms
    if kernels.needs_norms(kernel) and sn is None:
        sn = sq_norms(X)

    def one(y, valid, alpha0, C, gamma, resume_state=None):
        # dtype discipline: a solo solve receives C/gamma as WEAK python
        # floats, which adopt the context dtype (gamma the f32 kernel
        # pipeline, C the accum-dtype comparisons); the batched lanes
        # arrive as STRONG f64 array elements, which would silently
        # promote the f32 kernel evaluations to f64 — cast each to the
        # dtype its solo trace computes in, so the batched program is
        # the vmap of the identical program
        return blocked_smo_core(
            X, y, valid, alpha0, sn=sn,
            C=C.astype(adt), gamma=gamma.astype(X.dtype), eps=eps,
            tau=tau, max_iter=max_iter, q=q, max_outer=max_outer,
            max_inner=max_inner, warm_start=warm_start,
            accum_dtype=accum_dtype, inner="xla", wss=wss,
            selection=selection, refine=refine, max_refines=max_refines,
            matmul_precision=matmul_precision, fused_fupdate=False,
            telemetry=telemetry, kernel=kernel, degree=degree,
            coef0=coef0, kernel_fast=kernel_fast,
            resume_state=resume_state, pause_at=pause_at,
            return_state=return_state,
        )

    mapped = (Ys, valids, alpha0s, jnp.asarray(Cs), jnp.asarray(gammas))
    if resume_states is None:
        return jax.vmap(one)(*mapped)
    return jax.vmap(one)(*mapped, resume_states)


# observatory + IR-audit registration: the fleet launch is a first-class
# jit entry point — `ir-audit` traces its batched jaxpr (JXIR101-106) and
# `--trace` runs record its lower/compile cost like every other entry
fleet_smo_solve = prof.profiled_jit(
    "solver.fleet_smo_solve", _fleet_smo_solve_jit, static=_FLEET_STATIC,
)


def _slice_lanes(tree, idx):
    """Slice every leaf of a batched pytree down to the given lanes."""
    idx = jnp.asarray(idx, jnp.int32)
    return jax.tree.map(lambda x: x[idx], tree)


def fleet_train(
    X,
    Ys: Sequence,
    Cs: Sequence[float],
    gammas: Sequence[float],
    *,
    valids=None,
    alpha0s=None,
    sn=None,
    bucket: Optional[int] = None,
    compact_every: int = 0,
    **solver_opts,
) -> List[SMOResult]:
    """Pack -> fleet launch(es) -> per-problem SMOResults.

    The convenience driver consumers call (models.ovr trains all heads
    through one of these; tune dispatches each rung's fold batch as
    one): packs the B problems into a power-of-two bucket with inert
    padding (fleet/batch.py), validates the static knobs are
    fleet-compatible, launches, and unpacks the padded batched result
    back into per-problem SMOResults (fleet/results.py). solver_opts
    are the fleet statics (q, wss, telemetry, kernel, ...) plus
    eps/tau/max_iter.

    compact_every=0 (default): ONE launch to global convergence — one
    program, one dispatch; right when the fleet's round counts are
    balanced (OvR heads) or the backend is parallel enough that the
    lockstep waste is hidden (TPU). R > 0: problem-axis COMPACTION —
    run R outer rounds per segment (pause_at), harvest lanes whose
    status left RUNNING, slice the surviving lanes' carries down to the
    next power-of-two bucket and resume (resume_states). The batched
    while-loop otherwise runs every lane until the SLOWEST converges
    (a finished lane's carry is frozen but its lockstep body compute is
    not free), so an imbalanced fleet — a tune rung's (C, gamma)
    population — pays ~B*max(rounds) lane-rounds; compaction bounds
    that at ~sum(rounds) + B*R. Each lane's carry is independent state,
    so segmenting + slicing is exact per problem; compiled programs
    stay bounded at <= 2 per bucket (cold entry + resume entry).
    """
    errors = fleet_opt_errors(solver_opts)
    if errors:
        raise ValueError("; ".join(errors))
    if compact_every < 0:
        raise ValueError(
            f"compact_every must be >= 0 rounds, got {compact_every}"
        )
    family = solver_opts.get("kernel", "rbf")
    if kernels.is_approx(family) and len(set(map(float, gammas))) > 1:
        # explicit interop decision (no silent wrong-answer path): an
        # approx family's X is ALREADY the mapped features, whose map
        # was built from ONE gamma — the per-problem gammas array is
        # inert for the linear-geometry dispatch, so distinct values
        # would silently all train against the map's gamma
        raise ValueError(
            f"fleet with the approximate family {family!r} requires a "
            "single shared gamma: gamma parameterises the feature map "
            "the shared X was built with (tpusvm.approx), not the "
            "per-problem kernel — got distinct gammas "
            f"{sorted(set(map(float, gammas)))}"
        )
    # strip knobs at their inert defaults: the fleet jit's signature
    # does not carry them (they are pinned inside the vmapped call)
    opts = {k: v for k, v in solver_opts.items()
            if k not in ("inner", "fused_fupdate", "krow_cache",
                         "shrink_stable", "pallas_fused_selection",
                         "pallas_eta_exclude", "pallas_multipair",
                         "resume_state", "pause_at", "return_state",
                         "pallas_layout")}
    batch = pack_problems(Ys, Cs, gammas, valids=valids,
                          alpha0s=alpha0s, bucket=bucket)
    if batch.alpha0s is not None:
        # seeded problems need the warm-start f reconstruction; cold
        # lanes carry alpha0=0, whose reconstruction is exactly -z, so
        # mixing seeded and cold problems in one warm launch is exact
        opts.setdefault("warm_start", True)
    adt = opts.get("accum_dtype")
    Ys_d = jnp.asarray(batch.Ys)
    valids_d = (None if batch.valids is None
                else jnp.asarray(batch.valids))
    alpha0s_d = (None if batch.alpha0s is None
                 else jnp.asarray(batch.alpha0s,
                                  adt if adt is not None else X.dtype))
    Cs_d = jnp.asarray(batch.Cs)
    gs_d = jnp.asarray(batch.gammas)

    if not compact_every:
        res = fleet_smo_solve(X, Ys_d, valids_d, alpha0s_d,
                              Cs=Cs_d, gammas=gs_d, sn=sn, **opts)
        return unpack_results(res, batch.n_problems)

    # segment driver: lanes = positions into the ORIGINAL problem list;
    # padding lanes terminate NO_WORKING_SET in segment 1 and are
    # dropped with the first harvest (their results are discarded)
    results = {}
    live = list(range(batch.bucket))
    states = None
    seg = 0
    while live:
        seg += 1
        pause = jnp.int32(seg * compact_every)
        res, states = fleet_smo_solve(
            X, Ys_d, valids_d, alpha0s_d, Cs=Cs_d, gammas=gs_d, sn=sn,
            resume_states=states, pause_at=pause, return_state=True,
            **opts,
        )
        statuses = np.asarray(res.status)
        for i, lane in enumerate(live):
            if statuses[i] != Status.RUNNING and lane < batch.n_problems:
                results[lane] = lane_result(res, i)
        keep = [i for i in range(len(live))
                if statuses[i] == Status.RUNNING]
        live = [live[i] for i in keep]
        if not live:
            break
        # re-bucket the survivors: pad the KEPT lane list back up to a
        # power of two by repeating the last survivor — a duplicated
        # lane computes identical (discarded) results, stays inert to
        # its twin, and keeps every array at a bucketed shape
        bkt = bucket_for(len(live))
        sel = keep + [keep[-1]] * (bkt - len(keep))
        Ys_d = Ys_d[jnp.asarray(sel)]
        valids_d = None if valids_d is None else valids_d[jnp.asarray(sel)]
        alpha0s_d = (None if alpha0s_d is None
                     else alpha0s_d[jnp.asarray(sel)])
        Cs_d = Cs_d[jnp.asarray(sel)]
        gs_d = gs_d[jnp.asarray(sel)]
        states = _slice_lanes(states, sel)
        live = live + [live[-1]] * (bkt - len(live))
    missing = [i for i in range(batch.n_problems) if i not in results]
    if missing:  # pragma: no cover — every lane terminates (max_outer)
        raise RuntimeError(f"fleet_train lost lanes {missing}")
    return [results[i] for i in range(batch.n_problems)]
