"""Unpacking a batched fleet result into per-problem SMOResults.

The fleet launch returns ONE SMOResult whose every field carries the
leading problem axis (padding lanes included). Consumers — models.ovr's
head loop replacement, tune's rung scoring, the CLI — want the same
per-problem surface the host loop gave them: this module slices the
batch back apart, drops the inert padding lanes, and re-wraps each
problem's telemetry ring slice as its own ConvergenceTelemetry, so a
fleet-trained problem's downstream handling is indistinguishable from a
loop-trained one.
"""

from __future__ import annotations

from typing import List

from tpusvm.solver.smo import SMOResult
from tpusvm.status import Status

__all__ = ["lane_result", "unpack_results", "fleet_convergence_summary"]


def lane_result(res: SMOResult, i: int) -> SMOResult:
    """One lane of a batched SMOResult as a per-problem SMOResult.

    Pure slicing: lane i's alpha/b/status/counters come back bitwise as
    the batched program computed them. The telemetry ring (when the
    launch carried telemetry=T) is sliced and re-wrapped so
    obs.convergence consumers (gap tables, trace events) work per head.
    """
    tele = None
    if res.telemetry is not None:
        t = res.telemetry
        tele = type(t)(gap=t.gap[i], n_upd=t.n_upd[i],
                       status=t.status[i], count=t.count[i],
                       active=t.active[i])
    return SMOResult(
        alpha=res.alpha[i],
        b=res.b[i],
        b_high=res.b_high[i],
        b_low=res.b_low[i],
        n_iter=res.n_iter[i],
        status=res.status[i],
        n_outer=None if res.n_outer is None else res.n_outer[i],
        n_refines=(None if res.n_refines is None
                   else res.n_refines[i]),
        telemetry=tele,
        cache_hits=(None if res.cache_hits is None
                    else res.cache_hits[i]),
        cache_misses=(None if res.cache_misses is None
                      else res.cache_misses[i]),
    )


def unpack_results(res: SMOResult, n_problems: int) -> List[SMOResult]:
    """Batched SMOResult -> per-problem SMOResults (padding dropped)."""
    B = res.alpha.shape[0]
    if n_problems > B:
        raise ValueError(
            f"unpack_results: {n_problems} problems from a {B}-lane "
            "batch"
        )
    return [lane_result(res, i) for i in range(n_problems)]


def fleet_convergence_summary(results: List[SMOResult]) -> dict:
    """Per-problem convergence telemetry, aggregated for logs/benches.

    One host materialisation pass over the unpacked lanes: per-problem
    statuses/updates/rounds plus the fleet-level counts a log line or
    bench row wants. Works with telemetry on or off (the ring only adds
    per-problem recorded-round counts)."""
    statuses = [Status(int(r.status)) for r in results]
    summary = {
        "problems": len(results),
        "converged": sum(s == Status.CONVERGED for s in statuses),
        "statuses": [s.name for s in statuses],
        "updates": [int(r.n_iter) - 1 for r in results],
        "outer_rounds": [int(r.n_outer) for r in results],
    }
    if results and results[0].telemetry is not None:
        summary["telemetry_rounds"] = [int(r.telemetry.count)
                                       for r in results]
    return summary
