"""Problem packing and power-of-two bucketing for fleet solves.

A fleet launch batches B optimisation problems that share one training
matrix X but differ in (y, C, gamma) — OvR heads, a tune rung's
(C, gamma) population, per-tenant classifiers — into ONE jit program
(tpusvm.fleet.solve). Two disciplines keep that program cheap to own:

  * power-of-two problem-count buckets: the batch axis is padded up to
    the next power of two, so the number of distinct jit signatures per
    (n, d, static-config) is log2-bounded — the same bucketing rule
    serve's AOT compile cache and the shrink driver's compaction use.
    Padding problems are PROVABLY inert: an all-zero label vector
    belongs to neither Keerthi index set (ops.selection masks test
    y == +1 / y == -1), so the padded lane terminates NO_WORKING_SET at
    its first masked iteration with alpha identically zero, and the
    while-loop batching rule freezes its carry from then on.

  * per-problem statics validation: everything jit-static (q, kernel
    family, precision rung, telemetry...) is necessarily SHARED by the
    whole launch — one program, one config. The per-problem axis is
    exactly (y, valid, alpha0, C, gamma); anything else a caller wants
    to vary across problems needs separate launches (one per
    kernel-family bucket, the module docstring of fleet/solve.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "FleetBatch",
    "bucket_for",
    "pack_problems",
    "UNSUPPORTED_FLEET_OPTS",
    "fleet_opt_errors",
]

# static solver knobs a fleet launch cannot honour, with the reason a
# caller sees — the vmap-clean restriction of the blocked core
# (solver/blocked.py "Fleet vmap contract"). Values are checked against
# the knob's inert default; requesting anything else raises.
UNSUPPORTED_FLEET_OPTS = {
    "inner": ("xla", "the Pallas inner-SMO kernel has no batching rule; "
              "fleet solves run the XLA subproblem engine"),
    "fused_fupdate": (False, "the fused Pallas f-update has no batching "
                      "rule; fleet uses the kernel-dispatch contraction"),
    "krow_cache": (0, "the K-row LRU cache carries (slots, n) state per "
                   "problem — a (B, slots, n) carry defeats the cache's "
                   "memory model; deferred"),
    "shrink_stable": (0, "the shrinking driver segments the solve "
                     "host-side per problem; fleet problems share one "
                     "uninterrupted program"),
    "pallas_fused_selection": (False, "requires the fused Pallas "
                               "f-update (no batching rule)"),
    "pallas_eta_exclude": (False, "pallas engine flag; fleet runs the "
                           "XLA engine"),
    "pallas_multipair": (1, "pallas engine flag; fleet runs the XLA "
                         "engine"),
    "resume_state": (None, "checkpoint/resume of a fleet launch is a "
                     "future PR"),
    "pause_at": (None, "checkpoint/resume of a fleet launch is a "
                 "future PR"),
    "return_state": (False, "checkpoint/resume of a fleet launch is a "
                     "future PR"),
}


def fleet_opt_errors(opts: dict) -> list:
    """Validation errors for solver knobs a fleet launch cannot honour.

    Returns human-readable messages (empty = clean). Knobs at their
    inert defaults pass — only an ACTIVE unsupported knob is a config
    lie, the same rule pallas_flag_errors applies to engine flags.
    """
    errors = []
    for key, (inert, why) in UNSUPPORTED_FLEET_OPTS.items():
        if key in opts and opts[key] != inert:
            errors.append(
                f"fleet: {key}={opts[key]!r} is not fleet-compatible "
                f"({why})"
            )
    return errors


def bucket_for(n_problems: int) -> int:
    """Smallest power-of-two bucket holding n_problems (min 1)."""
    if n_problems < 1:
        raise ValueError(f"need at least one problem, got {n_problems}")
    return 1 << (n_problems - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class FleetBatch:
    """B problems packed + padded to a power-of-two bucket.

    All arrays carry the bucket-sized leading axis; lanes at index >=
    n_problems are the inert zero-label padding. valids/alpha0s stay
    None when no problem supplied them (the solver's own defaults are
    cheaper than materialised all-true / all-zero arrays)."""

    Ys: np.ndarray                    # (bucket, n) int32
    Cs: np.ndarray                    # (bucket,) float64
    gammas: np.ndarray                # (bucket,) float64
    valids: Optional[np.ndarray]      # (bucket, n) bool or None
    alpha0s: Optional[np.ndarray]     # (bucket, n) float64 or None
    n_problems: int
    bucket: int


def pack_problems(
    Ys: Sequence[np.ndarray],
    Cs: Sequence[float],
    gammas: Sequence[float],
    valids: Optional[Sequence[Optional[np.ndarray]]] = None,
    alpha0s: Optional[Sequence[Optional[np.ndarray]]] = None,
    bucket: Optional[int] = None,
) -> FleetBatch:
    """Stack per-problem (y, C, gamma[, valid, alpha0]) into a FleetBatch.

    Validates the per-problem dynamics: every label vector has the
    shared row count with labels in {-1, 0, +1} (0 only on rows that
    problem's valid mask excludes — a live zero label would silently
    freeze the row), and C/gamma are positive finite. A None entry in
    alpha0s means that problem starts cold (alpha0 = 0, exactly the
    state the solver's own default builds); a None entry in valids
    means all rows live.

    bucket: explicit bucket size (>= n_problems, power of two) — a tune
    rung that will shrink can pin the LARGER bucket so every rung
    reuses one compiled program; default = bucket_for(B).
    """
    B = len(Ys)
    if B == 0:
        raise ValueError("pack_problems: empty problem list")
    if not (len(Cs) == len(gammas) == B):
        raise ValueError(
            f"pack_problems: {B} label vectors but {len(Cs)} C values "
            f"and {len(gammas)} gamma values"
        )
    if valids is not None and len(valids) != B:
        raise ValueError(f"pack_problems: {len(valids)} valid masks "
                         f"for {B} problems")
    if alpha0s is not None and len(alpha0s) != B:
        raise ValueError(f"pack_problems: {len(alpha0s)} alpha0 seeds "
                         f"for {B} problems")

    n = int(np.asarray(Ys[0]).shape[0])
    Y_mat = np.zeros((B, n), np.int32)
    for i, y in enumerate(Ys):
        y = np.asarray(y)
        if y.shape != (n,):
            raise ValueError(
                f"pack_problems: problem {i} has {y.shape} labels; the "
                f"fleet shares X, so every problem needs ({n},)"
            )
        if not np.isin(y, (-1, 0, 1)).all():
            raise ValueError(
                f"pack_problems: problem {i} carries labels outside "
                "{-1, 0, +1}"
            )
        live = y if valids is None or valids[i] is None \
            else y[np.asarray(valids[i], bool)]
        if (live == 0).any():
            raise ValueError(
                f"pack_problems: problem {i} has zero labels on live "
                "rows — a live y=0 row belongs to neither index set and "
                "silently freezes; mask it invalid instead"
            )
        Y_mat[i] = y.astype(np.int32)

    C_vec = np.asarray(Cs, np.float64)
    g_vec = np.asarray(gammas, np.float64)
    for name, vec in (("C", C_vec), ("gamma", g_vec)):
        if not (np.isfinite(vec).all() and (vec > 0).all()):
            raise ValueError(
                f"pack_problems: every per-problem {name} must be a "
                f"positive finite float, got {vec.tolist()}"
            )

    bkt = bucket_for(B) if bucket is None else bucket
    if bkt < B or bkt & (bkt - 1):
        raise ValueError(
            f"pack_problems: bucket={bkt} must be a power of two >= "
            f"the {B} packed problems"
        )
    pad = bkt - B
    if pad:
        # inert padding: zero labels (outside both index sets), C/gamma
        # at any positive value — the lane ends NO_WORKING_SET on its
        # first masked iteration with alpha identically zero
        Y_mat = np.concatenate([Y_mat, np.zeros((pad, n), np.int32)])
        C_vec = np.concatenate([C_vec, np.ones(pad)])
        g_vec = np.concatenate([g_vec, np.ones(pad)])

    valid_mat = None
    if valids is not None and any(v is not None for v in valids):
        valid_mat = np.ones((bkt, n), bool)
        for i, v in enumerate(valids):
            if v is not None:
                v = np.asarray(v, bool)
                if v.shape != (n,):
                    raise ValueError(
                        f"pack_problems: problem {i} valid mask has "
                        f"shape {v.shape}, want ({n},)"
                    )
                valid_mat[i] = v

    alpha_mat = None
    if alpha0s is not None and any(a is not None for a in alpha0s):
        alpha_mat = np.zeros((bkt, n), np.float64)
        for i, a in enumerate(alpha0s):
            if a is not None:
                a = np.asarray(a, np.float64)
                if a.shape != (n,):
                    raise ValueError(
                        f"pack_problems: problem {i} alpha0 has shape "
                        f"{a.shape}, want ({n},)"
                    )
                alpha_mat[i] = a

    return FleetBatch(Ys=Y_mat, Cs=C_vec, gammas=g_vec, valids=valid_mat,
                      alpha0s=alpha_mat, n_problems=B, bucket=bkt)
