"""tpusvm.fleet — batched many-model SMO training (one XLA program).

Public surface:
  fleet_smo_solve  — the batched jit entry (X shared, (B,)-axis y/C/gamma)
  fleet_train      — pack -> one launch -> per-problem SMOResults
  pack_problems / FleetBatch / bucket_for — problem packing + bucketing
  unpack_results / fleet_convergence_summary — result unpacking
"""

from tpusvm.fleet.batch import (
    FleetBatch,
    bucket_for,
    fleet_opt_errors,
    pack_problems,
)
from tpusvm.fleet.results import fleet_convergence_summary, unpack_results
from tpusvm.fleet.solve import fleet_smo_solve, fleet_train

__all__ = [
    "FleetBatch",
    "bucket_for",
    "fleet_opt_errors",
    "pack_problems",
    "fleet_convergence_summary",
    "unpack_results",
    "fleet_smo_solve",
    "fleet_train",
]
