"""Blocked working-set SMO — the TPU-first performance solver.

The pairwise solver (tpusvm.solver.smo) reproduces the reference's
one-pair-per-iteration structure; its per-iteration cost is one O(n*d) HBM
stream for a single 2-variable update, so the machine's MXU sits idle. This
solver restructures the same optimisation the way TPU hardware wants it
(the redesign SURVEY.md §7.3 calls "the whole ballgame"):

  outer iteration:
    1. global Keerthi stop check: b_low <= b_high + 2*tau over the full
       masked f (identical criterion to main3.cpp:213);
    2. working-set selection: the q/2 worst violators from I_high (smallest
       f) and q/2 from I_low (largest f), distinct, via lax.top_k — the
       batched generalisation of calc_i_high/calc_i_low (main3.cpp:107-142);
    3. subproblem: precompute K_BB = K(X_B, X_B) (one small MXU matmul,
       VMEM-resident) and run many pairwise SMO updates entirely inside it
       — each inner iteration is O(q) with NO HBM traffic;
    4. global error-vector update: f += K(X, X_B) @ (dalpha * y_B) — ONE
       (n,d)x(d,q) MXU contraction streamed in blocks (ops.rbf_cross_matvec)
       replaces q individual O(n*d) row updates.

One X stream is amortised over hundreds of alpha updates, and the FLOPs
land on the systolic array. The optimisation problem and stopping rule are
unchanged, so the converged solution matches the serial oracle at the
solution level (same SV set / b within the tau-limited tolerance), which is
the reference's own cross-implementation parity criterion (SURVEY.md §4) —
the iteration *trajectory* is intentionally different.

This is the same working-set strategy GPU SVM solvers use (e.g. Catanzaro
et al.'s adaptive heuristics and ThunderSVM's q-sized working sets, papers
the reference itself cites in papers/ — see SURVEY.md §2 literature list),
re-expressed as jit-compiled XLA: top_k selection, gather, one MXU
contraction, lax.while_loop orchestration, zero host round-trips.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpusvm import kernels
from tpusvm.config import RAW_BF16, pallas_flag_errors
from tpusvm.obs import prof
from tpusvm.obs.convergence import ConvergenceTelemetry
from tpusvm.ops.rbf import coef_matvec, sq_norms
from tpusvm.ops.selection import i_high_mask, i_low_mask
from tpusvm.solver.analytic import pair_update
from tpusvm.solver.smo import SMOResult
from tpusvm.status import Status

_PALLAS_LANE = 128


def _clamp_q(n: int, q: int) -> int:
    """q clamps to the (even) training-set size; tiny n floors at 2."""
    return min(q, n if n % 2 == 0 else n - 1) if n >= 2 else 2


def pad_alpha0(alpha, n: int):
    """Resize a previous solution's alphas to n rows for a warm re-solve.

    The resume-shape helper for warm starts across problem sizes: a donor
    solution transfers to a GROWN training set (successive-halving rungs
    are nested prefixes of one fixed row order, tpusvm.tune) by giving the
    new rows alpha=0 — exactly the state cold SMO would start them in —
    and to a truncated set by dropping the tail rows' alphas. Works on
    numpy and jax arrays alike (returns the same family it was given);
    note truncation generally breaks the dual equality constraint
    sum(alpha*y)=0, so callers should re-project the seed feasible
    (tpusvm.tune.warm.feasible_seed) before passing it as alpha0.
    """
    m = alpha.shape[0]
    if m == n:
        return alpha
    if m > n:
        return alpha[:n]
    xp = jnp if isinstance(alpha, jax.Array) else np
    return xp.concatenate([alpha, xp.zeros((n - m,), alpha.dtype)])


def resolve_solver_config(n: int, q: int = 1024, inner: str = "auto",
                          wss: int = 1, selection: str = "auto"):
    """Effective (q, inner, wss, selection) blocked_smo_solve will run.

    The single source of truth for the solver's config-resolution rules —
    q clamps to the (even) training-set size, inner='auto' resolves to the
    pallas engine only on TPU with a lane-aligned q, and selection='auto'
    resolves by backend. wss passes through unchanged: BOTH inner engines
    implement first-order (1) and second-order (2) partner selection
    (round 4; previously the XLA engine was first-order only and wss
    degraded here). Benchmarks that record per-row effective config MUST
    derive it from this helper rather than re-implementing the rules, so
    recorded rows cannot silently claim an engine/wss/selection they did
    not run. blocked_smo_solve itself resolves through this helper too;
    it layers its own validation errors (explicit inner='pallas' with
    unaligned q) on top.
    """
    q = _clamp_q(n, q)
    if selection == "auto":
        selection = "approx" if jax.default_backend() == "tpu" else "exact"
    if inner == "auto":
        inner = ("pallas" if jax.default_backend() == "tpu"
                 and q % _PALLAS_LANE == 0 else "xla")
    return q, inner, wss, selection


def resolve_fused_fupdate(n: int, d: int, *, q: int = 1024,
                          fused="auto", matmul_precision=None,
                          backend: Optional[str] = None) -> bool:
    """Effective fused_fupdate flag blocked_smo_solve will run.

    Companion to resolve_solver_config (same contract: benchmarks that
    record per-row effective config derive it from here, and the solver
    itself resolves through this helper). 'auto' — the default since the
    round-4 hardware A/B (benchmarks/results/tpu_capture_r4/
    fused_fixed_*.jsonl: fused 0.476/0.478 s vs unfused 0.497 s
    same-session at the bench shape, plus the eliminated (n, q) HBM
    slabs) — resolves to True exactly when the kernel can actually run:
    on a real TPU backend (off-TPU the kernel would interpret, orders of
    magnitude slower than the XLA contraction), at full-f32 precision
    (matmul_precision='default' requests bf16, which the fused dot does
    not implement), and when the (q, d) shape fits the kernel's VMEM
    model (fused_feasible). Explicit True keeps the current behavior:
    raise on bf16 or VMEM-infeasible shapes rather than silently running
    something else. q is clamped to n the same way resolve_solver_config
    clamps it.
    """
    # identity checks, not membership: `1 in (True, False, 'auto')` is
    # True (1 == True), which would let a truthy int bypass the bf16
    # rejection the solver applies only to `fused is True`
    if fused is True:
        # mirror blocked_smo_solve's validation: explicit fused=True with
        # reduced-precision matmuls is a config the solver REJECTS, so the
        # helper must not report fused_eff=True for it (a benchmark
        # deriving its recorded "effective config" from here would
        # otherwise describe a run that cannot exist)
        if matmul_precision in ("default", "bf16_f32", "bf16_f32c"):
            raise ValueError(
                "fused_fupdate=True cannot honour matmul_precision="
                f"{matmul_precision!r} (the fused dot runs at the "
                "full-f32 trust-anchor tier); blocked_smo_solve rejects "
                "this combination — use fused='auto' or the XLA path"
            )
        return True
    if fused is False:
        return False
    if fused != "auto":
        raise ValueError(
            f"fused_fupdate must be True, False or 'auto', got {fused!r}"
        )
    # backend override: callers that have already established which
    # platform the run targets (bench.py's canary gate, which must agree
    # with its own devices[0].platform detection rather than re-derive it)
    # can pin it; None = the live default backend, which is what the
    # solver itself and effective-config records use
    if (backend or jax.default_backend()) != "tpu" \
            or matmul_precision in ("default", "bf16_f32", "bf16_f32c"):
        return False
    from tpusvm.ops.pallas.fused_fupdate import fused_feasible

    q = _clamp_q(n, q)
    # lane-aligned q only, mirroring the inner-engine 'auto' gate:
    # every hardware proof of this kernel (A/B, canary shapes) ran
    # lane-aligned; unaligned-q problems are small ones where the
    # XLA contraction is already cheap
    return q % _PALLAS_LANE == 0 and fused_feasible(q, d, n)


class _OuterState(NamedTuple):
    alpha: jax.Array      # (n,) accum dtype
    f: jax.Array          # (n,) accum dtype
    b_high: jax.Array
    b_low: jax.Array
    n_updates: jax.Array  # total inner updates (scalar int32)
    n_outer: jax.Array
    status: jax.Array
    f_exact: jax.Array    # bool: f freshly reconstructed from alpha, with no
                          # accumulated per-round deltas on top (refine mode)
    n_refines: jax.Array  # reconstructions done so far (refine mode)
    # convergence telemetry ring (telemetry=T > 0; shape-(0,) when off):
    # written every outer-loop body execution, never read by the solve —
    # the carry-resident alternative to a host callback per round
    tele_gap: jax.Array     # (T,) accum dtype: b_low - b_high per round
    tele_upd: jax.Array     # (T,) int32: inner updates that round
    tele_status: jax.Array  # (T,) int32: end-of-round Status
    tele_i: jax.Array       # scalar int32: rounds recorded so far
    tele_active: jax.Array  # (T,) int32: live (unfrozen) rows that round
    # shrink-stability counters (shrink_stable=S > 0; shape-(0,) when
    # off): consecutive rounds each row has been at-bound AND Keerthi-safe
    # — written every round, read only by the shrinking driver
    # (tpusvm.solver.shrink), so the solve itself is bit-transparent to S
    stable: jax.Array       # (n,) int32
    # K-row LRU cache (krow_cache=slots > 0; zero-size when off): rows of
    # K(X[key], X) keyed by training-row index, with carry-resident age
    # counters — consulted before the (n,d)x(d,q) refresh
    cache: jax.Array        # (slots, n) float32
    cache_keys: jax.Array   # (slots,) int32; -1 = empty slot
    cache_age: jax.Array    # (slots,) int32: rounds since last touch
    cache_hits: jax.Array   # int32: rows served from cache (all-hit rounds)
    cache_misses: jax.Array  # int32: rows computed fresh (X streamed)
    # fused-selection candidate ring (pallas_fused_selection; (0,) when
    # off): per-block working-set candidates written by the fused
    # f-update kernel's epilogue at the END of round r, consumed by round
    # r+1's selection — the two-pass mask+top_k over all n rows is gone
    cand_up_val: jax.Array   # (ncand,) f32; +inf = filler (non-member)
    cand_up_idx: jax.Array   # (ncand,) int32
    cand_low_val: jax.Array  # (ncand,) f32; -inf = filler
    cand_low_idx: jax.Array  # (ncand,) int32


def _inner_smo(K_BB, y_B, a_B, f_B, active_B, C, eps, tau, max_inner,
               wss: int = 1):
    """Pairwise SMO restricted to the working set, all VMEM-sized.

    K_BB is (q, q); each iteration is the reference's 2-variable analytic
    update (solver/analytic.py) with kernel entries read from the resident
    sub-matrix. Returns (a_B_new, updates, made_progress, end_reason) where
    end_reason is the Status value that terminated the subproblem
    (CONVERGED / NO_WORKING_SET / INFEASIBLE_UV / NONPOS_ETA / STALLED /
    MAX_ITER-for-the-inner-cap) — the outer loop decides what it means
    globally.

    wss=1 picks i_low by first-order Keerthi argmax-f (the reference's
    heuristic, main3.cpp:124-142); wss=2 picks the maximal-gain partner —
    among violating I_low members j maximise (f_j - b_high)^2 / eta_j, the
    LIBSVM-WSS2-style second-order rule, the same math as the pallas
    kernel (ops/pallas/inner_smo.py) on NON-degenerate partners, so both
    engines reach the optimum in comparably fewer updates. The Keerthi
    STOP decision stays on the global (b_high, b_low) pair either way;
    when no violating partner exists the iteration is exactly the
    converged/not-found exit (an I_low member with f > b_high exists
    whenever b_low > b_high + 2*tau).

    Degenerate-partner asymmetry (deliberate): partners with
    eta <= eps are excluded from this loop's gain selection (the analytic
    update bails on them, and without shrinking that would end the
    subproblem — fuzz seed 4047), while the pallas kernel still selects
    them and SELF-HEALS by shrinking the dead pair (its documented
    zero-progress policy, hardware-proven). Same optimum either way; the
    trajectories differ only when a degenerate candidate would win the
    gain argmax. Folding the same exclusion into the kernel awaits a
    hardware measurement (it adds a reduction to the kernel hot loop).
    """
    adt = f_B.dtype
    if wss == 2:
        diag_B = jnp.diagonal(K_BB).astype(adt)

    def cond(st):
        return st[4] == Status.RUNNING

    def body(st):
        a_B, f_B, n_upd, progress, _ = st
        m_h = i_high_mask(a_B, y_B, C, eps, active_B)
        m_l = i_low_mask(a_B, y_B, C, eps, active_B)
        i_h = jnp.argmin(jnp.where(m_h, f_B, jnp.inf)).astype(jnp.int32)
        found = jnp.any(m_h) & jnp.any(m_l)
        b_h = f_B[i_h]
        if wss == 2:
            # stop on the global Keerthi gap; partner by maximal gain
            masked_low = jnp.where(m_l, f_B, -jnp.inf)
            b_stop = jnp.max(masked_low)
            raw_eta = (K_BB[i_h, i_h].astype(adt) + diag_B
                       - 2.0 * K_BB[i_h, :].astype(adt))
            # partners with eta <= eps are EXCLUDED from the gain
            # selection: the clamped denominator would otherwise make a
            # near-duplicate of x[i_h] the argmax (gain ~ 1/1e-12), and
            # the analytic update bails on exactly that pair
            # (NONPOS_ETA), ending a subproblem the first-order rule
            # would have solved — found by the parity fuzz (seed 4047:
            # rings with near-coincident points, approx+wss2 died
            # mid-solve with b off by 0.22 while every other engine
            # converged). The pallas kernel survives the same selection
            # by SHRINKING the dead pair instead; the XLA loop prevents
            # the dead selection up front via this eta exclusion.
            viol = m_l & (f_B > b_h) & (raw_eta > eps)
            vg = jnp.where(viol, (f_B - b_h) ** 2
                           / jnp.maximum(raw_eta, 1e-12), -jnp.inf)
            i_l2 = jnp.argmax(vg).astype(jnp.int32)
            # every violating partner degenerate w.r.t. i_h: fall back
            # to the first-order pick — identical failure semantics to
            # wss=1 on such data (the reference's own behaviour)
            i_l1 = jnp.argmax(masked_low).astype(jnp.int32)
            i_l = jnp.where(jnp.any(viol), i_l2, i_l1)
        else:
            i_l = jnp.argmax(jnp.where(m_l, f_B, -jnp.inf)).astype(jnp.int32)
            b_stop = None
        b_l = f_B[i_l]
        gap_l = b_stop if wss == 2 else b_l
        converged = found & (gap_l <= b_h + 2.0 * tau)
        proceed = found & ~converged

        y_h = y_B[i_h].astype(adt)
        y_l = y_B[i_l].astype(adt)
        upd = pair_update(
            K_BB[i_h, i_h].astype(adt),
            K_BB[i_l, i_l].astype(adt),
            K_BB[i_h, i_l].astype(adt),
            y_h, y_l, a_B[i_h], a_B[i_l], b_h, b_l, C, eps, proceed,
        )

        f_B = f_B + upd.da_h * y_h * K_BB[i_h, :].astype(adt) \
                  + upd.da_l * y_l * K_BB[i_l, :].astype(adt)
        a_B = a_B.at[i_h].add(upd.da_h)
        a_B = a_B.at[i_l].add(upd.da_l)
        ok = upd.do_update & ~upd.stalled
        # .astype on the bool, not jnp.where(ok, 1, 0): the literal
        # branches would make a WEAK int32, which the fleet's vmap
        # batches into a weak-typed array (JXIR102)
        n_upd = n_upd + ok.astype(jnp.int32)
        progress = progress | ok

        reason = jnp.where(
            ~found,
            Status.NO_WORKING_SET,
            jnp.where(
                converged,
                Status.CONVERGED,
                jnp.where(
                    ~upd.feasible,
                    Status.INFEASIBLE_UV,
                    jnp.where(
                        ~upd.eta_ok,
                        Status.NONPOS_ETA,
                        jnp.where(
                            upd.stalled,
                            Status.STALLED,
                            jnp.where(
                                n_upd >= max_inner,
                                Status.MAX_ITER,
                                Status.RUNNING,
                            ),
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)
        return (a_B, f_B, n_upd, progress, reason)

    a_B, f_B, n_upd, progress, reason = lax.while_loop(
        cond, body,
        (a_B, f_B, jnp.int32(0), jnp.array(False), jnp.int32(Status.RUNNING)),
    )
    return a_B, n_upd, progress, reason


# one definition of the solver's static argnames, shared with the compile
# observatory's wrapper below (static kwargs are baked into an AOT
# executable and must be stripped from its call)
_BLOCKED_STATIC = (
    "q", "max_outer", "max_inner", "warm_start",
    "accum_dtype", "inner", "refine", "max_refines", "wss",
    "matmul_precision", "selection", "fused_fupdate",
    "pallas_layout", "pallas_eta_exclude",
    "pallas_multipair", "pallas_fused_selection", "telemetry",
    "kernel", "degree", "kernel_fast", "shrink_stable", "krow_cache",
    "return_state",
)


def bootstrap_candidates(f, alpha, Y, valid, C, eps, ncand: int):
    """Working-set candidate lists from scratch (the two-pass XLA path).

    The fused-selection carry needs round-1 candidates before the kernel
    has ever run (and the shrinking driver needs them again after a
    compaction changes the candidate shapes): one exact masked top-ncand
    over the full f — the same arrays the kernel's per-block epilogue
    approximates every later round. Returns
    (up_val, up_idx, low_val, low_idx); fillers are +/-inf with idx 0.
    """
    n = f.shape[0]
    m_h = i_high_mask(alpha, Y, C, eps, valid)
    m_l = i_low_mask(alpha, Y, C, eps, valid)
    key_up = jnp.where(m_h, f, jnp.inf).astype(jnp.float32)
    key_lo = jnp.where(m_l, f, -jnp.inf).astype(jnp.float32)
    k = min(ncand, n)
    neg_uv, ui = lax.top_k(-key_up, k)
    lv, li = lax.top_k(key_lo, k)
    uv = -neg_uv
    pad = ncand - k
    if pad:
        uv = jnp.concatenate([uv, jnp.full((pad,), jnp.inf, uv.dtype)])
        lv = jnp.concatenate([lv, jnp.full((pad,), -jnp.inf, lv.dtype)])
        zi = jnp.zeros((pad,), jnp.int32)
        ui = jnp.concatenate([ui.astype(jnp.int32), zi])
        li = jnp.concatenate([li.astype(jnp.int32), zi])
    return (uv, ui.astype(jnp.int32), lv, li.astype(jnp.int32))


def blocked_smo_core(
    X: jax.Array,
    Y: jax.Array,
    valid: Optional[jax.Array] = None,
    alpha0: Optional[jax.Array] = None,
    *,
    sn: Optional[jax.Array] = None,
    C: float = 10.0,
    gamma: float = 0.00125,
    eps: float = 1e-12,
    tau: float = 1e-5,
    max_iter: int = 100000,
    q: int = 1024,
    max_outer: int = 5000,
    max_inner: int = 1024,
    warm_start: bool = False,
    accum_dtype=None,
    inner: str = "auto",
    refine: int = 0,
    max_refines: int = 2,
    wss: int = 1,
    matmul_precision: Optional[str] = None,
    selection: str = "auto",
    fused_fupdate="auto",
    pallas_layout: str = "packed",
    pallas_eta_exclude: bool = False,
    pallas_multipair: int = 1,
    pallas_fused_selection: bool = False,
    telemetry: int = 0,
    kernel: str = "rbf",
    degree: int = 3,
    coef0: float = 0.0,
    kernel_fast: bool = True,
    shrink_stable: int = 0,
    krow_cache: int = 0,
    targets: Optional[jax.Array] = None,
    resume_state: Optional["_OuterState"] = None,
    pause_at: Optional[jax.Array] = None,
    return_state: bool = False,
) -> SMOResult:
    """Train to the reference's stopping criterion with blocked working sets.

    Same semantics surface as smo_solve (masks, warm start, statuses,
    max_iter as a bound on total alpha updates — checked between outer
    rounds, so it can overshoot by at most max_inner); n_iter counts total
    inner alpha updates + 1. q is clamped to n.

    sn: optional precomputed per-row squared norms sq_norms(X), shape (n,).
    The solver needs them every outer round (the distance-dot trick of the
    f update); callers fitting MANY models on the SAME rows — the tune
    driver sweeps a whole (C, gamma) grid per fold — pass the cached
    vector so each fit skips its own O(n*d) X stream. The values feed the
    same rbf_cross_matvec every fit uses, so passing a correct cache
    changes nothing numerically; passing norms of DIFFERENT rows is
    undefined behaviour, exactly like a wrong alpha0.

    Defaults (q=1024, max_inner=1024) were tuned on the MNIST-shaped 60k
    benchmark: larger working sets amortise the outer O(n*d*q) update over
    more inner updates, while capping the inner loop stops the subproblem
    from being over-optimised against stale fixed alphas.

    inner selects the subproblem engine: "xla" = the lax.while_loop
    `_inner_smo` (runs anywhere, ~36us/update dispatch overhead on TPU);
    "pallas" = the fused single-launch kernel (ops/pallas/inner_smo.py,
    float32 subproblem, interpreted off-TPU); "auto" = pallas on TPU when
    q is lane-aligned, xla otherwise.

    wss (both engines): 1 = Keerthi argmax-f partner selection (the
    reference's heuristic), 2 = maximal-gain second-order partner
    selection (LIBSVM WSS2 style) — fewer updates to the same optimum;
    the stopping rule is unchanged. The pallas kernel and the XLA loop
    implement the same wss=2 math (ops/pallas/inner_smo.py vs
    _inner_smo), so the choice of engine never silently changes the
    selection order anymore (round 4; previously XLA was first-order
    only and wss=2 degraded with a warning).

    refine (static): 0 = judge convergence on the per-round ACCUMULATED
    error vector, like the reference's GPU build accumulates f on device.
    refine=cap > 0 = drift control: when the accumulated f claims
    convergence, reconstruct f from scratch out of the current alphas (one
    (n,d)x(d,cap) MXU pass over the <=cap rows with the largest |alpha·y| —
    all nonzeros when the SV count fits cap) and keep optimising unless the
    claim also holds on the reconstruction, up to max_refines
    reconstructions. This bounds the accumulated-delta drift without
    chasing an unreachable target: kernel evaluation itself is float32, so
    any f computation carries ~sum|alpha|*1e-7 noise (~1e-4 on MNIST-60k —
    the same order as the reference's published cross-implementation b
    agreement of <0.003%), and demanding the 2*tau criterion hold exactly
    on re-evaluated f would cycle forever below that floor (measured:
    3.9M updates without termination on MNIST-60k). For f64-grade
    convergence use float64 inputs with the pairwise solver instead.
    Size cap well above the expected SV count (MNIST-60k: ~2k SVs); when
    more alphas are live than cap, the reconstruction is skipped (the
    claim is accepted as-is) rather than computed from a truncated
    coefficient set, which would corrupt f.

    selection (static): how the q working-set members are picked from the
    violator masks. "exact" = lax.top_k (a full sort-based selection over
    all n rows, twice per outer round — the dominant non-matmul outer
    cost on TPU). "approx" = lax.approx_min_k/approx_max_k (the
    TPU-native partial-reduction top-k; recall ~0.95 per call). "auto"
    (default) = approx on TPU, exact elsewhere. Approximation only
    affects WHICH violators enter the working set — the heuristic choice
    SMO already makes freely; the Keerthi stopping decision stays on
    exact global min/max reductions, so the converged optimum and its
    certificate are unchanged. A missed violator is simply picked up in
    a later round once it ranks higher. Progress per round is also
    unaffected: the bucketed reduction loses an element only to a
    BETTER one in its bucket (aggregate_to_topk then keeps the best
    across buckets), so the extreme elements — the globally maximal
    violating pair (b_high, b_low) — always survive selection, and any
    round that would progress under exact selection progresses under
    approx too (no spurious STALLED terminations).

    fused_fupdate (static): route the O(n*d*q) error-vector contraction
    through the fused Pallas kernel (ops/pallas/fused_fupdate.py) —
    distance matmul, exp, and coefficient matvec in one VMEM pipeline,
    eliminating the (n, q) intermediate slabs the XLA path materialises
    in HBM between its two matmuls. "auto" (default since the round-4
    hardware A/B measured the fused kernel at/under the XLA path's time
    while cutting its HBM slab traffic; see resolve_fused_fupdate) =
    fused on TPU when the (q, d) shape fits the kernel's VMEM model,
    XLA contraction otherwise. The fused dot runs at precision=HIGHEST
    (the full-f32 trust-anchor tier); explicit True combined with
    matmul_precision="default" (raw bf16) raises, while "auto" simply
    resolves to the XLA path there. Refine reconstructions keep the XLA
    path either way (rare, off the hot loop).

    pallas_layout (static): vector layout inside the fused inner kernel —
    "packed" = sublane-packed (q//128, 128) full-vreg layout, "flat" =
    the (1, q) layout proven on hardware in round 1. Trajectories are
    bitwise identical; flat exists as a lowering fallback.

    Any ACTIVE pallas_* flag whose resolved config cannot honour it
    raises at trace time (previously only multipair; eta_exclude/layout
    were silently ignored — ADVICE r5): the flag-compatibility table is
    tpusvm.config.PALLAS_FLAG_RULES, shared with the static linter's
    JX008 rule, so recorded A/B configs cannot claim a kernel variant
    the run never executed.

    pallas_eta_exclude (static, wss=2 + pallas engine only): fold the XLA
    engine's degenerate-partner (eta <= eps) exclusion into the kernel's
    in-loop gain selection, unifying the two engines' selection rule
    (VERDICT r4 #5; the asymmetry is otherwise deliberate — the kernel
    self-heals dead pairs by shrinking, the XLA loop prevents them up
    front). Costs one extra cross-lane reduction per inner iteration;
    default False pending the hardware A/B (probe_split arg 10).

    pallas_multipair (static, pallas engine + wss=1 only): p > 1 runs the
    batched slot-pair kernel — p disjoint first-order analytic updates
    per kernel iteration (ops/pallas/inner_smo.py
    _make_multipair_kernel), amortising the sequential kernel's
    per-update cross-lane-reduction latency (the ~8us/update wall that
    makes the n=60k solve latency-bound at ~1% of HBM peak, ROOFLINE.md;
    VERDICT r4 #3). Same stopping rule; the inner trajectory is Jacobi
    across slots, and an all-idle subproblem degrades to the XLA retry
    hatch. Requires (q//128) % (2p) == 0.

    matmul_precision (static): MXU precision for the in-loop O(n*d*q)
    error-vector contraction — the solver's dominant cost. None keeps the
    ops-layer default ("float32": full-f32-equivalent multi-pass MXU
    matmuls, ops/rbf.py DEFAULT_PRECISION). "default" uses raw single-pass
    bf16 MXU matmuls (~3x the matmul throughput) for the in-loop f DELTAS
    only: working-set selection then sees a slightly noisier f, which can
    change which pairs are optimised but not what optimum they converge
    to, and every trust anchor stays full precision — K_BB (the analytic
    updates), the refine reconstructions, and the stopping decision made
    on the reconstructed f. Pair with refine > 0 and max_refines >= 1 (so
    convergence claims are re-validated on a full-precision rebuild) —
    requested fast mode without a refine budget raises. Note the refine
    cap semantics above still apply: if more alphas are live than the cap,
    the rebuild is skipped and the claim is accepted on the drifted f —
    in fast mode size the cap generously above the expected SV count.

    Rounds above the raw rung (round 9, the solver speed ladder —
    tpusvm.config.resolve_matmul_precision is the single resolver):
    matmul_precision="bf16_f32" ROUNDS the f-update operands to bfloat16
    and accumulates in f32 (preferred_element_type) — single-pass MXU
    throughput with exact adds; "bf16_f32c" adds one compensated
    residual pass. Both are backend-independent (operands are rounded,
    not hinted), so CPU parity runs exercise the real arithmetic. Every
    trust anchor stays full precision exactly as for "default" (K_BB,
    refine rebuilds, row norms); the drift guard is refine > 0 OR
    shrink_stable > 0 (the shrinking driver re-validates every
    convergence claim on a full-precision f rebuild at un-shrink).

    shrink_stable (static): S > 0 carries per-row stability counters
    through the loop — consecutive rounds a row has been at-bound
    (alpha in {0, C} to eps) and Keerthi-SAFE (unable to join a
    violating pair at the current band: not in I_high with
    f < b_low - 2*tau, not in I_low with f > b_high + 2*tau). The
    counters are written, never read, by the solve (bit-transparent,
    like the telemetry ring); the shrinking driver
    (tpusvm.solver.shrink.shrinking_blocked_solve) reads them between
    segments to freeze rows and compact the live set. 0 (default) = off,
    shape-(0,) carry.

    krow_cache (static): slots > 0 keeps a (slots, n) device-resident
    LRU cache of K-rows keyed by training-row index with carry-resident
    age counters, consulted before the f-update refresh. Rounds whose
    ENTIRE working set is cached compute f += rows^T @ dcoef straight
    from the cache — no X stream, no kernel evaluation (repeat
    violators are the common case near convergence); any miss streams X
    once through the K-row batch (kernels.rows_at), uses those rows for
    the update, and inserts all q of them into the oldest/empty slots.
    Cached values equal the fresh computation bitwise (K-rows are pure
    functions of X), so hit rounds are bit-identical to recomputing.
    Requires slots >= q (a whole working set must fit) and forces the
    rows-form f-update (fused_fupdate resolves off; explicit True
    raises). cache_hits/cache_misses count ROWS served per source and
    surface on SMOResult and the obs registry.

    pallas_fused_selection (static): fold the next round's violator-mask
    + per-block top-k candidate selection into the fused f-update
    kernel's epilogue (ops/pallas/fused_fupdate.py) — the kernel writes
    df AND, per row-block, the k best I_high/I_low candidates of the
    updated f, so the separate mask+top_k pass over all n rows
    disappears. Selection quality matches selection='approx'
    (each block's extremes always survive, so the globally maximal
    violating pair is always selectable and progress per round is
    preserved); the Keerthi STOP decision stays on exact global
    reductions over the full f, so the convergence criterion is
    unchanged. Requires the fused f-update to be the resolved path
    (pallas_flag_errors, requires_fused), refine=0 (a rebuilt f would
    orphan the carried candidates) and selection='auto' (the fused
    candidates replace that knob). XLA fallback = today's two-pass path.

    kernel/degree/coef0 (kernel and degree static): kernel family and its
    parameters (tpusvm.kernels). "rbf" (the default) runs the pre-refactor
    code path byte-for-byte — K_BB, the f-update contraction, warm starts
    and refine reconstructions all route through the same ops/rbf.py calls
    with the same arguments. "linear"/"poly" swap in their dot-form
    computations; sn is then ignored (no row norms exist for them) and
    fused_fupdate='auto' resolves to False (the fused kernel implements
    the RBF distance pipeline only; explicit True raises).

    kernel_fast (static, kernel="linear" only): True (default) routes the
    O(n*d*q) error-vector contraction and refine reconstructions through
    the primal form X @ (X_B^T coef) — a (d,) weight delta instead of a
    (block, q) kernel slab, the linear family's dedicated fast path.
    False keeps the generic blocked K-row path (the benchmark control
    arm, benchmarks/kernel_matrix.py). Ignored by rbf/poly.

    targets: optional (n,) pseudo-target vector z replacing the labels in
    the error vector f_i = sum_j a_j y_j K_ij - z_i (None = z = Y, the
    classification problem); the epsilon-SVR doubling
    (tpusvm.kernels.svr) is the intended caller. Selection, the stopping
    rule, and the analytic update are unchanged.

    telemetry (static): 0 (default) = off. T > 0 = carry a T-slot
    convergence ring through the outer loop: every outer-loop body
    execution writes its Keerthi gap (b_low - b_high; NaN when no
    working set existed), inner-update count, and end-of-round status
    into slot (round mod T), and the ring comes back on
    SMOResult.telemetry (obs.convergence.ConvergenceTelemetry) —
    materialised once with the rest of the result, exactly like alpha.
    ZERO host syncs are added inside the loop (the arrays are
    carry-resident writes; a per-round host callback is the JX009
    anti-pattern this replaces), and the solve is bit-transparent to the
    flag: the telemetry arrays are written, never read, so alpha/f/b and
    every status are bit-identical with it on or off
    (tests/test_obs.py asserts this; benchmarks/telemetry_overhead.py
    bounds the time cost at <= 3%). When the solve runs more than T
    outer rounds the ring holds the LAST T (count says how many ran).

    Fleet vmap contract (tpusvm.fleet): this un-jitted core is the
    function the batched many-model solver vmaps over a leading problem
    axis — (Y, valid, alpha0, C, gamma) mapped, (X, sn) broadcast. The
    whole solve state lives in the while-loop carry, so JAX's while/cond
    batching rules give per-problem convergence masking for free: the
    batched loop runs until every problem terminates, and a problem
    whose status has left RUNNING has its carry frozen by the batching
    rule's per-lane select — its alpha/f/counters are bit-identical to
    the same problem solved next to ANY companion set in the same
    bucket program (tests/test_fleet.py pins this bitwise). Only
    vmap-clean static configs batch: inner='xla' (the Pallas subproblem
    kernel has no batching rule), fused_fupdate=False, krow_cache=0,
    shrink_stable=0 (the shrinking driver is a host-side segmenter),
    pallas_fused_selection=False. tpusvm.fleet.solve enforces that
    restriction at its boundary.

    resume_state / pause_at / return_state: the crash-safe-training
    surface (tpusvm.solver.checkpoint). The outer loop's carry
    (_OuterState) is the COMPLETE solve state — the body reads nothing
    else that varies — so running the loop in segments is bit-identical
    to one uninterrupted loop: `pause_at=k` stops the loop once n_outer
    reaches k (or the solve terminates), `return_state=True` returns
    (SMOResult, _OuterState) so the caller can persist the carry, and
    `resume_state=state` re-enters the loop from a persisted carry
    (alpha0/warm_start/f0 construction is then dead code; the carry IS
    the state). The checkpoint driver owns the host-side snapshotting,
    atomic writes and fingerprint validation.
    """
    n = Y.shape[0]
    dtype = X.dtype
    adt = dtype if accum_dtype is None else accum_dtype

    if inner not in ("auto", "xla", "pallas"):
        raise ValueError(f"inner must be auto|xla|pallas, got {inner!r}")
    if wss not in (1, 2):
        raise ValueError(f"wss must be 1 or 2, got {wss}")
    if matmul_precision not in (None, "float32", "default", "highest",
                                "bf16_f32", "bf16_f32c"):
        raise ValueError(
            f"matmul_precision must be None, 'float32', 'default', "
            f"'highest', 'bf16_f32' or 'bf16_f32c', "
            f"got {matmul_precision!r}"
        )
    if selection not in ("auto", "exact", "approx"):
        raise ValueError(
            f"selection must be auto|exact|approx, got {selection!r}"
        )
    if not isinstance(telemetry, int) or telemetry < 0:
        raise ValueError(
            f"telemetry must be a non-negative int ring size, "
            f"got {telemetry!r}"
        )
    if not isinstance(shrink_stable, int) or shrink_stable < 0:
        raise ValueError(
            f"shrink_stable must be a non-negative int round count, "
            f"got {shrink_stable!r}"
        )
    if not isinstance(krow_cache, int) or krow_cache < 0:
        raise ValueError(
            f"krow_cache must be a non-negative int slot count, "
            f"got {krow_cache!r}"
        )
    if pallas_fused_selection and selection != "auto":
        raise ValueError(
            "pallas_fused_selection replaces working-set selection with "
            "the kernel epilogue's per-block candidates; an explicit "
            f"selection={selection!r} would be silently ignored — pass "
            "selection='auto'"
        )
    if pallas_fused_selection and refine:
        raise ValueError(
            "pallas_fused_selection carries next-round candidates "
            "computed by the f-update kernel; refine mode rebuilds f "
            "outside the kernel, which would orphan them — use one or "
            "the other"
        )
    q, inner, wss, selection = resolve_solver_config(
        n, q, inner=inner, wss=wss, selection=selection
    )
    if krow_cache and krow_cache < q:
        raise ValueError(
            f"krow_cache={krow_cache} slots cannot hold a full working "
            f"set (q={q} after clamping): a miss round inserts all q "
            "fresh rows at once — use krow_cache >= q or a smaller q"
        )
    half = q // 2
    if pallas_layout not in ("packed", "flat"):
        raise ValueError(
            f"pallas_layout must be packed|flat, got {pallas_layout!r}"
        )
    # active pallas_* flags must reach the engine they configure: an
    # explicitly-requested kernel variant silently measuring the plain XLA
    # engine is a recorded-config lie (ADVICE r5 — pallas_eta_exclude=True
    # on a CPU-pinned probe resolved to inner='xla' and was ignored). The
    # flag-compatibility table lives in tpusvm.config, shared with the
    # static linter's JX008 rule.
    flag_errors = pallas_flag_errors(inner, wss, {
        "pallas_layout": pallas_layout,
        "pallas_eta_exclude": pallas_eta_exclude,
        "pallas_multipair": pallas_multipair,
    })
    if flag_errors:
        raise ValueError("; ".join(flag_errors))
    kernels.validate_family(kernel)
    if krow_cache:
        # the cache consults/streams EXPLICIT K-rows; the fused Pallas
        # f-update never materialises them — the two paths are disjoint
        if fused_fupdate is True:
            raise ValueError(
                "krow_cache consults explicit K-rows before the refresh; "
                "the fused Pallas f-update (fused_fupdate=True) never "
                "materialises rows to cache — pick one "
                "(fused_fupdate='auto' resolves to the rows path)"
            )
        fused_fupdate = False
    elif kernel != "rbf":
        # the fused Pallas contraction implements the RBF distance+exp
        # pipeline only; an explicit request for it with another family is
        # a config lie, 'auto' just resolves to the generic path
        if fused_fupdate is True:
            raise ValueError(
                f"fused_fupdate=True implements the RBF pipeline only; "
                f"kernel={kernel!r} uses its own contraction "
                "(use fused_fupdate='auto')"
            )
        fused_fupdate = False
    else:
        # fused=True + bf16 matmuls is rejected INSIDE resolve_fused_fupdate
        # (single source of truth; the fused contraction runs at the full-f32
        # trust-anchor tier and cannot honour reduced-precision rungs)
        fused_fupdate = resolve_fused_fupdate(
            n, X.shape[1], q=q, fused=fused_fupdate,
            matmul_precision=matmul_precision,
        )
    # an ACTIVE pallas_fused_selection must reach the fused kernel it
    # extends — same recorded-config-lie rule as the engine flags, judged
    # against the RESOLVED fused-f-update path
    flag_errors = pallas_flag_errors(
        None, None, {"pallas_fused_selection": pallas_fused_selection},
        fused=fused_fupdate,
    )
    if flag_errors:
        raise ValueError("; ".join(flag_errors))
    if matmul_precision == "default" and (refine <= 0 or max_refines < 1):
        raise ValueError(
            "matmul_precision='default' (raw bf16 MXU passes) accumulates "
            "f drift and must be paired with refine > 0 and max_refines "
            ">= 1 so convergence claims are re-validated on a "
            "full-precision reconstruction"
        )
    if matmul_precision in ("bf16_f32", "bf16_f32c") \
            and (refine <= 0 or max_refines < 1) and shrink_stable <= 0:
        raise ValueError(
            f"matmul_precision={matmul_precision!r} rounds the f-update "
            "operands to bfloat16; accumulated convergence claims need a "
            "full-precision revalidation — pair with refine > 0 and "
            "max_refines >= 1, or run under the shrinking driver "
            "(shrink_stable > 0: tpusvm.solver.shrink re-checks every "
            "claim on a rebuilt f at un-shrink)"
        )
    # the jax name "default" (raw single-pass bf16) is rejected by the
    # ops-layer resolver; having validated the refine pairing above, the
    # solver requests it by its unmistakable token (config.RAW_BF16)
    ops_precision = (RAW_BF16 if matmul_precision == "default"
                     else matmul_precision)
    if inner == "pallas" and q % _PALLAS_LANE:
        raise ValueError(
            f"inner='pallas' needs the working-set size to be a multiple of "
            f"{_PALLAS_LANE}, but q={q} after clamping to the n={n} training "
            f"rows; use inner='auto' to fall back to the XLA engine on "
            f"small/unaligned problems"
        )
    if valid is None:
        valid = jnp.ones((n,), bool)
    if alpha0 is None:
        alpha0 = jnp.zeros((n,), adt)
    alpha0 = jnp.where(valid, alpha0, 0.0).astype(adt)

    yf = Y.astype(adt)
    z = yf if targets is None else jnp.asarray(targets).astype(adt)
    if warm_start:
        f0 = kernels.matvec(
            kernel, X, (alpha0 * yf).astype(dtype), gamma=gamma,
            coef0=coef0, degree=degree,
        ).astype(adt) - z
    else:
        f0 = -z
    f0 = jnp.where(valid, f0, 0.0)

    # hoisted out of the outer loop: one X stream per solve, not per round
    # (or zero, when the caller supplied its fold-level cache). Only the
    # RBF family has row norms; others carry sn=None (a cache passed by a
    # kernel-agnostic caller like tune is simply unused).
    if kernels.needs_norms(kernel):
        if sn is None:
            sn = sq_norms(X)
    else:
        sn = None

    refine_cap = min(refine, n) if refine > 0 else 0

    if pallas_fused_selection:
        from tpusvm.ops.pallas.fused_fupdate import selection_shape

        _kblock, _knb, _kcand, _ncand = selection_shape(n, X.shape[1], q)
        # invalid rows enter the kernel with y=0, which belongs to neither
        # index set — one operand instead of a separate mask input
        y_eff = (Y * valid).astype(jnp.int32)

    def body(st: _OuterState) -> _OuterState:
        alpha, f = st.alpha, st.f
        m_h = i_high_mask(alpha, Y, C, eps, valid)
        m_l = i_low_mask(alpha, Y, C, eps, valid)
        found = jnp.any(m_h) & jnp.any(m_l)
        b_high = jnp.where(found, jnp.min(jnp.where(m_h, f, jnp.inf)), st.b_high)
        b_low = jnp.where(found, jnp.max(jnp.where(m_l, f, -jnp.inf)), st.b_low)
        converged = found & (b_low <= b_high + 2.0 * tau)
        if shrink_stable:
            # per-row shrink stability: at-bound AND unable to join a
            # violating pair at this round's band. Written, never read,
            # by the solve (the shrinking driver consumes the counters
            # between segments), so the trajectory is bit-identical with
            # tracking on or off.
            at_bound = (alpha <= eps) | (alpha >= C - eps)
            unsafe = (m_h & (f < b_low - 2.0 * tau)) \
                | (m_l & (f > b_high + 2.0 * tau))
            keep = at_bound & ~unsafe & valid
            stable = jnp.where(
                found, jnp.where(keep, st.stable + 1, 0), st.stable)
        else:
            stable = st.stable
        # refine mode: a convergence claim on an accumulated (drifted) f is
        # not an exit while the reconstruction budget lasts — it triggers a
        # from-scratch rebuild of f, and the claim must survive on the
        # rebuilt f (or the budget run out) to terminate
        if refine_cap:
            budget_left = st.n_refines < max_refines
            # a truncated rebuild (more live alphas than cap) would REPLACE
            # f with a worse approximation and derail the solve — skip
            # reconstruction entirely in that case and accept the claim
            fits_cap = jnp.sum((alpha > 0) & valid) <= refine_cap
            needs_refine = converged & ~st.f_exact & budget_left & fits_cap
            exit_converged = converged & ~needs_refine
        else:
            needs_refine = jnp.array(False)
            exit_converged = converged
        proceed = found & ~converged

        def do_round(args):
            (alpha, f, cache, cache_keys, cache_age,
             cand_up_val, cand_up_idx, cand_low_val, cand_low_idx) = args
            # --- working-set selection: q distinct indices ----------------
            if pallas_fused_selection:
                # consume the candidate lists the PREVIOUS round's fused
                # f-update epilogue wrote (round 1 / resume: the
                # bootstrap lists) — no mask+top_k pass over n here, only
                # a top-k over the ncand-sized candidate pool. Filler
                # lanes carry +/-inf values and possibly out-of-range or
                # duplicate indices: clamp here, dedup below.
                _, sel_up = lax.top_k(-cand_up_val, half)
                idx_up = jnp.minimum(cand_up_idx[sel_up], n - 1)
                in_up = jnp.zeros((n,), bool).at[idx_up].set(m_h[idx_up])
                low_safe = jnp.minimum(cand_low_idx, n - 1)
                low_key = jnp.where(in_up[low_safe], -jnp.inf,
                                    cand_low_val)
                _, sel_lo = lax.top_k(low_key, half)
                idx_low = low_safe[sel_lo]
            else:
                key_up = jnp.where(m_h, f, jnp.inf).astype(jnp.float32)
                if selection == "approx":
                    _, idx_up = lax.approx_min_k(key_up, half)
                else:
                    _, idx_up = lax.top_k(-key_up, half)  # q/2 smallest f in I_high
                # only genuine I_high members count as taken: when |I_high| < q/2
                # top_k pads idx_up with arbitrary non-members, and excluding
                # those from the I_low pick could hide real violators
                in_up = jnp.zeros((n,), bool).at[idx_up].set(m_h[idx_up])
                key_low = jnp.where(m_l & ~in_up, f, -jnp.inf).astype(jnp.float32)
                if selection == "approx":
                    _, idx_low = lax.approx_max_k(key_low, half)
                else:
                    _, idx_low = lax.top_k(key_low, half)  # q/2 largest f in I_low
            B = jnp.concatenate([idx_up, idx_low]).astype(jnp.int32)

            # B can contain one sample twice (an idx_up filler re-picked by
            # idx_low); keep only the first occurrence active — two live
            # copies of one dual variable would corrupt the f update.
            if pallas_fused_selection:
                # fused candidates are per-block top-k lists: beyond the
                # cross-half case, one row can also appear twice WITHIN a
                # half via clamped filler lanes, so first-occurrence is
                # computed over the whole q (a q^2 membership test, the
                # same idiom as the cross-half check below)
                pos_q = jnp.arange(q, dtype=jnp.int32)
                earlier = (B[:, None] == B[None, :]) \
                    & (pos_q[None, :] < pos_q[:, None])
                is_first = ~jnp.any(earlier, axis=1)
            else:
                # Each half's indices are distinct (top-k picks distinct
                # positions), so duplicates are only cross-half and
                # first-occurrence means the up-half copy wins: a (q/2)^2
                # membership test, not an (n,)-sized scatter-min
                # (scatters lower poorly on TPU)
                dup_low = (idx_low[:, None] == idx_up[None, :]).any(axis=1)
                is_first = jnp.concatenate(
                    [jnp.ones((half,), bool), ~dup_low]
                )

            X_B = X[B]
            y_B = Y[B]
            a_B = alpha[B]
            f_B = f[B]
            # members selected only as +/-inf filler (sets smaller than q/2)
            # must not participate in the subproblem
            active_B = valid[B] & is_first & (i_high_mask(a_B, y_B, C, eps)
                                              | i_low_mask(a_B, y_B, C, eps))

            K_BB = kernels.cross(kernel, X_B, X_B, gamma=gamma,
                                 coef0=coef0, degree=degree)
            if inner == "pallas":
                from tpusvm.ops.pallas.inner_smo import inner_smo_pallas

                # delta against the f32-QUANTIZED baseline, not the f64 a_B:
                # the kernel round-trips alpha through f32, so lanes it never
                # touched come back as f32(a_B) — diffing against a_B would
                # scatter ~6e-8*C quantization residues into the f64
                # accumulator on every selected-but-unchanged lane (and
                # double-count them on inactive duplicate rows)
                a_B_q = a_B.astype(jnp.float32).astype(adt)
                a_B_new, upd, progress, inner_reason = inner_smo_pallas(
                    K_BB, y_B, a_B, f_B, active_B, C, eps, tau,
                    max_inner=max_inner,
                    interpret=jax.default_backend() != "tpu",
                    wss=wss, layout=pallas_layout,
                    eta_exclude=pallas_eta_exclude,
                    multipair=pallas_multipair,
                )
                da_B = a_B_new - a_B_q
                # f32 rescue hatch: if the fused kernel's float32 subproblem
                # made zero progress, retry the round with the accum-dtype
                # XLA engine before letting the outer loop declare a stall.
                # The slow path compiles into the graph but executes only on
                # zero-progress rounds (rare: none on the converged MNIST-60k
                # runs, but q=1536 runs hit it mid-solve). Deliberately NOT
                # gated on the kernel's end reason: the kernel can only end
                # CONVERGED / NO_WORKING_SET / MAX_ITER (it shrinks
                # box-pinned pairs instead of bailing out), so a
                # zero-progress NO_WORKING_SET is precisely the
                # all-violators-stalled-at-f32-resolution signature the
                # rescue exists for, and a zero-progress CONVERGED is an
                # f32-rounding borderline of the 2*tau criterion where the
                # accum-dtype engine can still make progress. B is built
                # from global violator masks, so neither can mean "nothing
                # to do at entry".
                da_B, upd, progress, inner_reason = lax.cond(
                    progress,
                    lambda: (da_B, upd, progress, inner_reason),
                    lambda: (lambda r: (r[0] - a_B, r[1], r[2], r[3]))(
                        _inner_smo(K_BB, y_B, a_B, f_B, active_B, C, eps,
                                   tau, max_inner, wss=wss)
                    ),
                )
            else:
                a_B_new, upd, progress, inner_reason = _inner_smo(
                    K_BB, y_B, a_B, f_B, active_B, C, eps, tau, max_inner,
                    wss=wss,
                )
                da_B = a_B_new - a_B

            dcoef = da_B * y_B.astype(adt)
            zero_i = jnp.int32(0)
            alpha_new = alpha.at[B].add(da_B)  # .add, not .set: inactive
            # duplicate rows carry a zero delta, so double-indexed
            # scatter stays correct
            if pallas_fused_selection:
                from tpusvm.ops.pallas.fused_fupdate import (
                    fused_fupdate_select_pallas,
                )

                # the epilogue needs POST-round alphas (next round's masks)
                # and the f32 face of f — selection keys were already f32
                # in the two-pass path, and the stop decision stays on the
                # exact adt f in the body above
                (df32, cand_up_val, cand_up_idx, cand_low_val,
                 cand_low_idx) = fused_fupdate_select_pallas(
                    X, X_B, dcoef.astype(dtype), gamma, sn,
                    f.astype(jnp.float32),
                    alpha_new.astype(jnp.float32), y_eff, C, eps,
                    k_cand=_kcand, block=_kblock,
                    interpret=jax.default_backend() != "tpu",
                )
                return (alpha_new, f + df32.astype(adt),
                        cache, cache_keys, cache_age, zero_i, zero_i,
                        cand_up_val, cand_up_idx, cand_low_val,
                        cand_low_idx, upd, progress, inner_reason)
            if krow_cache:
                # LRU K-row cache: a round needs a K-row only for members
                # whose alpha actually MOVED (dcoef == 0 contributes
                # nothing to df) — near convergence the inner solve
                # touches a few repeat violators per round, so the needed
                # set is small and hot. Rounds whose entire needed set is
                # cached are served straight from HBM-resident rows (no X
                # stream, no kernel evaluation); any needed miss streams
                # X once through the K-row batch and re-inserts ALL q
                # rows (hit rows recompute to the exact bytes the cache
                # holds — K-rows are pure functions of X — so
                # overwriting them is a no-op in value)
                match = cache_keys[None, :] == B[:, None]  # (q, slots)
                hit = jnp.any(match, axis=1)
                moved = dcoef != 0.0
                all_hit = jnp.all(hit | ~moved)
                slot_of = jnp.argmax(match, axis=1)
                dc32 = dcoef.astype(dtype)
                # un-moved misses have slot_of pointing at an arbitrary
                # slot; their dcoef is exactly 0, so the gathered row is
                # multiplied away — zero the coef explicitly so that
                # holds even if dtypes round
                dc32_cached = jnp.where(hit, dc32, 0.0).astype(dc32.dtype)

                def from_cache(cache, keys, age):
                    rows = cache[slot_of]  # (q, n) gather, no X stream
                    df = coef_matvec(rows.T, dc32_cached,
                                     ops_precision).astype(adt)
                    age = (age + 1).at[jnp.where(hit, slot_of, 0)].min(
                        jnp.where(hit, 0, jnp.int32(2 ** 30)))
                    return (df, cache, keys, age,
                            jnp.int32(q), jnp.int32(0))

                def from_fresh(cache, keys, age):
                    rows = kernels.rows_at(
                        kernel, X, B, gamma=gamma, coef0=coef0,
                        degree=degree, sn=sn, precision=ops_precision,
                    ).astype(jnp.float32)
                    df = coef_matvec(rows.T, dc32,
                                     ops_precision).astype(adt)
                    # evict empty-first, then oldest: top_k picks q
                    # DISTINCT slots, so the q-row insert cannot collide
                    score = jnp.where(keys < 0, jnp.int32(2 ** 30), age)
                    _, tgt = lax.top_k(score, q)
                    cache = cache.at[tgt].set(rows)
                    keys = keys.at[tgt].set(B)
                    age = (age + 1).at[tgt].set(0)
                    return (df, cache, keys, age,
                            jnp.int32(0), jnp.int32(q))

                df, cache, cache_keys, cache_age, d_hit, d_miss = lax.cond(
                    all_hit, from_cache, from_fresh,
                    cache, cache_keys, cache_age,
                )
                return (alpha_new, f + df, cache, cache_keys, cache_age,
                        d_hit, d_miss, cand_up_val, cand_up_idx,
                        cand_low_val, cand_low_idx, upd, progress,
                        inner_reason)
            if fused_fupdate:
                from tpusvm.ops.pallas.fused_fupdate import (
                    rbf_cross_matvec_pallas,
                )

                df = rbf_cross_matvec_pallas(
                    X, X_B, dcoef.astype(dtype), gamma, sn,
                    interpret=jax.default_backend() != "tpu",
                ).astype(adt)
            else:
                df = kernels.cross_matvec(
                    kernel, X, X_B, dcoef, gamma=gamma, coef0=coef0,
                    degree=degree, sn=sn, precision=ops_precision,
                    fast=kernel_fast,
                ).astype(adt)
            return (alpha_new, f + df, cache, cache_keys, cache_age,
                    zero_i, zero_i, cand_up_val, cand_up_idx,
                    cand_low_val, cand_low_idx, upd, progress,
                    inner_reason)

        def skip_round(args):
            (alpha, f, cache, cache_keys, cache_age,
             cand_up_val, cand_up_idx, cand_low_val, cand_low_idx) = args
            zero_i = jnp.int32(0)
            return (alpha, f, cache, cache_keys, cache_age, zero_i,
                    zero_i, cand_up_val, cand_up_idx, cand_low_val,
                    cand_low_idx, zero_i, jnp.array(False),
                    jnp.int32(Status.RUNNING))

        def do_refine(args):
            (alpha, f, cache, cache_keys, cache_age,
             cand_up_val, cand_up_idx, cand_low_val, cand_low_idx) = args
            coef = alpha * yf
            # largest-|coef| rows cover all nonzeros (needs_refine already
            # checked the live count fits refine_cap)
            _, idx = lax.top_k(jnp.abs(coef).astype(jnp.float32), refine_cap)
            f_new = kernels.cross_matvec(
                kernel, X, X[idx], coef[idx].astype(dtype), gamma=gamma,
                coef0=coef0, degree=degree, sn=sn, fast=kernel_fast,
            ).astype(adt) - z
            zero_i = jnp.int32(0)
            return (alpha, jnp.where(valid, f_new, 0.0), cache,
                    cache_keys, cache_age, zero_i, zero_i, cand_up_val,
                    cand_up_idx, cand_low_val, cand_low_idx, zero_i,
                    jnp.array(False), jnp.int32(Status.RUNNING))

        # terminal round (converged / no working set) skips the whole
        # selection + K_BB + inner solve + O(n*d*q) f-update machinery
        operands = (alpha, f, st.cache, st.cache_keys, st.cache_age,
                    st.cand_up_val, st.cand_up_idx, st.cand_low_val,
                    st.cand_low_idx)
        if refine_cap:
            out = lax.cond(
                needs_refine,
                do_refine,
                lambda args: lax.cond(proceed, do_round, skip_round, args),
                operands,
            )
        else:
            out = lax.cond(proceed, do_round, skip_round, operands)
        (alpha, f, cache, cache_keys, cache_age, d_hit, d_miss,
         cand_up_val, cand_up_idx, cand_low_val, cand_low_idx,
         upd, progress, inner_reason) = out
        cache_hits = st.cache_hits + d_hit
        cache_misses = st.cache_misses + d_miss
        f_exact = needs_refine | (st.f_exact & ~proceed)
        n_refines = st.n_refines + needs_refine.astype(jnp.int32)

        n_outer = st.n_outer + proceed.astype(jnp.int32)  # strong int32
        # (jnp.where(proceed, 1, 0) would be weak — JXIR102 under vmap)
        n_updates = st.n_updates + upd
        tele_gap, tele_upd, tele_status, tele_i, tele_active = (
            st.tele_gap, st.tele_upd, st.tele_status, st.tele_i,
            st.tele_active)
        # zero progress: surface the inner numerical bail-out that caused it
        # (same statuses as smo_solve on the same degenerate data), generic
        # STALLED otherwise
        no_progress_status = jnp.where(
            inner_reason == Status.INFEASIBLE_UV,
            Status.INFEASIBLE_UV,
            jnp.where(
                inner_reason == Status.NONPOS_ETA,
                Status.NONPOS_ETA,
                Status.STALLED,
            ),
        )
        status = jnp.where(
            ~found,
            Status.NO_WORKING_SET,
            jnp.where(
                needs_refine,
                Status.RUNNING,
                jnp.where(
                    exit_converged,
                    Status.CONVERGED,
                    jnp.where(
                        ~progress,
                        no_progress_status,
                        jnp.where(
                            (n_updates >= max_iter) | (n_outer >= max_outer),
                            Status.MAX_ITER,
                            Status.RUNNING,
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)
        if telemetry:
            # carry-resident telemetry: pure scatters into ring slot
            # (round mod T) — written, never read, so the solve's
            # trajectory is bit-identical with the ring on or off, and
            # nothing here touches the host until the loop terminates
            t_idx = tele_i % telemetry
            gap = jnp.where(found, b_low - b_high,
                            jnp.array(jnp.nan, adt))
            tele_gap = tele_gap.at[t_idx].set(gap)
            tele_upd = tele_upd.at[t_idx].set(upd)
            tele_status = tele_status.at[t_idx].set(status)
            # active-set size: rows the shrinking heuristic would keep
            # live right now (all valid rows when tracking is off) — the
            # per-round shrink trajectory `tpusvm report` renders
            if shrink_stable:
                n_live = jnp.sum(valid & (stable < shrink_stable))
            else:
                n_live = jnp.sum(valid)
            tele_active = tele_active.at[t_idx].set(
                n_live.astype(jnp.int32))
            tele_i = tele_i + 1
        return _OuterState(alpha, f, b_high, b_low, n_updates, n_outer,
                           status, f_exact, n_refines,
                           tele_gap, tele_upd, tele_status, tele_i,
                           tele_active, stable, cache, cache_keys,
                           cache_age, cache_hits, cache_misses,
                           cand_up_val, cand_up_idx, cand_low_val,
                           cand_low_idx)

    if pallas_fused_selection:
        cuv0, cui0, clv0, cli0 = bootstrap_candidates(
            f0, alpha0, Y, valid, C, eps, _ncand)
    else:
        cuv0 = clv0 = jnp.zeros((0,), jnp.float32)
        cui0 = cli0 = jnp.zeros((0,), jnp.int32)
    init = _OuterState(
        alpha=alpha0,
        f=f0,
        b_high=jnp.array(jnp.nan, adt),
        b_low=jnp.array(jnp.nan, adt),
        n_updates=jnp.int32(0),
        n_outer=jnp.int32(0),
        status=jnp.int32(Status.RUNNING),
        # -y (cold start) and the warm-start rbf_matvec are both exact
        # reconstructions of f(alpha0)
        f_exact=jnp.array(True),
        n_refines=jnp.int32(0),
        # NaN-filled gap slots distinguish "never written" from a real
        # gap in short solves; shape (0,) keeps the carry free when off
        tele_gap=jnp.full((telemetry,), jnp.nan, adt),
        tele_upd=jnp.zeros((telemetry,), jnp.int32),
        tele_status=jnp.zeros((telemetry,), jnp.int32),
        tele_i=jnp.int32(0),
        tele_active=jnp.zeros((telemetry,), jnp.int32),
        stable=jnp.zeros((n if shrink_stable else 0,), jnp.int32),
        cache=jnp.zeros((krow_cache, n), jnp.float32),
        cache_keys=jnp.full((krow_cache,), -1, jnp.int32),
        cache_age=jnp.zeros((krow_cache,), jnp.int32),
        cache_hits=jnp.int32(0),
        cache_misses=jnp.int32(0),
        cand_up_val=cuv0,
        cand_up_idx=cui0,
        cand_low_val=clv0,
        cand_low_idx=cli0,
    )
    if resume_state is not None:
        if resume_state.tele_gap.shape[0] != telemetry:
            raise ValueError(
                f"resume_state carries a {resume_state.tele_gap.shape[0]}-"
                f"slot telemetry ring but this solve was configured with "
                f"telemetry={telemetry}; resume with the checkpoint's "
                "telemetry setting"
            )
        if resume_state.alpha.shape[0] != n:
            raise ValueError(
                f"resume_state is for n={resume_state.alpha.shape[0]} "
                f"rows, this solve has n={n}"
            )
        init = _OuterState(*resume_state)
    if pause_at is None:
        cond = lambda s: s.status == Status.RUNNING  # noqa: E731
    else:
        stop = jnp.asarray(pause_at, jnp.int32)
        cond = lambda s: (s.status == Status.RUNNING) \
            & (s.n_outer < stop)  # noqa: E731
    final = lax.while_loop(cond, body, init)
    result = SMOResult(
        alpha=final.alpha,
        b=(final.b_high + final.b_low) / 2.0,
        b_high=final.b_high,
        b_low=final.b_low,
        n_iter=final.n_updates + 1,  # reference counting: updates + 1
        status=final.status,
        n_outer=final.n_outer,
        n_refines=final.n_refines,
        telemetry=(ConvergenceTelemetry(
            gap=final.tele_gap, n_upd=final.tele_upd,
            status=final.tele_status, count=final.tele_i,
            active=final.tele_active,
        ) if telemetry else None),
        cache_hits=(final.cache_hits if krow_cache else None),
        cache_misses=(final.cache_misses if krow_cache else None),
    )
    if return_state:
        return result, final
    return result


# the single-problem jit entry: blocked_smo_core traced once per static
# config, exactly as before the fleet refactor split the core out (the
# fleet solver jits its OWN vmap of the core instead of nesting jits)
_blocked_smo_solve_jit = functools.partial(
    jax.jit, static_argnames=_BLOCKED_STATIC
)(blocked_smo_core)

# every caller (models, tune, checkpoint, kernels.svr, CLI) goes through
# this wrapper: with the compile observatory off it is the jit call,
# byte-for-byte; with it on (CLI --trace) lower/compile wall time and the
# executable's cost/memory analysis are recorded (tpusvm.obs.prof). The
# `.lower` AOT surface and the introspectable signature are preserved.
blocked_smo_solve = prof.profiled_jit(
    "solver.blocked_smo_solve", _blocked_smo_solve_jit,
    static=_BLOCKED_STATIC,
)
