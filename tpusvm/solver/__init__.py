from tpusvm.solver.predict import decision_function, predict
from tpusvm.solver.smo import SMOResult, SMOState, smo_solve

__all__ = ["SMOResult", "SMOState", "smo_solve", "decision_function", "predict"]
