from tpusvm.solver.blocked import blocked_smo_solve
from tpusvm.solver.predict import decision_function, predict
from tpusvm.solver.shrink import shrinking_blocked_solve
from tpusvm.solver.smo import SMOResult, SMOState, smo_solve

__all__ = [
    "SMOResult",
    "SMOState",
    "smo_solve",
    "blocked_smo_solve",
    "shrinking_blocked_solve",
    "decision_function",
    "predict",
]
