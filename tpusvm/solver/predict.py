"""On-device batched prediction.

TPU-native replacement for the reference's predict kernels: serial SV-only
sum (main3.cpp:391-402, C15), GPU all-points sum (gpu_svm_main3.cu:277-296,
C16). Both are algebraically sign(sum_j a_j y_j K(x, x_j) - b) with a_j = 0
for non-SVs; here the sum over training points is one blocked MXU matmul per
test block — K(X_test_blk, X_train) @ (alpha * y) — so XLA tiles the d- and
n-contractions onto the systolic array.

Sign convention: strict `> 0 -> +1`, matching the serial oracle
(main3.cpp:399). The reference's MPI build uses `>= 0` (mpi_svm_main3.cpp:800)
— a documented discrepancy (SURVEY.md §3.5); the oracle convention wins.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpusvm import kernels
from tpusvm.obs import prof
from tpusvm.ops.rbf import coef_matvec, sq_norms


_DECISION_STATIC = ("gamma", "block", "kernel", "degree", "coef0")


@functools.partial(jax.jit, static_argnames=_DECISION_STATIC)
def _decision_function_jit(
    X_test: jax.Array,
    X_train: jax.Array,
    coef: jax.Array,  # alpha * y, zeros for non-SVs / padding
    b,
    *,
    gamma: float,
    block: int = 2048,
    kernel: str = "rbf",
    degree: int = 3,
    coef0: float = 0.0,
) -> jax.Array:
    """f(x) = sum_j coef_j K(x, x_j) - b for each test row. Shape (m,).

    Serves every (kernel, task) cell: classification scores AND epsilon-SVR
    regressed values are the same sum (tpusvm.kernels.svr), so serve's
    bucket executables and the streamed scorer need no second code path.
    All kernel parameters are static here (they come from a fitted model's
    config — one executable per model, the serving contract).
    """
    m, d = X_test.shape
    nb = -(-m // block)
    pad = nb * block - m
    Xp = jnp.pad(X_test, ((0, pad), (0, 0)))
    sn_train = (sq_norms(X_train) if kernels.needs_norms(kernel) else None)

    def step(_, Xb):
        K = kernels.cross(kernel, Xb, X_train, gamma=gamma, coef0=coef0,
                          degree=degree, snB=sn_train)
        return None, coef_matvec(K, coef)

    _, scores = jax.lax.scan(step, None, Xp.reshape(nb, block, d))
    return scores.reshape(-1)[:m] - b


_DECISION_FLAT_STATIC = ("gamma", "kernel", "degree", "coef0")


@functools.partial(jax.jit, static_argnames=_DECISION_FLAT_STATIC)
def _decision_function_flat_jit(
    X_test: jax.Array,
    X_train: jax.Array,
    coef: jax.Array,
    b,
    *,
    gamma: float,
    kernel: str = "rbf",
    degree: int = 3,
    coef0: float = 0.0,
) -> jax.Array:
    """Unblocked variant of decision_function: one flat matmul.

    Used by mesh-sharded serving (models.*.decision_function(mesh=...)):
    the blocked variant's reshape-to-(nb, block, d) + lax.scan destroys a
    row sharding — XLA all-gathers the whole test set onto every device —
    while a flat matmul partitions cleanly along the sharded rows with
    zero collectives (each device computes its own rows' scores). The
    (m, n_train) kernel slab is materialised, but sharded: each device
    holds m/P rows, which is exactly the memory scaling sharded serving
    is for. Single-device callers should prefer the blocked variant,
    which bounds the slab at (block, n_train).
    """
    snB = sq_norms(X_train) if kernels.needs_norms(kernel) else None
    K = kernels.cross(kernel, X_test, X_train, gamma=gamma, coef0=coef0,
                      degree=degree, snB=snB)
    return coef_matvec(K, coef) - b


# compile-observatory wrappers (tpusvm.obs.prof): the jit call when
# profiling is off; lower/compile + cost-analysis accounting when on.
# Serve's bucket cache keeps using the preserved `.lower` AOT surface
# (it owns its own compile accounting in serve/buckets.py).
decision_function = prof.profiled_jit(
    "predict.decision_function", _decision_function_jit,
    static=_DECISION_STATIC,
)
decision_function_flat = prof.profiled_jit(
    "predict.decision_function_flat", _decision_function_flat_jit,
    static=_DECISION_FLAT_STATIC,
)


def predict(
    X_test: jax.Array,
    X_train: jax.Array,
    Y_train: jax.Array,
    alpha: jax.Array,
    b,
    *,
    gamma: float,
    sv_tol: float = 1e-8,
    block: int = 2048,
    kernel: str = "rbf",
    degree: int = 3,
    coef0: float = 0.0,
) -> jax.Array:
    """Labels in {+1,-1}; strict >0 -> +1 (main3.cpp:399).

    Sub-threshold alphas (<= sv_tol) are zeroed before the sum so the score
    matches the oracle's SV-only sum exactly (main3.cpp:394-397), not just
    algebraically-up-to-clipped-residuals.
    """
    a = jnp.where(alpha > sv_tol, alpha, 0.0)
    coef = a * Y_train.astype(X_train.dtype)
    scores = decision_function(X_test, X_train, coef, b, gamma=gamma,
                               block=block, kernel=kernel, degree=degree,
                               coef0=coef0)
    return jnp.where(scores > 0, 1, -1).astype(jnp.int32)
