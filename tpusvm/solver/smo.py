"""On-device SMO solver: the whole hot loop inside one `lax.while_loop`.

This is the TPU-native redesign of the reference's GPU solver
(gpu_svm_main3.cu:318-483). The reference's structure — a host-driven loop
with 4+ kernel launches and 9 scalar cudaMemcpys per iteration (SURVEY.md
§3.2) — is exactly what XLA removes: the entire SMO iteration (working-set
selection, kernel-row refresh, analytic 2-alpha update, error-vector update)
is traced once and compiled into a single on-device while loop with zero
host round trips. One jit call runs the full training to convergence.

Design notes (SURVEY.md §7.1):
  - solver state is a pytree carried through `lax.while_loop`;
  - selection = masked argmin/argmax (the INF-masking trick of
    gpu_svm_main3.cu:166-176 is the natural XLA expression);
  - the kernel-row cache (recompute only when i_high/i_low changed,
    main3.cpp:191-232) becomes `lax.cond` on index change;
  - i_high and i_low rows are computed in ONE fused pass over X
    (rbf_rows_at) when both changed — half the HBM traffic of the
    reference's two separate launches;
  - padded rows (cascade capacity buffers) are excluded from the index sets
    via a validity mask and can never become support vectors;
  - warm start reconstructs f with a blocked MXU matvec (rbf_matvec), the
    cascade's SMO_train(init=false) semantics (mpi_svm_main3.cpp:156-186).

All numerical constants and tie-breaks match the serial oracle
(tpusvm.oracle.smo); parity is enforced by tests/test_solver_parity.py.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpusvm import kernels
from tpusvm.config import SVMConfig
from tpusvm.ops.rbf import sq_norms
from tpusvm.solver.analytic import pair_update
from tpusvm.ops.selection import (
    i_high_mask,
    i_low_mask,
    masked_argmax,
    masked_argmin,
)
from tpusvm.obs import prof
from tpusvm.status import Status


class SMOState(NamedTuple):
    """Loop-carried solver state (SURVEY.md §7.1 state pytree)."""

    alpha: jax.Array      # (n,) dual variables
    f: jax.Array          # (n,) error vector f_i = sum_j a_j y_j K_ij - y_i
    k_high: jax.Array     # (n,) cached kernel row K(x_{i_high}, .)
    k_low: jax.Array      # (n,) cached kernel row K(x_{i_low}, .)
    i_high_prev: jax.Array  # scalar int32; n = "no cached row" sentinel
    i_low_prev: jax.Array
    b_high: jax.Array     # scalar
    b_low: jax.Array
    n_iter: jax.Array     # scalar int32, reference counting: updates + 1
    status: jax.Array     # scalar int32, Status enum


class SMOResult(NamedTuple):
    alpha: jax.Array
    b: jax.Array
    b_high: jax.Array
    b_low: jax.Array
    n_iter: jax.Array
    status: jax.Array
    # blocked solver only: number of outer (working-set) iterations
    n_outer: Optional[jax.Array] = None
    # blocked solver only: f reconstructions done by refine mode
    n_refines: Optional[jax.Array] = None
    # blocked solver only, telemetry=T > 0: the carry-resident
    # convergence ring (obs.convergence.ConvergenceTelemetry), None when
    # telemetry is off — the default, so the pair solver and every
    # existing caller see an unchanged result surface
    telemetry: Optional[Any] = None
    # blocked solver only, krow_cache=slots > 0: rows served from the
    # K-row LRU cache vs computed fresh (int32 scalars; None when off)
    cache_hits: Optional[jax.Array] = None
    cache_misses: Optional[jax.Array] = None


def _body(state: SMOState, X, Y, valid, sn, C, gamma, eps, tau, max_iter,
          kernel, degree, coef0):
    alpha, f = state.alpha, state.f
    n = Y.shape[0]

    m_high = i_high_mask(alpha, Y, C, eps, valid)
    m_low = i_low_mask(alpha, Y, C, eps, valid)
    i_high, found_h = masked_argmin(f, m_high)
    i_low, found_l = masked_argmax(f, m_low)
    found = found_h & found_l
    i_high = i_high.astype(jnp.int32)
    i_low = i_low.astype(jnp.int32)

    b_high = jnp.where(found, f[i_high], state.b_high)
    b_low = jnp.where(found, f[i_low], state.b_low)
    converged = found & (b_low <= b_high + 2.0 * tau)
    proceed = found & ~converged

    # --- kernel-row cache refresh (main3.cpp:216-232 -> lax.cond) ---------
    need_h = proceed & (i_high != state.i_high_prev)
    need_l = proceed & (i_low != state.i_low_prev)

    def refresh(_):
        # One fused pass computes both rows; lax.cond skips it entirely when
        # neither index changed (both-cached iterations are common: the pair
        # often repeats while alpha walks along the box boundary).
        rows = kernels.rows_at(kernel, X, jnp.stack([i_high, i_low]),
                               gamma=gamma, coef0=coef0, degree=degree,
                               sn=sn)
        kh = jnp.where(need_h, rows[0], state.k_high)
        kl = jnp.where(need_l, rows[1], state.k_low)
        return kh, kl

    k_high, k_low = lax.cond(
        need_h | need_l, refresh, lambda _: (state.k_high, state.k_low), None
    )

    # --- analytic 2-variable update (main3.cpp:234-279) -------------------
    # Scalar math runs in the accumulator dtype (= f.dtype): with the
    # mixed-precision mode (f32 features, f64 accumulators) the tiny
    # near-convergence updates stay representable (SURVEY.md §7.3 Precision).
    adt = f.dtype
    y_h = Y[i_high].astype(adt)
    y_l = Y[i_low].astype(adt)
    upd = pair_update(
        k_high[i_high].astype(adt),
        k_low[i_low].astype(adt),
        k_high[i_low].astype(adt),
        y_h, y_l,
        alpha[i_high], alpha[i_low],
        b_high, b_low, C, eps, proceed,
    )
    feasible, eta_ok = upd.feasible, upd.eta_ok
    do_update, stalled = upd.do_update, upd.stalled
    da_h, da_l = upd.da_h, upd.da_l

    # --- error-vector update (main3.cpp:271-275 / update_f kernel) --------
    fdt = f.dtype
    f = f + da_h * y_h.astype(fdt) * k_high.astype(fdt) \
          + da_l * y_l.astype(fdt) * k_low.astype(fdt)
    alpha = alpha.at[i_high].add(da_h)
    alpha = alpha.at[i_low].add(da_l)

    n_iter = state.n_iter + jnp.where(do_update, 1, 0).astype(jnp.int32)

    # --- status resolution (reference break order: no-WS, converged at loop
    # top; infeasible-UV checked before eta, main3.cpp:246-257) ------------
    status = jnp.where(
        ~found,
        Status.NO_WORKING_SET,
        jnp.where(
            converged,
            Status.CONVERGED,
            jnp.where(
                ~feasible,
                Status.INFEASIBLE_UV,
                jnp.where(
                    ~eta_ok,
                    Status.NONPOS_ETA,
                    jnp.where(
                        stalled,
                        Status.STALLED,
                        jnp.where(
                            n_iter > max_iter, Status.MAX_ITER, Status.RUNNING
                        ),
                    ),
                ),
            ),
        ),
    ).astype(jnp.int32)

    return SMOState(
        alpha=alpha,
        f=f,
        k_high=k_high,
        k_low=k_low,
        i_high_prev=jnp.where(do_update, i_high, state.i_high_prev),
        i_low_prev=jnp.where(do_update, i_low, state.i_low_prev),
        b_high=b_high,
        b_low=b_low,
        n_iter=n_iter,
        status=status,
    )


# Only max_iter/warm_start/accum_dtype/kernel/degree are static: the float
# hyperparameters are traced scalars so a C/gamma (or coef0) grid search
# reuses one compiled solver per (kernel, degree) family.
_SMO_STATIC = ("max_iter", "warm_start", "accum_dtype", "kernel", "degree")


@functools.partial(jax.jit, static_argnames=_SMO_STATIC)
def _smo_solve_jit(
    X: jax.Array,
    Y: jax.Array,
    valid: Optional[jax.Array] = None,
    alpha0: Optional[jax.Array] = None,
    *,
    C: float = 10.0,
    gamma: float = 0.00125,
    eps: float = 1e-12,
    tau: float = 1e-5,
    max_iter: int = 100000,
    warm_start: bool = False,
    accum_dtype=None,
    kernel: str = "rbf",
    degree: int = 3,
    coef0: float = 0.0,
    targets: Optional[jax.Array] = None,
) -> SMOResult:
    """Run SMO to termination entirely on device.

    Args:
      X: (n, d) scaled features (rows beyond the valid count may be padding).
      Y: (n,) labels in {+1,-1}; padded rows should be 0.
      valid: (n,) bool mask of real rows; None = all valid.
      alpha0: warm-start duals (cascade); zeros if None.
      warm_start: reconstruct f from alpha0 via a blocked MXU matvec.
      accum_dtype: dtype of f/alpha/scalar math (default: X.dtype). Pass
        jnp.float64 with float32 X for the mixed-precision mode: kernel rows
        stay f32 (full HBM-bandwidth win) while the O(n) accumulators match
        the f64 reference's ability to resolve tiny near-convergence updates.
      kernel/degree/coef0: kernel family and its parameters
        (tpusvm.kernels); family and degree are static, gamma/coef0 traced.
        "rbf" (the default) runs the pre-refactor code path byte-for-byte.
      targets: optional (n,) pseudo-target vector z replacing the labels in
        the error vector f_i = sum_j a_j y_j K_ij - z_i (None = z = Y, the
        classification problem). The epsilon-SVR doubling
        (tpusvm.kernels.svr) is the intended caller; everything else —
        selection, stopping rule, analytic update — is unchanged.

    Returns SMOResult; `alpha` of padded rows is guaranteed 0.
    """
    kernels.validate_family(kernel)
    n = Y.shape[0]
    dtype = X.dtype
    adt = dtype if accum_dtype is None else accum_dtype
    if valid is None:
        valid = jnp.ones((n,), bool)
    if alpha0 is None:
        alpha0 = jnp.zeros((n,), adt)
    alpha0 = jnp.where(valid, alpha0, 0.0).astype(adt)

    yf = Y.astype(adt)
    z = yf if targets is None else jnp.asarray(targets).astype(adt)
    if warm_start:
        f0 = kernels.matvec(
            kernel, X, (alpha0 * yf).astype(dtype), gamma=gamma,
            coef0=coef0, degree=degree,
        ).astype(adt) - z
    else:
        f0 = -z
    # Padded rows never enter the index sets; park their f at 0 for tidiness.
    f0 = jnp.where(valid, f0, 0.0)

    init = SMOState(
        alpha=alpha0,
        f=f0,
        k_high=jnp.zeros((n,), dtype),
        k_low=jnp.zeros((n,), dtype),
        i_high_prev=jnp.int32(n),
        i_low_prev=jnp.int32(n),
        b_high=jnp.array(jnp.nan, adt),
        b_low=jnp.array(jnp.nan, adt),
        n_iter=jnp.int32(1),
        status=jnp.int32(Status.RUNNING),
    )

    # Row squared-norms hoisted out of the loop: the dot-form kernel-row
    # refresh then streams X from HBM exactly once per iteration. Only the
    # RBF family consumes them; linear/poly skip the O(n*d) pass.
    sn = sq_norms(X) if kernels.needs_norms(kernel) else None
    body = functools.partial(
        _body, X=X, Y=Y, valid=valid, sn=sn, C=C, gamma=gamma, eps=eps,
        tau=tau, max_iter=max_iter, kernel=kernel, degree=degree,
        coef0=coef0,
    )
    final = lax.while_loop(
        lambda st: st.status == Status.RUNNING, lambda st: body(st), init
    )
    b = (final.b_high + final.b_low) / 2.0
    return SMOResult(
        alpha=final.alpha,
        b=b,
        b_high=final.b_high,
        b_low=final.b_low,
        n_iter=final.n_iter,
        status=final.status,
    )


# compile-observatory wrapper (tpusvm.obs.prof): identical to the jit
# call when profiling is off; records lower/compile time + cost analysis
# per distinct signature when on. Inside vmap/shard_map traces (the OVR
# batched path, cascade bodies) the wrapper sees tracers and passes
# straight through to the jitted function.
smo_solve = prof.profiled_jit("solver.smo_solve", _smo_solve_jit,
                              static=_SMO_STATIC)
