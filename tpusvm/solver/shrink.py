"""Active-set shrinking: solver work scales with the live set, not n.

The classical SMO shrinking heuristic (Joachims '98; LIBSVM; the
working-set GPU solver literature the reference builds on — Catanzaro et
al.'s adaptive heuristics, ThunderSVM's q-sized sets, SURVEY §2): alphas
that sit at a box bound and stay Keerthi-safe for S consecutive rounds
almost never move again, so the solver stops carrying them. XLA's static
shapes rule out LIBSVM's in-place dynamic index juggling; this driver
re-expresses the idea the way the repo's checkpoint driver segments the
loop (solver/checkpoint.py proved segmenting is bit-identical):

  1. run blocked_smo_solve for `shrink_every` outer rounds with
     shrink_stable=S stability tracking in the carry (solver/blocked.py:
     per-row counters of consecutive at-bound-and-safe rounds — written,
     never read, by the solve itself);
  2. at the pause, FREEZE rows whose counter reached S and COMPACT the
     live rows into a static-shape capacity bucket (power-of-two, floored
     at shrink_min) — jit signatures stay bounded: each bucket size
     compiles once, and buckets only shrink;
  3. resume the loop on the compacted problem via the solver's
     resume_state surface. The carried f values of live rows stay EXACT:
     f_i depends on frozen alphas only through terms that no longer
     change, and the working set is always drawn from live rows, so the
     accumulated deltas never touch a frozen coefficient;
  4. when the compacted problem converges (or hits a terminal status),
     UN-SHRINK: scatter the alphas back, rebuild the full f from scratch
     out of the nonzero coefficients (a cross_matvec over a padded
     SV-bucket — the same reconstruction refine mode uses), reactivate
     every row, and resume on the full problem. The solver's own first
     global Keerthi check then decides — the final stopping decision is
     IDENTICAL to the unshrunk criterion, so a wrongly frozen alpha is
     revived and re-optimised, never silently dropped.

Counters (n_outer / n_updates / max_iter budgets), the convergence
telemetry ring (which records the live-set size per round) and the K-row
cache hit counters are carried across compactions — the ring and scalars
are n-independent; per-row state is gathered/scattered with the rows.

bf16_f32 drift guard: for matmul_precision='bf16_f32'/'bf16_f32c' with
refine=0, a convergence claim made on the full problem's ACCUMULATED f
is additionally re-validated on a from-scratch rebuild (one extra
verification segment) before being accepted — the un-shrink discipline
applied to the precision ladder, which is why the solver admits those
rungs without refine when shrink_stable > 0.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from tpusvm import kernels
from tpusvm.solver.blocked import (
    _OuterState,
    blocked_smo_solve,
    bootstrap_candidates,
    resolve_solver_config,
)
from tpusvm.solver.smo import SMOResult
from tpusvm.status import Status

#: kwargs of blocked_smo_solve the driver owns (callers must not pass)
_DRIVER_RESERVED = ("resume_state", "pause_at", "return_state")


def _bucket(n_live: int, lo: int, hi: int) -> int:
    """Static-shape capacity for n_live rows: power-of-two, floored at
    lo, capped at hi — bounded jit signatures, shrink-only transitions."""
    cap = max(lo, 1 << max(0, int(n_live - 1).bit_length()))
    return min(cap, hi)


def _rebuild_f(X_eval, X_full, Y_full, valid_eval, alpha_np, z_eval,
               dtype, kern_kw, sn_eval):
    """f at the X_eval rows from scratch: K(X_eval, X_full[nz]) @ coef -
    z over a padded nonzero bucket (power-of-two, so repeated rebuilds
    reuse executables). alpha_np/Y_full index the FULL problem — frozen
    coefficients contribute like live ones — while X_eval may be the
    full matrix (un-shrink) or a compacted bucket (the bf16 periodic
    rebuild), always at the trust-anchor precision."""
    n = X_full.shape[0]
    nz = np.flatnonzero(alpha_np != 0.0)
    cap = min(n, max(64, 1 << max(0, int(len(nz) - 1).bit_length())))
    idx = np.zeros(cap, np.int64)
    idx[: len(nz)] = nz
    coef = np.zeros(cap, np.float64)
    yf = np.asarray(Y_full, np.float64)
    coef[: len(nz)] = alpha_np[nz] * yf[nz]
    f = kernels.cross_matvec(
        kernels.validate_family(kern_kw["kernel"]), X_eval,
        X_full[jnp.asarray(idx)], jnp.asarray(coef).astype(dtype),
        gamma=kern_kw["gamma"], coef0=kern_kw["coef0"],
        degree=kern_kw["degree"], sn=sn_eval, fast=kern_kw["kernel_fast"],
    )
    f = f.astype(z_eval.dtype) - z_eval
    return jnp.where(valid_eval, f, 0.0)


def shrinking_blocked_solve(
    X,
    Y,
    valid=None,
    alpha0=None,
    *,
    shrink_every: int = 8,
    shrink_stable: int = 3,
    shrink_min: int = 256,
    shrink_gap_factor: float = 10.0,
    max_unshrinks: int = 10,
    targets=None,
    return_history: bool = False,
    **kw,
) -> SMOResult:
    """blocked_smo_solve with active-set shrinking (see module docstring).

    shrink_every: outer rounds between freeze/compaction decisions (the
    segment length; also the checkpointing granularity of the stability
    counters). shrink_stable: consecutive at-bound-and-Keerthi-safe
    rounds before a row may freeze. shrink_min: smallest compaction
    bucket — below this, compaction overhead beats the savings.

    shrink_gap_factor: shrinking stops once the Keerthi gap falls within
    this factor of the stopping band (gap <= factor * 2 * tau) — the
    LIBSVM discipline. Near convergence a frozen row's STALE f makes the
    safety judgement unreliable (its true f drifts as live alphas move),
    and re-freezing after every un-shrink can oscillate: the live set
    re-converges against fixed frozen terms, un-shrink reveals band-edge
    violators, repeat. Far from convergence the judgement is robust (the
    band tightens monotonically in trend), which is where the savings
    live anyway. max_unshrinks is the hard backstop on re-shrink cycles;
    after it, the solve runs unshrunk to termination.

    Accepts every blocked_smo_solve kwarg except the segmenting surface
    (resume_state/pause_at/return_state, which the driver owns) and
    pallas_fused_selection composes too (candidate lists are re-seeded
    across compactions). refine > 0 applies to FULL-problem segments
    only (a compacted reconstruction would drop the frozen rows'
    contributions and corrupt f).

    return_history=True returns (SMOResult, history) where history is a
    list of {"event": "shrink"|"unshrink"|"verify", "round", "active",
    "cap"} dicts — the bench harness's active-set trajectory.
    """
    for k in _DRIVER_RESERVED:
        if k in kw:
            raise ValueError(
                f"{k} belongs to the shrinking driver's segmenting "
                "surface; it cannot be passed through "
                "shrinking_blocked_solve"
            )
    if shrink_stable < 1:
        raise ValueError(
            f"shrink_stable must be >= 1 round, got {shrink_stable}"
        )
    if shrink_every < 1:
        raise ValueError(
            f"shrink_every must be >= 1 outer round, got {shrink_every}"
        )
    if kw.get("matmul_precision") == "default":
        raise ValueError(
            "matmul_precision='default' (raw bf16) requires refine-mode "
            "drift control, which compacted segments cannot run (a "
            "reconstruction would drop the frozen rows' contributions); "
            "use matmul_precision='bf16_f32' with shrinking — its f32 "
            "accumulation is covered by the un-shrink revalidation"
        )

    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    n, d = X.shape
    C = kw.get("C", 10.0)
    eps = kw.get("eps", 1e-12)
    refine_user = kw.pop("refine", 0)
    max_refines = kw.pop("max_refines", 2)
    krow_cache = kw.get("krow_cache", 0)
    telemetry = kw.get("telemetry", 0)
    fused_sel = kw.get("pallas_fused_selection", False)
    matmul_precision = kw.get("matmul_precision")
    kern_kw = {
        "kernel": kw.get("kernel", "rbf"),
        "gamma": kw.get("gamma", 0.00125),
        "coef0": kw.get("coef0", 0.0),
        "degree": kw.get("degree", 3),
        "kernel_fast": kw.get("kernel_fast", True),
    }
    # bf16 runs ANNEAL: the throughput rung carries the bulk descent,
    # and once the (rebuilt, trust-tier) gap is within this factor of
    # the stopping band the remaining tail runs at full f32. Below that
    # gap the bf16 operand noise exceeds the progress per round —
    # selection chases phantom violators and the strict 2*tau tail
    # crawls (measured: a ~30x round blowup at the smoke shape) — while
    # above it the noise is irrelevant next to the genuine violations.
    bf16_anneal_factor = 50.0
    cur_precision = matmul_precision

    def _is_bf16(p):
        return p in ("bf16_f32", "bf16_f32c") and refine_user <= 0

    if valid is None:
        valid_full = jnp.ones((n,), bool)
    else:
        valid_full = jnp.asarray(valid)
    yf64 = np.asarray(Y, np.float64)
    z_full = (jnp.asarray(Y).astype(X.dtype) if targets is None
              else jnp.asarray(targets).astype(X.dtype))
    sn_full = kernels.sq_norms_for(kern_kw["kernel"], X)

    history = []

    def seg_kw(cap_n, refine_on):
        out = dict(kw)
        out["shrink_stable"] = shrink_stable
        out["matmul_precision"] = cur_precision
        if refine_on and refine_user > 0:
            out["refine"] = refine_user
            out["max_refines"] = max_refines
        return out

    def ncand_for(cap_n):
        from tpusvm.ops.pallas.fused_fupdate import selection_shape

        q_eff = resolve_solver_config(cap_n, kw.get("q", 1024))[0]
        return selection_shape(cap_n, d, q_eff)[3]

    # ---- current problem (starts as the full one) -----------------------
    gids = np.arange(n, dtype=np.int64)  # global row id per local row
    X_c, Y_c, valid_c, z_c, sn_c = X, Y, valid_full, z_full, sn_full
    state: Optional[_OuterState] = None
    alpha_full = np.zeros(n, np.float64)
    is_full = True
    last_verified_updates = -1
    n_unshrinks = 0

    # first segment: the plain entry path (alpha0/warm_start honoured)
    seg_precision = cur_precision
    res, st = blocked_smo_solve(
        X_c, Y_c, valid=valid_c, alpha0=alpha0, targets=targets,
        sn=sn_c, pause_at=np.int32(shrink_every), return_state=True,
        **seg_kw(n, refine_on=True),
    )
    state = st

    for _ in range(1_000_000):  # bounded by max_iter/max_outer inside
        status = Status(int(state.status))
        if status != Status.RUNNING:
            # ---------------- terminal segment ---------------------------
            valid_np = np.asarray(valid_c)
            alpha_np = np.asarray(state.alpha, np.float64)
            alpha_full[gids[valid_np]] = alpha_np[valid_np]
            if is_full and not (_is_bf16(seg_precision)
                                and status == Status.CONVERGED
                                and last_verified_updates
                                != int(state.n_updates)):
                if return_history:
                    return res, history
                return res
            # un-shrink (or bf16 claim verification): rebuild the FULL f
            # from the scattered-back alphas and let the solver's own
            # global check decide — the unshrunk stopping criterion
            event = "verify" if is_full else "unshrink"
            last_verified_updates = int(state.n_updates)
            alpha_dev = jnp.asarray(alpha_full).astype(state.alpha.dtype)
            alpha_dev = jnp.where(valid_full, alpha_dev, 0.0)
            f_dev = _rebuild_f(X, X, Y, valid_full, alpha_full, z_full,
                               X.dtype, kern_kw, sn_full)
            f_dev = f_dev.astype(state.f.dtype)
            if fused_sel:
                cuv, cui, clv, cli = bootstrap_candidates(
                    f_dev, alpha_dev, Y, valid_full, C, eps, ncand_for(n))
            else:
                cuv = clv = jnp.zeros((0,), jnp.float32)
                cui = cli = jnp.zeros((0,), jnp.int32)
            stable0 = jnp.zeros((n,), jnp.int32)
            state = _OuterState(
                alpha=alpha_dev, f=f_dev,
                b_high=state.b_high, b_low=state.b_low,
                n_updates=state.n_updates, n_outer=state.n_outer,
                status=jnp.int32(Status.RUNNING),
                f_exact=jnp.array(True), n_refines=state.n_refines,
                tele_gap=state.tele_gap, tele_upd=state.tele_upd,
                tele_status=state.tele_status, tele_i=state.tele_i,
                tele_active=state.tele_active,
                stable=stable0,
                cache=jnp.zeros((krow_cache, n), jnp.float32),
                cache_keys=jnp.full((krow_cache,), -1, jnp.int32),
                cache_age=jnp.zeros((krow_cache,), jnp.int32),
                cache_hits=state.cache_hits,
                cache_misses=state.cache_misses,
                cand_up_val=cuv, cand_up_idx=cui,
                cand_low_val=clv, cand_low_idx=cli,
            )
            gids = np.arange(n, dtype=np.int64)
            X_c, Y_c, valid_c, z_c, sn_c = X, Y, valid_full, z_full, sn_full
            is_full = True
            if event == "unshrink":
                n_unshrinks += 1
            history.append({"event": event,
                            "round": int(state.n_outer),
                            "active": int(np.sum(np.asarray(valid_full))),
                            "cap": n})
        else:
            # ---------------- paused: freeze + compact? ------------------
            if _is_bf16(cur_precision):
                # bf16 drift control, the cadence half (the claim half is
                # the un-shrink verification): bf16-computed deltas leave
                # a PERMANENT bias in the accumulated f (early rounds'
                # large deltas carry ~2^-9 relative error that later
                # rounds never re-evaluate), and once that bias exceeds
                # tau the strict 2*tau stop is unreachable on the
                # accumulated f — measured as a MAX_ITER livelock at the
                # smoke shape. Rebuilding f at the trust tier every pause
                # bounds the bias to one segment's worth of deltas.
                valid_np = np.asarray(valid_c)
                alpha_np = np.asarray(state.alpha, np.float64)
                alpha_full[gids[valid_np]] = alpha_np[valid_np]
                f_c = _rebuild_f(X_c, X, Y, valid_c, alpha_full, z_c,
                                 X.dtype, kern_kw, sn_c)
                state = state._replace(
                    f=f_c.astype(state.f.dtype),
                    f_exact=jnp.array(True))
                # anneal decision on the REBUILT (trust-tier) gap: once
                # within bf16_anneal_factor of the stopping band, the
                # remaining tail runs at full f32
                f_np = np.asarray(f_c, np.float64)
                a_np = np.asarray(state.alpha, np.float64)
                y_np = np.asarray(Y_c)
                C_ = float(C)
                m_h = np.where(y_np == 1, a_np < C_ - eps,
                               (y_np == -1) & (a_np > eps)) & valid_np
                m_l = np.where(y_np == 1, a_np > eps,
                               (y_np == -1) & (a_np < C_ - eps)) & valid_np
                if m_h.any() and m_l.any():
                    gap_now = float(f_np[m_l].max() - f_np[m_h].min())
                    tau_ = kw.get("tau", 1e-5)
                    if gap_now <= bf16_anneal_factor * 2.0 * tau_:
                        cur_precision = None
            stable_np = np.asarray(state.stable)
            valid_np = np.asarray(valid_c)
            # geometric damping: every un-shrink that revealed frozen
            # violators doubles the stability requirement, so a set that
            # keeps re-freezing wrongly has to prove itself for
            # exponentially longer — the anti-oscillation counterpart of
            # the gap guard (which handles the near-convergence end)
            s_eff = shrink_stable * (1 << min(n_unshrinks, 20))
            live = valid_np & (stable_np < s_eff)
            n_live = int(live.sum())
            cur_cap = len(gids)
            new_cap = _bucket(n_live, shrink_min, n)
            # near-convergence guard (see docstring): frozen-f staleness
            # makes late shrinking oscillatory, so once the gap is within
            # shrink_gap_factor of the stopping band — or the un-shrink
            # budget is spent — the problem runs unshrunk to termination
            gap = float(state.b_low) - float(state.b_high)
            tau = kw.get("tau", 1e-5)
            gap_ok = not np.isfinite(gap) \
                or gap > shrink_gap_factor * 2.0 * tau
            if gap_ok and n_unshrinks < max_unshrinks \
                    and 0 < n_live < int(valid_np.sum()) \
                    and new_cap < cur_cap:
                # write ALL current alphas back (soon-frozen rows
                # included) before dropping rows from the problem
                alpha_np = np.asarray(state.alpha, np.float64)
                alpha_full[gids[valid_np]] = alpha_np[valid_np]
                live_pos = np.flatnonzero(live)
                pad = new_cap - n_live
                sel = np.concatenate([live_pos,
                                      np.zeros(pad, live_pos.dtype)])
                new_valid = np.zeros(new_cap, bool)
                new_valid[:n_live] = True
                gids = np.concatenate([gids[live_pos],
                                       np.zeros(pad, gids.dtype)])
                sel_dev = jnp.asarray(sel)
                vmask = jnp.asarray(new_valid)
                X_c = X_c[sel_dev]
                Y_c = jnp.where(vmask, Y_c[sel_dev], 0)
                z_c = jnp.where(vmask, z_c[sel_dev], 0)
                sn_c = (None if sn_full is None else
                        kernels.sq_norms_for(kern_kw["kernel"], X_c))
                alpha_c = jnp.where(vmask, state.alpha[sel_dev], 0.0)
                f_c = jnp.where(vmask, state.f[sel_dev], 0.0)
                if fused_sel:
                    cuv, cui, clv, cli = bootstrap_candidates(
                        f_c, alpha_c, Y_c, vmask, C, eps,
                        ncand_for(new_cap))
                else:
                    cuv = clv = jnp.zeros((0,), jnp.float32)
                    cui = cli = jnp.zeros((0,), jnp.int32)
                state = _OuterState(
                    alpha=alpha_c, f=f_c,
                    b_high=state.b_high, b_low=state.b_low,
                    n_updates=state.n_updates, n_outer=state.n_outer,
                    status=jnp.int32(Status.RUNNING),
                    f_exact=state.f_exact, n_refines=state.n_refines,
                    tele_gap=state.tele_gap, tele_upd=state.tele_upd,
                    tele_status=state.tele_status, tele_i=state.tele_i,
                    tele_active=state.tele_active,
                    stable=jnp.where(vmask, state.stable[sel_dev], 0),
                    cache=jnp.zeros((krow_cache, new_cap), jnp.float32),
                    cache_keys=jnp.full((krow_cache,), -1, jnp.int32),
                    cache_age=jnp.zeros((krow_cache,), jnp.int32),
                    cache_hits=state.cache_hits,
                    cache_misses=state.cache_misses,
                    cand_up_val=cuv, cand_up_idx=cui,
                    cand_low_val=clv, cand_low_idx=cli,
                )
                valid_c = vmask
                is_full = False
                history.append({"event": "shrink",
                                "round": int(state.n_outer),
                                "active": n_live, "cap": new_cap})
        start = int(state.n_outer)
        seg_precision = cur_precision
        # compacted segments run 4x longer between pauses: the expensive
        # decision (what to freeze) concerns FULL rounds, while a pause
        # on an already-compacted problem only re-checks for further
        # shrinkage — and each pause costs real host-sync/dispatch
        # latency (~tens of ms), which at small compacted round cost is
        # the driver's dominant overhead
        stride = shrink_every if is_full else 4 * shrink_every
        res, state = blocked_smo_solve(
            X_c, Y_c, valid=valid_c, targets=z_c.astype(X.dtype),
            sn=sn_c, resume_state=state,
            pause_at=np.int32(start + stride), return_state=True,
            **seg_kw(len(gids), refine_on=is_full),
        )
    raise RuntimeError("shrinking driver failed to terminate")  # pragma: no cover
