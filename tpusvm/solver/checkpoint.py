"""Crash-safe single-chip training: periodic, bit-exact solver checkpoints.

Only the cascade's inter-round state survived a crash before this module
(parallel/cascade.py:save_round_state); a 10M-row single-chip solve that
died at outer round 4000 restarted from zero. This driver runs
blocked_smo_solve's outer loop in segments of `every` rounds, snapshots
the COMPLETE loop carry (_OuterState: alpha, the accumulated error
vector f, b_high/b_low, counters, refine flags, the telemetry ring)
host-side between segments, and writes it with the house atomic
discipline (temp file + os.replace, format-versioned).

The bit-identity argument: the outer-loop body is a pure function of
the carry plus invariants (X, Y, the static config), so a resumed run
replays exactly the rounds an uninterrupted run would have executed with
exactly the same carry values — numpy round-trips float arrays bit-exact
— and the final alpha bytes, SV ids and b are identical. The chaos test
(tests/test_faults.py) kills at EVERY checkpoint in turn and asserts
this; `python -m tpusvm.faults kill-resume-smoke` is the CI gate.

A checkpoint from a different run is refused by fingerprint, not by a
shape crash: the file carries the solve's static config and a CRC of the
training bytes, and any mismatch names the differing fields.

The checkpoint write is an injection point ("solver.outer_checkpoint")
wrapped in the shared Retry policy: transient write faults are retried,
a SimulatedKill escapes — exactly like a real death at that moment.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from tpusvm import faults
from tpusvm.utils.durable import fsync_replace
from tpusvm.solver.blocked import _OuterState, blocked_smo_solve
from tpusvm.solver.smo import SMOResult
from tpusvm.status import Status

# v2 (round 9): the carry gained the shrink-stability counters, the
# K-row cache (rows/keys/ages/hit counters) and the fused-selection
# candidate ring — all snapshotted like every other field, so resumed
# solves stay bit-identical. v1 files predate those fields and cannot
# resume into this build (the carry would be incomplete); the version
# gate names that instead of a KeyError.
SOLVER_CKPT_VERSION = 2

#: static config the fingerprint pins (a resumed solve with any of these
#: changed would silently walk a different trajectory)
_FP_KEYS = ("C", "gamma", "eps", "tau", "max_iter", "q", "max_outer",
            "max_inner", "wss", "inner", "refine", "max_refines",
            "selection", "matmul_precision", "kernel", "degree", "coef0",
            "kernel_fast", "telemetry", "shrink_stable", "krow_cache",
            "pallas_fused_selection")

_STATE_FIELDS = _OuterState._fields


class WatchdogTimeout(RuntimeError):
    """A checkpointed solve exceeded its supervisor's deadline and was
    stopped BETWEEN segments — after its latest checkpoint was written,
    so a resume=True re-run continues bit-identically from that carry.
    The honest in-process "kill a hung fit": XLA segments cannot be
    interrupted mid-flight, but the segment boundary is a safe,
    checkpointed stop the autopilot can resume from."""

    def __init__(self, path: str, n_outer: int):
        self.checkpoint_path = path
        self.n_outer = n_outer
        super().__init__(
            f"solve stopped by watchdog at outer round {n_outer}; resume "
            f"from checkpoint {path!r}"
        )


def solve_fingerprint(X: np.ndarray, Y: np.ndarray, accum_dtype,
                      solver_kwargs: dict) -> dict:
    """JSON-able identity of a solve: shapes, dtypes, data CRC, config."""
    X = np.asarray(X)
    Y = np.asarray(Y)
    fp = {
        "n": int(X.shape[0]),
        "d": int(X.shape[1]),
        "x_dtype": str(X.dtype),
        "accum_dtype": str(np.dtype(accum_dtype)) if accum_dtype else None,
        "x_crc32": zlib.crc32(np.ascontiguousarray(X).tobytes()),
        "y_crc32": zlib.crc32(np.ascontiguousarray(Y).tobytes()),
    }
    for k in _FP_KEYS:
        if k in solver_kwargs:
            fp[k] = solver_kwargs[k]
    return fp


def save_solver_state(path: str, state: _OuterState, fingerprint: dict,
                      retry: Optional[faults.Retry] = None) -> None:
    """Atomically persist an outer-loop carry + its fingerprint.

    The injection point fires inside the retried write, so a transient
    rule fails the write and the retry re-runs it, while a kill rule
    dies exactly where a real crash would — before the rename, leaving
    the PREVIOUS checkpoint intact."""
    def _write():
        faults.point("solver.outer_checkpoint", path=path,
                     round=int(state.n_outer))
        tmp = path + ".tmp"
        arrays = {f: np.asarray(getattr(state, f)) for f in _STATE_FIELDS}
        np.savez(tmp, ckpt_version=SOLVER_CKPT_VERSION,
                 fingerprint=json.dumps(fingerprint, sort_keys=True),
                 **arrays)
        fsync_replace(tmp + ".npz", path)  # np.savez appends .npz

    if retry is None:
        retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                             op="solver.outer_checkpoint")
    retry(_write)


def load_solver_state(path: str, fingerprint: dict) -> _OuterState:
    """Load a carry; refuse (with the differing fields named) any
    checkpoint whose fingerprint does not match this solve."""
    with np.load(path, allow_pickle=False) as z:
        if "ckpt_version" not in z.files:
            raise ValueError(
                f"{path!r} is not a tpusvm solver checkpoint "
                "(no ckpt_version)"
            )
        v = int(z["ckpt_version"])
        if v != SOLVER_CKPT_VERSION:
            raise ValueError(
                f"unsupported solver checkpoint version {v} (this build "
                f"reads version {SOLVER_CKPT_VERSION}"
                + (": v1 carries predate the round-9 shrink/cache/"
                   "candidate fields — restart the solve fresh"
                   if v == 1 else "")
                + ")"
            )
        saved = json.loads(str(z["fingerprint"]))
        want = json.loads(json.dumps(fingerprint, sort_keys=True))
        if saved != want:
            diff = sorted(
                k for k in set(saved) | set(want)
                if saved.get(k) != want.get(k)
            )
            raise ValueError(
                "solver checkpoint does not belong to this solve "
                f"(differing fields: {diff}); it was written for "
                f"{ {k: saved.get(k) for k in diff} }, this run has "
                f"{ {k: want.get(k) for k in diff} }"
            )
        return _OuterState(*(np.asarray(z[f]) for f in _STATE_FIELDS))


def checkpointed_blocked_solve(
    X,
    Y,
    *,
    checkpoint_path: str,
    checkpoint_every: int = 64,
    resume: bool = False,
    keep_checkpoint: bool = False,
    watchdog=None,
    accum_dtype=None,
    **solver_kwargs,
) -> SMOResult:
    """blocked_smo_solve with periodic crash-safe checkpoints.

    Runs the solve in `checkpoint_every`-outer-round segments; after each
    segment the loop carry is pulled host-side and written atomically to
    `checkpoint_path`. resume=True restarts from that file when it exists
    (missing file = fresh start, like the cascade's documented resume
    semantics); a checkpoint from a different solve (other data, other
    config) is refused with the differing fields named. On successful
    termination the checkpoint is deleted unless keep_checkpoint=True —
    a completed solve's artifact is the model, not the carry.

    The resumed trajectory is BIT-IDENTICAL to an uninterrupted one
    (same alpha bytes / SV set / b): the carry is the complete loop
    state and numpy round-trips it exactly. Asserted against plain
    blocked_smo_solve and under kill-at-every-checkpoint chaos in
    tests/test_faults.py.

    Accepts every blocked_smo_solve kwarg EXCEPT warm-start-shaping args
    that the carry supersedes on resume (alpha0/valid/targets are still
    honoured on the FRESH segments). max_iter/max_outer semantics are
    unchanged — they live inside the loop body.

    watchdog: optional zero-arg callable consulted after each segment's
    checkpoint is durable; returning truthy raises WatchdogTimeout — the
    supervisor's deadline enforcement (a later resume=True run continues
    bit-identically from the checkpoint just written).
    """
    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}"
        )
    fp = solve_fingerprint(X, Y, accum_dtype, solver_kwargs)
    state = None
    if resume and os.path.exists(checkpoint_path):
        state = load_solver_state(checkpoint_path, fp)

    Xd = jnp.asarray(X)
    Yd = jnp.asarray(Y)
    retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                         op="solver.outer_checkpoint")
    while True:
        start = int(state.n_outer) if state is not None else 0
        res, st = blocked_smo_solve(
            Xd, Yd, accum_dtype=accum_dtype, resume_state=state,
            pause_at=np.int32(start + checkpoint_every),
            return_state=True, **solver_kwargs,
        )
        # one host sync materialises the whole carry (the checkpoint
        # payload); segments make this a per-K-rounds cost, not per-round
        state = _OuterState(*(np.asarray(x) for x in st))
        if Status(int(state.status)) != Status.RUNNING:
            if not keep_checkpoint and os.path.exists(checkpoint_path):
                os.remove(checkpoint_path)
            return res
        save_solver_state(checkpoint_path, state, fp, retry=retry)
        if watchdog is not None and watchdog():
            raise WatchdogTimeout(checkpoint_path, int(state.n_outer))
