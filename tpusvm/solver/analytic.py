"""The shared analytic 2-variable SMO update (main3.cpp:145-159, :234-279).

Single source of truth for the numerically delicate scalar step used by both
the pairwise solver (solver/smo.py) and the blocked working-set solver
(solver/blocked.py): box bounds [U, V] from s = y_h*y_l, the eta positivity
guard, the reference's exact clip order (cap at V first, then floor at U,
main3.cpp:261-264), and zero-progress (stall) detection.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class PairUpdate(NamedTuple):
    da_h: jax.Array      # change to alpha[i_high] (0 unless do_update)
    da_l: jax.Array      # change to alpha[i_low]
    feasible: jax.Array  # U <= V + 1e-12 (main3.cpp:158)
    eta_ok: jax.Array    # eta > eps (main3.cpp:253)
    do_update: jax.Array
    stalled: jax.Array   # do_update but both deltas rounded to exactly 0


def pair_update(K11, K22, K12, y_h, y_l, a_h, a_l, b_high, b_low, C, eps,
                proceed) -> PairUpdate:
    """Compute the clipped 2-alpha step. All inputs are scalars (traced).

    `proceed` gates the update (False -> zero deltas), so callers can keep
    the computation unconditional inside compiled loops.
    """
    s = y_h * y_l
    eta = K11 + K22 - 2.0 * K12
    U = jnp.where(s < 0, jnp.maximum(0.0, a_l - a_h),
                  jnp.maximum(0.0, a_l + a_h - C))
    V = jnp.where(s < 0, jnp.minimum(C, C + a_l - a_h),
                  jnp.minimum(C, a_l + a_h))
    feasible = U <= V + 1e-12
    eta_ok = eta > eps
    do_update = proceed & feasible & eta_ok
    safe_eta = jnp.where(eta_ok, eta, jnp.ones_like(eta))
    a_l_new = a_l + y_l * (b_high - b_low) / safe_eta
    # reference clip order: cap at V first, then floor at U (main3.cpp:261-264)
    a_l_new = jnp.maximum(jnp.minimum(a_l_new, V), U)
    a_h_new = a_h + s * (a_l - a_l_new)
    da_h = jnp.where(do_update, a_h_new - a_h, jnp.zeros_like(a_h))
    da_l = jnp.where(do_update, a_l_new - a_l, jnp.zeros_like(a_l))
    stalled = do_update & (da_h == 0) & (da_l == 0)
    return PairUpdate(da_h, da_l, feasible, eta_ok, do_update, stalled)
