"""`tpusvm refresh`: crash-safe warm-started refits that hot-swap in.

The online-learning loop's missing middle (ROADMAP "Online learning"):
data arrives, the deployed model goes stale, and until this module the
only move was a cold retrain + full server restart. A refresh instead:

  1. loads the DEPLOYED artifact and seeds the refit from its duals.
     The seed construction dispatches on the artifact's task:
       svc  scatter (sv_ids, sv_alpha) to full length
            (`tune.warm.deployed_seed`);
       ovr  per-head |coef| scattered to the union sv_ids, projected
            feasible against each head's one-vs-rest labels, all heads
            sharing one hoisted row-norms precompute
            (`tune.warm.deployed_seed_ovr` + `OneVsRestSVC.fit(
            warm_seeds=...)`);
       svr  the doubled-variable inversion beta = [max(coef,0);
            max(-coef,0)] (`tune.warm.deployed_seed_svr`).
     In every case the refresh training set must keep the deployed
     run's rows as a prefix (appended micro-batches — the
     stream.ShardWriter.open_append tail contract);
  2. runs the fit through `checkpointed_blocked_solve` when a
     checkpoint path is given — a killed refresh resumes BIT-IDENTICAL
     to an uninterrupted one (the PR 7 carry-snapshot machinery), and
     an optional `watchdog` deadline callable stops a too-slow fit at a
     checkpointed segment boundary (solver.checkpoint.WatchdogTimeout)
     so a supervisor can resume it later. Binary classifiers only — the
     OvR/SVR outer drivers have no checkpoint surface yet and reject
     those flags by name;
  3. saves the result atomically (save_model: temp + os.replace — a
     `--watch` directory never sees a torn artifact);
  4. hands the artifact to the running server: in-process
     `Server.swap()`, or `POST /admin/swap` over HTTP (`--swap URL`) —
     either way the staged-flip semantics apply and a failed stage
     leaves the old generation serving.

Approx-primal artifacts are rejected by name for every task: the warm
seed is a dual-space object.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional

import numpy as np


def _reject_approx(cfg, model_path: str) -> None:
    from tpusvm.config import APPROX_FAMILIES

    if cfg.kernel in APPROX_FAMILIES:
        raise ValueError(
            f"refresh warm-starts the DUAL solve; {model_path!r} was "
            f"trained in the approximate primal regime ({cfg.kernel}) — "
            "retrain it with `tpusvm train --kernel "
            f"{cfg.kernel}` on the grown dataset instead"
        )


def refresh_fit(model_path: str, X: np.ndarray, Y: np.ndarray, *,
                out_path: str,
                checkpoint_path: Optional[str] = None,
                checkpoint_every: int = 64,
                resume: bool = False,
                warm: bool = True,
                dtype=None,
                accum_dtype="auto",
                solver_opts: Optional[dict] = None,
                watchdog=None):
    """Warm-started (optionally checkpointed) refit of a deployed model.

    Dispatches on the artifact's task (svc | ovr | svr); Y is labels for
    the classifiers and continuous targets for SVR. Returns the fitted
    estimator (already saved to `out_path`). `warm=False` is the control
    arm — the cold refit the warm path's update savings are measured
    against. `watchdog` (requires a checkpoint path) is a zero-arg
    deadline callable: truthy between solve segments raises
    WatchdogTimeout with the checkpoint durable."""
    from tpusvm.models import model_task

    if watchdog is not None and checkpoint_path is None:
        raise ValueError(
            "watchdog needs checkpoint_path: the deadline stops the fit "
            "at a checkpointed segment boundary so it can resume"
        )
    task = model_task(model_path)
    if task == "ovr":
        fit = _refresh_ovr
    elif task == "svr":
        fit = _refresh_svr
    else:
        fit = _refresh_svc
    return fit(model_path, X, Y, out_path=out_path,
               checkpoint_path=checkpoint_path,
               checkpoint_every=checkpoint_every, resume=resume,
               warm=warm, dtype=dtype, accum_dtype=accum_dtype,
               solver_opts=solver_opts, watchdog=watchdog)


def _refresh_svc(model_path, X, Y, *, out_path, checkpoint_path,
                 checkpoint_every, resume, warm, dtype, accum_dtype,
                 solver_opts, watchdog):
    import jax.numpy as jnp

    from tpusvm.models import BinarySVC
    from tpusvm.tune.warm import deployed_seed

    base = BinarySVC.load(model_path)
    cfg = base.config
    _reject_approx(cfg, model_path)
    n = int(np.asarray(X).shape[0])
    opts = dict(solver_opts or {})
    if warm:
        a0 = deployed_seed(base.sv_ids_, base.sv_alpha_, n,
                           np.asarray(Y), cfg.C)
        if a0.any():
            opts["alpha0"] = jnp.asarray(a0)
            opts["warm_start"] = True
    if watchdog is not None:
        # checkpointed_blocked_solve pops this named kwarg; guarded at
        # refresh_fit entry so it can never leak into a plain solve
        opts["watchdog"] = watchdog
    model = BinarySVC(
        config=cfg,
        dtype=dtype if dtype is not None else jnp.float32,
        scale=base.scale,
        accum_dtype=accum_dtype,
        solver="blocked",
        solver_opts=opts,
    )
    model.fit(X, Y, checkpoint_path=checkpoint_path,
              checkpoint_every=checkpoint_every, resume=resume)
    model.save(out_path)
    return model


def _reject_checkpoint(task: str, checkpoint_path) -> None:
    if checkpoint_path is not None:
        raise ValueError(
            f"checkpointed {task} refresh is a future PR (the {task} "
            "outer driver has no per-head checkpoint surface yet); drop "
            "--checkpoint or refresh a binary artifact"
        )


def _refresh_ovr(model_path, X, Y, *, out_path, checkpoint_path,
                 checkpoint_every, resume, warm, dtype, accum_dtype,
                 solver_opts, watchdog):
    import jax.numpy as jnp

    from tpusvm.models import OneVsRestSVC
    from tpusvm.tune.warm import deployed_seed_ovr

    _reject_checkpoint("OvR", checkpoint_path)
    base = OneVsRestSVC.load(model_path)
    cfg = base.config
    _reject_approx(cfg, model_path)
    seeds = None
    if warm:
        if base.sv_ids_ is None:
            raise ValueError(
                f"{model_path!r} predates per-head OvR refresh (no "
                "sv_ids in the artifact); retrain and re-save it, or "
                "run a cold refresh (warm=False / --cold)"
            )
        seeds = deployed_seed_ovr(base.sv_ids_, base.coef_,
                                  int(np.asarray(X).shape[0]),
                                  np.asarray(Y), base.classes_, cfg.C)
        if not seeds.any():
            seeds = None
    model = OneVsRestSVC(
        config=cfg,
        dtype=dtype if dtype is not None else jnp.float32,
        scale=base.scale,
        accum_dtype=accum_dtype,
        solver="blocked",
        solver_opts=dict(solver_opts or {}),
    )
    model.fit(X, Y, warm_seeds=seeds)
    model.save(out_path)
    return model


def _refresh_svr(model_path, X, Y, *, out_path, checkpoint_path,
                 checkpoint_every, resume, warm, dtype, accum_dtype,
                 solver_opts, watchdog):
    import jax.numpy as jnp

    from tpusvm.models.svr import EpsilonSVR
    from tpusvm.tune.warm import deployed_seed_svr

    _reject_checkpoint("SVR", checkpoint_path)
    base = EpsilonSVR.load(model_path)
    cfg = base.config
    _reject_approx(cfg, model_path)
    opts = dict(solver_opts or {})
    if warm:
        beta0 = deployed_seed_svr(base.sv_ids_, base.sv_coef_,
                                  int(np.asarray(X).shape[0]), cfg.C)
        if beta0.any():
            opts["alpha0"] = jnp.asarray(beta0)
            opts["warm_start"] = True
    model = EpsilonSVR(
        config=cfg,
        dtype=dtype if dtype is not None else jnp.float32,
        scale=base.scale,
        accum_dtype=accum_dtype,
        solver="blocked",
        solver_opts=opts,
    )
    model.fit(X, Y)
    model.save(out_path)
    return model


def swap_via_http(server_url: str, name: str, path: str,
                  timeout_s: float = 60.0) -> dict:
    """POST /admin/swap on a running `tpusvm serve` frontend.

    Returns the server's JSON verdict; raises RuntimeError with the
    server's error body on a refused swap (404/409) so callers see the
    rollback reason, not a bare HTTPError."""
    import urllib.error

    body = json.dumps({"name": name, "path": path}).encode()
    req = urllib.request.Request(
        server_url.rstrip("/") + "/admin/swap", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read()).get("error", "")
        except ValueError:
            detail = ""
        raise RuntimeError(
            f"swap of {name!r} refused by {server_url} "
            f"(HTTP {e.code}): {detail or e.reason}"
        ) from e
