"""`tpusvm refresh`: crash-safe warm-started refits that hot-swap in.

The online-learning loop's missing middle (ROADMAP "Online learning"):
data arrives, the deployed model goes stale, and until this module the
only move was a cold retrain + full server restart. A refresh instead:

  1. loads the DEPLOYED artifact and seeds the refit from its alphas
     (`tune.warm.deployed_seed`: scatter sv_alpha back to full length,
     zero the appended rows, project feasible — the measured 43.8%
     update saving of warm vs cold from the tune round, applied to the
     deployment loop). The refresh training set must keep the deployed
     run's rows as a prefix (appended micro-batches, the ShardWriter
     tail contract);
  2. runs the fit through `checkpointed_blocked_solve` when a
     checkpoint path is given — a killed refresh resumes BIT-IDENTICAL
     to an uninterrupted one (the PR 7 carry-snapshot machinery; the
     kill-at-every-checkpoint test extends to this surface);
  3. saves the result atomically (save_model: temp + os.replace — a
     `--watch` directory never sees a torn artifact);
  4. hands the artifact to the running server: in-process
     `Server.swap()`, or `POST /admin/swap` over HTTP (`--swap URL`) —
     either way the staged-flip semantics apply and a failed stage
     leaves the old generation serving.

Exact binary classifiers only for now: the warm seed is a dual-space
object, so approx-primal / OvR / SVR refreshes are rejected by name.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional

import numpy as np


def refresh_fit(model_path: str, X: np.ndarray, Y: np.ndarray, *,
                out_path: str,
                checkpoint_path: Optional[str] = None,
                checkpoint_every: int = 64,
                resume: bool = False,
                warm: bool = True,
                dtype=None,
                accum_dtype="auto",
                solver_opts: Optional[dict] = None):
    """Warm-started (optionally checkpointed) refit of a deployed model.

    Returns the fitted BinarySVC (already saved to `out_path`). `warm=
    False` is the control arm — the cold refit the warm path's update
    savings are measured against."""
    import jax.numpy as jnp

    from tpusvm.config import APPROX_FAMILIES
    from tpusvm.models import BinarySVC, model_task
    from tpusvm.tune.warm import deployed_seed

    task = model_task(model_path)
    if task != "svc":
        raise ValueError(
            f"refresh supports binary classifiers; {model_path!r} is a "
            f"{task!r} artifact (OvR/SVR refresh is a future PR)"
        )
    base = BinarySVC.load(model_path)
    cfg = base.config
    if cfg.kernel in APPROX_FAMILIES:
        raise ValueError(
            f"refresh warm-starts the DUAL solve; {model_path!r} was "
            f"trained in the approximate primal regime ({cfg.kernel}) — "
            "retrain it with `tpusvm train --kernel "
            f"{cfg.kernel}` on the grown dataset instead"
        )
    n = int(np.asarray(X).shape[0])
    opts = dict(solver_opts or {})
    if warm:
        a0 = deployed_seed(base.sv_ids_, base.sv_alpha_, n,
                           np.asarray(Y), cfg.C)
        if a0.any():
            opts["alpha0"] = jnp.asarray(a0)
            opts["warm_start"] = True
    model = BinarySVC(
        config=cfg,
        dtype=dtype if dtype is not None else jnp.float32,
        scale=base.scale,
        accum_dtype=accum_dtype,
        solver="blocked",
        solver_opts=opts,
    )
    model.fit(X, Y, checkpoint_path=checkpoint_path,
              checkpoint_every=checkpoint_every, resume=resume)
    model.save(out_path)
    return model


def swap_via_http(server_url: str, name: str, path: str,
                  timeout_s: float = 60.0) -> dict:
    """POST /admin/swap on a running `tpusvm serve` frontend.

    Returns the server's JSON verdict; raises RuntimeError with the
    server's error body on a refused swap (404/409) so callers see the
    rollback reason, not a bare HTTPError."""
    import urllib.error

    body = json.dumps({"name": name, "path": path}).encode()
    req = urllib.request.Request(
        server_url.rstrip("/") + "/admin/swap", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read()).get("error", "")
        except ValueError:
            detail = ""
        raise RuntimeError(
            f"swap of {name!r} refused by {server_url} "
            f"(HTTP {e.code}): {detail or e.reason}"
        ) from e
