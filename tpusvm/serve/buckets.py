"""Bucketed compile cache: pad batches to power-of-two row buckets.

A jit-compiled scorer keyed on exact batch shape would recompile for every
distinct coalesced batch size the micro-batcher happens to form — up to
max_batch executables per model, each compile a multi-ms stall in the
serving hot path. Padding every batch up to its power-of-two bucket caps
the shape universe at len(buckets) ~ log2(max_batch)+1 shapes per model,
all compiled AHEAD OF TIME by warmup(); steady state then never compiles.

Padding is safe because per-row scores are independent of the surrounding
batch (each padded row contributes only garbage rows that get sliced off),
and every bucket executable runs the SAME internal block geometry as the
offline scorer — the contraction shape, not just the row set, is pinned,
because XLA's CPU dot kernels drift ~1 ulp across shapes at degenerate
sizes (see the block comments below). Executables are built with
.lower().compile() rather than
relying on jax's internal jit cache, so COMPILES ARE OBSERVABLE: the cache
counts them, and compiles after warm-up surface as the `recompiles` metric
(steady-state target: 0).
"""

from __future__ import annotations

import threading
from typing import Dict, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from tpusvm.models.ovr import _ovr_scores
from tpusvm.serve.registry import ModelEntry
from tpusvm.solver.predict import decision_function


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Power-of-two row buckets 1, 2, 4, ... covering max_batch."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


def bucket_for(m: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits m rows."""
    for b in buckets:
        if m <= b:
            return b
    raise ValueError(f"batch of {m} rows exceeds the largest bucket "
                     f"{max(buckets)}")


# Bucket floors, the price of the bit-identity contract on the CPU
# backend: XLA dispatches DIFFERENT dot kernels at degenerate row counts,
# with ~1-ulp contraction-order drift against the vectorized kernel every
# other geometry shares. Measured (tests/test_serve.py, test_predict.py):
#   - binary (matvec K(m,n) @ coef): only the m == 1 program drifts —
#     floor 2, so a lone request pads to a 2-row program;
#   - ovr (gemm K(m,n) @ coef.T): programs below 4 rows drift — floor 4;
#     every power-of-two bucket >= 4 is mutually identical and matches
#     direct multiple-of-4-row calls bitwise.
# The padding cost is one or three zero rows on an idle server — noise.
# svr shares the binary scorer program (same matvec shape, the score IS
# the regressed value), so it inherits the binary floor.
_MIN_BUCKET = {"binary": 2, "ovr": 4, "svr": 2}


class CompileCache:
    """(bucket -> AOT-compiled scorer) for one model, with compile counts."""

    def __init__(self, entry: ModelEntry, buckets: Sequence[int],
                 block: int = 2048, registry=None):
        self.entry = entry
        floor = _MIN_BUCKET[entry.kind]
        self.buckets = tuple(sorted({max(int(b), floor) for b in buckets}))
        # the binary scorer's scan block; bucket rows pad up to one block
        # internally, which does not change per-row scores (bit-identity)
        self.block = block
        self._compiled: Dict[int, object] = {}
        self._lock = threading.Lock()
        self.compiles = 0          # total executable builds
        self.recompiles = 0        # builds AFTER warm-up completed
        self.warmed = False
        # compile-observatory target: per-bucket lower/compile wall time
        # and cost analysis land here (the hosting worker passes its
        # Metrics registry so the accounting shows up on /metrics)
        self.registry = registry

    # ------------------------------------------------------------ compile
    def _build(self, bucket: int):
        import time

        from tpusvm.obs import prof

        e = self.entry
        cfg = e.config
        Xz = jnp.zeros((bucket, e.n_features), e.dtype)
        t0 = time.perf_counter()
        lowered = self._lower(bucket, Xz)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        prof.record_compile(
            f"serve.bucket[{e.name}:b{bucket}]", t1 - t0, t2 - t1, compiled,
            registry=self.registry, model=e.name, bucket=bucket,
            kind=e.kind,
        )
        return compiled

    def _lower(self, bucket: int, Xz):
        e = self.entry
        cfg = e.config
        if e.fmap is not None:
            # approximate families: the bucket executable is the FUSED
            # map+decision program (tpusvm.approx) over RAW padded rows,
            # with the pinned map parameter arrays as operands — the
            # same jitted entry the offline decision_function calls, so
            # served scores are bit-identical by construction
            from tpusvm.approx import (
                approx_decision_function,
                approx_ovr_scores,
            )

            if e.kind in ("binary", "svr"):
                # block deliberately NOT capped at the bucket (unlike the
                # exact path below): the fused program pads raw rows to a
                # block multiple BEFORE the map, so offline (block=2048
                # default) and every bucket then run IDENTICAL matmul
                # shapes — the bit-identity contract. Capping would run
                # e.g. a 4-row gemm whose CPU dot kernel drifts ~1 ulp
                # against the 2048-row program (measured at m=3/bucket=4;
                # the same degenerate-shape physics as _MIN_BUCKET). The
                # exact path's throughput rationale for the cap weighs
                # differently here: the map+decision flops are MXU-dense
                # and the padded rows vectorise, while a score that
                # differs from the offline artifact is a correctness bug.
                return approx_decision_function.lower(
                    Xz, e.map_params, e.X_sv, e.coef, e.b,
                    family=cfg.kernel, block=self.block)
            return approx_ovr_scores.lower(
                Xz, e.map_params, e.X_sv, e.coef, e.b,
                family=cfg.kernel)
        if e.kind in ("binary", "svr"):
            # block deliberately NOT capped at the bucket (this path used
            # block=min(block, bucket) until the tenants tier's chaos
            # harness falsified the "any block is bit-identical" claim it
            # rested on): decision_function pads m up to a block multiple
            # INSIDE the jit, so with block=2048 every bucket runs the
            # identical (2048, n_sv) matvec the offline scorer runs —
            # bit-identity by construction, for every n_sv. Capping
            # instead runs a (bucket, n_sv) matvec whose CPU dot kernel
            # drifts ~1 ulp against the 2048-row program at degenerate SV
            # counts (measured at n_sv=49/m=8; n_sv=47,48 agree — the
            # same shape-dependent contraction physics as _MIN_BUCKET and
            # the fused-map branch above). The cap bought throughput on
            # sparse traffic (a 1-row request now computes a full block
            # of kernel rows), but a served score that differs from the
            # offline artifact breaks the torn-generation oracle every
            # rollout gate is built on — correctness wins, as it already
            # did for the approximate families above. The kernel family/
            # params come from the model's config — one executable per
            # (model, bucket) regardless of family
            lowered = decision_function.lower(
                Xz, e.X_sv, e.coef, e.b, gamma=cfg.gamma,
                block=self.block, kernel=cfg.kernel,
                degree=cfg.degree, coef0=cfg.coef0)
        else:
            gamma = jnp.asarray(cfg.gamma, e.dtype)
            coef0 = jnp.asarray(cfg.coef0, e.dtype)
            lowered = _ovr_scores.lower(Xz, e.X_sv, e.coef, e.b, gamma,
                                        coef0, kernel=cfg.kernel,
                                        degree=cfg.degree)
        return lowered

    def _get(self, bucket: int):
        with self._lock:
            fn = self._compiled.get(bucket)
            if fn is None:
                fn = self._build(bucket)
                self._compiled[bucket] = fn
                self.compiles += 1
                if self.warmed:
                    self.recompiles += 1
            return fn

    def warmup(self) -> int:
        """Compile every bucket; returns how many were newly built.

        Idempotent: a second call builds nothing and keeps `warmed` set, so
        the recompile counter keeps meaning "compiles the warm-up missed".
        """
        before = self.compiles
        for b in self.buckets:
            self._get(b)
        self.warmed = True
        return self.compiles - before

    @property
    def compiled_shapes(self) -> int:
        with self._lock:
            return len(self._compiled)

    # -------------------------------------------------------------- score
    def scores(self, X: np.ndarray) -> Tuple[np.ndarray, int]:
        """Scores for the m rows of X via the padded bucket executable.

        X must already be scaled and of the entry's dtype/width. Returns
        (scores for the real rows, bucket used). Binary: (m,); ovr: (m, K).
        """
        m = X.shape[0]
        bucket = bucket_for(m, self.buckets)
        e = self.entry
        # the pad buffer is built in the model dtype: the assignment casts
        # the f64-scaled rows exactly like the offline path's device upload
        Xp = np.zeros((bucket, X.shape[1]), np.dtype(jnp.dtype(e.dtype)))
        Xp[:m] = X
        fn = self._get(bucket)
        if e.fmap is not None:
            # fused map+decision executable: raw padded rows + the
            # pinned map operands (padding rows map to garbage scores
            # that are sliced off — row independence holds through the
            # map's matmuls exactly as through the kernel's)
            out = fn(jnp.asarray(Xp), e.map_params, e.X_sv, e.coef, e.b)
        elif e.kind in ("binary", "svr"):
            out = fn(jnp.asarray(Xp), e.X_sv, e.coef, e.b)
        else:
            gamma = jnp.asarray(e.config.gamma, e.dtype)
            coef0 = jnp.asarray(e.config.coef0, e.dtype)
            out = fn(jnp.asarray(Xp), e.X_sv, e.coef, e.b, gamma, coef0)
        return np.asarray(out)[:m], bucket
