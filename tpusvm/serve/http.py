"""Stdlib-only JSON-over-HTTP frontend for the serving subsystem.

http.server.ThreadingHTTPServer gives one handler thread per connection;
handler threads block in Server.submit_many(), so concurrent HTTP clients'
rows coalesce in the micro-batcher exactly like in-process callers — the
HTTP layer adds no batching logic of its own. No third-party dependencies
(the container bans installs; stdlib is the point).

Routes:
  GET  /healthz                   {"status": "ok"|"degraded"|"draining",
                                   "models": {name: breaker state}};
                                  HTTP 200 while serving (degraded
                                  included — other models still work),
                                  503 once draining
  POST /admin/drain               stop admitting requests, wait for
                                  in-flight work ({"drained": bool});
                                  the zero-downtime-restart hook
  POST /admin/swap                {"name": ..., "path": model.npz}
                                  atomic hot-swap: stage the artifact
                                  fully off to the side (load, compile,
                                  probe-verify), then flip the serving
                                  generation — in-flight batches finish
                                  on the old model. 200 {"swapped":
                                  true, "generation": g, "latency_s"};
                                  a failed stage rolls back (the old
                                  generation keeps serving) and returns
                                  409 {"swapped": false, "error": ...};
                                  unknown model name -> 404. The
                                  `tpusvm refresh` handoff endpoint.
  GET  /v1/models                 hosted-model summaries (Server.status())
  GET  /v1/models/<name>/metrics  one model's metrics JSON
  GET  /metrics                   plaintext metrics for every model
  GET  /metrics.json              this replica's fleet snapshot payload
                                  (obs.fleet: role/instance-attributed,
                                  mergeable registry snapshot — what
                                  `tpusvm fleet-metrics` scrapes)
  POST /v1/models/<name>:predict  {"instances": [[...], ...]}
                                  -> {"predictions": [...], "scores": [...],
                                      "statuses": [...]}
                                  Calibrated binary models add
                                  "proba": [P(y=+1), ...] (Platt-scaled
                                  host-side from the served scores — the
                                  exact predict_proba arithmetic); SVR
                                  models serve the regressed value as the
                                  prediction.

Degraded-mode response codes (per-request detail always in `statuses`):
  200  every row scored
  429  load shed (OVERLOADED) or backpressure (QUEUE_FULL) — retryable
       after backoff; Retry-After: 1 is set
  503  the model's breaker is open (UNAVAILABLE), the server is
       draining (DRAINING), or a scoring error/timeout
"""

from __future__ import annotations

import contextlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from tpusvm.status import ServeStatus


class _Handler(BaseHTTPRequestHandler):
    # the Server instance is attached to the HTTP server object
    protocol_version = "HTTP/1.1"

    @property
    def _srv(self):
        return self.server.tpusvm_server

    def log_message(self, fmt, *args):  # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, obj, code: int = 200,
                   retry_after: bool = False) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after:
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/healthz":
            health = self._srv.health()
            self._send_json(
                health,
                code=503 if health["status"] == "draining" else 200,
            )
        elif self.path == "/metrics":
            self._send(200, self._srv.metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif self.path == "/metrics.json":
            # the fleet collector's scrape target: one mergeable
            # (role, instance)-attributed registry snapshot payload
            self._send_json(self._srv.fleet_snapshot())
        elif self.path == "/v1/models":
            self._send_json(self._srv.status())
        elif self.path.startswith("/v1/models/") and self.path.endswith("/metrics"):
            name = self.path[len("/v1/models/"):-len("/metrics")]
            try:
                self._send_json(self._srv.metrics(name))
            except KeyError as e:
                self._send_json({"error": str(e)}, code=404)
        else:
            self._send_json({"error": f"no route {self.path}"}, code=404)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/admin/drain":
            ok = self._srv.drain()
            self._send_json({"drained": ok})
            return
        if self.path == "/admin/swap":
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                name = payload["name"]
                path = payload["path"]
            except (ValueError, KeyError, TypeError) as e:
                self._send_json(
                    {"error": f"bad request body (need name+path): {e}"},
                    code=400)
                return
            try:
                out = self._srv.swap(name, path)
            except KeyError as e:
                self._send_json({"swapped": False, "error": str(e)},
                                code=404)
                return
            except Exception as e:  # noqa: BLE001 — the stage rolled
                # back; the old generation is still serving, so this is
                # a conflict report, not a handler crash
                self._send_json(
                    {"swapped": False,
                     "error": f"{type(e).__name__}: {e}"},
                    code=409)
                return
            self._send_json({"swapped": True, **out})
            return
        if not (self.path.startswith("/v1/models/")
                and self.path.endswith(":predict")):
            self._send_json({"error": f"no route {self.path}"}, code=404)
            return
        name = self.path[len("/v1/models/"):-len(":predict")]
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            instances = payload["instances"]
            X = np.asarray(instances, dtype=np.float64)
        except (ValueError, KeyError, TypeError) as e:
            self._send_json({"error": f"bad request body: {e}"}, code=400)
            return
        # honor a propagated trace context: with a tracer attached
        # (serve --trace) the scoring lands as a serve.request span whose
        # attrs carry the caller's ctx, so the merged report re-parents
        # it under the router's forward span; without one the header is
        # accepted and ignored
        tracer = getattr(self.server, "tpusvm_tracer", None)
        span = contextlib.nullcontext()
        if tracer is not None:
            from tpusvm.obs.trace import TRACE_HEADER, TraceContext

            attrs = {"model": name, "rows": int(X.shape[0])}
            ctx = TraceContext.from_header(self.headers.get(TRACE_HEADER))
            if ctx is not None:
                attrs["ctx"] = ctx.to_dict()
            span = tracer.span("serve.request", **attrs)
        try:
            with span:
                results = self._srv.submit_many(
                    name, X, timeout_s=payload.get("timeout_s"))
        except KeyError as e:
            self._send_json({"error": str(e)}, code=404)
            return
        except ValueError as e:
            self._send_json({"error": str(e)}, code=400)
            return
        statuses = [ServeStatus(r.status).name for r in results]
        ok = all(r.ok for r in results)
        st_set = {ServeStatus(r.status) for r in results}
        if ok:
            code = 200
        elif st_set & {ServeStatus.UNAVAILABLE, ServeStatus.DRAINING,
                       ServeStatus.ERROR, ServeStatus.TIMEOUT,
                       ServeStatus.SHUTDOWN}:
            code = 503  # not retryable-by-backoff alone
        else:
            code = 429  # OVERLOADED / QUEUE_FULL: back off and retry
        body = {
            "predictions": [
                None if r.label is None else np.asarray(r.label).item()
                for r in results
            ],
            "scores": [
                None if r.scores is None else np.asarray(r.scores).tolist()
                for r in results
            ],
            "statuses": statuses,
        }
        entry = self._srv.registry.get(name)
        if entry.platt is not None and entry.kind == "binary":
            # calibrated model: Platt-scale the served scores host-side —
            # the exact predict_proba arithmetic (kernels.platt), so the
            # field is bit-identical to the offline estimator's P(y=+1)
            from tpusvm.kernels.platt import platt_proba

            body["proba"] = [
                None if r.scores is None
                else float(platt_proba(np.asarray(r.scores), *entry.platt))
                for r in results
            ]
        self._send_json(body, code=code, retry_after=code in (429, 503))


def make_http_server(server, host: str = "127.0.0.1", port: int = 8471,
                     verbose: bool = False) -> ThreadingHTTPServer:
    """Bind (not yet serving) a ThreadingHTTPServer over a serve.Server.

    port=0 binds an ephemeral port (tests); read httpd.server_address.
    Call .serve_forever() (blocking) or start_http_thread() below.
    """
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.tpusvm_server = server
    # set by the CLI when serve runs with --trace: per-request
    # serve.request spans (honoring propagated X-Tpusvm-Trace contexts)
    httpd.tpusvm_tracer = None
    httpd.verbose = verbose
    # handler threads must not block interpreter exit
    httpd.daemon_threads = True
    return httpd


def start_http_thread(httpd: ThreadingHTTPServer) -> threading.Thread:
    """Run an HTTP server on a daemon thread (in-process tests / CLI).

    Pair with stop_http_server (directly, or via Server.attach_http +
    Server.close) — daemon=True alone keeps interpreter exit unblocked
    but LEAKS the listening socket for the life of the process, which is
    exactly how back-to-back CI smokes hit EADDRINUSE."""
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="tpusvm-serve-http")
    t.start()
    return t


def stop_http_server(httpd: ThreadingHTTPServer,
                     thread: Optional[threading.Thread] = None,
                     timeout_s: float = 5.0) -> None:
    """Shut down the serve loop, CLOSE the listening socket, and join
    the serving thread. Idempotent; safe after a manual shutdown().

    shutdown() only stops serve_forever — without server_close() the
    bound port stays held, and without the join a still-draining handler
    can race interpreter teardown."""
    httpd.shutdown()
    httpd.server_close()
    if thread is not None:
        thread.join(timeout=timeout_s)
