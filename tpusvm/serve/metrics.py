"""Serving metrics: counters, batch-occupancy histogram, latency percentiles.

Since the unified-telemetry round this is a thin adapter over the shared
tpusvm.obs.registry primitives — serving, training, tuning and streaming
now emit into one metric vocabulary, and a server's registry snapshot
merges exactly with any other worker's (obs.registry.merge_snapshots).
The OUTPUT contracts are unchanged from the private implementation this
replaces: `snapshot()` returns the same dict (the serve smoke and HTTP
/metrics consumers parse it) and `render_text()` the same
`name{labels} value` lines — parity is asserted by
tests/test_serve.py::test_metrics_snapshot_and_text.

Everything is host-side Python (no JAX); one registry lock keeps a
scrape consistent (a request is never observed counted with its latency
missing). Latency percentiles come from a bounded reservoir of the most
recent completions (default 4096) rather than a streaming sketch: exact
over the window, O(window log window) only at scrape time, and the
window bounds memory regardless of uptime. (The reservoir is the one
piece that stays outside the registry: exact windowed percentiles are
not a mergeable aggregate, and the serving SLO checks want exactness.)
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Optional, Sequence

from tpusvm.obs.registry import MetricsRegistry

_COUNTERS = (
    "requests",      # rows accepted into the queue
    "ok",            # rows answered with a score
    "errors",        # rows failed by an exception in the scoring path
    "timeouts",      # rows that missed their deadline (client- or queue-side)
    "queue_full",    # rows fast-failed by backpressure (never enqueued)
    "batches",       # flushes executed by the micro-batcher
    "recompiles",    # bucket compiles AFTER warm-up (steady state target: 0)
    # degraded-mode serving (tpusvm.faults round):
    "overloaded",    # rows shed by the load-shedding threshold
    "unavailable",   # rows refused because the circuit breaker is open
    "draining",      # rows refused because the server is draining
    "retries",       # scoring attempts re-run by the retry policy
    "breaker_trips",       # closed -> open transitions
    "breaker_recoveries",  # half-open probe succeeded, breaker closed
)


class Metrics:
    """Thread-safe serving counters for one model (registry-backed)."""

    def __init__(self, buckets: Sequence[int], latency_window: int = 4096):
        self.registry = MetricsRegistry()
        self._counts = {k: self.registry.counter(f"serve.{k}")
                        for k in _COUNTERS}
        # per-bucket occupancy: how many batches flushed at this bucket
        # size, and how many real (non-padding) rows they carried
        self._buckets = sorted(int(b) for b in buckets)
        self._bucket_batches = {
            b: self.registry.counter("serve.bucket_batches", bucket=str(b))
            for b in self._buckets
        }
        self._bucket_rows = {
            b: self.registry.counter("serve.bucket_rows", bucket=str(b))
            for b in self._buckets
        }
        self._lock = threading.Lock()
        self._lat = collections.deque(maxlen=latency_window)

    def inc(self, name: str, n: int = 1) -> None:
        self._counts[name].inc(n)

    def observe_batch(self, bucket: int, rows: int) -> None:
        bucket = int(bucket)
        if bucket not in self._bucket_batches:
            # late-registered bucket (direct-path chunking can exceed the
            # configured set); get-or-create keeps the accounting complete
            self._bucket_batches[bucket] = self.registry.counter(
                "serve.bucket_batches", bucket=str(bucket))
            self._bucket_rows[bucket] = self.registry.counter(
                "serve.bucket_rows", bucket=str(bucket))
        self._counts["batches"].inc()
        self._bucket_batches[bucket].inc()
        self._bucket_rows[bucket].inc(rows)

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat.append(float(seconds))

    # ------------------------------------------------------------- export
    @staticmethod
    def _percentile(sorted_lat, frac: float) -> Optional[float]:
        if not sorted_lat:
            return None
        idx = min(len(sorted_lat) - 1, int(frac * len(sorted_lat)))
        return sorted_lat[idx]

    def snapshot(self) -> dict:
        """One consistent JSON-able view of every counter and derived stat
        (schema unchanged across the registry migration)."""
        counts = {k: c.value for k, c in self._counts.items()}
        batches: Dict[int, int] = {b: c.value
                                   for b, c in self._bucket_batches.items()}
        rows: Dict[int, int] = {b: c.value
                                for b, c in self._bucket_rows.items()}
        with self._lock:
            lat = sorted(self._lat)
        total_rows = sum(rows.values())
        total_batches = sum(batches.values())
        occupancy = {
            str(b): {
                "batches": batches[b],
                "rows": rows[b],
                # mean real rows per flushed batch of this bucket size
                "mean_rows": (rows[b] / batches[b]) if batches[b] else 0.0,
            }
            for b in sorted(batches)
        }
        return {
            **counts,
            "batch_occupancy": occupancy,
            "mean_batch_rows": (total_rows / total_batches) if total_batches else 0.0,
            "latency_s": {
                "count": len(lat),
                "p50": self._percentile(lat, 0.50),
                "p95": self._percentile(lat, 0.95),
                "p99": self._percentile(lat, 0.99),
                "max": lat[-1] if lat else None,
            },
        }

    def registry_snapshot(self) -> dict:
        """The mergeable obs.registry view of the same counters (for
        cross-worker aggregation / trace embedding)."""
        return self.registry.snapshot()

    def render_text(self, prefix: str = "tpusvm_serve", labels: str = "") -> str:
        """Plaintext /metrics-style dump (one `name{labels} value` per line)."""
        snap = self.snapshot()
        lab = f"{{{labels}}}" if labels else ""
        lines = [f"{prefix}_{k}_total{lab} {snap[k]}" for k in _COUNTERS]
        lines.append(
            f"{prefix}_mean_batch_rows{lab} {snap['mean_batch_rows']:.4f}"
        )
        for b, occ in snap["batch_occupancy"].items():
            sep = "," if labels else ""
            blab = f"{{{labels}{sep}bucket=\"{b}\"}}"
            lines.append(f"{prefix}_batches{blab} {occ['batches']}")
            lines.append(f"{prefix}_batch_rows{blab} {occ['rows']}")
        for p in ("p50", "p95", "p99"):
            v = snap["latency_s"][p]
            if v is not None:
                sep = "," if labels else ""
                qlab = f"{{{labels}{sep}quantile=\"{p[1:]}\"}}"
                lines.append(f"{prefix}_latency_seconds{qlab} {v:.6f}")
        return "\n".join(lines) + "\n"
