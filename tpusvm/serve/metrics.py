"""Serving metrics: counters, batch-occupancy histogram, latency percentiles.

Since the unified-telemetry round this is a thin adapter over the shared
tpusvm.obs.registry primitives — serving, training, tuning and streaming
now emit into one metric vocabulary, and a server's registry snapshot
merges exactly with any other worker's (obs.registry.merge_snapshots).
The OUTPUT contracts are unchanged from the private implementation this
replaces: `snapshot()` returns the same dict (the serve smoke and HTTP
/metrics consumers parse it) and `render_text()` the same
`name{labels} value` lines — parity is asserted by
tests/test_serve.py::test_metrics_snapshot_and_text.

Everything is host-side Python (no JAX); one registry lock keeps a
scrape consistent (a request is never observed counted with its latency
missing). Latency percentiles come from a bounded reservoir of the most
recent completions (default 4096) rather than a streaming sketch: exact
over the window, O(window log window) only at scrape time, and the
window bounds memory regardless of uptime. (The reservoir is the one
piece that stays outside the registry: exact windowed percentiles are
not a mergeable aggregate, and the serving SLO checks want exactness.)
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, Optional, Sequence

from tpusvm.obs.registry import MetricsRegistry

_COUNTERS = (
    "requests",      # rows accepted into the queue
    "ok",            # rows answered with a score
    "errors",        # rows failed by an exception in the scoring path
    "timeouts",      # rows that missed their deadline (client- or queue-side)
    "queue_full",    # rows fast-failed by backpressure (never enqueued)
    "batches",       # flushes executed by the micro-batcher
    "recompiles",    # bucket compiles AFTER warm-up (steady state target: 0)
    # degraded-mode serving (tpusvm.faults round):
    "overloaded",    # rows shed by the load-shedding threshold
    "unavailable",   # rows refused because the circuit breaker is open
    "draining",      # rows refused because the server is draining
    "retries",       # scoring attempts re-run by the retry policy
    "breaker_trips",       # closed -> open transitions
    "breaker_recoveries",  # half-open probe succeeded, breaker closed
    # resilient-serving round (hot-swap):
    "swaps",           # successful generation flips
    "swap_failures",   # staged swaps that failed + rolled back
)


# failures that BURN the SLO error budget: outcomes where the server
# accepted work and failed to serve it. Admission-control rejections
# (overloaded / queue_full / draining) deliberately do not burn — they
# are the mechanism protecting the budget, and counting them would make
# shedding indistinguishable from the overload it prevents.
_SLO_ERROR_COUNTERS = ("errors", "timeouts", "unavailable")


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Per-model serving SLO budgets (performance-observatory round).

    p99_ms:       latency target — at most 1% of windowed requests may
                  complete slower than this (the definition of p99);
    error_budget: allowed fraction of windowed completions that fail;
    window_s:     sliding evaluation window.

    BURN RATE is (observed violation rate) / (allowed rate): 1.0 means
    the budget is being consumed exactly as fast as allowed; above 1.0
    the SLO is burning and /healthz reports "degraded". The gauges are
    exported on /metrics (serve.slo_latency_burn / serve.slo_error_burn)
    and feed the admission-control path (ServeConfig.slo_shed)."""

    p99_ms: float
    error_budget: float = 0.001
    window_s: float = 60.0
    # the p99 definition: 1% of requests may exceed the target
    latency_budget: float = 0.01

    def validate(self) -> "SLOConfig":
        if self.p99_ms <= 0:
            raise ValueError(f"slo p99_ms must be > 0, got {self.p99_ms}")
        if not (0.0 < self.error_budget < 1.0):
            raise ValueError(
                f"slo error_budget must be in (0, 1), got "
                f"{self.error_budget}"
            )
        if self.window_s <= 0:
            raise ValueError(
                f"slo window_s must be > 0, got {self.window_s}"
            )
        return self


class Metrics:
    """Thread-safe serving counters for one model (registry-backed)."""

    def __init__(self, buckets: Sequence[int], latency_window: int = 4096,
                 slo: Optional[SLOConfig] = None, clock=None):
        self.registry = MetricsRegistry()
        self.slo = slo.validate() if slo is not None else None
        self._clock = clock or time.monotonic
        # sliding SLO windows: (t, latency_s) completions and
        # (t, ok_n, err_n) outcome batches, pruned at observation and
        # scrape time — memory is bounded by window traffic
        self._slo_lat: collections.deque = collections.deque()
        self._slo_out: collections.deque = collections.deque()
        self._counts = {k: self.registry.counter(f"serve.{k}")
                        for k in _COUNTERS}
        # per-bucket occupancy: how many batches flushed at this bucket
        # size, and how many real (non-padding) rows they carried
        self._buckets = sorted(int(b) for b in buckets)
        self._bucket_batches = {
            b: self.registry.counter("serve.bucket_batches", bucket=str(b))
            for b in self._buckets
        }
        self._bucket_rows = {
            b: self.registry.counter("serve.bucket_rows", bucket=str(b))
            for b in self._buckets
        }
        self._lock = threading.Lock()
        self._lat = collections.deque(maxlen=latency_window)

    def inc(self, name: str, n: int = 1) -> None:
        self._counts[name].inc(n)
        if self.slo is not None:
            if name == "ok":
                with self._lock:
                    self._slo_out.append((self._clock(), n, 0))
            elif name in _SLO_ERROR_COUNTERS:
                with self._lock:
                    self._slo_out.append((self._clock(), 0, n))

    def observe_batch(self, bucket: int, rows: int) -> None:
        bucket = int(bucket)
        if bucket not in self._bucket_batches:
            # late-registered bucket (direct-path chunking can exceed the
            # configured set); get-or-create keeps the accounting complete
            self._bucket_batches[bucket] = self.registry.counter(
                "serve.bucket_batches", bucket=str(bucket))
            self._bucket_rows[bucket] = self.registry.counter(
                "serve.bucket_rows", bucket=str(bucket))
        self._counts["batches"].inc()
        self._bucket_batches[bucket].inc()
        self._bucket_rows[bucket].inc(rows)

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat.append(float(seconds))
            if self.slo is not None:
                self._slo_lat.append((self._clock(), float(seconds)))

    # ---------------------------------------------------------------- SLO
    def _prune_slo(self, now: float) -> None:
        """Drop window entries older than window_s (caller holds _lock)."""
        cutoff = now - self.slo.window_s
        while self._slo_lat and self._slo_lat[0][0] < cutoff:
            self._slo_lat.popleft()
        while self._slo_out and self._slo_out[0][0] < cutoff:
            self._slo_out.popleft()

    def slo_status(self) -> Optional[dict]:
        """Burn rates over the current window (None when no SLO is set).

        Computed at scrape time from the windowed completions; also
        refreshes the serve.slo_* registry gauges so /metrics and merged
        registry snapshots carry the same numbers."""
        if self.slo is None:
            return None
        s = self.slo
        with self._lock:
            self._prune_slo(self._clock())
            lats = [v for _, v in self._slo_lat]
            ok = sum(o for _, o, _ in self._slo_out)
            err = sum(e for _, _, e in self._slo_out)
        target_s = s.p99_ms / 1e3
        slow = sum(1 for v in lats if v > target_s)
        slow_frac = (slow / len(lats)) if lats else 0.0
        latency_burn = slow_frac / s.latency_budget
        total = ok + err
        err_rate = (err / total) if total else 0.0
        error_burn = err_rate / s.error_budget
        burning = latency_burn >= 1.0 or error_burn >= 1.0
        self.registry.gauge("serve.slo_latency_burn").set(latency_burn)
        self.registry.gauge("serve.slo_error_burn").set(error_burn)
        self.registry.gauge("serve.slo_burning").set(1.0 if burning else 0.0)
        self.registry.gauge("serve.slo_window_requests").set(float(total))
        return {
            "p99_target_ms": s.p99_ms,
            "error_budget": s.error_budget,
            "window_s": s.window_s,
            "window_requests": total,
            "window_latencies": len(lats),
            "slow_frac": slow_frac,
            "error_rate": err_rate,
            "latency_burn": latency_burn,
            "error_burn": error_burn,
            "burning": burning,
        }

    # ------------------------------------------------------------- export
    @staticmethod
    def _percentile(sorted_lat, frac: float) -> Optional[float]:
        if not sorted_lat:
            return None
        idx = min(len(sorted_lat) - 1, int(frac * len(sorted_lat)))
        return sorted_lat[idx]

    def snapshot(self) -> dict:
        """One consistent JSON-able view of every counter and derived stat
        (schema unchanged across the registry migration).

        All counter values come from ONE registry.snapshot() call — a
        single acquisition of the shared registry lock — instead of a
        per-metric .value loop: N reacquisitions would cost N lock
        round-trips under scrape load AND let a concurrent batch be
        half-visible between two reads (ok incremented, its bucket row
        counts not yet), which breaks the occupancy arithmetic below."""
        reg = self.registry.snapshot()
        counts = {k: 0 for k in _COUNTERS}
        batches: Dict[int, int] = {b: 0 for b in self._bucket_batches}
        rows: Dict[int, int] = {b: 0 for b in self._bucket_rows}
        for e in reg["metrics"]:
            if e["type"] != "counter":
                continue
            if e["name"] == "serve.bucket_batches":
                batches[int(e["labels"]["bucket"])] = e["value"]
            elif e["name"] == "serve.bucket_rows":
                rows[int(e["labels"]["bucket"])] = e["value"]
            elif e["name"].startswith("serve."):
                key = e["name"][len("serve."):]
                if key in counts:
                    counts[key] = e["value"]
        with self._lock:
            lat = sorted(self._lat)
        total_rows = sum(rows.values())
        total_batches = sum(batches.values())
        occupancy = {
            str(b): {
                "batches": batches[b],
                "rows": rows[b],
                # mean real rows per flushed batch of this bucket size
                "mean_rows": (rows[b] / batches[b]) if batches[b] else 0.0,
            }
            for b in sorted(batches)
        }
        snap = {
            **counts,
            "batch_occupancy": occupancy,
            "mean_batch_rows": (total_rows / total_batches) if total_batches else 0.0,
            "latency_s": {
                "count": len(lat),
                "p50": self._percentile(lat, 0.50),
                "p95": self._percentile(lat, 0.95),
                "p99": self._percentile(lat, 0.99),
                "max": lat[-1] if lat else None,
            },
        }
        slo = self.slo_status()
        if slo is not None:
            snap["slo"] = slo
        return snap

    def registry_snapshot(self) -> dict:
        """The mergeable obs.registry view of the same counters (for
        cross-worker aggregation / trace embedding)."""
        return self.registry.snapshot()

    def render_text(self, prefix: str = "tpusvm_serve", labels: str = "") -> str:
        """Plaintext /metrics-style dump (one `name{labels} value` per line)."""
        snap = self.snapshot()
        lab = f"{{{labels}}}" if labels else ""
        lines = [f"{prefix}_{k}_total{lab} {snap[k]}" for k in _COUNTERS]
        lines.append(
            f"{prefix}_mean_batch_rows{lab} {snap['mean_batch_rows']:.4f}"
        )
        for b, occ in snap["batch_occupancy"].items():
            sep = "," if labels else ""
            blab = f"{{{labels}{sep}bucket=\"{b}\"}}"
            lines.append(f"{prefix}_batches{blab} {occ['batches']}")
            lines.append(f"{prefix}_batch_rows{blab} {occ['rows']}")
        for p in ("p50", "p95", "p99"):
            v = snap["latency_s"][p]
            if v is not None:
                sep = "," if labels else ""
                qlab = f"{{{labels}{sep}quantile=\"{p[1:]}\"}}"
                lines.append(f"{prefix}_latency_seconds{qlab} {v:.6f}")
        slo = snap.get("slo")
        if slo is not None:
            for k in ("latency_burn", "error_burn"):
                lines.append(f"{prefix}_slo_{k}{lab} {slo[k]:.6f}")
            lines.append(
                f"{prefix}_slo_burning{lab} {1 if slo['burning'] else 0}"
            )
        return "\n".join(lines) + "\n"
