"""Deadline-aware micro-batching of single-row predict requests.

The throughput lever: one padded-bucket kernel launch scores a whole batch
for roughly the cost of scoring one row (the (bucket, n_sv) matmul is tiny
against per-dispatch overhead at serving sizes), so coalescing k concurrent
single-row requests into one batch is ~k-fold throughput — IF no request
waits unboundedly for the batch to fill. Hence the deadline rule: a batch
flushes when it reaches max_batch rows OR when its OLDEST member has waited
max_delay; an idle server ships a lone request after at most max_delay.

Concurrency model: clients enqueue and block on a per-request event; ONE
worker thread per batcher drains the queue, runs the (JAX-calling) scoring
callback, and distributes results. All device work for a model therefore
happens on a single thread — no concurrent-dispatch hazards — while any
number of client threads submit.

Backpressure is a bounded queue with fast-fail: when the queue is full the
request is rejected immediately (QUEUE_FULL) instead of absorbing unbounded
latency — the Clipper/SLO-serving discipline. Per-request timeouts bound
the other tail: a client stops waiting after its deadline, and the worker
drops requests that are already dead on arrival rather than paying kernel
time for an answer nobody reads.

Degraded mode (tpusvm.faults round): an optional shed_at threshold answers
OVERLOADED before the hard bound is reached (deliberate load shedding a
dashboard can tell apart from a mis-sized queue); a BreakerOpenError from
the scoring callback fails the batch with UNAVAILABLE (the model's circuit
breaker is open — no kernel time spent); drain() stops admission
(DRAINING) and waits, via an in-queue barrier, for everything already
accepted to resolve — the zero-downtime-restart primitive.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from tpusvm.faults.breaker import BreakerOpenError
from tpusvm.status import ServeStatus

# run_batch: (m, d) scaled-or-raw rows -> (scores, labels) with leading dim m
RunBatch = Callable[[np.ndarray], Tuple[np.ndarray, np.ndarray]]


@dataclasses.dataclass
class ServeResult:
    """Outcome of one predict request."""

    status: ServeStatus
    scores: Optional[np.ndarray] = None   # binary: (); ovr: (K,)
    label: Optional[object] = None        # binary: +/-1; ovr: class id
    latency_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == ServeStatus.OK


class _Request:
    __slots__ = ("x", "enq_t", "deadline_t", "event", "result")

    def __init__(self, x: np.ndarray, enq_t: float,
                 deadline_t: Optional[float]):
        self.x = x
        self.enq_t = enq_t
        self.deadline_t = deadline_t
        self.event = threading.Event()
        self.result: Optional[ServeResult] = None


_SENTINEL = object()


class _DrainBarrier:
    """Queue marker for drain(): its event fires once every request that
    was enqueued before it has been scored (or failed)."""

    __slots__ = ("event",)

    def __init__(self):
        self.event = threading.Event()


class MicroBatcher:
    """Bounded request queue + one scoring worker for a single model.

    shed_at: load-shedding threshold (requests observed while the queue
    already holds >= shed_at entries come back OVERLOADED immediately —
    deliberate degraded-mode shedding, distinct from the hard QUEUE_FULL
    bound). None (default) disables shedding.
    """

    def __init__(self, run_batch: RunBatch, *, max_batch: int = 64,
                 max_delay_s: float = 0.002, queue_size: int = 1024,
                 timeout_s: float = 1.0, metrics=None,
                 shed_at: Optional[int] = None,
                 admission=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if shed_at is not None and shed_at < 1:
            raise ValueError(f"shed_at must be >= 1, got {shed_at}")
        self.run_batch = run_batch
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.timeout_s = timeout_s
        self.metrics = metrics
        self.shed_at = shed_at
        # optional admission predicate (e.g. the SLO burn gauge): False
        # sheds the request with OVERLOADED before it queues
        self.admission = admission
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._closed = False
        self._draining = False
        self._barriers: List[_DrainBarrier] = []  # worker-thread only
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="tpusvm-serve-batcher")
        self._worker.start()

    # ------------------------------------------------------------- client
    def _reject(self, t0: float) -> Optional[ServeResult]:
        """Admission control shared by submit paths: draining beats
        shedding beats the hard queue bound (checked at put time)."""
        if self._draining:
            if self.metrics:
                self.metrics.inc("draining")
            return ServeResult(ServeStatus.DRAINING,
                               latency_s=time.monotonic() - t0)
        if self.shed_at is not None and self._q.qsize() >= self.shed_at:
            if self.metrics:
                self.metrics.inc("overloaded")
            return ServeResult(ServeStatus.OVERLOADED,
                               latency_s=time.monotonic() - t0)
        if self.admission is not None and not self.admission():
            if self.metrics:
                self.metrics.inc("overloaded")
            return ServeResult(ServeStatus.OVERLOADED,
                               latency_s=time.monotonic() - t0)
        return None

    def submit(self, x: np.ndarray,
               timeout_s: Optional[float] = None) -> ServeResult:
        """Score one row; blocks until a result or the deadline."""
        if self._closed:
            return ServeResult(ServeStatus.SHUTDOWN)
        timeout = self.timeout_s if timeout_s is None else timeout_s
        t0 = time.monotonic()
        rejected = self._reject(t0)
        if rejected is not None:
            return rejected
        req = _Request(x, t0, t0 + timeout if timeout is not None else None)
        if self.metrics:
            self.metrics.inc("requests")
        try:
            self._q.put_nowait(req)
        except queue.Full:
            if self.metrics:
                self.metrics.inc("queue_full")
            return ServeResult(ServeStatus.QUEUE_FULL,
                               latency_s=time.monotonic() - t0)
        if not req.event.wait(timeout):
            if self.metrics:
                self.metrics.inc("timeouts")
            return ServeResult(ServeStatus.TIMEOUT,
                               latency_s=time.monotonic() - t0)
        res = req.result
        res.latency_s = time.monotonic() - t0
        if self.metrics:
            # the worker never counts timeouts (a dead-on-arrival drop and
            # the client's own expiry would double-count); the client
            # counts exactly one outcome per request
            if res.ok:
                self.metrics.observe_latency(res.latency_s)
            elif res.status == ServeStatus.TIMEOUT:
                self.metrics.inc("timeouts")
        return res

    def submit_many(self, rows: Sequence[np.ndarray],
                    timeout_s: Optional[float] = None) -> List[ServeResult]:
        """Enqueue every row, then wait for all — rows coalesce naturally."""
        if self._closed:
            return [ServeResult(ServeStatus.SHUTDOWN) for _ in rows]
        timeout = self.timeout_s if timeout_s is None else timeout_s
        t0 = time.monotonic()
        deadline = t0 + timeout if timeout is not None else None
        reqs: List[Optional[_Request]] = []
        results: List[Optional[ServeResult]] = []
        for x in rows:
            rejected = self._reject(t0)
            if rejected is not None:
                reqs.append(None)
                results.append(rejected)
                continue
            req = _Request(x, t0, deadline)
            if self.metrics:
                self.metrics.inc("requests")
            try:
                self._q.put_nowait(req)
                reqs.append(req)
                results.append(None)
            except queue.Full:
                if self.metrics:
                    self.metrics.inc("queue_full")
                reqs.append(None)
                results.append(ServeResult(ServeStatus.QUEUE_FULL))
        for i, req in enumerate(reqs):
            if req is None:
                continue
            remaining = None if deadline is None else deadline - time.monotonic()
            expired = remaining is not None and remaining <= 0
            if expired or not req.event.wait(remaining):
                if self.metrics:
                    self.metrics.inc("timeouts")
                results[i] = ServeResult(ServeStatus.TIMEOUT,
                                         latency_s=time.monotonic() - t0)
                continue
            res = req.result
            res.latency_s = time.monotonic() - t0
            if self.metrics:
                if res.ok:
                    self.metrics.observe_latency(res.latency_s)
                elif res.status == ServeStatus.TIMEOUT:
                    self.metrics.inc("timeouts")
            results[i] = res
        return results

    @property
    def depth(self) -> int:
        return self._q.qsize()

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Stop admitting requests (they come back DRAINING) and wait for
        everything already queued to complete. True if quiesced in time.
        Idempotent; safe to close() afterwards."""
        if self._closed:
            return True
        # readers tolerate staleness: a racing submit either drains or
        # lands before the in-queue barrier, which serializes the rest
        # tpusvm: guarded-by=one-way latch; bool store is GIL-atomic
        self._draining = True
        bar = _DrainBarrier()
        try:
            self._q.put(bar, timeout=timeout_s)
        except queue.Full:
            return False
        return bar.event.wait(timeout_s)

    def close(self, timeout_s: float = 5.0) -> None:
        if self._closed:
            return
        # requests that race past the stale read are resolved by the
        # post-join queue sweep below (the no-dropped-futures contract
        # conc-stress exercises)
        # tpusvm: guarded-by=one-way latch; bool store is GIL-atomic
        self._closed = True
        self._q.put(_SENTINEL)
        self._worker.join(timeout=timeout_s)
        # final sweep: requests that raced past the _closed check while
        # the worker was exiting must still resolve (no dropped futures)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if isinstance(req, _DrainBarrier):
                req.event.set()
            elif req is not _SENTINEL:
                req.result = ServeResult(ServeStatus.SHUTDOWN)
                req.event.set()

    # ------------------------------------------------------------- worker
    def _collect(self) -> Optional[List[_Request]]:
        """Block for the first request, then coalesce in two phases:

        1. GREEDY DRAIN — take everything already queued, up to max_batch.
           Under backlog (arrival rate > service rate) this is what keeps
           occupancy at max_batch: the oldest request's max_delay budget
           is already spent, and a deadline-only loop would degrade to
           one-request batches exactly when batching matters most
           (measured: occupancy 1.0 and 12ms p50 under 8-client overload).
        2. DEADLINE LINGER — if the batch still has room and the OLDEST
           member's max_delay budget is not yet spent, wait out the
           remainder for co-riders. An idle server therefore ships a lone
           request after at most max_delay.
        """
        while True:
            first = self._q.get()
            if first is _SENTINEL:
                return None
            if isinstance(first, _DrainBarrier):
                # everything enqueued before the barrier is already
                # scored (the previous batch completed before this
                # _collect): the drain is complete at this point
                first.event.set()
                continue
            batch = [first]
            while len(batch) < self.max_batch:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    break
                if req is _SENTINEL:
                    self._q.put(_SENTINEL)
                    return batch
                if isinstance(req, _DrainBarrier):
                    # fire only after THIS batch (its predecessors) runs
                    self._barriers.append(req)
                    return batch
                batch.append(req)
            flush_at = first.enq_t + self.max_delay_s
            while len(batch) < self.max_batch:
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    req = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if req is _SENTINEL:
                    # flush what we have; the next _collect sees the
                    # re-queued sentinel and exits
                    self._q.put(_SENTINEL)
                    break
                if isinstance(req, _DrainBarrier):
                    self._barriers.append(req)
                    break
                batch.append(req)
            return batch

    def _fire_barriers(self) -> None:
        for bar in self._barriers:
            bar.event.set()
        self._barriers.clear()

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                break
            now = time.monotonic()
            live = []
            for req in batch:
                # dead on arrival: its client already stopped waiting —
                # don't spend kernel time on it
                if req.deadline_t is not None and now > req.deadline_t:
                    req.result = ServeResult(ServeStatus.TIMEOUT)
                    req.event.set()
                else:
                    live.append(req)
            if not live:
                self._fire_barriers()
                continue
            X = np.stack([r.x for r in live])
            try:
                scores, labels = self.run_batch(X)
            except BreakerOpenError:
                # the model's circuit breaker refused the batch before
                # any kernel time was spent: degraded mode, not an error
                if self.metrics:
                    self.metrics.inc("unavailable", len(live))
                for req in live:
                    req.result = ServeResult(ServeStatus.UNAVAILABLE)
                    req.event.set()
                self._fire_barriers()
                continue
            except Exception:  # noqa: BLE001 — a scoring failure must fail
                # the batch's requests, never kill the worker
                if self.metrics:
                    self.metrics.inc("errors", len(live))
                for req in live:
                    req.result = ServeResult(ServeStatus.ERROR)
                    req.event.set()
                self._fire_barriers()
                continue
            if self.metrics:
                self.metrics.inc("ok", len(live))
            for i, req in enumerate(live):
                req.result = ServeResult(ServeStatus.OK, scores=scores[i],
                                         label=labels[i])
                req.event.set()
            self._fire_barriers()
        # drain anything still queued so no client waits out its full
        # timeout against a dead worker
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if isinstance(req, _DrainBarrier):
                req.event.set()
            elif req is not _SENTINEL:
                req.result = ServeResult(ServeStatus.SHUTDOWN)
                req.event.set()
