"""`tpusvm serve --watch DIR`: poll a directory, hot-swap newer models.

The deployment loop the refresh story needs with zero coordination
machinery: `tpusvm tune --save dir/model.npz` or `tpusvm refresh --save
dir/model.npz` drops an artifact (atomically — save_model writes temp +
os.replace, so a watcher never sees a half-written file), and the
serving process picks it up on its next poll:

  * a .npz whose stem is NOT yet hosted is loaded + warmed as a new
    model under that name;
  * a .npz whose stem IS hosted and whose mtime advanced is hot-swapped
    (Server.swap: staged off to the side, probe-verified, atomic flip —
    a bad artifact rolls back and the old generation keeps serving).

Failures are remembered per (path, mtime): a file that failed to stage
is not retried until its mtime changes again (no hot-looping on a
corrupt artifact), and every outcome lands in the log callback + the
swap metrics the server already keeps.

The poll thread is owned: daemon=True AND stop() joins it (JXC205
discipline). `poll_once()` is the test surface — deterministic, no
thread required.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple


class ModelWatcher:
    """Directory poller driving Server.load_model / Server.swap."""

    def __init__(self, server, watch_dir: str, interval_s: float = 2.0,
                 log_fn: Optional[Callable[[str], None]] = print,
                 warmup: bool = True):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.server = server
        self.watch_dir = watch_dir
        self.interval_s = interval_s
        self.log = log_fn or (lambda msg: None)
        self.warmup = warmup
        # path -> mtime of the last SUCCESSFULLY loaded/swapped version
        self._loaded: Dict[str, float] = {}
        # path -> mtime of the last FAILED version (skip until it moves)
        self._failed: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ polling
    def _scan(self) -> List[Tuple[str, float]]:
        """One os.scandir sweep: name filter + the dirent's own stat.

        At tenant-platform scale the directory holds THOUSANDS of
        artifacts; the former glob + per-file os.stat pass paid two
        directory walks and one stat syscall per entry per tick. A
        scandir entry carries its stat result from the directory read
        (cached on the DirEntry), so the whole mtime index costs one
        directory sweep regardless of entry count."""
        out = []
        try:
            with os.scandir(self.watch_dir) as it:
                for entry in it:
                    if not entry.name.endswith(".npz"):
                        continue
                    try:
                        if not entry.is_file():
                            continue
                        out.append((entry.path, entry.stat().st_mtime))
                    except OSError:
                        continue  # deleted between readdir and stat
        except OSError:
            return []  # watch dir missing/unreadable this tick
        out.sort()
        return out

    def poll_once(self) -> List[dict]:
        """One poll pass; returns the actions taken:
        [{"name", "path", "action": "loaded"|"swapped"|"failed",
          "error"?}]."""
        actions = []
        for path, mtime in self._scan():
            if self._loaded.get(path) == mtime \
                    or self._failed.get(path) == mtime:
                continue
            name = os.path.splitext(os.path.basename(path))[0]
            try:
                if name in self.server.registry:
                    out = self.server.swap(name, path)
                    action = {"name": name, "path": path,
                              "action": "swapped",
                              "generation": out["generation"]}
                    self.log(f"watch: swapped {name} -> generation "
                             f"{out['generation']} ({path})")
                else:
                    self.server.load_model(name, path)
                    if self.warmup:
                        self.server.warmup(name)
                    action = {"name": name, "path": path,
                              "action": "loaded"}
                    self.log(f"watch: loaded new model {name} ({path})")
                self._loaded[path] = mtime
                self._failed.pop(path, None)
            except Exception as e:  # noqa: BLE001 — a bad artifact must
                # not kill the watch loop; the server already rolled back
                self._failed[path] = mtime
                action = {"name": name, "path": path, "action": "failed",
                          "error": f"{type(e).__name__}: {e}"}
                self.log(f"watch: FAILED {name} ({path}): "
                         f"{type(e).__name__}: {e} — previous "
                         "generation keeps serving")
            actions.append(action)
        return actions

    # ------------------------------------------------------------ thread
    def start(self) -> "ModelWatcher":
        if self._thread is not None:
            raise RuntimeError("watcher already started")

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — keep polling
                    self.log(f"watch: poll error: "
                             f"{type(e).__name__}: {e}")

        # tpusvm: guarded-by=owner-only lifecycle; start/stop run on the owning thread, the poll thread never touches _thread
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="tpusvm-serve-watch")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            # tpusvm: guarded-by=owner-only lifecycle; cleared after the joined thread exited
            self._thread = None
