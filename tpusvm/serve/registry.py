"""Model registry: load serialized models once, pin their arrays on device.

The estimators' decision_function re-uploads sv_X/coef/b from host numpy on
every call — fine for offline scoring, hostile to a serving hot path (an
H2D transfer of the whole SV set per request). A ModelEntry does that
conversion exactly once at load; the compile cache (buckets.py) then feeds
the SAME pinned device arrays to every AOT-compiled bucket executable, so a
steady-state request uploads only its own padded rows.

Feature scaling stays on the host (numpy, per batch): it is O(m*d) on a
few-row batch, and keeping it host-side makes the served scores use the
exact scaler arithmetic of the offline path (bit-identity contract).

Resilient-serving round: the registry is VERSIONED — every entry carries
a generation counter that `swap()` bumps atomically under the registry
lock, `get_versioned()` returns a consistent (entry, generation) pair,
and artifact loads are classified: a missing/truncated/corrupted .npz
raises :class:`ModelLoadError` (ServeStatus.LOAD_FAILED) naming the
path, with transient I/O retried through faults.retry.DEFAULT_IO_POLICY
and the raw bytes routed through the ``registry.load`` injection point
(where chaos corrupt rules mangle them) before parsing.
"""

from __future__ import annotations

import dataclasses
import io
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm.config import SVMConfig
from tpusvm.data.scaler import MinMaxScaler
from tpusvm.status import ServeStatus


class ModelLoadError(Exception):
    """A model artifact could not be loaded/staged (ServeStatus.LOAD_FAILED).

    One named error for every way an artifact read goes bad — missing
    file, truncated/corrupted zip, a non-model npz, transient I/O that
    survived the retry budget — so `tpusvm serve`, POST /admin/swap and
    the --watch loop report the offending path and cause instead of a
    raw traceback, and a failed hot-swap stage rolls back cleanly."""

    status = ServeStatus.LOAD_FAILED

    def __init__(self, path: str, cause: BaseException):
        self.path = path
        self.cause = cause
        super().__init__(
            f"failed to load model artifact {path!r}: "
            f"{type(cause).__name__}: {cause}"
        )


def _read_model_bytes(path: str) -> bytes:
    """Artifact bytes through the retried ``registry.load`` fault point.

    The read itself is retried under DEFAULT_IO_POLICY (an injected
    transient or a real flaky disk behaves like the stream reader's
    shard reads); the returned payload may have been corrupted by an
    active corrupt rule — np.load's zip CRC then catches it downstream,
    which is exactly the staged-swap failure path under test."""
    from tpusvm import faults

    def _read():
        with open(path, "rb") as f:
            raw = f.read()
        # the point carries the bytes: transient/kill/latency rules act
        # like any other I/O fault, corrupt rules mangle the payload
        out = faults.point("registry.load", payload=raw, path=path)
        return out if out is not None else raw

    retry = faults.Retry(faults.DEFAULT_IO_POLICY, op="registry.load")
    return retry(_read)


@dataclasses.dataclass
class ModelEntry:
    """One servable model: pinned device arrays + host-side scaler."""

    name: str
    kind: str                      # "binary" | "ovr" | "svr"
    config: SVMConfig
    n_features: int                # RAW request-row width (approx models:
    #                                the pre-map input width; X_sv is mapped)
    X_sv: jax.Array                # (n_sv, d), device-resident
    coef: jax.Array                # binary: (n_sv,) alpha*y; ovr: (K, n_sv);
    #                                svr: (n_sv,) signed alpha - alpha*
    b: jax.Array                   # binary/svr: scalar; ovr: (K,)
    scaler: Optional[MinMaxScaler]
    classes: Optional[np.ndarray]  # ovr only
    dtype: object = jnp.float32
    # Platt sigmoid (A, B) of a calibrated binary classifier; the HTTP
    # frontend then adds a `proba` field computed host-side from the
    # served scores — the exact predict_proba arithmetic
    platt: Optional[tuple] = None
    # approximate-kernel models (config.kernel in APPROX_FAMILIES): the
    # fitted FeatureMap (host provenance) and its parameter arrays pinned
    # on device — the bucket cache lowers the FUSED map+decision program
    # (tpusvm.approx) and feeds these pinned operands to every call
    fmap: Optional[object] = None
    map_params: Optional[tuple] = None
    # hot-swap provenance: the registry bumps `generation` on every
    # swap (1 = the initially loaded model); `source_path` is the .npz
    # the entry came from (None for in-process add_model), recorded in
    # serve_state.json so a restarted server reloads its full model set
    generation: int = 1
    source_path: Optional[str] = None

    @property
    def n_sv(self) -> int:
        return int(self.X_sv.shape[0])

    def scale(self, X: np.ndarray) -> np.ndarray:
        return self.scaler.transform(X) if self.scaler is not None else X

    @classmethod
    def from_estimator(cls, name: str, model) -> "ModelEntry":
        """Pin an already-fitted BinarySVC / OneVsRestSVC / EpsilonSVR.

        The kernel family and its parameters travel in model.config — the
        bucket compile cache builds its executables from exactly that
        config, so every family serves through the same machinery. SVR
        models pin their signed sv_coef_ directly (the score IS the
        regressed value); calibrated classifiers carry their Platt
        coefficients for the frontend's proba field.
        """
        # OneVsRestSVC carries classes_/X_sv_/coef_; EpsilonSVR sv_coef_;
        # BinarySVC sv_X_/sv_alpha_
        fmap = getattr(model, "fmap_", None)
        map_kw = {}
        if fmap is not None:
            # pin the map's parameter arrays once, like the SV set — a
            # steady-state request uploads only its own padded raw rows
            map_kw = dict(fmap=fmap, map_params=tuple(
                jnp.asarray(a) for a in fmap.arrays))

        def nf(sv_arr) -> int:
            # approx models serve RAW rows (the executable maps inside);
            # sv_arr's width is the MAPPED dim there, not the row width
            return (int(fmap.n_features_in) if fmap is not None
                    else int(sv_arr.shape[1]))

        if getattr(model, "classes_", None) is not None:
            if model.X_sv_ is None:
                raise RuntimeError("model is not fitted")
            return cls(
                name=name, kind="ovr", config=model.config,
                n_features=nf(model.X_sv_),
                X_sv=jnp.asarray(model.X_sv_, model.dtype),
                coef=jnp.asarray(model.coef_, model.dtype),
                b=jnp.asarray(model.b_, model.dtype),
                scaler=model.scaler_ if model.scale else None,
                classes=np.asarray(model.classes_),
                dtype=model.dtype,
                **map_kw,
            )
        if model.sv_X_ is None:
            raise RuntimeError("model is not fitted")
        if getattr(model, "sv_coef_", None) is not None:
            return cls(
                name=name, kind="svr", config=model.config,
                n_features=nf(model.sv_X_),
                X_sv=jnp.asarray(model.sv_X_, model.dtype),
                coef=jnp.asarray(model.sv_coef_, model.dtype),
                b=jnp.asarray(model.b_, model.dtype),
                scaler=model.scaler_ if model.scale else None,
                classes=None,
                dtype=model.dtype,
                **map_kw,
            )
        coef = np.asarray(model.sv_alpha_) * np.asarray(model.sv_Y_)
        return cls(
            name=name, kind="binary", config=model.config,
            n_features=nf(model.sv_X_),
            X_sv=jnp.asarray(model.sv_X_, model.dtype),
            coef=jnp.asarray(coef, model.dtype),
            b=jnp.asarray(model.b_, model.dtype),
            scaler=model.scaler_ if model.scale else None,
            classes=None,
            dtype=model.dtype,
            platt=getattr(model, "platt_", None),
            **map_kw,
        )

    @classmethod
    def from_path(cls, name: str, path: str, dtype=jnp.float32) -> "ModelEntry":
        """Load a serialized model (binary/OVR/SVR auto-detected), pin it.

        Hardened (ShardError discipline): the artifact bytes are read
        with transient-I/O retries and parsed from memory — a corrupt or
        truncated file, a non-model npz, or exhausted retries raise
        :class:`ModelLoadError` naming the path, never a raw
        BadZipFile/zlib traceback from deep inside numpy."""
        import zlib
        from zipfile import BadZipFile

        from tpusvm import faults
        from tpusvm.models import load_any

        try:
            raw = _read_model_bytes(path)
            model = load_any(io.BytesIO(raw), dtype=dtype)
        except faults.SimulatedKill:
            raise  # a killed process does not get a classification
        except (OSError, ValueError, KeyError, BadZipFile, zlib.error,
                # zipfile raises NotImplementedError when corruption
                # lands on a member's compression-type field
                NotImplementedError,
                faults.RetryExhaustedError) as e:
            raise ModelLoadError(path, e) from e
        entry = cls.from_estimator(name, model)
        entry.source_path = path
        return entry

    def validate_rows(self, X: np.ndarray) -> np.ndarray:
        # float64 on the host regardless of the model dtype: the scaler
        # then runs the same f64 arithmetic as the offline path (numpy
        # promotes mixed f32/f64 to f64 there too), and the cast to the
        # model dtype happens once, at device upload — bit-identity with
        # model.decision_function on the same rows
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"model {self.name!r} expects rows of {self.n_features} "
                f"features, got array of shape {X.shape}"
            )
        return X

    # npz-load path used by `load_model` requires a SVMConfig; keep a tiny
    # summary for status endpoints instead of exposing device arrays
    def describe(self) -> dict:
        d = {
            "name": self.name,
            "kind": self.kind,
            "generation": self.generation,
            "source_path": self.source_path,
            "n_sv": self.n_sv,
            "n_features": self.n_features,
            "kernel": self.config.kernel,
            "gamma": self.config.gamma,
            "C": self.config.C,
            "scaled": self.scaler is not None,
            "calibrated": self.platt is not None,
        }
        if self.config.kernel == "poly":
            d["degree"] = self.config.degree
            d["coef0"] = self.config.coef0
        if self.config.kernel == "sigmoid":
            d["coef0"] = self.config.coef0
        if self.fmap is not None:
            # approx provenance: which map is fused into the executables
            d["map_seed"] = self.config.map_seed
            d["map_dim"] = self.fmap.dim
            if self.config.kernel == "nystrom":
                d["landmarks"] = self.config.landmarks
        if self.kind == "svr":
            d["epsilon"] = self.config.epsilon
        if self.classes is not None:
            d["classes"] = [int(c) for c in self.classes]
        return d


class ModelRegistry:
    """Thread-safe, VERSIONED name -> ModelEntry map.

    Every entry carries a generation counter: `add` installs generation
    1 (or the entry's own, when a serve_state.json restore carries a
    history forward), `swap` stamps old generation + 1 onto the
    replacement and stores it in ONE lock region — a reader calling
    `get_versioned` can never observe an entry whose `.generation` field
    disagrees with the generation the registry reports for it (the
    torn-read invariant the conc-stress `swap` suite perturbs)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, ModelEntry] = {}

    def add(self, entry: ModelEntry) -> ModelEntry:
        with self._lock:
            if entry.name in self._entries:
                raise ValueError(f"model {entry.name!r} already registered")
            self._entries[entry.name] = entry
        return entry

    def swap(self, entry: ModelEntry) -> int:
        """Replace the registered entry of the same name; returns the new
        generation. The name must already be registered (a swap of an
        unknown name is a caller bug, not an implicit add)."""
        with self._lock:
            old = self._entries.get(entry.name)
            if old is None:
                raise KeyError(
                    f"cannot swap unknown model {entry.name!r}; "
                    f"registered: {sorted(self._entries)}"
                )
            entry.generation = old.generation + 1
            self._entries[entry.name] = entry
            return entry.generation

    def load(self, name: str, path: str, dtype=jnp.float32) -> ModelEntry:
        return self.add(ModelEntry.from_path(name, path, dtype=dtype))

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            try:
                return self._entries[name]
            except KeyError:
                raise KeyError(
                    f"unknown model {name!r}; registered: {sorted(self._entries)}"
                ) from None

    def get_versioned(self, name: str) -> Tuple[ModelEntry, int]:
        """(entry, generation) read in one lock region — the pair is
        guaranteed consistent (entry.generation == generation)."""
        with self._lock:
            try:
                e = self._entries[name]
            except KeyError:
                raise KeyError(
                    f"unknown model {name!r}; registered: {sorted(self._entries)}"
                ) from None
            return e, e.generation

    def generation(self, name: str) -> int:
        return self.get_versioned(name)[1]

    def unload(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries
