"""Restart robustness: persisted compile cache + serialized registry state.

BENCH_r01 quantified the serving cold-start problem: 22.3 s of AOT
compile against 0.41 s of training — on every restart, because the
bucket executables lived only in process memory. This module closes it
with two persisted artifacts:

  * **jax's persistent compilation cache** (`configure_persistent_cache`):
    the bucket executables are ordinary XLA compiles, so pointing
    `jax_compilation_cache_dir` at a durable directory makes every
    `lowered.compile()` consult the on-disk cache first — a restarted
    server (or a scale-out replica sharing the directory) reaches first
    prediction with ZERO fresh XLA compiles. Hits and misses are counted
    through jax's own monitoring events into the obs default registry
    (`jax.persistent_cache.hits` / `.misses`), so "warm restart compiled
    nothing" is a machine-checkable gate (`serve --assert-cached`,
    benchmarks/cold_start.py), not a wall-clock impression.

  * **a bucket-signature manifest** (`tpusvm_cache_manifest.json` inside
    the cache dir): which (model-config, bucket) executables this
    deployment has ever built, alongside the jax/jaxlib versions that
    built them — the compile observatory's record of exactly which
    signatures matter, persisted. Purely advisory provenance (the XLA
    cache is keyed on the real HLO); a reader can tell an expected-warm
    restart from a first boot, and a jaxlib upgrade explains itself.

  * **serve_state.json** (`save_serve_state` / `load_serve_state`): the
    serialized registry manifest — every hosted model's source path and
    current generation, written atomically after each successful
    load/swap. `tpusvm serve --state serve_state.json` restores the full
    model set on restart, generations continuing where they left off.

The manifest/state reads sit behind the ``cache.read`` injection point
with the shared retry policy: a transiently unreadable manifest is
retried, a corrupt one is reported and treated as absent (serving must
start; the manifest is provenance, not truth), and a SimulatedKill dies
exactly like a real one.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

CACHE_MANIFEST_NAME = "tpusvm_cache_manifest.json"
CACHE_MANIFEST_VERSION = 1
SERVE_STATE_VERSION = 1

_listener_lock = threading.Lock()
_listener_installed = False
_stats = {"hits": 0, "misses": 0}


# ---------------------------------------------------- persistent XLA cache
def _on_cache_event(event: str, **kw) -> None:
    # jax._src.compilation_cache records these around every compile once
    # a cache dir is configured; mirror them into the obs registry
    if event == "/jax/compilation_cache/cache_hits":
        key = "hits"
    elif event == "/jax/compilation_cache/cache_misses":
        key = "misses"
    else:
        return
    from tpusvm.obs.registry import default_registry

    _stats[key] += 1
    default_registry().counter(f"jax.persistent_cache.{key}").inc()


def _install_cache_listener() -> None:
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        from jax._src import monitoring

        monitoring.register_event_listener(_on_cache_event)
        _listener_installed = True


def persistent_cache_stats() -> Dict[str, int]:
    """{hits, misses} observed since the listener was installed.

    `misses` after a warm restart against a populated cache dir is the
    cold-start gate: 0 means every executable came off disk."""
    return dict(_stats)


def reset_cache_stats() -> None:
    _stats["hits"] = 0
    _stats["misses"] = 0


def configure_persistent_cache(cache_dir: str) -> dict:
    """Point jax's persistent compilation cache at `cache_dir` and install
    the hit/miss accounting; returns the (possibly empty) signature
    manifest found there.

    Every entry is cached regardless of size or compile time (the
    serving bucket executables are small and fast to compile — exactly
    the entries the default thresholds would skip)."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _install_cache_listener()
    return read_cache_manifest(cache_dir)


# ------------------------------------------------- bucket-signature manifest
def bucket_signature(entry, bucket: int, block: int) -> str:
    """Stable provenance key of one (model config, bucket) executable.

    Mirrors what actually shapes the lowered program: the scorer kind and
    kernel statics, the operand shapes (bucket, features, SV count) and
    dtype. jax/jaxlib versions are recorded manifest-wide, not per key —
    an upgrade invalidates everything at once."""
    cfg = entry.config
    parts = [
        entry.kind, cfg.kernel, f"deg{cfg.degree}", f"b{bucket}",
        f"blk{block}", f"d{entry.n_features}", f"sv{entry.n_sv}",
        str(entry.dtype if isinstance(entry.dtype, str)
            else getattr(entry.dtype, "__name__", None)
            or str(entry.dtype)),
    ]
    if entry.fmap is not None:
        parts.append(f"map{entry.fmap.dim}")
    return ":".join(parts)


def _versions() -> dict:
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", None) or \
            jaxlib.version.__version__
    except Exception:  # noqa: BLE001 — provenance is best-effort
        jaxlib_v = None
    return {"jax": jax.__version__, "jaxlib": jaxlib_v}


def read_cache_manifest(cache_dir: str) -> dict:
    """The signature manifest in `cache_dir` ({} signatures when absent).

    Behind the retried ``cache.read`` fault point. A corrupt manifest is
    counted (`serve.cache_manifest_invalid`) and treated as absent —
    the manifest is provenance; refusing to serve over it would turn an
    advisory artifact into an availability hazard."""
    from tpusvm import faults
    from tpusvm.obs.registry import default_registry

    path = os.path.join(cache_dir, CACHE_MANIFEST_NAME)

    def _read():
        faults.point("cache.read", path=path)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return f.read()

    retry = faults.Retry(faults.DEFAULT_IO_POLICY, op="cache.read")
    raw = retry(_read)
    empty = {"format_version": CACHE_MANIFEST_VERSION,
             "versions": _versions(), "signatures": {}}
    if raw is None:
        return empty
    try:
        obj = json.loads(raw)
        if obj.get("format_version") != CACHE_MANIFEST_VERSION:
            raise ValueError(
                f"manifest format_version {obj.get('format_version')!r}"
            )
        if not isinstance(obj.get("signatures"), dict):
            raise ValueError("manifest has no signatures dict")
    except ValueError:
        default_registry().counter("serve.cache_manifest_invalid").inc()
        return empty
    return obj


def record_signatures(cache_dir: str, signatures) -> dict:
    """Merge `signatures` (iterable of bucket_signature strings) into the
    manifest and write it atomically; returns the merged manifest."""
    from tpusvm import faults

    manifest = read_cache_manifest(cache_dir)
    for sig in signatures:
        manifest["signatures"].setdefault(sig, _versions())
    manifest["versions"] = _versions()
    path = os.path.join(cache_dir, CACHE_MANIFEST_NAME)
    faults.point("serve.state_write", path=path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, sort_keys=True, indent=1)
    os.replace(tmp, path)
    return manifest


# ----------------------------------------------------------- serve state
def save_serve_state(path: str, models: Dict[str, dict],
                     cache_dir: Optional[str] = None,
                     address: Optional[str] = None,
                     replica_id: Optional[str] = None) -> None:
    """Atomically persist the registry manifest.

    `models` maps name -> {"path": source .npz, "generation": int}; only
    path-backed entries can be restored (in-process add_model entries
    have no durable source and are recorded with path=None so the
    restore names what it cannot bring back). `address` records the
    ACTUAL bound HTTP host:port (`serve --port 0` picks it at bind
    time) and `replica_id` the replica's fleet identity — both optional
    keys readers tolerate being absent, so version 1 states from before
    the routing tier still load."""
    from tpusvm import faults

    state = {
        "format_version": SERVE_STATE_VERSION,
        "cache_dir": cache_dir,
        "models": models,
    }
    if address is not None:
        state["address"] = address
    if replica_id is not None:
        state["replica_id"] = replica_id
    faults.point("serve.state_write", path=path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, sort_keys=True, indent=1)
    os.replace(tmp, path)


def load_serve_state(path: str) -> dict:
    """Read + validate a serve_state.json (cache.read fault point +
    retries). Raises ValueError with the path for anything that parses
    but is not a serve state; a missing file raises FileNotFoundError
    (the caller decides whether that means 'fresh start')."""
    from tpusvm import faults

    def _read():
        faults.point("cache.read", path=path)
        with open(path) as f:
            return f.read()

    retry = faults.Retry(faults.DEFAULT_IO_POLICY, op="cache.read")
    raw = retry(_read)
    try:
        obj = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"serve state {path!r} is not valid JSON: {e}")
    if not isinstance(obj, dict) or "format_version" not in obj:
        raise ValueError(
            f"{path!r} is not a tpusvm serve state (no format_version)"
        )
    v = obj["format_version"]
    if v != SERVE_STATE_VERSION:
        raise ValueError(
            f"unsupported serve state format_version {v!r} (this build "
            f"reads version {SERVE_STATE_VERSION})"
        )
    if not isinstance(obj.get("models"), dict):
        raise ValueError(f"serve state {path!r} has no models dict")
    return obj
