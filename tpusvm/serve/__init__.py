"""tpusvm.serve — batched online inference over trained SVM models.

The training side reproduces the reference's offline pipeline; this package
is the ROADMAP's serving leg: the path from a serialized model to
low-latency predictions under concurrent load. Design (the adaptive-batching
shape popularized by Clipper, Crankshaw et al. NSDI 2017 — PAPERS.md — on
top of the repo's own predict kernels):

  registry.py   load + pin: models come off disk once, their SV/coef/b
                arrays live on device for the server's lifetime
  batcher.py    deadline-aware micro-batching: single-row requests coalesce
                into batches under a max-latency budget, with a bounded
                queue (fast-fail backpressure) and per-request timeouts
  buckets.py    bucketed compile cache: batches pad to power-of-two row
                buckets so each (model, bucket) compiles exactly once —
                AOT-compiled executables, warm-up API, recompile counter
  metrics.py    request/error/timeout counters, batch-occupancy histogram,
                latency percentiles; JSON + plaintext /metrics dumps
  server.py     the in-process frontend: Server.submit()/submit_many(),
                atomic hot-swap (Server.swap: staged generation flip)
  http.py       stdlib-only JSON-over-HTTP endpoint (`tpusvm serve`),
                POST /admin/swap
  cache.py      restart robustness: jax persistent compilation cache +
                bucket-signature manifest (~zero cold start) and the
                serve_state.json registry manifest
  watch.py      `serve --watch DIR`: poll for newer artifacts, hot-swap
  refresh.py    `tpusvm refresh`: crash-safe warm-started refits that
                hot-swap into the running registry

Correctness contract: a served score is BIT-IDENTICAL to a direct
decision_function call on the same rows — per-row scores are independent of
the surrounding batch (each row's K-row feeds its own dot product).
tests/test_predict.py proves it across block/padding geometries; the two
degenerate row counts where XLA's CPU dot kernels drift by ~1 ulp are
engineered out by bucket floors (buckets.py: binary pads lone requests to
2-row programs, OVR to 4).
"""

from tpusvm.serve.batcher import MicroBatcher, ServeResult
from tpusvm.serve.buckets import CompileCache, bucket_for, default_buckets
from tpusvm.serve.metrics import Metrics
from tpusvm.serve.registry import ModelEntry, ModelLoadError, ModelRegistry
from tpusvm.serve.server import ServeConfig, Server, SwapError

__all__ = [
    "CompileCache",
    "Metrics",
    "MicroBatcher",
    "ModelEntry",
    "ModelLoadError",
    "ModelRegistry",
    "ServeConfig",
    "ServeResult",
    "Server",
    "SwapError",
    "bucket_for",
    "default_buckets",
]
