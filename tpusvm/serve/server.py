"""The serving frontend: Server.submit()/submit_many() over named models.

One Server hosts any number of models; each model gets its own pinned
ModelEntry (registry.py), bucketed compile cache (buckets.py), metrics
(metrics.py), and micro-batcher worker (batcher.py) — models are fully
independent, so a slow model cannot head-of-line-block another.

Lifecycle: load/add -> warmup() -> submit()/submit_many() -> close().
warmup() AOT-compiles every (model, bucket) executable so steady state is
compile-free (the `recompiles` metric proves it); skipping warm-up is legal
but the first request to each bucket then pays the compile and counts it.

predict_direct() is the sequential one-request-at-a-time path — the same
scoring arithmetic with no queue or coalescing. It exists as the benchmark
baseline (benchmarks/serve_latency.py measures batched-vs-sequential
throughput against it) and as the bit-identity oracle in tests.

Resilient-serving round — atomic hot-swap: a worker's servable state is
one immutable `_Generation` bundle (entry + compile cache + generation
number), and `Server.swap()` stages its replacement FULLY off to the
side — artifact load (retried, classified), device pinning, bucket
AOT-compiles, a probe-vector verification — before flipping the
worker's bundle reference under the server lock. A batch reads its
bundle exactly once, so in-flight work finishes on the old generation
and no request ever sees a torn entry/cache pair; a failed stage
(corrupt .npz, compile error, probe mismatch, injected fault) changes
NOTHING — the old generation keeps serving, the failure is recorded on
/healthz (`degraded`) and the swap_failures counter. Breaker state,
SLO windows and every metric survive the flip: only the bundle moves.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from tpusvm import faults
from tpusvm.serve.batcher import MicroBatcher, ServeResult
from tpusvm.serve.buckets import CompileCache, default_buckets
from tpusvm.serve.metrics import Metrics
from tpusvm.serve.registry import ModelEntry, ModelRegistry


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Per-server serving knobs (shared by every hosted model)."""

    max_batch: int = 64          # coalescing cap = largest bucket
    max_delay_ms: float = 2.0    # max added latency waiting for co-riders
    queue_size: int = 1024       # backpressure bound (fast-fail when full)
    timeout_ms: float = 1000.0   # default per-request deadline
    buckets: Optional[Tuple[int, ...]] = None  # default: powers of two
    block: int = 2048            # binary scorer's scan block
    # degraded-mode knobs (tpusvm.faults):
    # load shedding: requests arriving while the queue holds >= this
    # fraction of queue_size come back OVERLOADED instead of queueing;
    # None = off (the hard QUEUE_FULL bound alone, the pre-faults shape)
    shed_threshold: Optional[float] = None
    # transient-scoring-fault retry budget (TransientIOError class only;
    # a real scoring exception is not retried — it feeds the breaker)
    score_retries: int = 3
    # circuit breaker: consecutive failed BATCHES that trip it, and the
    # open-state cooldown before a half-open probe is admitted
    breaker_threshold: int = 5
    breaker_cooldown_s: float = 30.0
    # serving SLOs (performance-observatory round): a latency target
    # activates per-model budget tracking — burn-rate gauges on /metrics,
    # "degraded" on /healthz while a budget burns. None = no SLO (the
    # pre-observatory shape, zero overhead).
    slo_p99_ms: Optional[float] = None
    slo_error_budget: float = 0.001   # allowed windowed error fraction
    slo_window_s: float = 60.0        # sliding evaluation window
    # admission control fed by the burn gauges: True sheds new requests
    # (OVERLOADED, retryable) while the latency budget burns, protecting
    # in-flight work — the hook the serving-runtime ROADMAP item inherits
    slo_shed: bool = False

    def resolved_slo(self):
        """SLOConfig when a latency target is set, else None."""
        if self.slo_p99_ms is None:
            if self.slo_shed:
                raise ValueError(
                    "slo_shed=True needs an SLO to consult; set slo_p99_ms"
                )
            return None
        from tpusvm.serve.metrics import SLOConfig

        return SLOConfig(p99_ms=self.slo_p99_ms,
                         error_budget=self.slo_error_budget,
                         window_s=self.slo_window_s).validate()

    def resolved_buckets(self) -> Tuple[int, ...]:
        if self.buckets is not None:
            b = tuple(sorted(int(x) for x in self.buckets))
            if not b or b[-1] < self.max_batch:
                raise ValueError(
                    f"buckets {b} do not cover max_batch={self.max_batch}"
                )
            return b
        return default_buckets(self.max_batch)

    def resolved_shed_at(self) -> Optional[int]:
        if self.shed_threshold is None:
            return None
        if not (0.0 < self.shed_threshold <= 1.0):
            raise ValueError(
                f"shed_threshold must be in (0, 1], got "
                f"{self.shed_threshold}"
            )
        return max(1, int(self.shed_threshold * self.queue_size))


class _Generation:
    """One immutable servable bundle: the unit the hot-swap flips.

    A scoring path reads the worker's `_gen` reference ONCE and uses
    this bundle throughout — entry and compile cache can never be
    observed from different generations (the torn-model hazard the
    swap-under-load tests hammer). The reference store itself is a
    single GIL-atomic pointer write performed under the server lock."""

    __slots__ = ("entry", "cache", "generation", "loaded_t",
                 "probe_scores")

    def __init__(self, entry: ModelEntry, cache: CompileCache,
                 generation: int, loaded_t: float, probe_scores=None):
        self.entry = entry
        self.cache = cache
        self.generation = generation
        self.loaded_t = loaded_t
        self.probe_scores = probe_scores


class SwapError(Exception):
    """A hot-swap stage failed and was rolled back; the previous
    generation keeps serving. Wraps the staging failure (load error,
    compile failure, probe mismatch) with the model name."""

    def __init__(self, name: str, cause: BaseException):
        self.name = name
        self.cause = cause
        super().__init__(
            f"swap of model {name!r} failed and was rolled back: "
            f"{type(cause).__name__}: {cause}"
        )


class _ModelWorker:
    """Metrics + batcher + breaker for one hosted model, serving the
    current `_Generation` bundle (entry + compile cache)."""

    def __init__(self, entry: ModelEntry, config: ServeConfig,
                 clock=None):
        buckets = config.resolved_buckets()
        self.config = config
        self._clock = clock or time.monotonic
        self.metrics = Metrics(buckets, slo=config.resolved_slo(),
                               clock=clock)
        # the cache reports per-bucket compile time + cost analysis into
        # this worker's registry, so /metrics carries compile accounting
        cache = CompileCache(entry, buckets, block=config.block,
                             registry=self.metrics.registry)
        self._gen = _Generation(entry, cache, entry.generation,
                                self._clock())
        # last swap attempt's outcome (None until the first swap):
        # {"outcome": "ok"|"failed", "generation": int, "error": str?}
        # tpusvm: guarded-by=single dict ref, swapped whole under the server lock
        self._last_swap: Optional[dict] = None
        self.breaker = faults.CircuitBreaker(
            threshold=config.breaker_threshold,
            cooldown_s=config.breaker_cooldown_s,
            name=entry.name,
            listener=self._on_breaker,
            **({"clock": clock} if clock is not None else {}),
        )
        self._retry = faults.Retry(
            faults.RetryPolicy(max_attempts=config.score_retries + 1,
                               retryable=(faults.TransientIOError,)),
            op="serve.score",
            on_retry=lambda: self.metrics.inc("retries"),
        )
        # serializes predict_direct against the batcher thread: compiled
        # executables tolerate concurrent callers, but one at a time keeps
        # the latency accounting honest and the device queue short
        self._exec_lock = threading.Lock()
        self.batcher = MicroBatcher(
            self._run_batch,
            max_batch=config.max_batch,
            max_delay_s=config.max_delay_ms / 1e3,
            queue_size=config.queue_size,
            timeout_s=config.timeout_ms / 1e3,
            metrics=self.metrics,
            shed_at=config.resolved_shed_at(),
            admission=(self._slo_admission if config.slo_shed else None),
        )

    # ------------------------------------------------------- generations
    @property
    def entry(self) -> ModelEntry:
        return self._gen.entry

    @property
    def cache(self) -> CompileCache:
        return self._gen.cache

    @property
    def generation(self) -> int:
        return self._gen.generation

    def probe_rows(self, entry: Optional[ModelEntry] = None) -> np.ndarray:
        """The pinned probe vector: deterministic rows every staged
        generation must score before it may serve. Seeded per feature
        width, so A->B->A swaps verify against the identical probe."""
        e = entry if entry is not None else self._gen.entry
        rng = np.random.default_rng(0xFEED ^ e.n_features)
        return rng.random((2, e.n_features))

    def stage(self, entry: ModelEntry) -> _Generation:
        """Build a fully-warmed replacement bundle OFF TO THE SIDE.

        Device-pins are already in `entry`; this AOT-compiles every
        bucket executable (cold requests after the flip would otherwise
        pay a compile) and verifies the staged executables against the
        pinned probe vector — finite scores of the right shape, computed
        through the real bucket path. Nothing the serving path reads is
        touched; any failure here leaves the old generation serving."""
        faults.point("serve.swap", model=entry.name)
        cache = CompileCache(entry, self.config.resolved_buckets(),
                             block=self.config.block,
                             registry=self.metrics.registry)
        cache.warmup()
        probe = entry.validate_rows(self.probe_rows(entry))
        # exactly the serving arithmetic (scale host-side, cast at the
        # pad-buffer upload), so probe scores are the bundle's served
        # scores for these rows, bitwise
        with self._exec_lock:
            scores, _ = cache.scores(entry.scale(probe))
        want = ((probe.shape[0], len(entry.classes))
                if entry.kind == "ovr" else (probe.shape[0],))
        if scores.shape != want or not np.all(np.isfinite(scores)):
            raise SwapError(entry.name, ValueError(
                f"probe verification failed: scores shape {scores.shape} "
                f"(want {want}), finite={bool(np.all(np.isfinite(scores)))}"
            ))
        # generation is stamped by the registry at flip time
        return _Generation(entry, cache, entry.generation, self._clock(),
                           probe_scores=scores)

    def flip(self, gen: _Generation) -> None:
        """Install a staged bundle — one reference store (the caller
        holds the server lock; in-flight batches keep their old bundle)."""
        self._gen = gen
        self._last_swap = {"outcome": "ok", "generation": gen.generation}
        self.metrics.inc("swaps")
        reg = self.metrics.registry
        reg.gauge("serve.generation").set(float(gen.generation))
        reg.gauge("serve.last_swap_ok").set(1.0)

    def record_swap_failure(self, error: BaseException) -> None:
        g = self._gen
        self._last_swap = {
            "outcome": "failed",
            "generation": g.generation,   # the generation STILL serving
            "error": f"{type(error).__name__}: {error}",
        }
        self.metrics.inc("swap_failures")
        self.metrics.registry.gauge("serve.last_swap_ok").set(0.0)

    def swap_status(self) -> dict:
        """Per-model swap/staleness view for health() and /metrics.

        staleness_s = time since the serving generation was installed;
        refreshed into the registry gauges at every scrape so `tpusvm
        report` and merged snapshots carry the same numbers."""
        g = self._gen
        staleness = max(0.0, self._clock() - g.loaded_t)
        reg = self.metrics.registry
        reg.gauge("serve.generation").set(float(g.generation))
        reg.gauge("serve.staleness_s").set(staleness)
        out = {"generation": g.generation,
               "staleness_s": staleness,
               "last_swap": self._last_swap}
        return out

    def _slo_admission(self) -> bool:
        """SLO-fed admission control (config.slo_shed): refuse new work
        while the latency budget burns. Error burn deliberately does NOT
        shed — refusing traffic cannot un-fail requests, and shedding on
        errors would turn one bad batch into an outage."""
        st = self.metrics.slo_status()
        return st is None or st["latency_burn"] < 1.0

    def slo_status(self):
        return self.metrics.slo_status()

    def _on_breaker(self, event: str) -> None:
        if event == "tripped":
            self.metrics.inc("breaker_trips")
        elif event == "recovered":
            self.metrics.inc("breaker_recoveries")

    def _score(self, X: np.ndarray):
        """(scores, labels, [(bucket, rows), ...]) for validated f64 rows.

        Batches larger than the top bucket (possible only via the direct
        path — the batcher caps at max_batch) are chunked through it.

        The generation bundle is read ONCE: a swap flipping mid-batch
        changes nothing here — this batch finishes on the bundle it
        started with (entry and cache always from the same generation)."""
        g = self._gen
        e = g.entry
        if X.shape[0] == 0:
            shape = (0, len(e.classes)) if e.kind == "ovr" else (0,)
            empty_labels = (np.zeros(0) if e.kind == "svr"
                            else np.zeros(0, np.int32))
            return np.zeros(shape), empty_labels, []
        Xs = e.scale(X)
        top = g.cache.buckets[-1]
        parts, chunks = [], []
        with self._exec_lock:
            for i in range(0, Xs.shape[0], top):
                s, bucket = g.cache.scores(Xs[i:i + top])
                parts.append(s)
                chunks.append((bucket, s.shape[0]))
        scores = np.concatenate(parts) if len(parts) > 1 else parts[0]
        if e.kind == "binary":
            labels = np.where(scores > 0, 1, -1).astype(np.int32)
        elif e.kind == "svr":
            # regression: the score IS the prediction — serve the value
            labels = scores
        else:
            labels = e.classes[np.argmax(scores, axis=1)]
        return scores, labels, chunks

    def _score_injected(self, X: np.ndarray):
        faults.point("serve.score", model=self.entry.name)
        return self._score(X)

    def _run_batch(self, X: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """The batcher's scoring callback, hardened: breaker gate first
        (an open breaker fails the batch in microseconds, no kernel
        time), then the scoring attempt with transient-fault retries;
        outcomes feed the breaker so persistent failure trips it and a
        half-open probe recovers it."""
        if not self.breaker.allow():
            raise faults.BreakerOpenError(self.entry.name)
        try:
            scores, labels, chunks = self._retry(self._score_injected, X)
        except Exception:
            # exhausted retries or a non-retryable scoring failure: one
            # consecutive-failure tick (SimulatedKill, a BaseException,
            # bypasses this — a killed process counts nothing)
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        for bucket, rows in chunks:
            self.metrics.observe_batch(bucket, rows)
        if self._gen.entry.kind == "binary":
            # served-score sign tallies: the drift detector's input
            # (autopilot.drift.score_shift compares the positive-rate of
            # the traffic since the last refresh against the baseline
            # recorded at swap time). Registry counters, not snapshot()
            # keys — the legacy snapshot schema is frozen by parity tests.
            pos = int(np.count_nonzero(labels > 0))
            reg = self.metrics.registry
            reg.counter("serve.scores_pos").inc(pos)
            reg.counter("serve.scores_neg").inc(len(labels) - pos)
        return scores, labels

    def drain(self, timeout_s: float = 10.0) -> bool:
        return self.batcher.drain(timeout_s)

    def close(self) -> None:
        self.batcher.close()


class Server:
    """In-process serving frontend over named SVM models."""

    def __init__(self, config: ServeConfig = ServeConfig(),
                 dtype=jnp.float32, replica_id: Optional[str] = None):
        self.config = config
        self.dtype = dtype
        # the replica's fleet identity: minted once per fresh replica,
        # persisted in serve_state.json and re-adopted by restore_state,
        # so a revived replica keeps its identity across kill/restart
        # (the routing tier keys its health records on it)
        self.replica_id = replica_id or f"r-{uuid.uuid4().hex[:8]}"
        self._start_t = time.monotonic()
        self.registry = ModelRegistry()
        self._workers: Dict[str, _ModelWorker] = {}
        self._lock = threading.Lock()
        # serializes whole swap operations (stage + flip): staging is
        # slow (compiles), so it must not hold the server lock, but two
        # concurrent swaps of one model must not interleave their
        # stage/flip pairs (the second would flip over the first)
        self._swap_lock = threading.Lock()
        self._closed = False
        self._draining = False
        self._httpd = None
        self._http_thread = None
        self._state_path: Optional[str] = None
        self._cache_dir: Optional[str] = None
        self._bound_address: Optional[str] = None

    # ----------------------------------------------------------- hosting
    def _install(self, entry: ModelEntry) -> ModelEntry:
        self.registry.add(entry)
        with self._lock:
            self._workers[entry.name] = _ModelWorker(entry, self.config)
        self._persist_state()
        return entry

    def load_model(self, name: str, path: str) -> ModelEntry:
        """Load a serialized .npz model (binary/OVR auto-detected).

        A missing/corrupt/transiently-unreadable artifact raises the
        classified serve.ModelLoadError naming the path (transient I/O
        is retried first — tpusvm.faults.retry.DEFAULT_IO_POLICY)."""
        return self._install(ModelEntry.from_path(name, path,
                                                  dtype=self.dtype))

    def add_model(self, name: str, model) -> ModelEntry:
        """Host an already-fitted BinarySVC / OneVsRestSVC."""
        return self._install(ModelEntry.from_estimator(name, model))

    # --------------------------------------------------------- hot-swap
    def swap(self, name: str, model_or_path) -> dict:
        """Atomically replace a hosted model with a new generation.

        `model_or_path`: a serialized .npz path (the `tune`/`refresh`
        winner handoff) or an already-fitted estimator. The replacement
        is staged fully off to the side — load + device-pin +
        bucket-compile + probe-verify — and only then does the worker's
        generation bundle flip, under the server lock, together with
        the registry entry. In-flight batches finish on the old
        generation; breaker state, SLO windows and metrics carry over.

        On ANY staging failure the old model keeps serving: the failure
        is recorded (healthz degrades, swap_failures increments) and
        re-raised for the caller. A SimulatedKill propagates unrecorded
        — a killed process records nothing, and the restarted server
        reloads the old generation from serve_state.json.

        Returns {"name", "generation", "latency_s", "staleness_before_s"}.
        """
        w = self._worker(name)
        t0 = time.perf_counter()
        with self._swap_lock:
            old = w._gen
            try:
                if isinstance(model_or_path, str):
                    entry = ModelEntry.from_path(name, model_or_path,
                                                 dtype=self.dtype)
                elif isinstance(model_or_path, ModelEntry):
                    entry = model_or_path
                else:
                    entry = ModelEntry.from_estimator(name, model_or_path)
                gen = w.stage(entry)
            except faults.SimulatedKill:
                raise
            except BaseException as e:  # noqa: BLE001 — every staging
                # failure must roll back AND be visible on healthz
                w.record_swap_failure(e)
                faults.emit("serve.swap_failed", model=name,
                            error=f"{type(e).__name__}: {e}",
                            generation=old.generation)
                raise
            staleness_before = max(0.0, w._clock() - old.loaded_t)
            with self._lock:
                gen.generation = self.registry.swap(entry)
                w.flip(gen)
        latency = time.perf_counter() - t0
        w.metrics.registry.gauge("serve.swap_latency_s").set_max(latency)
        faults.emit("serve.swapped", model=name,
                    generation=gen.generation, latency_s=latency,
                    staleness_before_s=staleness_before)
        self._persist_state()
        return {"name": name, "generation": gen.generation,
                "latency_s": latency,
                "staleness_before_s": staleness_before}

    # ------------------------------------------------- restart robustness
    def configure_cache(self, cache_dir: str) -> dict:
        """Point jax's persistent compilation cache at `cache_dir` (see
        serve/cache.py) so bucket compiles persist across restarts;
        returns the signature manifest found there. warmup() then
        records every built signature back into the manifest."""
        from tpusvm.serve import cache as _cache

        manifest = _cache.configure_persistent_cache(cache_dir)
        self._cache_dir = cache_dir
        return manifest

    def enable_state(self, path: str) -> None:
        """Persist the registry manifest (model paths + generations) to
        `path` after every successful load/swap — the restart story."""
        self._state_path = path
        self._persist_state()

    def set_bound_address(self, host: str, port: int) -> None:
        """Record the ACTUAL bound HTTP address (host, port) into the
        persisted state. With `serve --port 0` the kernel picks the
        port, so serve_state.json is where a supervisor (or the chaos
        harness reviving this replica) reads the real address back."""
        self._bound_address = f"{host}:{int(port)}"
        self._persist_state()

    @property
    def bound_address(self) -> Optional[str]:
        return self._bound_address

    def _persist_state(self) -> None:
        if self._state_path is None:
            return
        from tpusvm.serve.cache import save_serve_state

        models = {}
        for n in self.registry.names():
            e, gen = self.registry.get_versioned(n)
            models[n] = {"path": e.source_path, "generation": gen}
        save_serve_state(self._state_path, models,
                         cache_dir=self._cache_dir,
                         address=self._bound_address,
                         replica_id=self.replica_id)

    def restore_state(self, path: str) -> dict:
        """Reload the model set recorded in a serve_state.json: every
        path-backed model is loaded and its generation counter restored
        (so staleness/generation history survives the restart). Models
        recorded without a source path (in-process add_model) cannot be
        restored and are reported in the returned dict's "skipped"."""
        from tpusvm.serve.cache import load_serve_state

        state = load_serve_state(path)
        if state.get("replica_id"):
            # a revived replica IS the replica that wrote the state:
            # keep its fleet identity (the router's health records and
            # the chaos harness both key on it across kill/restart)
            self.replica_id = state["replica_id"]
        restored, skipped = [], []
        for name, info in sorted(state["models"].items()):
            if name in self.registry:
                continue
            if not info.get("path"):
                skipped.append(name)
                continue
            entry = ModelEntry.from_path(name, info["path"],
                                         dtype=self.dtype)
            entry.generation = int(info.get("generation", 1))
            self._install(entry)
            restored.append(name)
        if state.get("cache_dir") and self._cache_dir is None:
            self.configure_cache(state["cache_dir"])
        return {"restored": restored, "skipped": skipped,
                "cache_dir": state.get("cache_dir")}

    def _worker(self, name: str) -> _ModelWorker:
        with self._lock:
            try:
                return self._workers[name]
            except KeyError:
                raise KeyError(
                    f"unknown model {name!r}; hosted: {sorted(self._workers)}"
                ) from None

    def warmup(self, name: Optional[str] = None) -> Dict[str, int]:
        """AOT-compile every bucket executable; {model: compiles done}.

        With a persistent cache configured, every built signature is
        recorded into the cache dir's manifest — the provenance record
        of exactly which executables a warm restart expects to find."""
        names = [name] if name is not None else self.registry.names()
        out = {n: self._worker(n).cache.warmup() for n in names}
        if self._cache_dir is not None:
            from tpusvm.serve.cache import bucket_signature, record_signatures

            sigs = []
            for n in names:
                w = self._worker(n)
                g = w._gen
                sigs.extend(bucket_signature(g.entry, b, g.cache.block)
                            for b in g.cache.buckets)
            record_signatures(self._cache_dir, sigs)
        return out

    # ----------------------------------------------------------- serving
    def submit(self, name: str, x: np.ndarray,
               timeout_s: Optional[float] = None) -> ServeResult:
        """Score one row through the micro-batcher; blocks for the result."""
        w = self._worker(name)
        row = w.entry.validate_rows(x)
        if row.shape[0] != 1:
            raise ValueError(
                f"submit takes one row, got {row.shape[0]} (use submit_many)"
            )
        return w.batcher.submit(row[0], timeout_s=timeout_s)

    def submit_many(self, name: str, X: np.ndarray,
                    timeout_s: Optional[float] = None) -> List[ServeResult]:
        """Score rows through the micro-batcher (rows coalesce freely with
        other callers' requests)."""
        w = self._worker(name)
        rows = w.entry.validate_rows(X)
        return w.batcher.submit_many(list(rows), timeout_s=timeout_s)

    def predict_direct(self, name: str, X: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(scores, labels) synchronously, bypassing the queue.

        The sequential baseline and bit-identity oracle: same scaler, same
        bucket executables, no batching."""
        w = self._worker(name)
        rows = w.entry.validate_rows(X)
        scores, labels, _ = w._score(rows)
        return scores, labels

    # ------------------------------------------------------------ status
    def metrics(self, name: str) -> dict:
        return self._worker(name).metrics.snapshot()

    def score_stats(self, name: str) -> dict:
        """Cumulative served-score sign tallies for a binary model —
        the autopilot's score-shift drift input. Both counters are 0
        for ovr/svr models (no sign semantics)."""
        reg = self._worker(name).metrics.registry
        return {"pos": reg.counter("serve.scores_pos").value,
                "neg": reg.counter("serve.scores_neg").value}

    def metrics_text(self) -> str:
        from tpusvm.obs.registry import escape_label_value

        chunks = []
        for n in self.registry.names():
            w = self._worker(n)
            snap_labels = f'model="{escape_label_value(n)}"'
            chunks.append(w.metrics.render_text(labels=snap_labels))
            chunks.append(
                f'tpusvm_serve_compiled_shapes{{{snap_labels}}} '
                f'{w.cache.compiled_shapes}\n'
            )
        return "".join(chunks)

    def fleet_snapshot(self) -> dict:
        """This replica's fleet payload (obs.fleet): every model
        worker's registry snapshot merged into ONE mergeable snapshot
        (each worker owns its own registry, so the per-model series are
        label-disjoint and the merge is exact), plus the status block
        `tpusvm top` renders (generation / breaker / p99 / burn per
        model). GET /metrics.json serves this verbatim."""
        from tpusvm.obs.fleet import snapshot_payload
        from tpusvm.obs.registry import merge_snapshots

        with self._lock:
            workers = dict(self._workers)
        snaps = [w.metrics.registry_snapshot() for w in workers.values()]
        merged = (merge_snapshots(*snaps) if snaps
                  else {"v": 1, "metrics": []})
        models = {}
        for n, w in workers.items():
            m = w.metrics.snapshot()
            slo = m.get("slo")
            models[n] = {
                "generation": w.generation,
                "breaker": w.breaker.state,
                "queue_depth": w.batcher.depth,
                "p99_s": m["latency_s"]["p99"],
                "burning": bool(slo["burning"]) if slo else False,
            }
        return snapshot_payload(
            "serve", self.replica_id, merged,
            status={"models": models,
                    "draining": self._draining,
                    "uptime_s": round(time.monotonic() - self._start_t,
                                      3)})

    def status(self) -> dict:
        """JSON-able server summary (models, buckets, compiles, queues)."""
        models = {}
        for n in self.registry.names():
            w = self._worker(n)
            g = w._gen  # one bundle: entry/cache stats stay consistent
            models[n] = {
                **g.entry.describe(),
                **w.swap_status(),
                "buckets": list(g.cache.buckets),
                "compiled_shapes": g.cache.compiled_shapes,
                "compiles": g.cache.compiles,
                "recompiles": g.cache.recompiles,
                "warmed": g.cache.warmed,
                "queue_depth": w.batcher.depth,
                "breaker": w.breaker.describe(),
            }
        return {
            "models": models,
            "draining": self._draining,
            "state_path": self._state_path,
            "cache_dir": self._cache_dir,
            "config": dataclasses.asdict(self.config),
        }

    def health(self) -> dict:
        """The /healthz payload: overall status + per-model breaker state.

        "ok" only when the server is accepting work; "draining" after
        drain(); a model with an open breaker, a burning SLO budget OR
        a failed last swap (the staged replacement rolled back — the
        old generation is serving, but the operator should know)
        degrades the report to "degraded" without failing the whole
        health check (the other models still serve). Per-model swap
        history — generation, staleness_s, last_swap outcome — rides in
        the "swap" key and the serve.generation / serve.staleness_s /
        serve.last_swap_ok gauges."""
        with self._lock:
            workers = dict(self._workers)
        breakers = {n: w.breaker.state for n, w in workers.items()}
        swap = {n: w.swap_status() for n, w in workers.items()}
        failed_swaps = [
            n for n, st in swap.items()
            if st["last_swap"] is not None
            and st["last_swap"]["outcome"] == "failed"
        ]
        slo = {n: st for n, w in workers.items()
               if (st := w.metrics.slo_status()) is not None}
        burning = [n for n, st in slo.items() if st["burning"]]
        if self._draining or self._closed:
            status = "draining"
        elif any(s != "closed" for s in breakers.values()) or burning \
                or failed_swaps:
            status = "degraded"
        else:
            status = "ok"
        out = {"status": status, "models": breakers, "swap": swap,
               "replica_id": self.replica_id,
               "uptime_s": round(time.monotonic() - self._start_t, 3)}
        if slo:
            out["slo"] = {
                n: {"latency_burn": st["latency_burn"],
                    "error_burn": st["error_burn"],
                    "burning": st["burning"]}
                for n, st in slo.items()
            }
        return out

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Stop admitting new requests (they come back DRAINING) and wait
        for in-flight work to finish. True when every model quiesced
        within the timeout. The zero-downtime-restart primitive: drain,
        then close, and no accepted request is ever dropped."""
        self._draining = True
        with self._lock:
            workers = list(self._workers.values())
        ok = True
        for w in workers:
            ok = w.drain(timeout_s) and ok
        faults.emit("serve.drained", complete=ok)
        return ok

    def attach_http(self, httpd, thread=None) -> None:
        """Register the HTTP frontend serving this Server so close()
        owns its shutdown: stop the serve loop, CLOSE the listener
        socket, join the serving thread. Without this the daemon HTTP
        thread leaks the bound port past close() — the CI-smoke
        EADDRINUSE trap the concurrency audit flagged."""
        with self._lock:
            self._httpd = httpd
            self._http_thread = thread

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            workers = list(self._workers.values())
            httpd, http_thread = self._httpd, self._http_thread
            self._httpd = self._http_thread = None
        if httpd is not None:
            # outside the lock: shutdown blocks on the serve loop, and a
            # handler thread mid-request may call back into this Server
            from tpusvm.serve.http import stop_http_server

            stop_http_server(httpd, http_thread)
        for w in workers:
            w.close()

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def sequential_qps(server: Server, name: str, rows: Sequence[np.ndarray],
                   duration_s: float) -> float:
    """Throughput of the one-request-at-a-time path (benchmark baseline)."""
    import itertools
    import time

    n = 0
    t0 = time.perf_counter()
    for x in itertools.cycle(rows):
        server.predict_direct(name, x)
        n += 1
        if time.perf_counter() - t0 >= duration_s:
            break
    return n / (time.perf_counter() - t0)
