#!/usr/bin/env python
"""Headline benchmark: MNIST-60k-shaped RBF SVM training on one TPU chip.

Prints ONE JSON line to stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Workload: the reference's headline configuration (SURVEY.md §6, B2) — a
60,000 x 784 one-vs-rest RBF SVM (gamma=0.00125, C=10, tau=1e-5) trained
to the reference's exact stopping criterion with the blocked working-set
solver (tpusvm.solver.blocked — the TPU-first redesign whose FLOPs ride
the MXU). Real MNIST CSVs are not available in this
environment (zero egress), so the workload is a deterministic synthetic
MNIST-shaped problem (tpusvm.data.mnist_like, noise=30, label_noise=0.005).

Workload recipe: DELIBERATELY FROZEN at the round-1 recipe (noise=30,
label_noise=0.005) so the headline number stays comparable across rounds —
every BENCH_r*.json measures the identical optimisation problem. The frozen
recipe matches real MNIST's difficulty in the dimensions this benchmark
measures — solver work (~57k SMO iterations, ~27 outer rounds) and model
size (~2000 SVs vs the reference's 1548) — but NOT held-out accuracy, which
the label flips pin at ~0.993 regardless of n (and which this benchmark does
not measure or report). Runs where the accuracy column carries information
(benchmarks/sweep_n.py) use the calibrated recipe instead
(tpusvm.data.synthetic.BENCH_NOISE = 330, no label flips — see its comment).

Baseline: the reference's GPU SMO trains MNIST-60k in 58.570 s on one GPU
(report Table 1, BASELINE.md B2; 56.09x over its 3285.662 s serial run).
vs_baseline = 58.570 / our wall-clock, i.e. >1 means faster than the
reference's single-accelerator headline.

Measurement notes:
  - The solver is compiled ahead of time (jit .lower().compile()) and the
    timed region is pure on-device execution of the full training loop —
    matching the reference's timing, which also excludes I/O and starts
    after data load (gpu_svm_main3.cu:516 cudaEvent after read_CSV).
  - One measurement per process: repeated heavy invocations on this
    environment's tunneled TPU runtime occasionally fault the device; the
    driver runs bench.py in a fresh process. jax.block_until_ready returns
    early on this runtime, so timing runs to host materialisation of the
    result. See .claude/skills/verify/SKILL.md.
  - Mixed precision (float32 features/kernel rows, float64 f/alpha
    accumulators) — f32 alone livelocks on hard problems (Status.STALLED),
    f64-everywhere wastes HBM bandwidth; this matches the f64 reference's
    convergence behaviour at f32 speed.
"""

import json
import os
import subprocess
import sys

import jax

jax.config.update("jax_enable_x64", True)

# Set by _reexec_cpu on the fallback child: pin the CPU backend BEFORE any
# backend initialises. The env-var JAX_PLATFORMS route does NOT work here —
# this environment's sitecustomize registers the accelerator plugin at
# interpreter startup and programmatically sets jax_platforms, overriding
# the env var; only a later jax.config.update wins (same mechanism as
# tests/conftest.py and __graft_entry__.py self-provisioning).
_FORCE_CPU_ENV = "_TPUSVM_BENCH_FORCE_CPU"
_INIT_ERR_ENV = "_TPUSVM_BENCH_INIT_ERROR"
if os.environ.get(_FORCE_CPU_ENV) == "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.config import PALLAS_FLAG_RULES  # noqa: E402
from tpusvm.data import MinMaxScaler, mnist_like  # noqa: E402
from tpusvm.solver.blocked import blocked_smo_solve  # noqa: E402
from tpusvm.status import Status  # noqa: E402

BASELINE_GPU_60K_S = 58.570  # BASELINE.md B2
# TPU v5e (v5 lite) peak HBM bandwidth, GB/s — the roofline the blocked
# solver's O(n*d) streams are limited by.
V5E_PEAK_HBM_GBPS = 819.0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# Backend-init insurance. Round 2's headline was LOST to exactly this:
# the TPU backend was unavailable when the driver ran bench.py, jax.devices()
# raised at the first line of main(), and rc=1 left NO json record — while
# every later failure mode (kernel canary, compile fallback) was covered.
# The observed init-failure modes are BOTH a fast raise (BENCH_r02.json:
# "UNAVAILABLE: TPU backend setup/compile error") and an indefinite HANG
# (a wedged TPU tunnel blocks xla_bridge.backends() without returning), so
# an in-process try/except is not enough: the probe runs in a SUBPROCESS
# with a timeout, and on failure/timeout/raise bench re-execs itself on the
# CPU backend with the init error recorded in the json detail. The
# reference's timing contract always reports (gpu_svm_main3.cu:516-694);
# a wedged accelerator must yield a degraded record, not nothing.
_PROBE_TIMEOUT_S = 240.0
# supervised accelerator child: generous bound on the WHOLE measurement
# (datagen ~1min + compile ~40s + train ~1s on the round-1 TPU capture,
# plus tunnel slack) — a post-probe wedge costs this long, then degrades
_ACCEL_TIMEOUT_S = 1800.0
_ACCEL_CHILD_ENV = "_TPUSVM_BENCH_ACCEL_CHILD"


def _has_record(out):
    """True if some stdout line is a benchmark record (a JSON object with
    a metric field — not just any parseable JSON, so a stray numeric line
    can't count as one)."""
    for line in (out or "").strip().splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return True
    return False


def _probe_backend():
    """Initialise the default JAX backend in a throwaway subprocess.

    Returns None when init succeeds, else a short string saying why not
    (raise or hang). Run before the parent process touches jax.devices()
    so a hanging init cannot wedge the benchmark itself.

    Fast path: when the accelerator is the tunneled `axon` plugin (this
    dev environment), its transport is a `relay.py` process — if that
    process is GONE, backend init is known to hang until timeout, so skip
    the 240s probe and fail immediately with the diagnosis (the verify
    skill's documented root-cause check). On any real TPU host the axon
    plugin is absent and this shortcut never fires.
    """
    if "axon" in sys.modules:
        try:
            relay_alive = subprocess.run(
                ["pgrep", "-f", "relay.py"], capture_output=True, timeout=10
            ).returncode == 0
        except Exception:  # noqa: BLE001 — pgrep missing: fall through
            relay_alive = True
        if not relay_alive:
            return ("axon tunnel relay process is dead (backend init "
                    "would hang; see verify skill root-cause check)")
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=_PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return (f"backend init did not complete within "
                f"{_PROBE_TIMEOUT_S:.0f}s (hang)")
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()
        detail = tail[-1] if tail else f"rc={p.returncode}"
        return f"backend init failed: {detail}"[:300]
    return None


def _reexec_cpu(err):
    """Re-run this benchmark on the CPU backend, recording why. Exits.

    The child gets the CPU pin via _FORCE_CPU_ENV (config-update route, see
    top of file) and the init error via _INIT_ERR_ENV so the record it
    emits says the accelerator was unusable. If even the child produces no
    json line, emit a last-resort record here — under no circumstances may
    the driver see a run with no parseable record.
    """
    log(f"WARNING: accelerator backend unusable; re-running on CPU. ({err})")
    env = {**os.environ, _FORCE_CPU_ENV: "1", _INIT_ERR_ENV: err}
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, text=True, timeout=5400,
        )
        out, rc = p.stdout or "", p.returncode
    except subprocess.TimeoutExpired as te:
        out, rc = (te.stdout.decode() if isinstance(te.stdout, bytes)
                   else te.stdout) or "", -1
    sys.stdout.write(out)
    sys.stdout.flush()
    if not _has_record(out):
        print(json.dumps({
            "metric": "mnist60k_smo_train_time",
            "value": None,
            "unit": "s",
            "vs_baseline": None,
            "workload": {"gen": "mnist_like", "synthetic": True},
            "detail": {
                "error": "no backend produced a measurement",
                "init_fallback": err,
                "cpu_child_rc": rc,
                # no provenance_record() here: this branch exists because
                # backend init FAILED — touching jax again could hang
            },
        }))
    sys.exit(0)


def _should_probe():
    """Supervise only when this process could still touch an accelerator:
    not the forced-CPU child, not the supervised accelerator child itself,
    jax_platforms not already pinned to cpu (the test suite's conftest
    pins it before calling main() in-process), and backends not already
    initialised. Probing in the pinned/initialised cases would re-init
    the accelerator plugin in a throwaway subprocess and hang for the
    full timeout per call without affecting the run."""
    forced_cpu = os.environ.get(_FORCE_CPU_ENV) == "1"
    accel_child = os.environ.get(_ACCEL_CHILD_ENV) == "1"
    cpu_pinned = (getattr(jax.config, "jax_platforms", None) or "") == "cpu"
    try:
        # private API: if a JAX upgrade moves/renames it, conservatively
        # treat backends as uninitialised (probe anyway) so the insurance
        # chain survives internals churn instead of crashing pre-fallback
        from jax._src import xla_bridge

        initialised = bool(xla_bridge.backends_are_initialized())
    except Exception:
        initialised = False
    return (not forced_cpu and not accel_child and not cpu_pinned
            and not initialised)


def _run_supervised_accel():
    """Run the real accelerator measurement as a supervised child. Exits.

    The probe passing proves the backend was healthy moments ago, not that
    it stays healthy: a tunnel that wedges AFTER the probe would hang an
    in-process jax.devices()/compile/execute indefinitely — no exception
    to catch, no record emitted (the residual window of the probe-only
    design). Supervising the whole measurement in a child with a timeout
    closes it: any hang anywhere in the accelerator path degrades to the
    CPU re-exec instead of losing the headline.
    """
    env = {**os.environ, _ACCEL_CHILD_ENV: "1"}
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, text=True,
            timeout=_ACCEL_TIMEOUT_S,
        )
        out, rc = p.stdout or "", p.returncode
    except subprocess.TimeoutExpired as te:
        out = (te.stdout.decode() if isinstance(te.stdout, bytes)
               else te.stdout) or ""
        rc = None
    if rc == 0 and _has_record(out):
        sys.stdout.write(out)
        sys.stdout.flush()
        sys.exit(0)
    err = ("accelerator measurement hung "
           f"(no result after {_ACCEL_TIMEOUT_S:.0f}s)" if rc is None
           else f"accelerator measurement failed (rc={rc}, "
                f"record={_has_record(out)})")
    _reexec_cpu(err)  # exits


def _devices_or_fallback():
    """jax.devices() that degrades to a CPU re-exec instead of dying."""
    if _should_probe():
        err = _probe_backend()
        if err is not None:
            _reexec_cpu(err)  # exits
        _run_supervised_accel()  # exits
    try:
        return jax.devices()
    except Exception as e:  # noqa: BLE001 — init race after a good probe
        if os.environ.get(_FORCE_CPU_ENV) == "1":
            raise  # CPU itself broken: nothing lower; parent emits record
        if os.environ.get(_ACCEL_CHILD_ENV) == "1":
            # exit nonzero and let the SUPERVISING parent run the single
            # CPU fallback: a _reexec_cpu from in here would start a
            # full-size CPU measurement (timeout 5400s) inside the
            # parent's 1800s supervision window — the parent would kill
            # this child mid-measurement, orphan the CPU grandchild, and
            # then run a second CPU measurement contending with it
            raise
        _reexec_cpu(f"{type(e).__name__}: {e}"[:300])


def main():
    devices = _devices_or_fallback()
    log(f"devices: {devices}")
    init_fallback = os.environ.get(_INIT_ERR_ENV)
    if init_fallback:
        log(f"NOTE: degraded run — accelerator init failed upstream: "
            f"{init_fallback}")
    if os.environ.get("_TPUSVM_BENCH_SMOKE") == "1":
        # shrunken workload for fast end-to-end tests of the fallback
        # machinery in a REAL child process (tests/test_bench_fallback.py;
        # the in-process tests shrink by monkeypatching mnist_like instead)
        log("smoke workload (n=512, d=32)")
        wl = dict(n=512, d=32, noise=3.0, label_noise=0.005)
    else:
        log("generating synthetic MNIST-60k workload...")
        wl = dict(n=60000, d=784, noise=30.0, label_noise=0.005)
    X, Y = mnist_like(**wl)
    # record-level data provenance: this benchmark trains a SYNTHETIC
    # MNIST-shaped instance (egress-blocked environment, no real MNIST;
    # noise/label_noise calibrated so SV count and update count land in
    # the real workload's range — see the module docstring). The field
    # exists so the one JSON line a dashboard ingests can never be
    # mistaken for the reference's real-MNIST 0.9969/1548 constants.
    # Derived from the CANONICAL generator (not the patchable module
    # attribute above, which tests monkeypatch to shrink the workload)
    # so unspecified fields like seed track the real signature defaults.
    from benchmarks.common import workload_record
    from tpusvm.data.synthetic import mnist_like as _mnist_like_canonical

    workload = {**workload_record(_mnist_like_canonical, **wl),
                "calibration": "noise/label_noise tuned to real-MNIST "
                               "difficulty (SV count, update count)"}
    Xs = MinMaxScaler().fit_transform(X).astype(np.float32)
    Xd = jax.device_put(jnp.asarray(Xs))
    Yd = jax.device_put(jnp.asarray(Y))

    # max_iter is a SAFETY bound, not part of the stopping rule (the
    # reference iterates until the Keerthi criterion with no update cap);
    # the deeper CPU-fallback inner budget below legitimately spends ~146k
    # updates, so the old 100k default would truncate a converging run
    traced_kwargs = dict(C=10.0, gamma=0.00125, eps=1e-12, tau=1e-5,
                         max_iter=10**6)
    on_tpu = devices[0].platform == "tpu"
    # q/max_inner/wss tuned with benchmarks/probe_split.py on this workload;
    # wss=2 = second-order partner selection — implemented by BOTH inner
    # engines since round 4 (same stopping rule, ~25% fewer updates than
    # first-order on TPU, 23% on CPU).
    # max_inner is platform-conditional because the engines price inner
    # updates differently:
    #   - TPU (pallas kernel): 4096 measured ~11% faster than 2048; 8192
    #     was flat (over-optimising stale subproblems costs kernel time).
    #     Grid: benchmarks/results/probe_split_tpu_v5e.jsonl and its README
    #     row (q=1536 probed 3% faster once but with 21% more inner
    #     updates — inside noise, more latency exposure; not adopted).
    #   - CPU fallback (XLA loop): the O(n*d*q) outer contraction dominates
    #     on one core, so deeper subproblems that cut outer rounds win even
    #     at +90% updates: wss=2 grid (probe_cpu_fallback.jsonl round-4
    #     rows) measured 4096=38.5s / 8192=29.2s / 16384=27.0s /
    #     32768=24.0s (9 outers) in-session vs 47.2s for the round-3
    #     wss=1/4096 config — 2.0x, and 2.4x the reference GPU's 58.57s.
    # matmul_precision="default" (bf16 MXU passes) was evaluated and NOT
    # adopted: a CPU-emulated drift study (bf16-quantised inputs) converged
    # to the identical SV set but needed ~1.8x the outer rounds + all its
    # refine budget — roughly a wash net of the ~3x matmul speedup, with a
    # weaker convergence guarantee. It remains an opt-in
    # (tpusvm/solver/blocked.py matmul_precision).
    static_kwargs = dict(q=2048, max_outer=5000,
                         max_inner=4096 if on_tpu else 32768, wss=2,
                         accum_dtype=jnp.float64)
    # Tiny-shape kernel canary BEFORE the heavy compile (TPU only — off
    # TPU the solver's inner='auto' resolves to the XLA engine and the
    # canary could not affect the run): a Mosaic regression that compiles
    # but miscomputes or faults at runtime would otherwise burn the
    # unattended round's one heavy measurement. Each layout runs a q=256
    # subproblem twice — wss=1 checked against the XLA inner loop's
    # trajectory, and wss=2 (the mode the benchmark actually runs)
    # checked against the subproblem invariants (box feasibility,
    # sum(y*a)=0 conservation, dual ascent) since its trajectory
    # legitimately differs. q=256 and not 128: at q=128 the packed layout
    # degenerates to (R=1, L=128) — bitwise the flat layout — so a canary
    # there would test the same lowering twice and wave through a
    # multi-row regression (the exact class it exists to catch); 256 is
    # the smallest q where packed (R=2) genuinely exercises the multi-row
    # index mapping, row reshapes, and cross-sublane reductions. First
    # layout passing both runs is used; none passing degrades to the XLA
    # engine. The compile-failure chain below stays as the backstop for
    # the full-size q=2048 lowering.
    fallback = None
    # None = canary not applicable (non-TPU); True = the selected kernel
    # layout passed; False = the canary harness itself failed, so the
    # engine field describes an UNVETTED config
    canary_passed = None
    # off TPU the solver's inner='auto' resolves to the XLA engine
    engine = "pallas-packed" if on_tpu else "xla"
    if on_tpu:
        try:
            from tpusvm.ops.pallas.inner_smo import inner_smo_pallas
            from tpusvm.ops.rbf import rbf_cross
            from tpusvm.solver.blocked import _inner_smo

            rngc = np.random.default_rng(0)
            Xc = jnp.asarray(rngc.random((256, 8)), jnp.float32)
            yc_np = np.where(rngc.random(256) < 0.5, 1, -1)
            yc = jnp.asarray(yc_np, jnp.int32)
            Kc = rbf_cross(Xc, Xc, 0.5)
            a0c = jnp.zeros(256, jnp.float32)
            f0c = -yc.astype(jnp.float32)
            actc = jnp.ones(256, bool)
            a_ref = np.asarray(_inner_smo(Kc, yc, a0c, f0c, actc, 10.0,
                                          1e-12, 1e-5, 64)[0])
            Qc = np.asarray(Kc) * np.outer(yc_np, yc_np)
            picked = None
            for layout in ("packed", "flat"):
                try:
                    a_k = np.asarray(inner_smo_pallas(
                        Kc, yc, a0c, f0c, actc, 10.0, 1e-12, 1e-5,
                        max_inner=64, interpret=False, wss=1,
                        layout=layout,
                    )[0])
                    np.testing.assert_allclose(a_k, a_ref, atol=1e-3)
                    a_k2 = np.asarray(inner_smo_pallas(
                        Kc, yc, a0c, f0c, actc, 10.0, 1e-12, 1e-5,
                        max_inner=64, interpret=False, wss=2,
                        layout=layout,
                    )[0])
                    assert np.isfinite(a_k2).all()
                    assert (a_k2 >= -1e-6).all() and (a_k2 <= 10.0 + 1e-6).all()
                    assert abs(float(a_k2 @ yc_np)) < 1e-3
                    assert a_k2.sum() - 0.5 * a_k2 @ Qc @ a_k2 > 0.0
                    picked = layout
                    break
                except Exception as ce:  # noqa: BLE001 — any canary failure
                    msg = f"{type(ce).__name__}: {ce}"[:300]
                    log(f"WARNING: {layout}-layout kernel canary failed: "
                        f"{msg}")
                    fallback = (fallback + " | " if fallback else "") + \
                        f"{layout} canary: {msg}"
            if picked is None:
                log("WARNING: no kernel layout passed the canary; using "
                    "the XLA inner engine")
                # wss=2 stays: the XLA loop implements the same
                # second-order selection as the kernel (round 4)
                static_kwargs = dict(static_kwargs, inner="xla")
                engine = "xla"
                canary_passed = True  # the engine that runs IS vetted
            else:
                canary_passed = True
                if picked != "packed":
                    # pin inner explicitly alongside the layout: the
                    # solver REJECTS an active pallas_layout whose
                    # resolved engine is not pallas (shared
                    # flag-compatibility table) instead of silently
                    # ignoring it, and the canary has just vetted the
                    # pallas engine — on a real TPU inner='auto' resolves
                    # to pallas anyway, so this only makes the recorded
                    # config self-consistent
                    static_kwargs = dict(static_kwargs, pallas_layout=picked,
                                         inner="pallas")
                    engine = f"pallas-{picked}"
        except Exception as ce:  # noqa: BLE001 — canary harness broke
            log(f"WARNING: kernel canary harness failed; proceeding with "
                f"the tuned config unvetted. Full error:\n"
                f"{type(ce).__name__}: {ce}")
            fallback = ("canary harness failed (kernel unvetted): "
                        + f"{type(ce).__name__}: {ce}"[:300])
            canary_passed = False
        # fused f-update canary (round-4 adoption made the fused kernel
        # the TPU default, so it joins the "vet before the one heavy
        # measurement" club): tiny-shape fused contraction checked
        # against the XLA contraction it replaces. A compiles-but-
        # miscomputes Mosaic regression here would poison f and burn the
        # unattended headline with canary_passed=True — exactly the
        # class the inner-kernel canary exists to catch. Any failure
        # pins fused_fupdate=False for the run (recorded via
        # solver_config.fused_fupdate + the fallback note); it does not
        # touch canary_passed, which describes the inner engine.
        # Gated on the run's OWN fused resolution: when 'auto' already
        # resolves False for the actual shape/precision (bf16 matmuls,
        # VMEM-infeasible or unaligned q), the kernel cannot run in the
        # measurement, so a canary failure would only append a
        # degradation note and pin a flag that was never going to be
        # True — noise in the unattended record.
        from tpusvm.solver.blocked import resolve_fused_fupdate as _rff

        try:
            fused_would_run = _rff(
                Xd.shape[0], Xd.shape[1], q=static_kwargs["q"],
                fused=static_kwargs.get("fused_fupdate", "auto"),
                matmul_precision=static_kwargs.get("matmul_precision"),
                backend="tpu",  # we are inside the on_tpu branch
            )
        except Exception as ce:  # noqa: BLE001 — the 'auto' path imports
            # the fused kernel module (fused_feasible); a breakage there
            # must degrade to an unfused TPU run with a note, not crash
            # the healthy-TPU measurement into the CPU fallback
            msg = f"{type(ce).__name__}: {ce}"[:300]
            log(f"WARNING: fused resolution failed; pinning "
                f"fused_fupdate=False for this run: {msg}")
            fallback = (fallback + " | " if fallback else "") + \
                f"fused resolution: {msg}"
            static_kwargs = dict(static_kwargs, fused_fupdate=False)
            fused_would_run = False
        if not fused_would_run:
            log("fused f-update canary skipped: 'auto' already resolves "
                "fused OFF for this run's shape/precision")
        else:
            try:
                from tpusvm.ops.pallas.fused_fupdate import (
                    rbf_cross_matvec_pallas,
                )
                from tpusvm.ops.rbf import rbf_cross_matvec

                rngf = np.random.default_rng(1)
                Xf = jnp.asarray(rngf.random((384, 8)), jnp.float32)
                XBf = jnp.asarray(rngf.random((128, 8)), jnp.float32)
                cf = jnp.asarray(rngf.standard_normal(128), jnp.float32)
                got = np.asarray(rbf_cross_matvec_pallas(
                    Xf, XBf, cf, 0.5, interpret=False))
                want = np.asarray(rbf_cross_matvec(Xf, XBf, cf, 0.5))
                np.testing.assert_allclose(got, want, atol=1e-4)
            except Exception as ce:  # noqa: BLE001 — any canary failure
                msg = f"{type(ce).__name__}: {ce}"[:300]
                log(f"WARNING: fused f-update canary failed; pinning "
                    f"fused_fupdate=False for this run: {msg}")
                fallback = (fallback + " | " if fallback else "") + \
                    f"fused canary: {msg}"
                static_kwargs = dict(static_kwargs, fused_fupdate=False)

    # end-of-run timing goes through the shared obs render path (the same
    # three-line contract cli.py prints; single source: obs.report)
    from tpusvm.utils import PhaseTimer

    timer = PhaseTimer()
    log("compiling solver (AOT)...")
    # Insurance for the unattended round-end run: a Mosaic lowering
    # regression must degrade the headline, not lose it. Degradation
    # ladder: tuned config (fused f-update resolves 'auto', i.e. ON for
    # TPU at this shape) -> fused f-update off (same inner engine) ->
    # flat-layout inner kernel (the round-1 hardware-proven lowering,
    # fused off) -> XLA inner engine (always compiles, ~10x slower,
    # fused off). The JSON record gets each failure truncated to ~300
    # chars (Mosaic failures embed whole IR dumps and the output
    # contract is ONE parseable JSON line); the FULL text of every
    # failure goes to stderr.
    from tpusvm.solver.blocked import (
        resolve_fused_fupdate,
        resolve_solver_config,
    )

    # the fused-off rung exists only when rung 0 actually runs fused —
    # otherwise 'auto' already resolves False and the rung would retry
    # the identical failing config (doubling the failure wall-clock and
    # duplicating the error note)
    rung0_fused = resolve_fused_fupdate(
        Xd.shape[0], Xd.shape[1], q=static_kwargs["q"],
        fused=static_kwargs.get("fused_fupdate", "auto"),
        matmul_precision=static_kwargs.get("matmul_precision"),
    )
    base = (dict(static_kwargs, fused_fupdate=False) if rung0_fused
            else static_kwargs)
    ladder = [(static_kwargs, engine)]
    if rung0_fused:
        ladder.append((base, engine))
    if engine == "pallas-packed":
        ladder.append((dict(base, pallas_layout="flat"), "pallas-flat"))
    if engine != "xla":
        # the XLA rung must drop any active pallas_* flags: the solver now
        # REJECTS active kernel flags on a non-pallas engine (shared
        # flag-compatibility table, tpusvm.config.PALLAS_FLAG_RULES)
        # instead of silently ignoring them, so a canary-picked flat
        # layout must not ride along into the fallback config
        xla_kw = dict(base, inner="xla")
        for flag in PALLAS_FLAG_RULES:
            xla_kw.pop(flag, None)
        ladder.append((xla_kw, "xla"))
    with timer.phase("compile"):
        for i, (kw, eng) in enumerate(ladder):
            try:
                compiled = blocked_smo_solve.lower(
                    Xd, Yd, **traced_kwargs, **kw
                ).compile()
                static_kwargs, engine = kw, eng
                break
            except Exception as e:  # noqa: BLE001 — any lowering/compile
                e_full = f"{type(e).__name__}: {e}"
                fallback = (fallback + " | " if fallback else "") \
                    + e_full[:300]
                log(f"WARNING: the {eng} config (rung {i}: "
                    f"fused_fupdate={kw.get('fused_fupdate', 'auto')!r}, "
                    f"layout={kw.get('pallas_layout', 'packed')}) failed "
                    f"to compile at full size. Full error:\n{e_full}")
                if i == len(ladder) - 1:
                    # the always-compilable engine itself failed: nothing
                    # lower to fall to — surface the error rather than loop
                    raise
                log("WARNING: trying the next ladder rung")
    log(f"compile: {timer['compile']:.1f}s")

    # Effective config via the solver's own resolution rules (the shared
    # helper blocked_smo_solve itself resolves through), computed from the
    # FINAL static_kwargs — after any canary/compile fallback — so a
    # degraded record is self-describing: selection='auto' resolves by
    # backend (approx on TPU, exact elsewhere) and any canary/compile
    # fallback's engine change shows up here, not just as stderr text.
    eff_q, eff_inner, eff_wss, eff_selection = resolve_solver_config(
        Xd.shape[0],
        q=static_kwargs["q"],
        inner=static_kwargs.get("inner", "auto"),
        wss=static_kwargs.get("wss", 1),
        selection=static_kwargs.get("selection", "auto"),
    )
    eff_fused = resolve_fused_fupdate(
        Xd.shape[0], Xd.shape[1],
        q=static_kwargs["q"],
        fused=static_kwargs.get("fused_fupdate", "auto"),
        matmul_precision=static_kwargs.get("matmul_precision"),
    )

    # Force the H2D transfer of X/Y to COMPLETE before the timed region
    # (benchmarks.common.h2d_sync). The 188MB X upload otherwise lands
    # inside the first executable invocation and adds ~6.5s of development
    # SSH tunnel — not TPU DMA (188MB over a real TPU host's PCIe/DMA is
    # ~10ms). The reference's timer DOES include its own H->D copies
    # (cudaEventRecord at gpu_svm_main3.cu:524 precedes the memcpys at
    # :543-547) — but those are ~1.2GB over local PCIe, ~0.1s of its
    # 58.57s, a negligible fraction it pays and we don't; noted here rather
    # than hidden. Excluding the tunnel keeps the measurement about the
    # framework, not the dev harness.
    from benchmarks.common import h2d_sync

    h2d_sync(Xd, Yd)

    log("training (timed region)...")
    # NOTE: jax.block_until_ready returns early on this environment's
    # experimental axon TPU runtime; a device->host copy is the only reliable
    # completion barrier, so the timed region ends when alpha lands on host.
    with timer.phase("training"):
        res = compiled(Xd, Yd, **traced_kwargs)
        alpha_host = np.asarray(res.alpha)
    train_s = timer["training"]

    status = Status(int(res.status))
    n_iter = int(res.n_iter)
    n_outer = int(res.n_outer)
    n_sv = int((alpha_host > 1e-8).sum())
    # Achieved-HBM-bandwidth estimate, so the headline is explainable and
    # regressions diagnosable (is the solver still bandwidth-bound?). The
    # dominant traffic is one full f32 X stream per outer round — the
    # rbf_cross_matvec f-update reads all of X once; the q-row gathers,
    # K_BB, and the f/alpha vectors are second-order by comparison. This
    # UNDERCOUNTS (ignores those extras) and assumes no cache residency, so
    # treat it as a floor on achieved bandwidth.
    n, d = Xd.shape
    hbm_bytes = (n_outer + 1) * n * d * 4  # +1: the sq_norms pass
    hbm_gbps = hbm_bytes / train_s / 1e9
    # the 819 GB/s roofline is v5e-specific: report the fraction only when
    # actually running on a TPU so non-TPU result files aren't misleading
    peak_note = (
        f" ({hbm_gbps / V5E_PEAK_HBM_GBPS:.0%} of v5e peak)" if on_tpu else ""
    )
    log(
        f"status={status.name} updates={n_iter} outers={n_outer} "
        f"SVs={n_sv} b={float(res.b):.6f} train={train_s:.3f}s "
        f"~{hbm_gbps:.0f}GB/s streamed{peak_note}"
    )
    log(timer.report())  # the shared three-line contract (obs.report)
    if status != Status.CONVERGED:
        log("WARNING: solver did not converge; reporting anyway")

    from benchmarks.common import provenance_record

    print(
        json.dumps(
            {
                "metric": "mnist60k_smo_train_time",
                "value": round(train_s, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_GPU_60K_S / train_s, 2),
                # top-level on purpose: a dashboard ingesting only the
                # headline line still sees synthetic-vs-real provenance
                "workload": workload,
                # backend/version/host provenance so benchdiff can refuse
                # cross-backend comparisons (the r02-r05 CPU-fallback trap)
                "provenance": provenance_record(),
                "detail": {
                    "baseline": "reference GPU SMO 58.570s on MNIST-60k (B2)",
                    "status": status.name,
                    "iterations": n_iter,
                    "n_outer": n_outer,
                    "n_sv": n_sv,
                    # floor estimate: one X stream per outer round (see
                    # comment above); peak = 819 GB/s (TPU v5e HBM),
                    # reported only when running on a TPU
                    "hbm_gbps_est": round(hbm_gbps, 1),
                    "hbm_peak_fraction_est": round(
                        hbm_gbps / V5E_PEAK_HBM_GBPS, 3
                    ) if on_tpu else None,
                    "platform": devices[0].platform,
                    # which inner engine actually ran: "pallas-packed"
                    # (the tuned config), "pallas-flat", or "xla"
                    "engine": engine,
                    # the EFFECTIVE solver config this measurement ran
                    # (resolve_solver_config on the final static_kwargs):
                    # requested knobs can resolve differently — q clamps
                    # to n, selection='auto' resolves by backend — and a
                    # record must say what actually ran
                    "solver_config": {
                        "q": eff_q,
                        "inner": eff_inner,
                        "wss": eff_wss,
                        "selection": eff_selection,
                        "max_inner": static_kwargs["max_inner"],
                        "max_outer": static_kwargs["max_outer"],
                        # fused f-update contraction (round-4 adoption:
                        # 'auto' = on for TPU at this shape); False on a
                        # compile-fallback rung or off-TPU
                        "fused_fupdate": eff_fused,
                    },
                    # True: the engine above was canary-vetted (or is the
                    # reference XLA engine); False: the canary harness
                    # crashed and the engine ran UNVETTED; null: non-TPU
                    # run, canary not applicable
                    "canary_passed": canary_passed,
                    # non-null if any canary or compile fallback fired;
                    # records each failure (separately truncated)
                    "compile_fallback": fallback,
                    # non-null on a degraded run: the accelerator backend
                    # failed to initialise with this error and the
                    # measurement below ran on the CPU backend instead
                    "init_fallback": init_fallback,
                    # provenance for CPU measurements (a 41s CPU run on a
                    # 128-core host is not a 41s CPU run on a laptop)
                    "cpu_count": (os.cpu_count()
                                  if devices[0].platform == "cpu" else None),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
