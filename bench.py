#!/usr/bin/env python
"""Headline benchmark: MNIST-60k-shaped RBF SVM training on one TPU chip.

Prints ONE JSON line to stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Workload: the reference's headline configuration (SURVEY.md §6, B2) — a
60,000 x 784 one-vs-rest RBF SVM (gamma=0.00125, C=10, tau=1e-5) trained
to the reference's exact stopping criterion with the blocked working-set
solver (tpusvm.solver.blocked — the TPU-first redesign whose FLOPs ride
the MXU). Real MNIST CSVs are not available in this
environment (zero egress), so the workload is a deterministic synthetic
MNIST-shaped problem (tpusvm.data.mnist_like, noise=30, label_noise=0.005)
tuned to the same difficulty band as real MNIST: ~57k SMO iterations and
~2000 support vectors (vs. the reference's 1548 SVs; its iteration count is
unpublished).

Baseline: the reference's GPU SMO trains MNIST-60k in 58.570 s on one GPU
(report Table 1, BASELINE.md B2; 56.09x over its 3285.662 s serial run).
vs_baseline = 58.570 / our wall-clock, i.e. >1 means faster than the
reference's single-accelerator headline.

Measurement notes:
  - The solver is compiled ahead of time (jit .lower().compile()) and the
    timed region is pure on-device execution of the full training loop —
    matching the reference's timing, which also excludes I/O and starts
    after data load (gpu_svm_main3.cu:516 cudaEvent after read_CSV).
  - One measurement per process: repeated heavy invocations on this
    environment's tunneled TPU runtime occasionally fault the device; the
    driver runs bench.py in a fresh process. jax.block_until_ready returns
    early on this runtime, so timing runs to host materialisation of the
    result. See .claude/skills/verify/SKILL.md.
  - Mixed precision (float32 features/kernel rows, float64 f/alpha
    accumulators) — f32 alone livelocks on hard problems (Status.STALLED),
    f64-everywhere wastes HBM bandwidth; this matches the f64 reference's
    convergence behaviour at f32 speed.
"""

import json
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.data import MinMaxScaler, mnist_like  # noqa: E402
from tpusvm.solver.blocked import blocked_smo_solve  # noqa: E402
from tpusvm.status import Status  # noqa: E402

BASELINE_GPU_60K_S = 58.570  # BASELINE.md B2
# TPU v5e (v5 lite) peak HBM bandwidth, GB/s — the roofline the blocked
# solver's O(n*d) streams are limited by.
V5E_PEAK_HBM_GBPS = 819.0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    log(f"devices: {jax.devices()}")
    log("generating synthetic MNIST-60k workload...")
    X, Y = mnist_like(n=60000, d=784, noise=30.0, label_noise=0.005)
    Xs = MinMaxScaler().fit_transform(X).astype(np.float32)
    Xd = jax.device_put(jnp.asarray(Xs))
    Yd = jax.device_put(jnp.asarray(Y))

    traced_kwargs = dict(C=10.0, gamma=0.00125, eps=1e-12, tau=1e-5)
    # q/max_inner/wss tuned with benchmarks/probe_split.py on this workload;
    # wss=2 = second-order partner selection in the fused inner kernel
    # (same stopping rule, ~25% fewer updates than first-order).
    # max_inner=4096 (deeper subproblems per K-block) measured ~11% faster
    # than 2048 — fewer O(n*d*q) outer passes buy more cheap VMEM updates;
    # 8192 was flat vs 4096 (over-optimising stale subproblems). Grid +
    # pick rationale: benchmarks/results/probe_split_tpu_v5e.jsonl and its
    # README row (q=1536 probed 3% faster once but with 21% more inner
    # updates — inside noise, more latency exposure; not adopted).
    # matmul_precision="default" (bf16 MXU passes) was evaluated and NOT
    # adopted: a CPU-emulated drift study (bf16-quantised inputs) converged
    # to the identical SV set but needed ~1.8x the outer rounds + all its
    # refine budget — roughly a wash net of the ~3x matmul speedup, with a
    # weaker convergence guarantee. It remains an opt-in
    # (tpusvm/solver/blocked.py matmul_precision).
    static_kwargs = dict(q=2048, max_outer=5000, max_inner=4096, wss=2,
                         accum_dtype=jnp.float64)
    on_tpu = jax.devices()[0].platform == "tpu"
    # Tiny-shape kernel canary BEFORE the heavy compile (TPU only — off
    # TPU the solver's inner='auto' resolves to the XLA engine and the
    # canary could not affect the run): a Mosaic regression that compiles
    # but miscomputes or faults at runtime would otherwise burn the
    # unattended round's one heavy measurement. Each layout runs a q=128
    # subproblem twice — wss=1 checked against the XLA inner loop's
    # trajectory, and wss=2 (the mode the benchmark actually runs)
    # checked against the subproblem invariants (box feasibility,
    # sum(y*a)=0 conservation, dual ascent) since its trajectory
    # legitimately differs. First layout passing both is used; none
    # passing degrades to the XLA engine. The compile-failure chain below
    # stays as the backstop for the full-size q=2048 lowering.
    fallback = None
    # off TPU the solver's inner='auto' resolves to the XLA engine
    engine = "pallas-packed" if on_tpu else "xla"
    if on_tpu:
        try:
            from tpusvm.ops.pallas.inner_smo import inner_smo_pallas
            from tpusvm.ops.rbf import rbf_cross
            from tpusvm.solver.blocked import _inner_smo

            rngc = np.random.default_rng(0)
            Xc = jnp.asarray(rngc.random((128, 8)), jnp.float32)
            yc_np = np.where(rngc.random(128) < 0.5, 1, -1)
            yc = jnp.asarray(yc_np, jnp.int32)
            Kc = rbf_cross(Xc, Xc, 0.5)
            a0c = jnp.zeros(128, jnp.float32)
            f0c = -yc.astype(jnp.float32)
            actc = jnp.ones(128, bool)
            a_ref = np.asarray(_inner_smo(Kc, yc, a0c, f0c, actc, 10.0,
                                          1e-12, 1e-5, 64)[0])
            Qc = np.asarray(Kc) * np.outer(yc_np, yc_np)
            picked = None
            for layout in ("packed", "flat"):
                try:
                    a_k = np.asarray(inner_smo_pallas(
                        Kc, yc, a0c, f0c, actc, 10.0, 1e-12, 1e-5,
                        max_inner=64, interpret=False, wss=1,
                        layout=layout,
                    )[0])
                    np.testing.assert_allclose(a_k, a_ref, atol=1e-3)
                    a_k2 = np.asarray(inner_smo_pallas(
                        Kc, yc, a0c, f0c, actc, 10.0, 1e-12, 1e-5,
                        max_inner=64, interpret=False, wss=2,
                        layout=layout,
                    )[0])
                    assert np.isfinite(a_k2).all()
                    assert (a_k2 >= -1e-6).all() and (a_k2 <= 10.0 + 1e-6).all()
                    assert abs(float(a_k2 @ yc_np)) < 1e-3
                    assert a_k2.sum() - 0.5 * a_k2 @ Qc @ a_k2 > 0.0
                    picked = layout
                    break
                except Exception as ce:  # noqa: BLE001 — any canary failure
                    msg = f"{type(ce).__name__}: {ce}"[:300]
                    log(f"WARNING: {layout}-layout kernel canary failed: "
                        f"{msg}")
                    fallback = (fallback + " | " if fallback else "") + \
                        f"{layout} canary: {msg}"
            if picked is None:
                log("WARNING: no kernel layout passed the canary; using "
                    "the XLA inner engine")
                static_kwargs = dict(static_kwargs, inner="xla", wss=1)
                engine = "xla"
            elif picked != "packed":
                static_kwargs = dict(static_kwargs, pallas_layout=picked)
                engine = f"pallas-{picked}"
        except Exception as ce:  # noqa: BLE001 — canary harness broke
            log(f"WARNING: kernel canary harness failed; proceeding with "
                f"the tuned config unvetted. Full error:\n"
                f"{type(ce).__name__}: {ce}")
            fallback = ("canary harness failed (kernel unvetted): "
                        + f"{type(ce).__name__}: {ce}"[:300])

    class _AlreadyFailed(Exception):
        """Sentinel: the canary-selected flat layout failed at full size;
        retrying it would recompile the identical failing config."""

    log("compiling solver (AOT)...")
    t0 = time.perf_counter()
    try:
        compiled = blocked_smo_solve.lower(
            Xd, Yd, **traced_kwargs, **static_kwargs
        ).compile()
    except Exception as e:  # noqa: BLE001 — any lowering/compile failure
        # Insurance for the unattended round-end run: a Mosaic lowering
        # regression must degrade the headline, not lose it. Chain:
        # packed-layout kernel (tuned) -> flat-layout kernel (the round-1
        # hardware-proven lowering) -> XLA inner engine (always compiles,
        # ~10x slower). The JSON record gets each failure truncated to
        # ~300 chars (Mosaic failures embed whole IR dumps and the output
        # contract is ONE parseable JSON line); the FULL text of every
        # failure goes to stderr here.
        e_full = f"{type(e).__name__}: {e}"
        fallback = (fallback + " | " if fallback else "") + e_full[:300]
        log(f"WARNING: the {engine} config failed to compile at full "
            f"size. Full error:\n{e_full}")
        if engine == "xla":
            # the always-compilable engine itself failed: nothing lower
            # to fall to — surface the error rather than loop
            raise
        try:
            if engine == "pallas-flat":
                raise _AlreadyFailed from e
            log("WARNING: trying the flat-layout kernel")
            static_kwargs = dict(static_kwargs, pallas_layout="flat")
            compiled = blocked_smo_solve.lower(
                Xd, Yd, **traced_kwargs, **static_kwargs
            ).compile()
            engine = "pallas-flat"
        except Exception as e2:  # noqa: BLE001
            if not isinstance(e2, _AlreadyFailed):
                e2_full = f"{type(e2).__name__}: {e2}"
                log(f"WARNING: flat-layout kernel also failed. Full "
                    f"error:\n{e2_full}")
                fallback = f"{fallback} | {e2_full[:300]}"
            log("WARNING: falling back to inner='xla', wss=1")
            static_kwargs = dict(static_kwargs, inner="xla", wss=1)
            engine = "xla"
            compiled = blocked_smo_solve.lower(
                Xd, Yd, **traced_kwargs, **static_kwargs
            ).compile()
    log(f"compile: {time.perf_counter() - t0:.1f}s")

    # Force the H2D transfer of X/Y to COMPLETE before the timed region
    # (benchmarks.common.h2d_sync). The 188MB X upload otherwise lands
    # inside the first executable invocation and adds ~6.5s of development
    # SSH tunnel — not TPU DMA (188MB over a real TPU host's PCIe/DMA is
    # ~10ms). The reference's timer DOES include its own H->D copies
    # (cudaEventRecord at gpu_svm_main3.cu:524 precedes the memcpys at
    # :543-547) — but those are ~1.2GB over local PCIe, ~0.1s of its
    # 58.57s, a negligible fraction it pays and we don't; noted here rather
    # than hidden. Excluding the tunnel keeps the measurement about the
    # framework, not the dev harness.
    from benchmarks.common import h2d_sync

    h2d_sync(Xd, Yd)

    log("training (timed region)...")
    # NOTE: jax.block_until_ready returns early on this environment's
    # experimental axon TPU runtime; a device->host copy is the only reliable
    # completion barrier, so the timed region ends when alpha lands on host.
    t0 = time.perf_counter()
    res = compiled(Xd, Yd, **traced_kwargs)
    alpha_host = np.asarray(res.alpha)
    train_s = time.perf_counter() - t0

    status = Status(int(res.status))
    n_iter = int(res.n_iter)
    n_outer = int(res.n_outer)
    n_sv = int((alpha_host > 1e-8).sum())
    # Achieved-HBM-bandwidth estimate, so the headline is explainable and
    # regressions diagnosable (is the solver still bandwidth-bound?). The
    # dominant traffic is one full f32 X stream per outer round — the
    # rbf_cross_matvec f-update reads all of X once; the q-row gathers,
    # K_BB, and the f/alpha vectors are second-order by comparison. This
    # UNDERCOUNTS (ignores those extras) and assumes no cache residency, so
    # treat it as a floor on achieved bandwidth.
    n, d = Xd.shape
    hbm_bytes = (n_outer + 1) * n * d * 4  # +1: the sq_norms pass
    hbm_gbps = hbm_bytes / train_s / 1e9
    # the 819 GB/s roofline is v5e-specific: report the fraction only when
    # actually running on a TPU so non-TPU result files aren't misleading
    peak_note = (
        f" ({hbm_gbps / V5E_PEAK_HBM_GBPS:.0%} of v5e peak)" if on_tpu else ""
    )
    log(
        f"status={status.name} updates={n_iter} outers={n_outer} "
        f"SVs={n_sv} b={float(res.b):.6f} train={train_s:.3f}s "
        f"~{hbm_gbps:.0f}GB/s streamed{peak_note}"
    )
    if status != Status.CONVERGED:
        log("WARNING: solver did not converge; reporting anyway")

    print(
        json.dumps(
            {
                "metric": "mnist60k_smo_train_time",
                "value": round(train_s, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_GPU_60K_S / train_s, 2),
                "detail": {
                    "baseline": "reference GPU SMO 58.570s on MNIST-60k (B2)",
                    "status": status.name,
                    "iterations": n_iter,
                    "n_outer": n_outer,
                    "n_sv": n_sv,
                    # floor estimate: one X stream per outer round (see
                    # comment above); peak = 819 GB/s (TPU v5e HBM),
                    # reported only when running on a TPU
                    "hbm_gbps_est": round(hbm_gbps, 1),
                    "hbm_peak_fraction_est": round(
                        hbm_gbps / V5E_PEAK_HBM_GBPS, 3
                    ) if on_tpu else None,
                    "platform": jax.devices()[0].platform,
                    # which inner engine actually ran: "pallas-packed"
                    # (the tuned config), "pallas-flat", or "xla"
                    "engine": engine,
                    # non-null if any canary or compile fallback fired;
                    # records each failure (separately truncated)
                    "compile_fallback": fallback,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
