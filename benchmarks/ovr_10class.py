#!/usr/bin/env python
"""Full 10-class one-vs-rest MNIST-scale benchmark (BASELINE config 5).

The reference never ran this — its code trains exactly one one-vs-rest
digit — so there is no reference number; the natural yardstick is 10x its
single-binary result (the 10 problems are independent). One JSON line:

  {"n": ..., "classes": ..., "train_s": ..., "predict_s": ...,
   "accuracy": ..., "statuses": ...}

Usage:
  python benchmarks/ovr_10class.py                # 60k x 784, 10 classes
  python benchmarks/ovr_10class.py --smoke       # tiny, CPU-safe
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

from benchmarks.common import (  # noqa: E402
    emit,
    log,
    pin_platform,
    workload_record,
)

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=60000)
    ap.add_argument("--n-test", type=int, default=10000)
    ap.add_argument("--d", type=int, default=784)
    ap.add_argument("--gamma", type=float, default=0.00125)
    ap.add_argument("--solver", choices=["blocked", "pair"], default="blocked")
    # blocked-solver defaults = bench.py's TPU-tuned per-binary config
    # (each one-vs-rest class is the same 60k workload bench measures;
    # bench's CPU fallback additionally deepens max_inner to 32768 —
    # platform-conditional, not mirrored here); rows are self-describing
    # via the recorded solver_opts
    ap.add_argument("--q", type=int, default=2048)
    ap.add_argument("--max-inner", type=int, default=4096)
    ap.add_argument("--wss", type=int, default=2, choices=(1, 2))
    ap.add_argument("--selection", default="auto",
                    choices=("auto", "exact", "approx"))
    ap.add_argument("--class-parallel", action="store_true",
                    help="shard the class axis over the local device mesh "
                    "(pair solver only; BASELINE config 5's 'vmapped over "
                    "chips')")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.n_test, args.d = 2048, 512, 64
        args.gamma = 1.0 / args.d
    if args.class_parallel and args.solver != "pair":
        # validate BEFORE the (minutes-long at full size) dataset generation
        log("ERROR: --class-parallel requires --solver pair")
        return 2

    import jax.numpy as jnp  # noqa: E402

    from tpusvm.config import SVMConfig
    from tpusvm.data.synthetic import mnist_like_multiclass
    from tpusvm.models import OneVsRestSVC
    from tpusvm.status import Status

    log(f"devices: {jax.devices()}")
    total = args.n + args.n_test
    from tpusvm.data.synthetic import BENCH_NOISE_MULTICLASS

    wl = dict(n=total, d=args.d, noise=BENCH_NOISE_MULTICLASS)
    X, labels = mnist_like_multiclass(**wl)
    Xtr, ytr = X[: args.n], labels[: args.n]
    Xte, yte = X[args.n :], labels[args.n :]

    solver_opts = {}
    if args.solver == "blocked":
        solver_opts = dict(q=args.q, max_inner=args.max_inner, wss=args.wss,
                           selection=args.selection)
    elif any(
        getattr(args, k) != ap.get_default(k)
        for k in ("q", "max_inner", "wss", "selection")
    ):
        # compare against the PARSER defaults so the warning tracks them
        log("WARNING: --q/--max-inner/--wss/--selection are blocked-solver "
            "knobs; --solver pair ignores them")
    model = OneVsRestSVC(
        config=SVMConfig(gamma=args.gamma),  # other constants = reference
        accum_dtype=jnp.float64,
        solver=args.solver,
        solver_opts=solver_opts,
        class_parallel=args.class_parallel,
    )
    log("training 10 one-vs-rest SVMs...")
    # NOTE train_s comes from fit(), which times the whole training phase
    # INCLUDING the one-off jit compile and the H2D upload — unlike the
    # compile-excluded train numbers in bench.py / sweep_n.py. Recorded
    # as-is because the model API owns the timer; treat it as an upper
    # bound when comparing against the per-binary benchmarks.
    model.fit(Xtr, ytr)
    train_s = model.train_time_s_

    # serve-path latency: warm up compile + transfers on the same shapes,
    # then time a steady-state call (sweep_n.py methodology)
    model.predict(Xte)
    t0 = time.perf_counter()
    yp = model.predict(Xte)
    predict_s = time.perf_counter() - t0

    emit({
        "n": args.n,
        "d": args.d,
        # SYNTHETIC MNIST-shaped multiclass instance, not real MNIST;
        # derived from the generator call (n = train+test generated rows)
        "workload": workload_record(mnist_like_multiclass, **wl),
        "classes": len(model.classes_),
        "solver": args.solver,
        # requested blocked-solver knobs ({} for pair); the solver resolves
        # wss/selection by backend and alignment at run time — see
        # sweep_n.py's effective-config fields for the resolution rules
        "solver_opts": solver_opts,
        "train_s": round(train_s, 3),
        "predict_s": round(predict_s, 3),
        "accuracy": round(float((yp == yte).mean()), 4),
        "n_sv_union": int(model.X_sv_.shape[0]),
        "class_parallel": args.class_parallel,
        # the mesh fit() actually trained over (class_parallel only):
        # axes/shape say the effective process geometry of this row
        "mesh": (
            {k: v for k, v in model.class_mesh_.items() if k != "devices"}
            if model.class_mesh_ else None
        ),
        "statuses": [Status(int(s)).name for s in model.statuses_],
        "platform": jax.devices()[0].platform,
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
