"""Convergence-telemetry overhead: trace-on vs trace-off solve time.

The acceptance bar for the carry-resident convergence ring (ISSUE 5):
enabling `blocked_smo_solve(telemetry=T)` must cost <= 3% of solve time
on the midscale workload, AND be bit-transparent — identical alpha
bytes, b, status and update counts with the ring on or off. This
harness measures both arms AOT-compiled (the ring changes the compiled
program, so compile time is excluded from both sides, like every house
timing) and emits one JSONL record with the gates — the house
provenance style (workload_record, violations list, rc != 0 on any gate
failure).

Timing protocol: the arms are run INTERLEAVED (off/on per repeat) and
the per-arm time is the MIN across repeats — the standard
noise-rejection protocol for a host-timed CPU measurement where a
stray scheduler tick can cost more than the effect being measured.
Each timed run ends at host materialisation of alpha (the completion
barrier this environment's runtime requires; see benchmarks/common.py).

Usage: python benchmarks/telemetry_overhead.py [--smoke] [--n 4096]
           [--d 128] [--telemetry 256] [--repeats 5] [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

OVERHEAD_GATE = 0.03  # full-size runs only; --smoke checks identity gates


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run): bit-identity gates "
                    "only, no overhead floor")
    ap.add_argument("--n", type=int, default=4096, help="dataset rows")
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--seed", type=int, default=17, help="data seed")
    ap.add_argument("--telemetry", type=int, default=256,
                    help="ring size for the trace-on arm")
    ap.add_argument("--q", type=int, default=256)
    ap.add_argument("--max-inner", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=8,
                    help="interleaved timed repeats per arm (min is kept)")
    ap.add_argument("--jsonl", default=None,
                    help="also append the record to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.d, args.repeats = 512, 32, 2
        args.q, args.max_inner = 128, 128
        args.telemetry = 64

    import jax.numpy as jnp
    import numpy as np

    from benchmarks.common import h2d_sync, make_workload
    from tpusvm.data.synthetic import mnist_like
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.status import Status

    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE

    gen_kwargs = dict(n=args.n, d=args.d, seed=args.seed)
    # provenance records the generator call make_workload actually makes
    # (accuracy-calibrated recipe), not mnist_like's defaults
    wl_kwargs = dict(gen_kwargs, noise=BENCH_NOISE,
                     label_noise=BENCH_LABEL_NOISE)
    Xs, Y = make_workload(**gen_kwargs)
    Xd = jnp.asarray(Xs, jnp.float32)
    Yd = jnp.asarray(Y)
    h2d_sync(Xd, Yd)

    static = dict(q=args.q, max_inner=args.max_inner,
                  accum_dtype=jnp.float64)
    kwargs = dict(C=10.0, gamma=1.0 / args.d, tau=1e-5)

    log("compiling both arms (AOT)...")
    arms = {}
    for name, tele in (("off", 0), ("on", args.telemetry)):
        arms[name] = blocked_smo_solve.lower(
            Xd, Yd, telemetry=tele, **static, **kwargs
        ).compile()

    def timed(compiled):
        t0 = time.perf_counter()
        res = compiled(Xd, Yd, **kwargs)
        alpha = np.asarray(res.alpha)  # completion barrier
        return time.perf_counter() - t0, res, alpha

    # one untimed warm run per arm (first-call allocator noise), then the
    # interleaved timed repeats
    for name in ("off", "on"):
        timed(arms[name])
    times = {"off": [], "on": []}
    res_h = {}
    for _ in range(args.repeats):
        for name in ("off", "on"):
            dt, res, alpha = timed(arms[name])
            times[name].append(dt)
            res_h[name] = (res, alpha)

    t_off = min(times["off"])
    t_on = min(times["on"])
    overhead = (t_on - t_off) / t_off

    (res0, a0), (res1, a1) = res_h["off"], res_h["on"]
    bit_identical = (
        np.array_equal(a0, a1)
        and float(res0.b) == float(res1.b)
        and int(res0.status) == int(res1.status)
        and int(res0.n_iter) == int(res1.n_iter)
        and int(res0.n_outer) == int(res1.n_outer)
    )
    status = Status(int(res0.status))

    from tpusvm.obs.convergence import materialize

    conv = materialize(res1.telemetry)
    rounds = int(res1.n_outer)

    violations = []
    if not bit_identical:
        violations.append("telemetry arm is not bit-identical to off arm")
    if conv["rounds_recorded"] == 0:
        violations.append("telemetry ring recorded nothing")
    if not args.smoke and overhead > OVERHEAD_GATE:
        violations.append(
            f"overhead {overhead:.4f} exceeds the {OVERHEAD_GATE:.0%} gate"
        )

    record = {
        "bench": "telemetry_overhead",
        "workload": workload_record(mnist_like, **wl_kwargs),
        "n": args.n,
        "d": args.d,
        "telemetry": args.telemetry,
        "repeats": args.repeats,
        "t_off_s": round(t_off, 6),
        "t_on_s": round(t_on, 6),
        "overhead_frac": round(overhead, 6),
        "gate_frac": OVERHEAD_GATE,
        "status": status.name,
        "n_updates": int(res0.n_iter) - 1,
        "n_outer": rounds,
        "rounds_recorded": conv["rounds_recorded"],
        "ring_wrapped": bool(conv["wrapped"]),
        "final_gap": (None if len(conv["gap"]) == 0
                      or not np.isfinite(conv["gap"][-1])
                      else float(conv["gap"][-1])),
        "bit_identical": bit_identical,
        "platform": jax.devices()[0].platform,
        "smoke": bool(args.smoke),
        "violations": violations,
    }
    emit(record)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            f.write(json.dumps(record) + "\n")
    if violations:
        for v in violations:
            log(f"GATE FAILED: {v}")
        return 1
    log(f"telemetry overhead: {overhead:+.2%} "
        f"(off {t_off:.3f}s, on {t_on:.3f}s, {rounds} outer rounds)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
