"""Approximate-kernel scale bench: exact rbf vs rff vs nystrom at growing n.

ISSUE 13's acceptance harness. Three training arms run the SAME
bench-recipe workload (make_workload) at each n of a growing ladder —
the exact rbf blocked solver (the control whose cost superlinearity the
approx regime exists to escape), the rff-mapped and nystrom-mapped
solves (the identical dual SMO machinery routed through the linear
primal fast path over Phi(X)) — plus one STREAMED rff arm at the top n
(shards ingested to a temp dir, per-shard mapping in the prefetch hook,
the tpusvm.approx.primal epoch schedule; its row records the reader's
audited live-shard high-water mark). House timing protocol: one warm
run per arm so every jit bucket is compiled, then interleaved timed
repeats ending at host materialisation, min kept.

A second record family is the KERNEL-APPROXIMATION-ERROR PROBE:
max |Phi(a).Phi(b) - K(a,b)| over 2048 seeded row pairs for an rff D
ladder (and the nystrom arm's k) — the direct measurement that the map
error falls as D grows, committed alongside the timing rows so a map
regression (a bad omega draw path, a broken eigenvalue floor) shows up
as a number, not an accuracy mystery.

Gates (violations land in the summary row; non-zero exit):
  * every arm's solve terminates CONVERGED (the streamed primal arm may
    also plateau-CONVERGE; MAX_ITER there is a violation);
  * each approx arm's held-out accuracy within ACC_BAND of the exact
    arm's at the same n;
  * the rff probe errors are monotone non-increasing in D (5% slack for
    the sampling noise of the pair draw);
  * the streamed arm's live shards <= prefetch_depth + 1.

Usage: python benchmarks/approx_scale.py [--smoke] [--repeats 2]
           [--jsonl PATH]
Committed artifacts: benchmarks/results/approx_scale_cpu.jsonl (full),
benchmarks/results/approx_scale_smoke_cpu.jsonl (the CI benchdiff
baseline; `tpusvm benchdiff --level smoke` gates direction-only).
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

# held-out accuracy band of an approx arm vs the exact arm at the same
# n — the fuzz harness's corpus-calibrated band (fuzz_parity.py
# APPROX_ACC_BAND rationale)
ACC_BAND = 0.055
# slack on the "probe error falls with D" gate: the 2048-pair sample
# mean has ~5% max-statistic noise between adjacent D rungs
ERR_SLACK = 1.05


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes (the CI benchdiff baseline run)")
    ap.add_argument("--d", type=int, default=128,
                    help="feature count of the bench workload")
    ap.add_argument("--seed", type=int, default=587)
    ap.add_argument("--repeats", type=int, default=2,
                    help="interleaved timed repeats per arm (min kept)")
    ap.add_argument("--jsonl", default=None,
                    help="also append the records to this file")
    args = ap.parse_args(argv)

    import jax.numpy as jnp  # noqa: F401
    import numpy as np

    from benchmarks.common import make_workload
    from tpusvm.approx import build_map, kernel_approx_error
    from tpusvm.config import SVMConfig
    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE, \
        mnist_like
    from tpusvm.models import BinarySVC
    from tpusvm.stream import ingest_arrays, open_dataset

    if args.smoke:
        ns = [256, 512]
        rff_dim, landmarks, q = 512, 128, 128
        d_ladder = [128, 256, 512]
        n_test, args.repeats = 256, 1
        rows_per_shard, primal = 128, dict(primal_epochs=80,
                                           primal_batch=64)
    else:
        ns = [1024, 2048, 4096]
        rff_dim, landmarks, q = 2048, 256, 256
        d_ladder = [256, 512, 1024, 2048]
        n_test = 1024
        rows_per_shard, primal = 512, dict(primal_epochs=80,
                                           primal_batch=256)

    gamma = 0.00125 * 784 / args.d  # the bench recipe's width, d-scaled
    sink = open(args.jsonl, "a") if args.jsonl else None

    def put(rec):
        emit(rec)  # injects provenance centrally
        if sink is not None:
            import json

            print(json.dumps(rec), file=sink, flush=True)

    violations = []
    base_kw = dict(tau=1e-5, max_iter=50_000_000)
    wl_kwargs = dict(d=args.d, seed=args.seed, noise=BENCH_NOISE,
                     label_noise=BENCH_LABEL_NOISE)

    def arm_cfgs():
        return [
            ("exact-rbf", SVMConfig(C=10.0, gamma=gamma, **base_kw), {}),
            ("rff", SVMConfig(C=10.0, gamma=gamma, kernel="rff",
                              rff_dim=rff_dim, map_seed=args.seed,
                              **base_kw), {}),
            ("nystrom", SVMConfig(C=10.0, gamma=gamma, kernel="nystrom",
                                  landmarks=landmarks,
                                  map_seed=args.seed, **base_kw), {}),
        ]

    for n in ns:
        Xs, Y, Xt, Yt = make_workload(n, d=args.d, seed=args.seed,
                                      n_test=n_test)
        opts = dict(q=min(q, n), max_inner=1024, max_outer=50000)
        results = {}
        models = {}
        for arm, cfg, _ in arm_cfgs():
            models[arm] = lambda cfg=cfg: BinarySVC(
                config=cfg, solver_opts=dict(opts)).fit(Xs, Y)
            m = models[arm]()  # warm: compiles every bucket
            results[arm] = {"model": m, "t": float("inf")}
        for _ in range(args.repeats):
            for arm in results:
                t0 = time.perf_counter()
                m = models[arm]()
                # ending at host materialisation (train already ends at
                # the alpha device->host copy inside fit)
                results[arm]["t"] = min(results[arm]["t"],
                                        time.perf_counter() - t0)
                results[arm]["model"] = m
        acc_exact = results["exact-rbf"]["model"].score(Xt, Yt)
        for arm, cfg, _ in arm_cfgs():
            m = results[arm]["model"]
            acc = m.score(Xt, Yt) if arm != "exact-rbf" else acc_exact
            delta = round(acc_exact - acc, 6)
            status = m.status_.name
            if status != "CONVERGED":
                violations.append(f"{arm}@n={n}: {status}")
            if delta > ACC_BAND:
                violations.append(
                    f"{arm}@n={n}: accuracy_delta {delta} > {ACC_BAND}")
            put({
                "bench": "approx_scale", "arm": arm, "n": n, "d": args.d,
                "D": (m.sv_X_.shape[1] if arm != "exact-rbf" else args.d),
                "q": opts["q"], "smoke": bool(args.smoke),
                "status": status, "updates": int(m.n_iter_),
                "sv_count": int(m.n_support_),
                "train_s": round(results[arm]["t"], 4),
                "accuracy": round(acc, 6), "accuracy_delta": delta,
                "workload": workload_record(mnist_like, n=n + n_test,
                                            **wl_kwargs),
            })
        log(f"n={n}: exact {results['exact-rbf']['t']:.2f}s "
            f"rff {results['rff']['t']:.2f}s "
            f"nystrom {results['nystrom']['t']:.2f}s acc {acc_exact:.4f}")

    # ---------------------------------------------- streamed rff arm (top n)
    n_top = ns[-1]
    Xs, Y, Xt, Yt = make_workload(n_top, d=args.d, seed=args.seed,
                                  n_test=n_test)
    cfg = SVMConfig(C=10.0, gamma=gamma, kernel="rff", rff_dim=rff_dim,
                    map_seed=args.seed, **base_kw)
    with tempfile.TemporaryDirectory() as tmp:
        # make_workload already scaled Xs; the streamed model re-derives
        # the (identity-on-this-data) manifest scaler — harmless
        ingest_arrays(tmp, Xs, Y, rows_per_shard=rows_per_shard)
        ds = open_dataset(tmp)
        t_min, model = float("inf"), None
        for _ in range(max(1, args.repeats)):
            m = BinarySVC(config=cfg, solver_opts=dict(primal))
            t0 = time.perf_counter()
            m.fit_stream(ds)
            t_min = min(t_min, time.perf_counter() - t0)
            model = m
    acc = model.score(Xt, Yt)
    delta = round(float(results["exact-rbf"]["model"].score(Xt, Yt))
                  - acc, 6)
    live = int(model.stream_max_live_shards_)
    if model.status_.name != "CONVERGED":
        violations.append(f"rff-stream@n={n_top}: {model.status_.name}")
    if delta > ACC_BAND:
        violations.append(
            f"rff-stream@n={n_top}: accuracy_delta {delta} > {ACC_BAND}")
    if live > 3:
        violations.append(
            f"rff-stream@n={n_top}: {live} live shards > "
            "prefetch_depth + 1 = 3")
    put({
        "bench": "approx_scale", "arm": "rff-stream", "n": n_top,
        "d": args.d, "D": rff_dim, "smoke": bool(args.smoke),
        "status": model.status_.name, "updates": int(model.n_iter_),
        "train_s": round(t_min, 4), "accuracy": round(acc, 6),
        "accuracy_delta": delta, "max_live_shards": live,
    })
    log(f"rff-stream n={n_top}: {t_min:.2f}s acc {acc:.4f} "
        f"live_shards {live}")

    # -------------------------------------------- kernel-error probe ladder
    n_probe = min(2048, ns[-1])
    Xp = make_workload(n_probe, d=args.d, seed=args.seed + 1)[0]
    errs = []
    for D in d_ladder:
        fm = build_map(SVMConfig(C=10.0, gamma=gamma, kernel="rff",
                                 rff_dim=D, map_seed=args.seed),
                       n_features=args.d)
        err = kernel_approx_error(Xp, fm, gamma, seed=args.seed)
        errs.append(err)
        put({"bench": "approx_scale", "arm": "probe-rff", "n": n_probe,
             "d": args.d, "D": D, "smoke": bool(args.smoke),
             "kmax_err": round(err, 6)})
    fmn = build_map(SVMConfig(C=10.0, gamma=gamma, kernel="nystrom",
                              landmarks=landmarks, map_seed=args.seed),
                    X_scaled=Xp)
    errn = kernel_approx_error(Xp, fmn, gamma, seed=args.seed)
    put({"bench": "approx_scale", "arm": "probe-nystrom", "n": n_probe,
         "d": args.d, "D": landmarks, "smoke": bool(args.smoke),
         "kmax_err": round(errn, 6)})
    err_decreasing = all(b <= a * ERR_SLACK
                         for a, b in zip(errs, errs[1:]))
    if not err_decreasing:
        violations.append(f"probe-rff errors not decreasing in D: {errs}")
    log(f"probe: rff errs {[round(e, 4) for e in errs]} "
        f"nystrom@k={landmarks} {errn:.4f}")

    put({"bench": "approx_scale", "summary": True,
         "smoke": bool(args.smoke), "arms": ["exact-rbf", "rff",
                                             "nystrom", "rff-stream"],
         "d_ladder": d_ladder, "err_decreasing": bool(err_decreasing),
         "acc_band": ACC_BAND, "violations": violations})
    if sink is not None:
        sink.close()
    if violations:
        log(f"VIOLATIONS: {violations}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
