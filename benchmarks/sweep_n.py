#!/usr/bin/env python
"""Training-size sweep — the reference's gpu_svm4.sh experiment (B3).

The reference sweeps n in 10000..60000 on one GPU via SLURM array-style
re-launches (code/gpu_svm4.sh; gpu_svm_main4.cu takes argv[1] = n_limit) and
reports per-size train and predict seconds (report Table 2). This harness
reproduces that sweep on one TPU chip with the blocked working-set solver
and the on-device predictor, emitting one JSON line per size:

  {"n": ..., "train_s": ..., "predict_s": ..., "predict_all_n_s": ...,
   "vs_gpu_train": ..., "vs_gpu_predict_sv": ..., "vs_gpu_predict_all_n":
   ..., "status": ..., "n_sv": ...}

Predict-speedup methodology: predict_s times the SV-compacted serving path
(C15 semantics — sum over the n_sv support vectors only), while the
reference's per-size predict numbers come from its GPU all-n-train-points
kernel (C16) — algebraically identical scores but ~n/n_sv more FLOPs. So
vs_gpu_predict_sv mixes framework speed with an ~n/n_sv algorithmic factor;
vs_gpu_predict_all_n divides by predict_all_n_s (the same all-n semantics
on TPU) and is the like-for-like framework comparison.

Usage:
  python benchmarks/sweep_n.py                    # reference sizes
  python benchmarks/sweep_n.py --sizes 10000 20000
  python benchmarks/sweep_n.py --smoke            # tiny sizes, CPU-safe
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from benchmarks.common import (  # noqa: E402
    GPU_PREDICT_S,
    GPU_TRAIN_S,
    emit,
    h2d_sync,
    log,
    pin_platform,
    workload_record,
)

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)
from tpusvm.data import MinMaxScaler, mnist_like  # noqa: E402
from tpusvm.oracle.smo import get_sv_indices  # noqa: E402
from tpusvm.solver.blocked import (  # noqa: E402
    blocked_smo_solve,
    resolve_solver_config,
)
from tpusvm.solver.predict import predict as device_predict  # noqa: E402
from tpusvm.status import Status  # noqa: E402


def run_size(n, Xs, Y, Xt, Yt, solver_opts, gamma, all_n_predict=True,
             max_iter=10**6):
    # effective config from the solver's own resolution rules (shared
    # helper) so a result row cannot silently claim an engine/wss/selection
    # it did not run if those rules ever change
    q_eff, engine, eff_wss, eff_selection = resolve_solver_config(
        n, solver_opts["q"], wss=solver_opts["wss"],
        selection=solver_opts["selection"],
    )
    Xd = jax.device_put(jnp.asarray(Xs[:n]))
    Yd = jax.device_put(jnp.asarray(Y[:n]))
    # max_iter is a SAFETY bound, not part of the stopping rule (bench.py
    # carries the same note): the blocked default of 1e5 total updates is
    # comfortable at the reference sizes but the beyond-60k sweep
    # legitimately spends more — the first 120k-480k CPU capture came
    # back MAX_ITER at ~1.05e5 updates across all three sizes, and the
    # recipe's convergence tail keeps growing with n (240k ran a full 1e6
    # without closing the strict Keerthi gap on one core). Beyond-60k
    # captures should pass --max-iter 10000000 where the platform can
    # afford it (TPU: minutes); a MAX_ITER row still records accuracy.
    traced = dict(C=10.0, gamma=gamma, eps=1e-12, tau=1e-5,
                  max_iter=max_iter)

    compiled = blocked_smo_solve.lower(Xd, Yd, **traced, **solver_opts).compile()
    # the upload is the dev tunnel, not TPU DMA — keep it out of the timer
    h2d_sync(Xd, Yd)
    t0 = time.perf_counter()
    res = compiled(Xd, Yd, **traced)
    alpha = np.asarray(res.alpha)  # host materialisation = barrier
    train_s = time.perf_counter() - t0

    # predict over the COMPACTED SV set — the framework's real serving path
    # (C15 semantics, solver/predict.py; models.BinarySVC predicts the same
    # way). The reference's per-size predict numbers come from its GPU
    # all-n-train-points kernel (C16) — algebraically identical scores,
    # ~n/n_sv times more FLOPs.
    sv = get_sv_indices(alpha)  # canonical SV threshold, same as n_sv below
    Xsv = jax.device_put(jnp.asarray(Xs[:n][sv]))
    Ysv = jax.device_put(jnp.asarray(Y[:n][sv]))
    asv = jax.device_put(jnp.asarray(alpha[sv], Xd.dtype))
    Xtd = jax.device_put(jnp.asarray(Xt))
    pred_fn = jax.jit(
        lambda Xq, Xs_, Ys_, as_: device_predict(
            Xq, Xs_, Ys_, as_, res.b.astype(Xd.dtype), gamma=gamma,
        )
    )
    # keep and call the compiled executable — jit's own dispatch cache is
    # not populated by .lower().compile(), so calling pred_fn would retrace
    # inside the timed region
    pred_exe = pred_fn.lower(Xtd, Xsv, Ysv, asv).compile()
    h2d_sync(Xtd, Xsv, Ysv, asv)
    t0 = time.perf_counter()
    yp = np.asarray(pred_exe(Xtd, Xsv, Ysv, asv))
    predict_s = time.perf_counter() - t0

    # like-for-like timing vs the reference's GPU predict (C16): sum over
    # ALL n train points, zeros included — same FLOP count as the baseline.
    # Skippable for big-n CPU runs (O(m*n*d) on one host core is ~13 min
    # at n=480k — pure harness wall-clock, no signal off-TPU).
    predict_all_n_s = None
    if all_n_predict:
        ad = jax.device_put(jnp.asarray(alpha, Xd.dtype))
        pred_all_exe = pred_fn.lower(Xtd, Xd, Yd, ad).compile()
        h2d_sync(ad)
        t0 = time.perf_counter()
        yp_all = np.asarray(pred_all_exe(Xtd, Xd, Yd, ad))
        predict_all_n_s = time.perf_counter() - t0
        # the two paths are algebraically identical but reduce in
        # different orders/sizes, so near-boundary points may flip sign
        # within f32 noise
        mismatch = int((yp_all != yp).sum())
        if mismatch:
            log(f"note: {mismatch} test points flip sign between "
                "SV-compacted and all-n predict (f32 accumulation-order "
                "noise)")

    # Roofline attribution (same model as tpu_capture_r4/ROOFLINE.md): the
    # solver's dominant HBM traffic is one full f32 X stream per outer
    # round (the (n,d)x(d,q) f-update contraction). v5e HBM peak 819 GB/s.
    # At the reference's n=60k this sits near 1% (latency-bound on the
    # sequential inner loop); the extended sizes exist to show it climbing
    # out of that regime.
    outers = int(res.n_outer) if hasattr(res, "n_outer") else None
    # the 819 GB/s peak is TPU v5e HBM: the estimate is meaningless for a
    # CPU run (pin_platform makes those a supported path), so gate on the
    # backend and record which platform the row ran on either way
    hbm_frac = None
    if outers and train_s > 0 and jax.default_backend() == "tpu":
        est_bytes = outers * n * Xs.shape[1] * 4
        hbm_frac = round(est_bytes / train_s / 819e9, 4)

    return {
        "n": n,
        "platform": jax.default_backend(),
        "train_s": round(train_s, 4),
        "hbm_peak_fraction_est": hbm_frac,
        "predict_s": round(predict_s, 4),
        "predict_all_n_s": (round(predict_all_n_s, 4)
                            if predict_all_n_s is not None else None),
        "accuracy": float((yp == Yt).mean()),
        "n_sv": int(len(get_sv_indices(alpha))),
        "iterations": int(res.n_iter),
        # the bound the run was configured with, so a MAX_ITER row
        # self-describes which ceiling (1e6 default / 1e7 TPU capture)
        # it hit — same convention as the effective-config fields
        "max_iter": max_iter,
        "status": Status(int(res.status)).name,
        # effective solver config via blocked.resolve_solver_config — the
        # solver's own resolution, not a re-implementation
        "q": q_eff,
        "inner_engine": engine,
        "wss": eff_wss,
        "selection": eff_selection,
        "vs_gpu_train": round(GPU_TRAIN_S[n] / train_s, 2) if n in GPU_TRAIN_S else None,
        # SV-compacted serving path vs the reference's all-n GPU kernel:
        # includes an ~n/n_sv fewer-FLOPs factor on top of framework speed
        "vs_gpu_predict_sv": round(GPU_PREDICT_S[n] / predict_s, 2) if n in GPU_PREDICT_S else None,
        # same all-n semantics as the baseline: the framework comparison
        "vs_gpu_predict_all_n": (
            round(GPU_PREDICT_S[n] / predict_all_n_s, 2)
            if n in GPU_PREDICT_S and predict_all_n_s is not None else None),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", type=int, nargs="+",
                    default=[10000, 20000, 30000, 40000, 50000, 60000])
    ap.add_argument("--n-test", type=int, default=10000)
    ap.add_argument("--d", type=int, default=784)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes for a fast functional check")
    ap.add_argument("--q", type=int, default=2048,
                    help="working-set size (default = bench.py's tuned "
                    "value; clamps to n at small sizes)")
    ap.add_argument("--gamma", type=float, default=0.00125,
                    help="RBF width (reference MNIST value); scaled to ~1/d in --smoke")
    ap.add_argument("--max-inner", type=int, default=4096,
                    help="inner budget (default = bench.py's TPU-tuned "
                    "value)")
    ap.add_argument("--wss", type=int, default=2, choices=(1, 2),
                    help="inner partner selection (default 2 = "
                    "second-order, bench.py's tuned value; both engines "
                    "implement it since round 4)")
    ap.add_argument("--selection", default="auto",
                    choices=("auto", "exact", "approx"),
                    help="outer working-set selection engine")
    ap.add_argument("--max-iter", type=int, default=10**6,
                    help="total-update safety bound (NOT part of the "
                    "stopping rule); raise to 1e7 for beyond-60k sizes "
                    "on platforms that can afford it")
    ap.add_argument("--skip-all-n-predict", action="store_true",
                    help="skip the all-n-train-points predict timing "
                    "(the reference-comparison row); use for big-n CPU "
                    "runs where the O(m*n*d) single-core pass is pure "
                    "harness wall-clock")
    args = ap.parse_args(argv)

    if args.smoke:
        args.sizes = [512, 1024]
        args.n_test = 256
        args.d = 64
        # gamma=0.00125 is tuned for d=784 in [0,1]; at small d the kernel
        # degenerates to ~1 everywhere, so keep gamma*d roughly constant
        args.gamma = 1.0 / args.d

    log(f"devices: {jax.devices()}")
    n_max = max(args.sizes)
    log(f"generating workload (n={n_max + args.n_test}, d={args.d})...")
    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE

    X, Y = mnist_like(n=n_max + args.n_test, d=args.d,
                      noise=BENCH_NOISE, label_noise=BENCH_LABEL_NOISE)
    sc = MinMaxScaler().fit(X[:n_max])  # reference: scale with TRAIN min/max
    Xs = sc.transform(X[:n_max]).astype(np.float32)
    Xt = sc.transform(X[n_max:]).astype(np.float32)
    Yt = Y[n_max:]

    # q is clamped to n inside blocked_smo_solve
    solver_opts = dict(q=args.q, max_outer=5000, max_inner=args.max_inner,
                       accum_dtype=jnp.float64, wss=args.wss,
                       selection=args.selection)
    # every row self-describes its data provenance: these are SYNTHETIC
    # mnist_like instances, not real MNIST (the reference's 0.9969/1548
    # constants are real-MNIST and must not be conflated with these rows).
    # Derived from the generator call so it cannot drift from the data.
    workload = workload_record(mnist_like, n=n_max + args.n_test, d=args.d,
                               noise=BENCH_NOISE,
                               label_noise=BENCH_LABEL_NOISE)
    for n in args.sizes:
        log(f"--- n = {n} ---")
        row = run_size(n, Xs, Y[:n_max], Xt, Yt, solver_opts, args.gamma,
                       all_n_predict=not args.skip_all_n_predict,
                       max_iter=args.max_iter)
        # keep the GENERATOR'S n in the record: mnist_like is not
        # prefix-stable in n (per-class allocation and the final
        # permutation both depend on it), so overriding n with the trained
        # prefix size would describe a generator call that produces
        # DIFFERENT data than what was trained (ADVICE r5). n_train is the
        # prefix of that instance this row actually trained on.
        row["workload"] = dict(workload, n_train=n)
        emit(row)
    return 0


if __name__ == "__main__":
    sys.exit(main())
