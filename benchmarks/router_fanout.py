"""Router fan-out under concurrent load: steady state vs mid-run outage.

The routing tier (tpusvm/router/) promises that a replica outage is
absorbed by failover — clients see latency, never errors. This harness
measures that promise with an in-process two-replica fleet behind a
real Router:

  arm "steady"    both replicas stay up; baseline throughput/latency
                  and the invariant failovers == 0;
  arm "failover"  the replica every "m" request PREFERS (first in HRW
                  placement order) goes dark after a quarter of the
                  load; the gate is **lost_responses == 0** with
                  failovers > 0 (`failover_ok`) — the outage was both
                  real (forwards met it) and invisible (every client
                  got a bitwise-correct score).

The poller is deliberately slow to mark replicas down, so the outage
is met by forward failures (the failover path), not by admission
quietly excluding the dark replica first. `tpusvm benchdiff` gates
lost_responses/failover_ok exactly and the counter/timing columns
directionally (SCHEMA_RULES["router_fanout"]).

Usage:
  python benchmarks/router_fanout.py [--smoke] [--jsonl OUT.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def run_arm(arm, urls, frontends, Xq, ref, threads, requests, failures):
    """One load arm against a FRESH router (per-arm counters)."""
    import numpy as np

    from tpusvm.obs.registry import MetricsRegistry
    from tpusvm.router import Router, RouterConfig
    from tpusvm.serve.http import stop_http_server

    # slow poller: 0.9s of grace before a dark replica leaves admission,
    # so the outage below is absorbed by failover, not admission; a
    # PRIVATE registry keeps each arm's counters from bleeding into the
    # next (default_registry() is process-global)
    router = Router(RouterConfig(
        replicas=tuple(urls), replication=2, seed=3,
        poll_interval_s=0.3, down_after=3, forward_timeout_s=15.0),
        registry=MetricsRegistry(), log_fn=lambda m: None)
    router.start()
    dark = urls.index(router.replica_set.placement("m")[0])
    bad, lat_ms = [], []
    lock = threading.Lock()

    def metric(name):
        return sum(m["value"] for m
                   in router._registry.snapshot()["metrics"]
                   if m["name"] == name)

    def client(t):
        mine = []
        for i in range(requests):
            idx = (t + i) % len(Xq)
            body = json.dumps(
                {"instances":
                 [np.asarray(Xq[idx], float).tolist()]}).encode()
            t0 = time.perf_counter()
            code, data, _ra = router.forward("m", body)
            dt = (time.perf_counter() - t0) * 1e3
            if code == 429:
                time.sleep(0.05)
                continue
            if code != 200:
                with lock:
                    bad.append(("code", code, data[:120]))
                continue
            s = json.loads(data)["scores"][0]
            if isinstance(s, list):
                s = s[0]
            if s != ref[idx]:
                with lock:
                    bad.append(("torn", idx, s))
                continue
            mine.append(dt)
        with lock:
            lat_ms.extend(mine)

    try:
        workers = [threading.Thread(target=client, args=(t,))
                   for t in range(threads)]
        t_start = time.perf_counter()
        for w in workers:
            w.start()
        if arm == "failover":
            # cut the cord only once a quarter of the load is through —
            # wall-clock sleeps race ~2ms in-process forwards
            target = (threads * requests) // 4
            deadline = time.monotonic() + 60.0
            while metric("router.requests") < target \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            stop_http_server(frontends[dark][0])
        for w in workers:
            w.join(120.0)
        wall_s = time.perf_counter() - t_start
        failovers = metric("router.failovers")
        if bad:
            failures.append(f"{arm}: lost/torn responses: {bad[:5]} "
                            f"({len(bad)} total)")
        if arm == "failover" and not failovers:
            failures.append("failover arm never exercised failover "
                            "(router.failovers == 0)")
        if arm == "steady" and failovers:
            failures.append(f"steady arm failed over {int(failovers)} "
                            "times with every replica up")
        p = np.percentile(np.asarray(lat_ms), [50, 99]) if lat_ms \
            else [float("nan")] * 2
        return {
            "arm": arm,
            "requests": int(metric("router.requests")),
            "lost_responses": len(bad),
            "failovers": int(failovers),
            "retries": int(metric("router.retries")),
            "no_replica": int(metric("router.no_replica")),
            "failover_ok": not bad and (failovers > 0
                                        if arm == "failover"
                                        else failovers == 0),
            "qps": len(lat_ms) / max(wall_s, 1e-9),
            "p50_ms": float(p[0]),
            "p99_ms": float(p[1]),
        }
    finally:
        router.close()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--jsonl", default=None)
    ap.add_argument("--threads", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None)
    args = ap.parse_args()

    from benchmarks.common import emit, log, pin_platform

    pin_platform()
    import jax.numpy as jnp
    import numpy as np

    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.http import make_http_server, start_http_thread

    threads = args.threads or (4 if args.smoke else 8)
    requests = args.requests or (40 if args.smoke else 150)

    X, Y = rings(n=240, seed=2)
    log("training the served model ...")
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float32).fit(X, Y)
    Xq, _ = rings(n=16, seed=3)

    out = open(args.jsonl + ".tmp", "w") if args.jsonl else None

    def row(rec):
        rec = {"bench": "router_fanout", "smoke": bool(args.smoke),
               "replicas": 2, "threads": threads,
               "n": threads * requests, **rec}
        emit(rec)
        if out:
            json.dump(rec, out)
            out.write("\n")

    failures = []
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "m.npz")
        model.save(path)
        servers, frontends = [], []
        try:
            for _ in range(2):
                srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
                srv.load_model("m", path)
                srv.warmup()
                httpd = make_http_server(srv, port=0)
                srv.attach_http(httpd, start_http_thread(httpd))
                host, port = httpd.server_address[:2]
                servers.append(srv)
                frontends.append((httpd, host, port))
            urls = [f"http://{h}:{p}" for _, h, p in frontends]
            ref, _ = servers[0].predict_direct("m", Xq)
            ref = [float(v) for v in np.asarray(ref).ravel()]

            # steady first: the failover arm leaves a replica dark
            for arm in ("steady", "failover"):
                log(f"arm {arm}: {threads} threads x {requests} "
                    f"requests over 2 replicas ...")
                rec = run_arm(arm, urls, frontends, Xq, ref,
                              threads, requests, failures)
                log(f"arm {arm}: {rec['requests']} forwards, "
                    f"{rec['failovers']} failovers, "
                    f"{rec['lost_responses']} lost, "
                    f"qps {rec['qps']:.0f}, p99 {rec['p99_ms']:.2f}ms")
                row(rec)
        finally:
            for srv in servers:
                srv.close()
    if out:
        out.close()
        os.replace(args.jsonl + ".tmp", args.jsonl)
    if failures:
        for f in failures:
            log(f"ROUTER FANOUT GATE FAILED: {f}")
        return 1
    log("router fanout gate ok: outage absorbed with zero lost "
        "responses, steady arm failover-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
