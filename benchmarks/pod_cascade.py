#!/usr/bin/env python
"""Out-of-core pod cascade vs the in-memory cascade: parity + cost.

The pod tier's acceptance harness: the same rings workload is trained
two ways per (topology, P) cell —

  inmem  ``BinarySVC.fit_cascade`` with every row materialized up front
         (the shard_map cascade's host fallback on plain CPU jax)
  pod    ``BinarySVC.fit_pod`` over P worker PROCESSES, each streaming
         ONLY its manifest shards (tpusvm.pod) — nothing holds the full
         array, residency is bounded by the reader's prefetch window

with HARD parity gates (the whole point of the pod tier: going
out-of-core must cost zero model quality):

  * sv_parity / alpha_parity / b_parity: the pod fit reproduces the
    in-memory cascade bit-for-bit — same SV-ID set, byte-identical
    alpha vector over that set, bitwise-equal b;
  * accuracy: held-out accuracy equal across arms (implied by the
    bitwise gates, kept as an independent end-to-end check);
  * rows_ok: the leaf partition conserves rows (sum over workers == n);
  * max_live_shards: every worker's reader stayed within
    prefetch_depth + 1 resident shards (the bounded-RSS contract);

plus the cost axis benchdiff tracks release-over-release: pod wall
clock per cell and its overhead ratio over the in-memory arm (worker
processes + sockets are pure overhead at benchmark scale; the ratio is
the price of the out-of-core capability and must not silently grow).

Timing rows keep the MIN over --repeats interleaved passes; benchdiff
gates them at --level full only (Rule.timing) so the committed smoke
baseline stays machine-portable.

Usage:
  python benchmarks/pod_cascade.py --smoke --jsonl out.jsonl
  python benchmarks/pod_cascade.py --workers 2,4 --repeats 3
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import emit, log, pin_platform

pin_platform()

import numpy as np  # noqa: E402

from tpusvm.config import CascadeConfig, SVMConfig  # noqa: E402
from tpusvm.data import rings  # noqa: E402
from tpusvm.models import BinarySVC  # noqa: E402
from tpusvm.stream.format import ingest_arrays  # noqa: E402

PREFETCH_DEPTH = 2  # fit_pod default; the residency gate derives from it


def _fit_inmem(X, Y, cfg, cc):
    model = BinarySVC(cfg, solver="pair")
    t0 = time.perf_counter()
    model.fit_cascade(X, Y, cc)
    return model, time.perf_counter() - t0


def _fit_pod(data_dir, cfg, cc):
    model = BinarySVC(cfg, solver="pair")
    t0 = time.perf_counter()
    model.fit_pod(data_dir, cc, prefetch_depth=PREFETCH_DEPTH)
    return model, time.perf_counter() - t0


def _sv_key(model):
    ids = np.asarray(model.sv_ids_)
    order = np.argsort(ids)
    alpha = np.asarray(model.sv_alpha_)[order]
    return (set(int(i) for i in ids),
            alpha.tobytes(),
            float(np.asarray(model.b_)))


def run(args) -> int:
    n = 192 if args.smoke else args.n
    workers = [int(w) for w in args.workers.split(",")]
    repeats = 1 if args.smoke else args.repeats
    topologies = (["tree", "star"] if args.topology == "both"
                  else [args.topology])
    cfg = SVMConfig(C=args.C, gamma=args.gamma, max_rounds=args.max_rounds)

    X, Y = rings(n=n + args.n_test, seed=args.seed)
    Xtr, Ytr = X[:n], Y[:n]
    Xte, Yte = X[n:], Y[n:]
    d = int(X.shape[1])

    rows, violations = [], []
    with tempfile.TemporaryDirectory(prefix="pod_cascade_bench_") as tmp:
        data_dir = os.path.join(tmp, "ds")
        ingest_arrays(data_dir, Xtr, Ytr,
                      rows_per_shard=args.rows_per_shard)

        for topo in topologies:
            for P in workers:
                cc = CascadeConfig(n_shards=P,
                                   sv_capacity=args.sv_capacity,
                                   topology=topo)
                best = {}   # arm -> (train_s, model)
                for _ in range(repeats):  # interleave arms, keep min
                    for arm, fit in (("inmem", None), ("pod", None)):
                        if arm == "inmem":
                            m, dt = _fit_inmem(Xtr, Ytr, cfg, cc)
                        else:
                            m, dt = _fit_pod(data_dir, cfg, cc)
                        if arm not in best or dt < best[arm][0]:
                            best[arm] = (dt, m)
                im_s, im = best["inmem"]
                pod_s, pod = best["pod"]
                cell = f"{topo}/P={P}"
                log(f"pod_cascade {cell}: inmem {im_s:.2f}s, "
                    f"pod {pod_s:.2f}s, {len(pod.sv_ids_)} SVs, "
                    f"{pod.cascade_rounds_} rounds")

                im_ids, im_alpha, im_b = _sv_key(im)
                pd_ids, pd_alpha, pd_b = _sv_key(pod)
                sv_parity = pd_ids == im_ids
                alpha_parity = pd_alpha == im_alpha
                b_parity = pd_b == im_b
                acc_im = float(im.score(Xte, Yte))
                acc_pod = float(pod.score(Xte, Yte))
                live = int(pod.stream_max_live_shards_)
                rows_ok = sum(pod.pod_worker_rows_) == n
                if not rows_ok:
                    violations.append(
                        f"{cell}: leaf partition lost rows "
                        f"({sum(pod.pod_worker_rows_)} != {n})")

                if not sv_parity:
                    violations.append(
                        f"{cell}: pod SV-ID set diverged from in-memory "
                        f"cascade ({len(pd_ids)} vs {len(im_ids)} SVs)")
                elif not alpha_parity:
                    violations.append(
                        f"{cell}: pod alpha bytes differ on an identical "
                        f"SV-ID set")
                if not b_parity:
                    violations.append(
                        f"{cell}: pod b={pd_b!r} != inmem b={im_b!r}")
                if acc_pod != acc_im:
                    violations.append(
                        f"{cell}: held-out accuracy diverged "
                        f"({acc_pod} vs {acc_im})")
                if live > PREFETCH_DEPTH + 1:
                    violations.append(
                        f"{cell}: a worker held {live} live shards, over "
                        f"the prefetch_depth+1={PREFETCH_DEPTH + 1} bound")
                for arm, m, dt in (("inmem", im, im_s), ("pod", pod, pod_s)):
                    if m.status_.name != "CONVERGED":
                        violations.append(
                            f"{cell}: {arm} arm ended {m.status_.name}")
                    row = {
                        "bench": "pod_cascade", "arm": arm,
                        "topology": topo, "P": P, "n": n, "d": d,
                        "smoke": bool(args.smoke),
                        "converged": m.status_.name == "CONVERGED",
                        "sv_count": len(m.sv_ids_),
                        "rounds": int(m.cascade_rounds_),
                        "accuracy": acc_im if arm == "inmem" else acc_pod,
                        "train_s": round(dt, 4),
                        "rows_per_s": round(n / dt, 1),
                    }
                    if arm == "pod":
                        row.update({
                            "sv_parity": sv_parity and alpha_parity,
                            "b_parity": b_parity,
                            "rows_ok": rows_ok,
                            "max_live_shards": live,
                            "pod_overhead_x": round(pod_s / im_s, 2),
                        })
                    rows.append(row)

    rows.append({
        "bench": "pod_cascade", "summary": True,
        "n": n, "d": d, "smoke": bool(args.smoke),
        "cells": len(topologies) * len(workers),
        "violations": violations,
    })

    out = open(args.jsonl, "a") if args.jsonl else None
    for row in rows:
        emit(row)  # prints to stdout, injects provenance in place
        if out:
            out.write(json.dumps(row, sort_keys=True) + "\n")
    if out:
        out.close()

    for v in violations:
        log(f"GATE FAILED: {v}")
    return 1 if violations else 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI shape: n=192, one timing pass per arm")
    ap.add_argument("--n", type=int, default=512,
                    help="training rows (smoke pins 192)")
    ap.add_argument("--n-test", type=int, default=128,
                    help="held-out rows for the accuracy gate")
    ap.add_argument("--workers", default="2,4",
                    help="comma-separated worker-process sweep")
    ap.add_argument("--topology", choices=["tree", "star", "both"],
                    default="both")
    ap.add_argument("--rows-per-shard", type=int, default=24)
    ap.add_argument("--sv-capacity", type=int, default=128)
    ap.add_argument("--C", type=float, default=10.0)
    ap.add_argument("--gamma", type=float, default=10.0)
    ap.add_argument("--max-rounds", type=int, default=12)
    ap.add_argument("--repeats", type=int, default=2,
                    help="interleaved timing passes, min kept (smoke: 1)")
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--jsonl", help="append result rows to this file")
    args = ap.parse_args()
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
