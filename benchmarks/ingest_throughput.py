"""Ingest + prefetch throughput for the out-of-core pipeline (tpusvm.stream).

Two numbers the stream layer stands on:

  - INGEST rate: CSV -> sharded dataset (streamed blocks, manifest stats +
    checksums computed per shard), in rows/s. This is the one-time cost of
    making a dataset a first-class on-disk artifact.
  - PREFETCH gain: batches/s of a ShardReader-fed consumer (background IO
    overlapping a fixed per-batch compute) vs. the same consumer doing
    cold synchronous shard loads. With compute >= IO per batch the reader
    should hide nearly all IO; the record carries both rates and the
    ratio, plus the reader's max_live_shards so the residency bound is
    part of the committed evidence.

Emits ONE JSON line (house provenance style: workload_record, explicit
platform), plus a summary gate: rc != 0 if the reader round-trip dropped
rows or the residency bound was violated — so a regression cannot commit a
plausible-looking curve.

Usage: python benchmarks/ingest_throughput.py [--smoke] [--n N] [--d D]
           [--rows-per-shard R] [--batch-size B] [--compute-ms MS]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import numpy as np  # noqa: E402


def _cold_batches(ds, batch_size):
    """Synchronous baseline: load each shard on the consumer thread, then
    re-chunk — the exact work ShardReader.batches does, minus the overlap."""
    rx = ry = None
    for i in range(ds.n_shards):
        X, Y = ds.load_shard(i)
        if rx is not None:
            X = np.concatenate([rx, X])
            Y = np.concatenate([ry, Y])
            rx = ry = None
        n_full = len(X) // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield X[s:s + batch_size], Y[s:s + batch_size]
        if n_full < len(X):
            rx, ry = X[n_full:].copy(), Y[n_full:].copy()
    if rx is not None:
        yield rx, ry


def _consume(batches, compute_s):
    """Drain a batch stream with a fixed per-batch 'compute' (sleep: the
    stand-in for device work, which releases the GIL exactly like a real
    dispatch would). Returns (n_batches, n_rows, elapsed_s)."""
    t0 = time.perf_counter()
    nb = rows = 0
    for X, _ in batches:
        time.sleep(compute_s)
        nb += 1
        rows += len(X)
    return nb, rows, time.perf_counter() - t0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run)")
    ap.add_argument("--n", type=int, default=16384, help="dataset rows")
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--seed", type=int, default=11, help="data seed")
    ap.add_argument("--rows-per-shard", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--prefetch-depth", type=int, default=2)
    ap.add_argument("--compute-ms", type=float, default=2.0,
                    help="simulated per-batch consumer compute")
    ap.add_argument("--jsonl", metavar="PATH",
                    help="also append the record to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.d = 1024, 16
        args.rows_per_shard, args.batch_size = 128, 64
        args.compute_ms = 1.0

    from tpusvm.data import mnist_like, write_csv
    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE
    from tpusvm.stream import ShardReader, ingest_csv, open_dataset

    gen_kwargs = dict(n=args.n, d=args.d, seed=args.seed,
                      noise=BENCH_NOISE, label_noise=BENCH_LABEL_NOISE)
    X, Y = mnist_like(**gen_kwargs)

    violations = []
    with tempfile.TemporaryDirectory() as tmp:
        csv_path = os.path.join(tmp, "data.csv")
        log(f"writing {args.n} x {args.d} CSV ...")
        write_csv(csv_path, X, Y)

        log("ingesting ...")
        t0 = time.perf_counter()
        manifest = ingest_csv(os.path.join(tmp, "ds"), csv_path,
                              rows_per_shard=args.rows_per_shard)
        ingest_s = time.perf_counter() - t0
        ds = open_dataset(os.path.join(tmp, "ds"))

        compute_s = args.compute_ms / 1000.0
        log("cold read ...")
        cold_nb, cold_rows, cold_s = _consume(
            _cold_batches(ds, args.batch_size), compute_s)
        log("prefetch read ...")
        reader = ShardReader(ds, prefetch_depth=args.prefetch_depth)
        pre_nb, pre_rows, pre_s = _consume(
            reader.batches(args.batch_size), compute_s)

        if pre_rows != ds.n_rows or cold_rows != ds.n_rows:
            violations.append(
                f"row drop: cold {cold_rows} / prefetch {pre_rows} "
                f"vs {ds.n_rows}")
        if reader.max_live_shards > args.prefetch_depth + 1:
            violations.append(
                f"residency: {reader.max_live_shards} > "
                f"{args.prefetch_depth + 1}")

    record = {
        "bench": "ingest_throughput",
        "workload": workload_record(mnist_like, **gen_kwargs),
        "platform": "cpu",
        "rows": args.n,
        "d": args.d,
        "rows_per_shard": args.rows_per_shard,
        "n_shards": len(manifest.shards),
        "batch_size": args.batch_size,
        "prefetch_depth": args.prefetch_depth,
        "compute_ms": args.compute_ms,
        "ingest_s": round(ingest_s, 4),
        "ingest_rows_per_s": round(args.n / ingest_s, 1),
        "cold_batches_per_s": round(cold_nb / cold_s, 2),
        "prefetch_batches_per_s": round(pre_nb / pre_s, 2),
        "prefetch_speedup": round(cold_s / pre_s, 4),
        "max_live_shards": int(reader.max_live_shards),
        "violations": violations,
    }
    emit(record)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            f.write(json.dumps(record) + "\n")
    if violations:
        log(f"GATES FAILED: {violations}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
