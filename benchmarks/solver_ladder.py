"""The solver speed ladder: f32 control vs shrink / +cache / +bf16 rungs.

ISSUE 9's acceptance harness: every rung of the active-set/precision
ladder is measured END-TO-END against the same f32/no-shrink control on
the bench-recipe workload (make_workload), with the house timing
protocol (warm run first so every jit bucket is compiled, then timed
runs ending at host materialisation; min over repeats), and the
reference's parity criterion asserted per rung (same SV set within a
tau-band flip allowance, b within the oracle-parity band, CONVERGED).

Rungs (each a complete solver config, recorded per row):
  f32           blocked_smo_solve, full-f32 contraction — the control
  shrink        + active-set shrinking (solver/shrink.py): work scales
                with the live set, not n
  shrink_cache  + K-row LRU cache (same q, krow_cache=4q slots): rounds
                whose MOVED members are all cached skip the X stream.
                Hit rates are workload-regime-dependent — high on
                long-tail small-q solves (the smoke shape), low at the
                full CPU bench shape — the row records them honestly
  shrink_bf16   + bf16_f32 contraction (bf16 operands, f32 accumulate;
                un-shrink rebuilds revalidate every claim). NOTE: the
                MXU-throughput win is TPU-only — CPU XLA emulates
                bfloat16, so on the CPU backend this rung documents
                parity, not speed.

Gates (full level; --smoke keeps correctness gates only):
  * every rung CONVERGED;
  * SV-set flips vs control <= max(2, |SV|/25) and |b - b_ctl| <= 1e-3
    (the cross-engine band tests/test_blocked.py uses);
  * best rung speedup: >= 2.0x on the TPU backend (the ROADMAP "Raw
    solver speed" target — the rungs are THROUGHPUT features: bf16 MXU
    passes, VMEM-resident cache rows, contraction-bound shrinking) and
    >= 1.0x (the ladder must not LOSE) on CPU, where the honest
    ceiling is lower: this container's single emulating core is
    latency-bound on the driver's segment syncs and has no bf16 units,
    so the committed CPU rows are PARITY + direction evidence, and the
    2x claim is re-verified on hardware (same discipline as the r02-r05
    CPU-fallback lesson: never let a CPU number impersonate a TPU one).

Usage: python benchmarks/solver_ladder.py [--smoke] [--n 8192]
           [--d 256] [--q 256] [--repeats 2] [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

SPEEDUP_GATE_TPU = 2.0  # the ROADMAP target, on the backend it names
SPEEDUP_GATE_CPU = 1.0  # CPU floor: the ladder must never LOSE


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run): parity gates only, "
                    "no speedup floor")
    ap.add_argument("--n", type=int, default=8192, help="dataset rows")
    ap.add_argument("--d", type=int, default=256)
    ap.add_argument("--seed", type=int, default=587, help="data seed")
    ap.add_argument("--q", type=int, default=256)
    ap.add_argument("--max-inner", type=int, default=2048)
    ap.add_argument("--repeats", type=int, default=2,
                    help="timed repeats per rung (min is kept)")
    ap.add_argument("--jsonl", default=None,
                    help="also append the records to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.d = 768, 32
        args.q, args.max_inner = 64, 256
        args.repeats = 1

    import jax.numpy as jnp
    import numpy as np

    from benchmarks.common import h2d_sync, make_workload
    from tpusvm.data.synthetic import (
        BENCH_LABEL_NOISE,
        BENCH_NOISE,
        mnist_like,
    )
    from tpusvm.oracle.smo import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.solver.predict import decision_function
    from tpusvm.solver.shrink import shrinking_blocked_solve
    from tpusvm.status import Status

    n_test = 1024 if not args.smoke else 256
    gen_kwargs = dict(n=args.n, d=args.d, seed=args.seed)
    wl_kwargs = dict(gen_kwargs, noise=BENCH_NOISE,
                     label_noise=BENCH_LABEL_NOISE)
    Xs, Y, Xt, Yt = make_workload(**gen_kwargs, n_test=n_test)
    Xd = jnp.asarray(Xs, jnp.float32)
    Yd = jnp.asarray(Y)
    h2d_sync(Xd, Yd)

    gamma = 0.00125 * 784 / args.d  # the bench recipe's width, d-scaled
    base = dict(C=10.0, gamma=gamma, tau=1e-5,
                accum_dtype=jnp.float64, max_outer=50000,
                max_iter=50_000_000)
    shr = dict(shrink_every=8, shrink_stable=3,
               shrink_min=max(64, args.n // 16))

    rungs = {
        "f32": lambda: blocked_smo_solve(
            Xd, Yd, q=args.q, max_inner=args.max_inner, **base),
        "shrink": lambda: shrinking_blocked_solve(
            Xd, Yd, q=args.q, max_inner=args.max_inner, **shr, **base),
        "shrink_cache": lambda: shrinking_blocked_solve(
            Xd, Yd, q=args.q, max_inner=args.max_inner,
            krow_cache=max(4 * args.q, 1024), **shr, **base),
        "shrink_bf16": lambda: shrinking_blocked_solve(
            Xd, Yd, q=args.q, max_inner=args.max_inner,
            matmul_precision="bf16_f32", **shr, **base),
    }

    # warm every rung first (compiles every jit bucket each driver will
    # touch), then INTERLEAVE the timed repeats — this host's throughput
    # drifts (shared machine), and interleaving spreads the drift across
    # every rung instead of biasing whichever ran last (the
    # telemetry_overhead protocol); per-rung time is the min over repeats
    for rung, fn in rungs.items():
        log(f"warming {rung}...")
        fn()
    times = {rung: [] for rung in rungs}
    results = {}
    for _ in range(args.repeats):
        for rung, fn in rungs.items():
            t0 = time.perf_counter()
            res = fn()
            np.asarray(res.alpha)  # completion barrier
            times[rung].append(time.perf_counter() - t0)
            results[rung] = res

    records = []
    violations = []
    ctl = {}
    for rung in rungs:
        res, train_s = results[rung], min(times[rung])
        alpha = np.asarray(res.alpha)
        status = Status(int(res.status))
        sv = get_sv_indices(alpha)
        coef = jnp.asarray(alpha[sv] * np.asarray(Y)[sv], jnp.float32)
        scores = decision_function(
            jnp.asarray(Xt, jnp.float32), Xd[jnp.asarray(sv)], coef,
            jnp.asarray(float(res.b), jnp.float32), gamma=gamma)
        acc = float((np.where(np.asarray(scores) > 0, 1, -1) == Yt).mean())
        rec = {
            "bench": "solver_ladder",
            "rung": rung,
            "workload": workload_record(mnist_like, **wl_kwargs),
            "n": args.n, "d": args.d, "q": args.q,
            "train_s": round(train_s, 6),
            "updates": int(res.n_iter) - 1,
            "n_outer": int(res.n_outer),
            "status": status.name,
            "sv_count": int(len(sv)),
            "b": float(res.b),
            "accuracy": round(acc, 6),
            "smoke": bool(args.smoke),
        }
        if res.cache_hits is not None:
            total = int(res.cache_hits) + int(res.cache_misses)
            rec["cache_hits"] = int(res.cache_hits)
            rec["cache_misses"] = int(res.cache_misses)
            rec["cache_hit_rate"] = round(
                int(res.cache_hits) / max(1, total), 6)
        if rung == "f32":
            ctl = {"t": train_s, "sv": set(sv.tolist()), "b": float(res.b),
                   "acc": acc}
            rec["speedup_vs_control"] = 1.0
        else:
            rec["speedup_vs_control"] = round(ctl["t"] / train_s, 4)
            flips = len(ctl["sv"] ^ set(sv.tolist()))
            rec["sv_flips_vs_control"] = flips
            rec["b_delta_vs_control"] = abs(float(res.b) - ctl["b"])
            if flips > max(2, len(ctl["sv"]) // 25):
                violations.append(
                    f"{rung}: {flips} SV flips vs control exceeds the "
                    "cross-engine band")
            if rec["b_delta_vs_control"] > 1e-3:
                violations.append(
                    f"{rung}: |b - b_ctl| = {rec['b_delta_vs_control']:g} "
                    "exceeds 1e-3")
        if status != Status.CONVERGED:
            violations.append(f"{rung}: terminated {status.name}")
        records.append(rec)

    best = max((r for r in records if r["rung"] != "f32"),
               key=lambda r: r["speedup_vs_control"])
    gate = (SPEEDUP_GATE_TPU if jax.default_backend() == "tpu"
            else SPEEDUP_GATE_CPU)
    if not args.smoke and best["speedup_vs_control"] < gate:
        violations.append(
            f"best rung {best['rung']} speedup "
            f"{best['speedup_vs_control']:.2f}x is under the "
            f"{gate}x {jax.default_backend()} gate")
    summary = {
        "bench": "solver_ladder",
        "summary": True,
        "n": args.n, "d": args.d, "q": args.q,
        "control_train_s": round(ctl["t"], 6),
        "best_rung": best["rung"],
        "best_speedup": best["speedup_vs_control"],
        "speedup_gate": gate if not args.smoke else None,
        "smoke": bool(args.smoke),
        "violations": violations,
    }
    records.append(summary)
    for rec in records:
        emit(rec)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    if violations:
        for v in violations:
            log(f"GATE FAILED: {v}")
        return 1
    log(f"solver ladder: best rung {best['rung']} at "
        f"{best['speedup_vs_control']:.2f}x over the f32 control "
        f"({ctl['t']:.3f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
