"""Cold-vs-warm tune sweep: measuring the warm-start iteration savings.

The tune acceptance bar (ISSUE 3): on the committed benchmark grid, the
warm-started grid search must spend >= 30% fewer total SMO alpha updates
than a cold-start sweep of the SAME grid on the SAME folds, while agreeing
with it on the winning (C, gamma) exactly and on every point's CV accuracy
within 1e-6 (the warm seed changes the optimisation trajectory, never the
optimum the stopping rule accepts). This harness runs both arms and emits
one JSONL row per grid point (cold vs warm update counts, the per-point
saving, both CV accuracies) plus a summary row with the gates — the house
provenance style (workload_record, violations list, rc != 0 on any gate
failure).

The workload is the MNIST-shaped synthetic family at a reduced
(n=768, d=64) shape: big enough that SMO update counts are in the tens of
thousands per arm (the savings signal is about active-set transfer, which
a toy 2-D problem with a handful of SVs cannot exhibit), small enough to
run on CPU in CI time. The grid is 5x5 multiplicative 2x steps bracketing
the reference's (C=10, gamma≈1/d) operating point — fine enough steps that
adjacent points share most of their active set, which is precisely the
regime warm-starting exploits (and how a real refinement sweep is shaped).

Usage: python benchmarks/tune_sweep.py [--smoke] [--n 768] [--d 64]
           [--folds 3] [--C-grid LIST] [--gamma-grid LIST] [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402

SAVINGS_GATE = 0.30  # full-size runs only; --smoke checks agreement gates
CV_TOL = 1e-6


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run): agreement gates "
                    "only, no savings floor")
    ap.add_argument("--n", type=int, default=768, help="dataset rows")
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--seed", type=int, default=11, help="data seed")
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--fold-seed", type=int, default=1)
    ap.add_argument("--C-grid", dest="C_grid",
                    default="2.5,5,10,20,40")
    ap.add_argument("--gamma-grid",
                    default="0.004,0.008,0.016,0.031,0.0625")
    ap.add_argument("--tau", type=float, default=1e-5)
    ap.add_argument("--jsonl", default=None,
                    help="also append rows to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.d, args.folds = 320, 16, 2
        args.C_grid, args.gamma_grid = "5,10", "0.03,0.06"

    from tpusvm.config import SVMConfig
    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE, mnist_like
    from tpusvm.tune import TuneConfig, make_grid, tune

    gen_kwargs = dict(n=args.n, d=args.d, seed=args.seed,
                      noise=BENCH_NOISE, label_noise=BENCH_LABEL_NOISE)
    X, Y = mnist_like(**gen_kwargs)
    grid = make_grid([float(v) for v in args.C_grid.split(",")],
                     [float(v) for v in args.gamma_grid.split(",")])
    base = SVMConfig(tau=args.tau)

    def arm(warm: bool):
        cfg = TuneConfig(folds=args.folds, seed=args.fold_seed,
                         warm_start=warm)
        return tune(X, Y, grid, cfg, base=base)

    log(f"tune_sweep: n={args.n} d={args.d} folds={args.folds} "
        f"grid={grid.shape[0]}x{grid.shape[1]}")
    cold = arm(False)
    log(f"cold arm: {cold.total_updates} updates, "
        f"winner C={cold.winner['C']:g} gamma={cold.winner['gamma']:g}")
    warm = arm(True)
    log(f"warm arm: {warm.total_updates} updates, "
        f"winner C={warm.winner['C']:g} gamma={warm.winner['gamma']:g}")

    sink = open(args.jsonl, "a") if args.jsonl else None

    def row(rec):
        emit(rec)
        if sink:
            sink.write(json.dumps(rec) + "\n")

    base_rec = {
        "bench": "tune_sweep",
        "workload": workload_record(mnist_like, **gen_kwargs),
        "folds": args.folds,
        "fold_seed": args.fold_seed,
        "tau": args.tau,
        "platform": jax.default_backend(),
    }

    max_cv_diff = 0.0
    for pc, pw in zip(cold.points, warm.points):
        assert (pc["C"], pc["gamma"]) == (pw["C"], pw["gamma"])
        cv_diff = abs(pc["cv_accuracy"] - pw["cv_accuracy"])
        max_cv_diff = max(max_cv_diff, cv_diff)
        saving = (1.0 - pw["n_updates"] / pc["n_updates"]
                  if pc["n_updates"] else 0.0)
        row({
            **base_rec, "C": pc["C"], "gamma": pc["gamma"],
            "cold_updates": pc["n_updates"],
            "warm_updates": pw["n_updates"],
            "saving": round(saving, 4),
            "cold_cv": pc["cv_accuracy"], "warm_cv": pw["cv_accuracy"],
            "warm_seeded": pw["warm_seeded"],
            "sv_count": pc["sv_count"],
        })

    total_saving = 1.0 - warm.total_updates / cold.total_updates
    same_winner = (cold.winner["C"] == warm.winner["C"]
                   and cold.winner["gamma"] == warm.winner["gamma"])
    violations = []
    if not same_winner:
        violations.append("winner_mismatch")
    if max_cv_diff > CV_TOL:
        violations.append("cv_accuracy_drift")
    if not args.smoke and total_saving < SAVINGS_GATE:
        violations.append("savings_below_gate")
    row({
        **base_rec, "summary": True,
        "cold_total_updates": cold.total_updates,
        "warm_total_updates": warm.total_updates,
        "total_saving": round(total_saving, 4),
        "savings_gate": None if args.smoke else SAVINGS_GATE,
        "same_winner": same_winner,
        "winner": warm.winner,
        "max_cv_diff": max_cv_diff,
        "cold_wall_s": round(cold.wall_s, 2),
        "warm_wall_s": round(warm.wall_s, 2),
        "violations": violations,
    })
    if sink:
        sink.close()
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
