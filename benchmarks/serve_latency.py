"""Closed-loop load generator for tpusvm.serve: throughput vs latency.

The serving acceptance bar (ISSUE 2): under >= 8 concurrent client threads
the micro-batched server must sustain >= 3x the sequential
one-request-at-a-time path, bit-identical scores, zero errors, zero
post-warm-up recompiles. This harness measures the whole curve: for each
offered concurrency (closed-loop client threads), achieved QPS, client
latency percentiles, batch occupancy, and the compile-cache counters —
JSONL rows in the house provenance style (workload_record, one row per
level, a summary row last).

The workload is the MNIST-shaped synthetic binary model (the bench
recipe): serving economics only show up when per-row kernel work dominates
per-request dispatch overhead, so a toy 2-D model would measure Python
overhead, not batching (see tests/test_serve.py's throughput test note).

Usage: python benchmarks/serve_latency.py [--smoke] [--n 4096] [--d 784]
           [--duration 2.0] [--threads 1,2,4,8,16] [--max-batch 16]
           [--max-delay-ms 1.0] [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def build_model(n: int, d: int, seed: int):
    from tpusvm.config import SVMConfig
    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE, mnist_like
    from tpusvm.models import BinarySVC

    gen_kwargs = dict(n=n + 64, d=d, seed=seed, noise=BENCH_NOISE,
                      label_noise=BENCH_LABEL_NOISE)
    X, Y = mnist_like(**gen_kwargs)
    t0 = time.perf_counter()
    model = BinarySVC(SVMConfig(C=10.0, gamma=0.00125),
                      dtype=jnp.float32).fit(X[:n], Y[:n])
    fit_s = time.perf_counter() - t0
    # the query pool: held-out rows beyond the training prefix
    return model, X[n:], workload_record(mnist_like, **gen_kwargs), fit_s


def run_level(server, name: str, Xq, n_threads: int, duration_s: float):
    """Closed-loop: n_threads clients, each submitting back-to-back."""
    counts = [0] * n_threads
    not_ok = [0] * n_threads
    stop_at = time.monotonic() + duration_s

    def client(t):
        i = t  # stagger the row streams so threads don't submit in lockstep
        while time.monotonic() < stop_at:
            r = server.submit(name, Xq[i % len(Xq)])
            counts[t] += 1
            if not r.ok:
                not_ok[t] += 1
            i += 1

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    return sum(counts), sum(not_ok), elapsed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + short levels (schema/CI run)")
    ap.add_argument("--n", type=int, default=4096, help="training rows")
    ap.add_argument("--d", type=int, default=784)
    ap.add_argument("--seed", type=int, default=587)
    ap.add_argument("--duration", type=float, default=2.0,
                    help="seconds per concurrency level")
    ap.add_argument("--threads", default="1,2,4,8,16",
                    help="comma-separated client-thread levels")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--max-delay-ms", type=float, default=1.0)
    ap.add_argument("--jsonl", default=None,
                    help="also append rows to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.d = 512, 64
        args.duration = 0.3
        args.threads = "1,8"

    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.server import sequential_qps

    levels = [int(t) for t in args.threads.split(",")]
    log(f"serve_latency: training n={args.n} d={args.d}")
    model, Xq, workload, fit_s = build_model(args.n, args.d, args.seed)
    log(f"fit {fit_s:.1f}s, {model.n_support_} SVs")
    cfg = ServeConfig(max_batch=args.max_batch,
                      max_delay_ms=args.max_delay_ms)

    sink = open(args.jsonl, "a") if args.jsonl else None

    def row(rec):
        emit(rec)
        if sink:
            sink.write(json.dumps(rec) + "\n")

    base = {
        "bench": "serve_latency",
        "workload": workload,
        "n_train": args.n,
        "n_sv": int(model.n_support_),
        "serve_config": {"max_batch": cfg.max_batch,
                         "max_delay_ms": cfg.max_delay_ms,
                         "queue_size": cfg.queue_size},
        "platform": jax.default_backend(),
    }

    # sequential baseline: one client, direct path, no queue/coalescing
    with Server(cfg, dtype=jnp.float32) as srv:
        srv.add_model("m", model)
        srv.warmup()
        seq_qps = sequential_qps(srv, "m", list(Xq), args.duration)
    row({**base, "mode": "sequential", "threads": 1,
         "qps": round(seq_qps, 1)})

    violations = []
    ratios = {}
    for n_threads in levels:
        # a fresh server per level keeps metrics (latency window,
        # occupancy) scoped to the level instead of smearing across the
        # sweep
        with Server(cfg, dtype=jnp.float32) as srv:
            srv.add_model("m", model)
            srv.warmup()
            n_req, n_not_ok, elapsed = run_level(
                srv, "m", Xq, n_threads, args.duration)
            snap = srv.metrics("m")
            st = srv.status()["models"]["m"]
        qps = n_req / elapsed
        ratios[n_threads] = qps / seq_qps
        lat = snap["latency_s"]
        rec = {
            **base, "mode": "batched", "threads": n_threads,
            "offered_closed_loop": True,
            "qps": round(qps, 1),
            "vs_sequential": round(qps / seq_qps, 2),
            "requests": n_req, "not_ok": n_not_ok,
            "errors": snap["errors"], "timeouts": snap["timeouts"],
            "queue_full": snap["queue_full"],
            "recompiles": snap["recompiles"],
            "compiled_shapes": st["compiled_shapes"],
            "mean_batch_rows": round(snap["mean_batch_rows"], 2),
            "p50_ms": round(lat["p50"] * 1e3, 3) if lat["p50"] else None,
            "p95_ms": round(lat["p95"] * 1e3, 3) if lat["p95"] else None,
            "p99_ms": round(lat["p99"] * 1e3, 3) if lat["p99"] else None,
        }
        row(rec)
        if snap["errors"] or snap["recompiles"]:
            violations.append(n_threads)

    row({**base, "summary": True, "sequential_qps": round(seq_qps, 1),
         "ratios": {str(k): round(v, 2) for k, v in ratios.items()},
         "violations": violations})
    if sink:
        sink.close()
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
