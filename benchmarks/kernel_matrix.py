"""Kernel-matrix benchmark: linear fast path vs generic K-row path vs RBF.

The acceptance bar for the linear family's primal fast path (ISSUE 6):
on a fixed synthetic grid, routing the blocked solver's error-vector
contraction through X @ (X_B^T coef) (kernels/linear.py, kernel_fast=True)
must be >= 1.5x faster wall-clock than the generic blocked K-row path
(kernel_fast=False) AT EQUAL SOLUTIONS — both arms converged, same SV set
(tau-band allowance, the fuzz-parity criterion) and b within the
classification band. RBF and poly(degree=2) rows ride along per cell so
the artifact reads as the full kernel matrix's cost picture at one shape.

Workload: overlapping Gaussian blobs (linearly separable with margin
noise) scaled to [0,1]^d — a problem every family CONVERGES on, so the
equal-solutions clause is meaningful (the mnist-like recipe drives linear
to MAX_ITER, where trajectories at the cutoff are not comparable).

Timing protocol: both linear arms AOT-compiled, run INTERLEAVED, min
across repeats (the house CPU-timing noise-rejection protocol,
benchmarks/telemetry_overhead.py); every timed run ends at host
materialisation of alpha.

Usage: python benchmarks/kernel_matrix.py [--smoke] [--repeats 5]
           [--jsonl PATH]
Emits one JSON line per (cell, engine) plus a summary line; committed
run: benchmarks/results/kernel_matrix_cpu.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

SPEEDUP_GATE = 1.5  # full-size runs only; --smoke checks parity gates

# (n, d, sep, C): blobs geometry per grid cell. sep < 2 leaves class
# overlap, so the solve does real working-set rounds instead of one pass.
GRID = [
    (8192, 128, 1.5, 1.0),
    (8192, 256, 1.5, 1.0),
    (4096, 256, 1.0, 1.0),
]

# (engine tag, kernel family, kernel_fast, extra config)
ENGINES = [
    ("rbf", "rbf", True, {}),
    ("poly-d2", "poly", True, {"degree": 2, "coef0": 1.0}),
    ("linear-generic", "linear", False, {}),
    ("linear-fast", "linear", True, {}),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run): equal-solutions "
                    "gates only, no speedup floor")
    ap.add_argument("--repeats", type=int, default=5,
                    help="interleaved timed repeats per engine (min kept)")
    ap.add_argument("--q", type=int, default=512)
    ap.add_argument("--max-inner", type=int, default=512)
    ap.add_argument("--seed", type=int, default=7, help="data seed")
    ap.add_argument("--jsonl", default=None,
                    help="also append records to this file")
    args = ap.parse_args(argv)
    grid = [(512, 32, 1.0, 1.0)] if args.smoke else GRID
    if args.smoke:
        args.q, args.max_inner, args.repeats = 128, 128, 2

    import jax.numpy as jnp
    import numpy as np

    from benchmarks.common import h2d_sync
    from tpusvm.data import MinMaxScaler, blobs
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.status import Status

    out = open(args.jsonl, "a") if args.jsonl else None

    def emit_rec(rec):
        emit(rec)
        if out:
            out.write(json.dumps(rec) + "\n")

    violations = []
    speedups = []
    for n, d, sep, C in grid:
        gen_kwargs = dict(n=n, d=d, sep=sep, seed=args.seed)
        X, Y = blobs(**gen_kwargs)
        Xs = MinMaxScaler().fit_transform(X).astype(np.float32)
        Xd, Yd = jnp.asarray(Xs), jnp.asarray(Y)
        h2d_sync(Xd, Yd)
        # hyper = traced operands (re-passed at every compiled call);
        # static = baked into the executable at lower() time
        hyper = dict(C=C, gamma=0.05, tau=1e-5, max_iter=400000)
        static = dict(q=args.q, max_inner=args.max_inner,
                      max_outer=4000, accum_dtype=jnp.float64)

        log(f"cell n={n} d={d} sep={sep}: compiling {len(ENGINES)} "
            "engines (AOT)...")
        compiled = {}
        for tag, kern, fast, extra in ENGINES:
            compiled[tag] = blocked_smo_solve.lower(
                Xd, Yd, kernel=kern, kernel_fast=fast,
                **{k: extra[k] for k in ("degree",) if k in extra},
                coef0=extra.get("coef0", 0.0), **static, **hyper,
            ).compile()

        def timed(tag, extra):
            t0 = time.perf_counter()
            res = compiled[tag](Xd, Yd, coef0=extra.get("coef0", 0.0),
                                **hyper)
            alpha = np.asarray(res.alpha)  # completion barrier
            return time.perf_counter() - t0, res, alpha

        # one untimed warm run per engine, then interleaved timed repeats
        for tag, _, _, extra in ENGINES:
            timed(tag, extra)
        times = {tag: [] for tag, _, _, _ in ENGINES}
        finals = {}
        for _ in range(args.repeats):
            for tag, _, _, extra in ENGINES:
                dt, res, alpha = timed(tag, extra)
                times[tag].append(dt)
                finals[tag] = (res, alpha)

        cell = {}
        for tag, kern, fast, extra in ENGINES:
            res, alpha = finals[tag]
            sv = set(np.nonzero(alpha > 1e-8)[0].tolist())
            status = Status(int(res.status))
            rec = {
                "bench": "kernel_matrix", "smoke": args.smoke,
                "workload": workload_record(blobs, **gen_kwargs),
                "n": n, "d": d, "C": C,
                "q": args.q, "max_inner": args.max_inner,
                "engine": tag, "kernel": kern, "kernel_fast": fast,
                "wall_s": round(min(times[tag]), 6),
                "repeats": args.repeats,
                "n_updates": int(res.n_iter) - 1,
                "n_outer": int(res.n_outer),
                "n_sv": len(sv),
                "b": float(res.b),
                "status": status.name,
                "platform": jax.default_backend(),
            }
            cell[tag] = (rec, sv)
            if status != Status.CONVERGED:
                violations.append(
                    f"n={n} d={d} {tag}: ended {status.name}")
            emit_rec(rec)

        # the equal-solutions + speedup verdict for the linear pair
        gen_rec, gen_sv = cell["linear-generic"]
        fast_rec, fast_sv = cell["linear-fast"]
        sym = len(gen_sv ^ fast_sv)
        allowed = max(2, len(gen_sv) // 25)
        db = abs(gen_rec["b"] - fast_rec["b"])
        speedup = gen_rec["wall_s"] / fast_rec["wall_s"]
        speedups.append(speedup)
        if sym > allowed:
            violations.append(
                f"n={n} d={d}: fast/generic SV sym diff {sym} > {allowed}")
        if db > 2e-3:
            violations.append(f"n={n} d={d}: fast/generic |db|={db:.2e}")
        if not args.smoke and speedup < SPEEDUP_GATE:
            violations.append(
                f"n={n} d={d}: linear fast path speedup {speedup:.2f} "
                f"< {SPEEDUP_GATE}")

    summary = {
        "summary": True, "bench": "kernel_matrix", "smoke": args.smoke,
        "cells": len(grid),
        "engines": [t for t, _, _, _ in ENGINES],
        "speedup_gate": SPEEDUP_GATE,
        "linear_fast_speedups": [round(s, 3) for s in speedups],
        "min_speedup": round(min(speedups), 3),
        "violations": violations,
        "platform": jax.default_backend(),
    }
    emit_rec(summary)
    if out:
        out.close()
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
