"""One-shot probe: time the blocked solver at a given (q, max_inner, max_outer).

Usage: python benchmarks/probe_split.py <q> <max_inner> <max_outer> \
           [wss] [matmul_precision] [refine] [selection] [fused] [layout] \
           [eta_exclude] [multipair]
Prints one JSON line {q, max_inner, ..., n_sv, b, time_s}. One heavy
measurement per process (axon runtime faults on repeats — see verify skill).
layout (packed|flat) reaches blocked_smo_solve's pallas_layout — needed to
reproduce the round-1 shipping config (flat) for same-session A/Bs.
eta_exclude (0|1) reaches pallas_eta_exclude — the VERDICT r4 #5 unified
selection rule's hardware cost measurement (wss=2 only).
multipair (int, default 1) reaches pallas_multipair — the batched
slot-pair kernel (VERDICT r4 #3); requires wss=1 and lane-aligned slots.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

from benchmarks.common import pin_platform, workload_record  # noqa: E402

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.data import MinMaxScaler, mnist_like  # noqa: E402
from tpusvm.solver.blocked import (  # noqa: E402
    blocked_smo_solve,
    resolve_solver_config,
)

q, max_inner, max_outer = (int(a) for a in sys.argv[1:4])
wss = int(sys.argv[4]) if len(sys.argv) > 4 else 1
precision = sys.argv[5] if len(sys.argv) > 5 else None
if precision in ("", "none", "None"):
    precision = None  # lets later positional args be passed explicitly
refine = int(sys.argv[6]) if len(sys.argv) > 6 else 0
selection = sys.argv[7] if len(sys.argv) > 7 else "auto"
if len(sys.argv) > 8:
    _ftok = sys.argv[8]
    if _ftok in ("1", "fused", "true"):
        fused = True
    elif _ftok in ("0", "false"):
        fused = False
    elif _ftok == "auto":
        fused = "auto"  # the TPU-default resolution (round-4 adoption)
    else:
        raise SystemExit(
            f"fused argument must be 1|fused|true|0|false|auto, got {_ftok!r}"
        )
else:
    fused = False
layout = sys.argv[9] if len(sys.argv) > 9 else "packed"
if layout not in ("packed", "flat"):
    raise SystemExit(f"layout argument must be packed|flat, got {layout!r}")
eta_exclude = bool(int(sys.argv[10])) if len(sys.argv) > 10 else False
multipair = int(sys.argv[11]) if len(sys.argv) > 11 else 1

# DELIBERATELY the headline benchmark's frozen recipe (bench.py — see its
# docstring: noise=30/label_noise=0.005, kept for cross-round
# comparability), NOT the accuracy-calibrated BENCH_NOISE recipe: this
# probe tunes the exact optimisation problem the headline measures.
# Different seed from bench.py (0 vs 587): tuning on a sibling instance
# of the same distribution guards against overfitting knobs to the
# measured instance.
_WL = dict(n=60000, d=784, seed=0, noise=30.0, label_noise=0.005)
X, Y = mnist_like(**_WL)
Xs = MinMaxScaler().fit_transform(X)
Xd = jnp.asarray(Xs, jnp.float32)
Yd = jnp.asarray(Y, jnp.int32)

solve = jax.jit(
    lambda X, Y: blocked_smo_solve(
        X, Y, C=10.0, gamma=0.00125, tau=1e-5, max_iter=10**9,
        q=q, max_inner=max_inner, max_outer=max_outer, wss=wss,
        accum_dtype=jnp.float64, matmul_precision=precision,
        refine=refine, max_refines=4, selection=selection,
        fused_fupdate=fused, pallas_layout=layout,
        pallas_eta_exclude=eta_exclude, pallas_multipair=multipair,
    )
)
lowered = solve.lower(Xd, Yd).compile()
from benchmarks.common import h2d_sync  # noqa: E402

h2d_sync(Xd, Yd)
t0 = time.perf_counter()
r = lowered(Xd, Yd)
out = (int(np.asarray(r.n_outer)), int(np.asarray(r.n_iter)) - 1,
       int(np.asarray(r.status)))
t1 = time.perf_counter()
n_sv = int((np.asarray(r.alpha) > 1e-8).sum())
# effective config via the solver's own resolution rules, so a row records
# what actually ran (q clamps to n; selection='auto' resolves by backend)
q_eff, inner_eff, wss_eff, selection_eff = resolve_solver_config(
    Xd.shape[0], q=q, wss=wss, selection=selection)
from tpusvm.solver.blocked import resolve_fused_fupdate  # noqa: E402

# for explicit-bool rows fused_eff == fused; for fused='auto' rows this
# is the backend-time resolution, making the row self-describing
fused_eff = resolve_fused_fupdate(
    Xd.shape[0], Xd.shape[1], q=q, fused=fused,
    matmul_precision=precision)
print(json.dumps({"q": q, "max_inner": max_inner, "wss": wss,
                  "precision": precision, "refine": refine,
                  "selection": selection, "fused": fused,
                  "layout": layout, "eta_exclude": eta_exclude,
                  "multipair": multipair,
                  "workload": workload_record(mnist_like, **_WL),
                  "q_eff": q_eff, "inner_eff": inner_eff,
                  "wss_eff": wss_eff, "selection_eff": selection_eff,
                  "fused_eff": fused_eff,
                  "platform": jax.default_backend(),
                  "outers": out[0], "updates": out[1], "status": out[2],
                  "n_sv": n_sv, "b": float(np.asarray(r.b)),
                  "time_s": round(t1 - t0, 4)}))
