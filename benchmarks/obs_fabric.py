#!/usr/bin/env python
"""Distributed-trace fabric: traced vs untraced pod fit, cost + parity.

The observability fabric's acceptance harness (ISSUE 20): running the
out-of-core pod cascade with the distributed tracer on — coordinator
trace file, one trace file per worker PROCESS, trace contexts
propagated inside the pod wire frames — must be

  * FREE of model consequence: the traced fit reproduces the untraced
    control bit-for-bit (same SV-ID set, byte-identical alpha vector,
    bitwise-equal b) — `bit_identical`, the hard exact gate;
  * USABLE: merging the trace directory stitches ONE cross-process
    timeline — every worker root span re-parents under the
    coordinator's via the propagated context (zero unresolved), and
    `render_report` renders the merged records without raising —
    `reparented_ok` / `report_ok`;
  * CHEAP: tracing costs <= 3% of pod wall clock (`overhead_frac`,
    full-size runs only — smoke checks the identity/usability gates).

Timing protocol: arms run INTERLEAVED (untraced/traced per repeat) with
the per-arm MIN kept — the standard noise-rejection protocol for a
host-timed multiprocess measurement. benchdiff gates the timing columns
at --level full only (Rule.timing), so the committed smoke baseline
stays machine-portable.

Usage:
  python benchmarks/obs_fabric.py --smoke --jsonl out.jsonl
  python benchmarks/obs_fabric.py --n 512 --repeats 3
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import emit, log, pin_platform

pin_platform()

import numpy as np  # noqa: E402

from tpusvm.config import CascadeConfig, SVMConfig  # noqa: E402
from tpusvm.data import rings  # noqa: E402
from tpusvm.obs.trace import Tracer  # noqa: E402
from tpusvm.pod import pod_fit  # noqa: E402
from tpusvm.stream.format import ingest_arrays  # noqa: E402

OVERHEAD_GATE = 0.03  # full-size runs only; --smoke gates identity/usability


def _fit(data_dir, cfg, cc, trace_dir=None):
    """One pod fit; trace_dir=None is the untraced control arm."""
    tracer = None
    if trace_dir is not None:
        os.makedirs(trace_dir, exist_ok=True)
        tracer = Tracer(os.path.join(trace_dir, "coordinator.jsonl"),
                        role="pod-coordinator", argv=["obs_fabric"])
    t0 = time.perf_counter()
    try:
        res = pod_fit(data_dir, cfg, cc, tracer=tracer,
                      trace_dir=trace_dir)
    finally:
        if tracer is not None:
            tracer.close()
    return res, time.perf_counter() - t0


def _sv_key(res):
    ids = np.asarray(res.sv_ids)
    order = np.argsort(ids)
    alpha = np.asarray(res.sv_alpha)[order]
    return (set(int(i) for i in ids), alpha.tobytes(), float(res.b))


def run(args) -> int:
    n = 192 if args.smoke else args.n
    repeats = 1 if args.smoke else args.repeats
    cfg = SVMConfig(C=args.C, gamma=args.gamma, max_rounds=args.max_rounds)
    cc = CascadeConfig(n_shards=args.workers, sv_capacity=args.sv_capacity,
                       topology=args.topology)

    X, Y = rings(n=n, seed=args.seed)
    d = int(X.shape[1])
    violations = []
    with tempfile.TemporaryDirectory(prefix="obs_fabric_bench_") as tmp:
        data_dir = os.path.join(tmp, "ds")
        ingest_arrays(data_dir, X, Y, rows_per_shard=args.rows_per_shard)

        best = {}  # arm -> (wall_s, result)
        last_trace_dir = None
        for rep in range(repeats):  # interleave arms, keep min
            for arm in ("off", "on"):
                tdir = None
                if arm == "on":
                    tdir = os.path.join(tmp, f"trace{rep}")
                    last_trace_dir = tdir
                res, dt = _fit(data_dir, cfg, cc, trace_dir=tdir)
                if arm not in best or dt < best[arm][0]:
                    best[arm] = (dt, res)
        t_off, r_off = best["off"]
        t_on, r_on = best["on"]
        overhead = (t_on - t_off) / t_off
        log(f"obs_fabric {cc.topology}/P={args.workers}: "
            f"untraced {t_off:.2f}s, traced {t_on:.2f}s "
            f"({overhead:+.2%}), {len(r_on.sv_ids)} SVs, "
            f"{r_on.rounds} rounds")

        bit_identical = _sv_key(r_off) == _sv_key(r_on)
        if not bit_identical:
            violations.append("traced fit is not bit-identical to the "
                              "untraced control")
        if not (r_off.converged and r_on.converged):
            violations.append("an arm did not converge")

        # usability gates over the LAST traced run's directory
        from tpusvm.obs.report import (
            merge_trace_files,
            render_report,
            reparent_stats,
        )

        tfiles = sorted(
            os.path.join(last_trace_dir, f)
            for f in os.listdir(last_trace_dir) if f.endswith(".jsonl"))
        trace_files = len(tfiles)
        if trace_files < args.workers + 1:
            violations.append(
                f"expected >={args.workers + 1} trace files "
                f"(coordinator + {args.workers} workers), "
                f"found {trace_files}")
        stats = {"spans": 0, "reparented": 0, "unresolved": -1,
                 "roles": []}
        reparented_ok = report_ok = False
        try:
            recs = merge_trace_files(tfiles)
            stats = reparent_stats(recs)
            reparented_ok = (stats["unresolved"] == 0
                             and stats["reparented"] > 0
                             and "pod-worker" in stats["roles"]
                             and "pod-coordinator" in stats["roles"])
            body = render_report(recs)
            report_ok = "cross-process timeline" in body
        except (ValueError, KeyError) as e:
            violations.append(f"merged trace unusable: {e}")
        if not reparented_ok:
            violations.append(
                f"re-parenting broken: {stats['unresolved']} unresolved "
                f"root span(s), {stats['reparented']} re-parented, "
                f"roles {stats['roles']}")
        if not report_ok:
            violations.append("merged report did not render the "
                              "cross-process timeline")
        if not args.smoke and overhead > OVERHEAD_GATE:
            violations.append(
                f"tracing overhead {overhead:.4f} exceeds the "
                f"{OVERHEAD_GATE:.0%} gate")

    record = {
        "bench": "obs_fabric",
        "topology": cc.topology, "P": args.workers, "n": n, "d": d,
        "smoke": bool(args.smoke),
        "repeats": repeats,
        "t_off_s": round(t_off, 4),
        "t_on_s": round(t_on, 4),
        "overhead_frac": round(overhead, 6),
        "gate_frac": OVERHEAD_GATE,
        "bit_identical": bit_identical,
        "converged": bool(r_off.converged and r_on.converged),
        "sv_count": len(r_on.sv_ids),
        "rounds": int(r_on.rounds),
        "trace_files": trace_files,
        "spans": stats["spans"],
        "reparented_spans": stats["reparented"],
        "unresolved_spans": stats["unresolved"],
        "reparented_ok": reparented_ok,
        "report_ok": report_ok,
        "violations": violations,
    }
    emit(record)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
    if violations:
        for v in violations:
            log(f"GATE FAILED: {v}")
        return 1
    log(f"obs fabric ok: {trace_files} files / {stats['spans']} spans "
        f"stitched ({stats['reparented']} re-parented, 0 unresolved), "
        f"fit bit-identical, overhead {overhead:+.2%}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI shape: n=192, one pass per arm, no "
                    "overhead floor")
    ap.add_argument("--n", type=int, default=512,
                    help="training rows (smoke pins 192)")
    ap.add_argument("--workers", type=int, default=4,
                    help="worker-process count = cascade leaves")
    ap.add_argument("--topology", choices=["tree", "star"], default="tree")
    ap.add_argument("--rows-per-shard", type=int, default=24)
    ap.add_argument("--sv-capacity", type=int, default=128)
    ap.add_argument("--C", type=float, default=10.0)
    ap.add_argument("--gamma", type=float, default=10.0)
    ap.add_argument("--max-rounds", type=int, default=12)
    ap.add_argument("--repeats", type=int, default=2,
                    help="interleaved timing passes, min kept (smoke: 1)")
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--jsonl", help="append the record to this file")
    args = ap.parse_args()
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
