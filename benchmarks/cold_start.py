"""Cold-start-to-first-prediction: restart against a persisted compile cache.

BENCH_r01 put the serving cold-start problem on the record: 22.3 s of
AOT bucket compile against 0.41 s of training — every restart re-paid
it, because the executables lived only in process memory. This harness
measures the fix (serve/cache.py: jax persistent compilation cache +
bucket-signature manifest) the only honest way: two REAL process
launches sharing one cache directory.

  arm "cold"  fresh process, empty cache dir: every bucket executable
              is an XLA cache MISS (compiled + persisted);
  arm "warm"  fresh process, the same cache dir: the restart. The gate
              is mechanical, not a wall-clock impression — the child
              counts jax's own /jax/compilation_cache/cache_{hits,
              misses} monitoring events, and the warm arm must report
              **misses == 0** (`warm_ok`): first prediction reached
              with zero fresh XLA compiles.

Each child measures `first_prediction_s` from its own main() entry
(interpreter up, before any jax import) to the first scored request —
the operator-visible restart-to-serving number. `tpusvm benchdiff`
gates warm_ok/misses exactly and the timing columns directionally
(SCHEMA_RULES["cold_start"]).

Usage:
  python benchmarks/cold_start.py [--smoke] [--jsonl OUT.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

CHILD_MARKER = "COLD_START_CHILD "


def child_main(args) -> int:
    """One serve process: configure cache, load, warm, score once."""
    t0 = time.perf_counter()
    from benchmarks.common import pin_platform

    pin_platform()
    import numpy as np

    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.cache import persistent_cache_stats

    server = Server(ServeConfig(max_batch=args.max_batch))
    server.configure_cache(args.cache_dir)
    entry = server.load_model("m", args.model)
    compiles = server.warmup()["m"]
    rng = np.random.default_rng(0)
    scores, _ = server.predict_direct(
        "m", rng.random((1, entry.n_features)))
    first_prediction_s = time.perf_counter() - t0
    stats = persistent_cache_stats()
    server.close()
    print(CHILD_MARKER + json.dumps({
        "compiles": compiles,
        "hits": stats["hits"],
        "misses": stats["misses"],
        "first_prediction_s": first_prediction_s,
        "score0": float(np.asarray(scores).ravel()[0]),
    }))
    return 0


def run_child(model: str, cache_dir: str, max_batch: int) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--model", model, "--cache-dir", cache_dir,
           "--max-batch", str(max_batch)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          env=dict(os.environ), timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith(CHILD_MARKER):
            return json.loads(line[len(CHILD_MARKER):])
    raise RuntimeError(
        f"cold-start child produced no result marker (rc={proc.returncode})"
        f"\nstdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-2000:]}"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--jsonl", default=None)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--model", default=None)
    ap.add_argument("--cache-dir", default=None)
    ap.add_argument("--max-batch", type=int, default=8)
    args = ap.parse_args()
    if args.child:
        return child_main(args)

    from benchmarks.common import emit, log, pin_platform

    pin_platform()
    import jax

    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.data.synthetic import mnist_like
    from tpusvm.models import BinarySVC

    if args.smoke:
        n, d, max_batch = 300, 2, 8
        X, Y = rings(n=n, seed=2)
    else:
        n, d, max_batch = 2048, 64, 64
        X, Y = mnist_like(n=n, d=d, seed=587)
    cfg = SVMConfig(C=10.0, gamma=(10.0 if args.smoke else 1.0 / d))

    out = open(args.jsonl + ".tmp", "w") if args.jsonl else None

    def row(rec):
        rec = {"bench": "cold_start", "smoke": bool(args.smoke),
               "n": n, "d": d, "max_batch": max_batch, **rec}
        emit(rec)
        if out:
            json.dump(rec, out)
            out.write("\n")

    failures = []
    with tempfile.TemporaryDirectory() as td:
        model_path = os.path.join(td, "model.npz")
        cache_dir = os.path.join(td, "cache")
        log(f"training the served model (n={n}, d={d}) ...")
        model = BinarySVC(cfg, dtype=jax.numpy.float32).fit(X, Y)
        model.save(model_path)
        log(f"model: {model.n_support_} SVs; launching cold child ...")
        cold = run_child(model_path, cache_dir, max_batch)
        log(f"cold: {cold['misses']} cache misses, first prediction in "
            f"{cold['first_prediction_s']:.2f}s; launching warm child ...")
        warm = run_child(model_path, cache_dir, max_batch)
        log(f"warm: {warm['hits']} hits / {warm['misses']} misses, "
            f"first prediction in {warm['first_prediction_s']:.2f}s")

        if cold["misses"] == 0:
            failures.append("cold arm reported zero cache misses — the "
                            "cache dir was not actually cold")
        if warm["misses"] != 0:
            failures.append(
                f"WARM RESTART COMPILED: {warm['misses']} cache misses "
                "(the ~zero-cold-start gate is misses == 0)")
        if warm["score0"] != cold["score0"]:
            failures.append(
                "cache-served executable changed the served score: "
                f"{warm['score0']!r} != {cold['score0']!r}")
        speedup = (cold["first_prediction_s"]
                   / max(warm["first_prediction_s"], 1e-9))
        for arm, rec in (("cold", cold), ("warm", warm)):
            row({
                "arm": arm,
                "n_sv": int(model.n_support_),
                "compiles": rec["compiles"],
                "hits": rec["hits"],
                "misses": rec["misses"],
                "warm_ok": (rec["misses"] == 0) if arm == "warm"
                else (rec["misses"] > 0),
                "score_parity": warm["score0"] == cold["score0"],
                "first_prediction_s": rec["first_prediction_s"],
                "warm_speedup": speedup if arm == "warm" else 1.0,
            })
    if out:
        out.close()
        os.replace(args.jsonl + ".tmp", args.jsonl)
    if failures:
        for f in failures:
            log(f"COLD-START GATE FAILED: {f}")
        return 1
    log(f"cold-start gate ok: warm restart hit the cache on every "
        f"compile ({warm['hits']} hits, 0 misses), first prediction "
        f"{warm['first_prediction_s']:.2f}s vs {cold['first_prediction_s']:.2f}s "
        f"cold ({speedup:.2f}x)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
