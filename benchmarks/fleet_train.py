#!/usr/bin/env python
"""Fleet training vs the host-looped control: B models, one XLA program.

ISSUE 12's acceptance harness: the 10 one-vs-rest heads of a
mnist-shaped multiclass workload are trained three ways on identical
data —

  loop           per-head blocked_smo_solve, host-looped (the control;
                 shares one hoisted sn= precompute across heads, the
                 same fix models/ovr.py carries, so the control is not
                 flattered by redundant X streams)
  fleet          ONE monolithic fleet_smo_solve launch: all heads in one
                 power-of-two bucket, per-problem convergence masking in
                 the batched while-loop carry (tpusvm.fleet)
  fleet_compact  fleet_train(compact_every=R): converged heads are
                 compacted out of the batch every R rounds, bounding the
                 lockstep waste at ~sum(rounds) + B*R lane-rounds

with the house timing protocol (warm every arm, interleave timed
repeats, keep the min) and HARD parity gates: every head CONVERGED, and
each fleet arm's per-head SV sets, statuses and held-out OvR accuracy
EXACTLY equal the control's. A (C, gamma) sweep through the warmed fleet
executable is also gated at ZERO recompiles (the per-problem
hyperparameters are arrays, so their values cannot bake into the trace —
the launch-economics half of the fleet story).

Speed gates (full level; --smoke keeps parity/recompile gates only):
  * TPU: best fleet arm >= 4.0x aggregate throughput over the loop (the
    ROADMAP fleet target — B problems individually too small to saturate
    the MXU ride one batched program);
  * CPU: best fleet arm >= 0.33x FLOOR. The honest CPU ceiling is BELOW
    1x by construction: a serial backend executes the batched program's
    lane-rounds one after another, so the fleet pays ~B*max(rounds)
    (compaction: ~sum(rounds) + B*R) against the loop's sum(rounds),
    plus inner-loop lockstep — there is no dispatch-overhead pool to
    win back, unlike on TPU where the batched contractions raise MXU
    utilisation. The committed CPU artifact is therefore PARITY +
    direction evidence (the r02-r05 discipline: a CPU number must never
    impersonate a TPU claim), and the >= 4x gate is armed for the next
    session with a reachable TPU backend.

Usage: python benchmarks/fleet_train.py [--smoke] [--n 512] [--d 32]
           [--q 64] [--compact-every 32] [--repeats 2] [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform, workload_record  # noqa: E402

pin_platform()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

SPEEDUP_GATE_TPU = 4.0   # the ROADMAP fleet target, on the backend it names
SPEEDUP_GATE_CPU = 0.33  # serial-backend floor (see module docstring)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run): parity + recompile "
                    "gates only, no speed floor")
    ap.add_argument("--n", type=int, default=512, help="training rows")
    ap.add_argument("--d", type=int, default=32)
    ap.add_argument("--n-test", type=int, default=256)
    ap.add_argument("--seed", type=int, default=587)
    ap.add_argument("--q", type=int, default=64)
    ap.add_argument("--max-inner", type=int, default=1024)
    ap.add_argument("--compact-every", type=int, default=32,
                    help="compaction cadence of the fleet_compact arm")
    ap.add_argument("--repeats", type=int, default=2,
                    help="timed repeats per arm (min is kept)")
    ap.add_argument("--jsonl", default=None,
                    help="also append the records to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.d, args.n_test = 384, 32, 96
        args.q, args.repeats = 32, 1
        args.compact_every = 16

    import jax.numpy as jnp
    import numpy as np

    from benchmarks.common import h2d_sync
    from tpusvm import kernels
    from tpusvm.data import MinMaxScaler
    from tpusvm.data.synthetic import (
        BENCH_NOISE_MULTICLASS,
        mnist_like_multiclass,
    )
    from tpusvm.fleet import bucket_for, fleet_train
    from tpusvm.obs import prof
    from tpusvm.ops.rbf import coef_matvec, sq_norms
    from tpusvm.oracle.smo import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.status import Status

    wl = dict(n=args.n + args.n_test, d=args.d, seed=args.seed,
              noise=BENCH_NOISE_MULTICLASS)
    X, labels = mnist_like_multiclass(**wl)
    sc = MinMaxScaler().fit(X[: args.n])
    Xs = sc.transform(X[: args.n]).astype(np.float32)
    Xt = sc.transform(X[args.n:]).astype(np.float32)
    ytr, yte = labels[: args.n], labels[args.n:]
    classes = np.unique(ytr)
    B = len(classes)
    bucket = bucket_for(B)
    Ys = [np.where(ytr == c, 1, -1).astype(np.int32) for c in classes]
    gamma = 1.0 / args.d
    C = 10.0

    Xd = jnp.asarray(Xs, jnp.float32)
    Yd = [jnp.asarray(y) for y in Ys]
    sn = sq_norms(Xd)
    h2d_sync(Xd, sn, *Yd)

    # max_iter far above any converged run's need: the arms must compare
    # converged solutions, not who crossed an update budget first
    base = dict(q=args.q, max_inner=args.max_inner,
                accum_dtype=jnp.float64, tau=1e-5, max_iter=5_000_000)
    Cs, gs = [C] * B, [gamma] * B

    def run_loop():
        # the host-looped control, with the hoisted shared sn (the
        # models/ovr.py fix) so it pays no redundant X streams
        outs = [blocked_smo_solve(Xd, y, sn=sn, C=C, gamma=gamma, **base)
                for y in Yd]
        for o in outs:
            np.asarray(o.alpha)
        return outs

    def run_fleet(compact):
        outs = fleet_train(Xd, Ys, Cs, gs, sn=sn,
                           compact_every=compact, **base)
        for o in outs:
            np.asarray(o.alpha)
        return outs

    arms = {
        "loop": run_loop,
        "fleet": lambda: run_fleet(0),
        "fleet_compact": lambda: run_fleet(args.compact_every),
    }

    for arm, fn in arms.items():
        log(f"warming {arm}...")
        fn()
    times = {arm: [] for arm in arms}
    results = {}
    for _ in range(args.repeats):
        for arm, fn in arms.items():
            t0 = time.perf_counter()
            res = fn()
            times[arm].append(time.perf_counter() - t0)
            results[arm] = res

    def evaluate(outs):
        """Per-head SV sets + held-out OvR argmax accuracy + statuses."""
        svs, statuses, bs = [], [], []
        coefs = np.zeros((B, args.n), np.float32)
        for i, o in enumerate(outs):
            alpha = np.asarray(o.alpha)
            sv = get_sv_indices(alpha)
            svs.append(set(int(s) for s in sv))
            statuses.append(Status(int(o.status)).name)
            bs.append(float(o.b))
            coefs[i] = (alpha * Ys[i]).astype(np.float32)
        K = kernels.cross("rbf", jnp.asarray(Xt, jnp.float32), Xd,
                          gamma=gamma, snB=sn)
        scores = np.asarray(coef_matvec(K, jnp.asarray(coefs).T)) \
            - np.asarray(bs)[None, :]
        acc = float((classes[np.argmax(scores, axis=1)] == yte).mean())
        return svs, statuses, bs, acc

    evals = {arm: evaluate(results[arm]) for arm in arms}
    ctl_svs, ctl_statuses, ctl_bs, ctl_acc = evals["loop"]
    t_loop = min(times["loop"])

    records, violations = [], []
    for arm in arms:
        svs, statuses, bs, acc = evals[arm]
        train_s = min(times[arm])
        sv_parity = svs == ctl_svs
        accuracy_parity = acc == ctl_acc
        rec = {
            "bench": "fleet_train",
            "mode": arm,
            "workload": workload_record(mnist_like_multiclass, **wl),
            "B": B, "bucket": bucket,
            "n": args.n, "d": args.d, "q": args.q,
            "compact_every": (args.compact_every
                              if arm == "fleet_compact" else 0),
            "train_s": round(train_s, 6),
            "problems_per_s": round(B / train_s, 4),
            "updates": sum(int(o.n_iter) - 1 for o in results[arm]),
            "statuses": statuses,
            "sv_counts": [len(s) for s in svs],
            "accuracy": round(acc, 6),
            "sv_parity": sv_parity,
            "accuracy_parity": accuracy_parity,
            "b_max_delta_vs_control": max(
                abs(a - b) for a, b in zip(bs, ctl_bs)),
            "agg_speedup": round(t_loop / train_s, 4),
            "smoke": bool(args.smoke),
        }
        records.append(rec)
        for head, status in enumerate(statuses):
            if status != "CONVERGED":
                violations.append(f"{arm}: head {head} ended {status}")
        if not sv_parity:
            flips = [len(a ^ b) for a, b in zip(svs, ctl_svs)]
            violations.append(
                f"{arm}: per-head SV sets differ from the control "
                f"(flips per head: {flips})")
        if not accuracy_parity:
            violations.append(
                f"{arm}: held-out accuracy {acc} != control {ctl_acc}")

    # (C, gamma) sweep through the WARMED fleet executable: per-problem
    # hyperparameters are arrays, so every sweep point must reuse the
    # one compiled program — any recompile is a launch-economics
    # regression (the weak-scalar discipline, enforced by construction)
    from tpusvm.fleet import fleet_smo_solve
    from tpusvm.obs.registry import MetricsRegistry

    sweep_pts = [(C, gamma), (3.0 * C, gamma), (C, 2.0 * gamma),
                 (0.5 * C, 0.5 * gamma)]
    with prof.profiling(registry=MetricsRegistry()) as obs:
        for (c_val, g_val) in sweep_pts:
            res = fleet_smo_solve(
                Xd, jnp.asarray(np.stack(Ys)),
                Cs=jnp.asarray([c_val] * B), gammas=jnp.asarray([g_val] * B),
                sn=sn, **base)
            np.asarray(res.alpha)
        sweep_compiles = sum(
            1 for r in obs.records
            if r["executable"] == "solver.fleet_smo_solve")
    sweep_recompiles = sweep_compiles - 1
    if sweep_recompiles != 0:
        violations.append(
            f"(C, gamma) sweep recompiled {sweep_recompiles} time(s) "
            "after warmup — per-problem hyperparameter values leaked "
            "into the trace")

    best = max((r for r in records if r["mode"] != "loop"),
               key=lambda r: r["agg_speedup"])
    gate = (SPEEDUP_GATE_TPU if jax.default_backend() == "tpu"
            else SPEEDUP_GATE_CPU)
    if not args.smoke and best["agg_speedup"] < gate:
        violations.append(
            f"best fleet arm {best['mode']} at "
            f"{best['agg_speedup']:.2f}x is under the {gate}x "
            f"{jax.default_backend()} gate")
    summary = {
        "bench": "fleet_train",
        "summary": True,
        "B": B, "bucket": bucket,
        "n": args.n, "d": args.d, "q": args.q,
        "loop_train_s": round(t_loop, 6),
        "best_mode": best["mode"],
        "agg_speedup": best["agg_speedup"],
        "sv_parity": all(r["sv_parity"] for r in records),
        "accuracy_parity": all(r["accuracy_parity"] for r in records),
        "sweep_points": len(sweep_pts),
        "sweep_compiles": sweep_compiles,
        "sweep_recompiles": sweep_recompiles,
        "speedup_gate": gate if not args.smoke else None,
        "smoke": bool(args.smoke),
        "violations": violations,
    }
    records.append(summary)
    for rec in records:
        emit(rec)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    if violations:
        for v in violations:
            log(f"GATE FAILED: {v}")
        return 1
    log(f"fleet_train: best arm {best['mode']} at "
        f"{best['agg_speedup']:.2f}x aggregate vs the {B}-head loop "
        f"({t_loop:.3f}s), sweep recompiles {sweep_recompiles}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
