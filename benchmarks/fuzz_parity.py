"""Randomized cross-engine parity fuzz: every engine vs the f64 oracle.

Breadth complement to the targeted parity artifacts: where
`midscale_parity.py` proves the reference's criterion at production scale
on the bench recipe, this sweeps RANDOM geometry (generator, n, d, C,
gamma) and checks every solver engine against the NumPy oracle on each
instance — the blocked solver across its selection × wss grid plus the
f64 pair solver. Criterion per instance (the cross-engine standard of
tests/test_solver_parity.py): both CONVERGED, SV symmetric difference
<= max(2, n_sv/25) (f32 features vs the oracle's f64 allow tau-band
boundary flips; the pair solver runs f64 and must match the SV set
exactly), and b within a scale-aware band.

The b band is scale-aware: max(2e-3 absolute, 0.02% of |b_oracle|). The
absolute floor matches the cross-engine test standard at the usual |b|~1
geometry; the relative term covers large-|b| instances (rings at C=100
put b ~ 40-46), where the f32 engines' kernel-evaluation noise scales
with the dual magnitudes (~sum|alpha|*1e-7, see solver/blocked.py's
refine discussion) — observed spread there is ~0.005-0.01% relative,
identical for exact and approx selection (so it is precision, not
selection), while the f64 pair solver stays within 1e-4 absolute.

Usage: python benchmarks/fuzz_parity.py [n_cases] [base_seed] [mode]
Emits one JSON line per case with per-engine verdicts, then a summary
line {cases, engines, violations}. A committed run lives in
benchmarks/results/fuzz_parity_cpu.jsonl.

mode='pallas' fuzzes the PALLAS inner engine instead (the kernel every
TPU headline runs; interpret mode off-TPU — true f32 math, same
program): inner='pallas' at q=128 across the wss grid, with the
instance n range floored at 160 so the clamped q stays lane-aligned
(128 | q). mode='pallas-packed' raises q to 256 (n floored at 288) —
the smallest GENUINE multi-row packed layout (R=2: cross-sublane index
mapping and reductions, the lowering the q=2048 headline runs at R=16;
q=128 is R=1, bitwise the flat layout). The kernel's deviations from
the XLA loop are documented in ops/pallas/inner_smo.py (f32 subproblem,
shrinking instead of bail-out) and covered by the same tau-band SV
allowance; committed runs live in
benchmarks/results/fuzz_parity_pallas_cpu.jsonl (one batch per mode;
the summary rows carry the mode). Each mode keeps its own seed-for-seed
reproduction contract (the default mode's committed rows predate this
flag and are unchanged).

Round 5 additions: both pallas modes also run the eta_exclude engine
(the VERDICT r4 #5 unified selection rule), and mode='pallas-mp' fuzzes
the batched slot-pair kernel (pallas_multipair=2 at q=512, VERDICT r4
#3) against the sequential kernel and the oracle. Engines run after the
rng-driven instance generation, so the added engines preserve each
mode's seed-for-seed instance contract.

Round 7 additions (the kernel/task matrix, ISSUE 6): modes 'linear',
'poly' and 'svr' fuzz the new scenarios against the kernel-extended
oracle. 'linear' runs the XLA engines with kernel='linear' — including a
kernel_fast=False engine, so the primal fast path and the generic K-row
path carry randomized equal-solutions evidence against each other as
well as the oracle. 'poly' draws degree from {2, 3} at coef0=1.0 (an
extra rng draw AFTER the shared instance stream — each mode owns its
seed contract). 'svr' derives a smooth continuous target from the drawn
instance's features (+ noise), doubles the variables
(tpusvm.kernels.svr), and checks the collapsed alpha - alpha*
coefficients' SV identity and b against oracle.svr_train; the f64
engine must match the SV set exactly, f32 engines get the usual
tau-band allowance. Committed batches live in
benchmarks/results/fuzz_parity_kernels_cpu.jsonl.

Round 13 additions (the approximate-kernel primal regime, ISSUE 13):
mode='sigmoid' fuzzes the tanh(gamma/8 x.z - 1) family against the
kernel-extended oracle like poly, but with FIRST-ORDER engines only
(SIGMOID_ENGINES: the kernel is indefinite, so wss=2's curvature-model
selection can converge to a different stationary point — excluded from
the gate by principle, not band); instances whose oracle bails
degenerate are recorded as skipped, the established rule. mode='rff' is DIFFERENT in kind:
the approximate families have no per-instance oracle kernel — their
correctness claim is that the EXACT rbf solution's quality survives the
map — so the gate is a held-out ACCURACY DELTA, not SV-set identity:
each instance draws 256 extra held-out rows, the f64 rbf oracle is
trained and scored on them, and the rff (D=2048) and nystrom (k=128)
arms must land within APPROX_ACC_BAND of the oracle's held-out accuracy
(n floored at 192 so the landmark draw fits). The committed batch lives
in benchmarks/results/fuzz_parity_approx_cpu.jsonl.

Round 6 addition: mode='pallas-mp-adv' — the multipair engines on an
ADVERSARIAL derivation of the drawn instance (ADVICE r5 #4 geometry):
rows reordered so the +/- labels form contiguous blocks (the outer
working-set gather then tends to place the global pair's ends in
different slot halves, the cross-slot case whose stale-b global step
the round-6 glob_touched guard skips) and neighbouring rows duplicated
in place to seed eta == 0 degenerate pairs — including contradictory-
label duplicates at the block boundary, the hardest shrink-path food.
A NEW mode rather than a change to 'pallas-mp', so the committed
pallas-mp rows keep their seed-for-seed instance contract.
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

from benchmarks.common import pin_platform, random_instance  # noqa: E402

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.config import SVMConfig  # noqa: E402
from tpusvm.data import MinMaxScaler  # noqa: E402
from tpusvm.kernels.svr import collapse_duals, doubled_problem  # noqa: E402
from tpusvm.oracle import get_sv_indices, smo_train, svr_train  # noqa: E402
from tpusvm.solver import smo_solve  # noqa: E402
from tpusvm.solver.blocked import blocked_smo_solve  # noqa: E402
from tpusvm.status import Status  # noqa: E402

# (engine name, solver kwargs, f64 features?) — f64 engines must match the
# oracle's SV set exactly; f32 engines get the tau-band allowance
ENGINES = [
    ("pair-f64", None, True),
    ("blocked-exact", dict(selection="exact", wss=1), False),
    ("blocked-approx", dict(selection="approx", wss=1), False),
    ("blocked-exact-wss2", dict(selection="exact", wss=2), False),
    ("blocked-approx-wss2", dict(selection="approx", wss=2), False),
]

# the pallas modes: the single-launch kernel across the wss grid
# (selection exact keeps the working-set pick deterministic; the kernel
# itself is the thing under test). Which layout the kernel runs — and
# the q / n-floor that selects it — is per-mode, in MODES below.
PALLAS_ENGINES = [
    ("pair-f64", None, True),
    ("blocked-pallas-wss1",
     dict(selection="exact", wss=1, inner="pallas"), False),
    ("blocked-pallas-wss2",
     dict(selection="exact", wss=2, inner="pallas"), False),
    # VERDICT r4 #5: the kernel with the XLA engine's degenerate-partner
    # exclusion folded into its gain selection (pallas_eta_exclude) —
    # fuzzed alongside the default shrink-policy kernel so the unified
    # selection rule carries the same randomized parity evidence.
    # Engines run after the rng-driven instance generation, so adding
    # this engine preserves the seed-for-seed instance contract.
    ("blocked-pallas-wss2-etax",
     dict(selection="exact", wss=2, inner="pallas",
          pallas_eta_exclude=True), False),
]

# VERDICT r4 #3: the batched slot-pair kernel (multipair) vs the
# sequential kernel, both first-order. q=512 is the smallest working set
# with a valid p=2 slot partition ((q//128) % (2p) == 0); the instance
# floor keeps the clamped q at 512.
MP_ENGINES = [
    ("pair-f64", None, True),
    ("blocked-pallas-wss1",
     dict(selection="exact", wss=1, inner="pallas"), False),
    ("blocked-pallas-mp2",
     dict(selection="exact", wss=1, inner="pallas",
          pallas_multipair=2), False),
]


# the kernel/task matrix modes (round 7): the XLA engines under each new
# scenario. The linear mode adds the generic-K-row-path engine so
# fast-vs-generic equal-solutions evidence rides every batch.
LINEAR_ENGINES = [
    ("pair-f64", None, True),
    ("blocked-exact", dict(selection="exact", wss=1), False),
    ("blocked-exact-wss2", dict(selection="exact", wss=2), False),
    ("blocked-generic-path",
     dict(selection="exact", wss=1, kernel_fast=False), False),
]
KERNEL_TASK_ENGINES = [
    ("pair-f64", None, True),
    ("blocked-exact", dict(selection="exact", wss=1), False),
    ("blocked-exact-wss2", dict(selection="exact", wss=2), False),
]

# the sigmoid mode runs FIRST-ORDER selections only: the kernel is
# indefinite (conditionally PSD — kernels/sigmoid.py), so the dual is
# non-convex and SMO converges to A stationary point; the first-order
# Keerthi rule follows the oracle's trajectory and lands on the same
# one, but wss=2's second-order gain model (eta as positive curvature)
# can legitimately steer to a DIFFERENT stationary point on indefinite
# instances — observed at seed 15033 (blobs, C=100: same CONVERGED
# claim, b apart by 287 — a different solution, not drift). That is a
# property of second-order selection on indefinite kernels, not an
# engine bug, so it is excluded from the parity gate rather than
# papered over with a band.
SIGMOID_ENGINES = [
    ("pair-f64", None, True),
    ("blocked-exact", dict(selection="exact", wss=1), False),
    ("blocked-approx", dict(selection="approx", wss=1), False),
]

# mode -> (engines, instance n range, working-set size q, scenario). The
# two pallas modes differ in which kernel layout the clamped q exercises:
# q=128 is R=1 (bitwise the flat layout), q=256 is the smallest GENUINE
# multi-row packed layout (R=2 — cross-sublane index mapping and
# reductions, the lowering the q=2048 headline runs at R=16); each
# floors n so clamping never unaligns q. `scenario` names the (kernel,
# task) cell the mode fuzzes; None = the original binary RBF family.
MODES = {
    "xla": (ENGINES, (96, 640), 256, None),
    "pallas": (PALLAS_ENGINES, (160, 640), 128, None),
    "pallas-packed": (PALLAS_ENGINES, (288, 768), 256, None),
    "pallas-mp": (MP_ENGINES, (520, 900), 512, None),
    # the ADVICE r5 #4 adversarial family (see module docstring): same
    # engines/q as pallas-mp, instance derivation differs
    "pallas-mp-adv": (MP_ENGINES, (520, 900), 512, None),
    # the kernel/task matrix (ISSUE 6): n capped lower for svr because
    # the doubling makes the solve 2n-sized
    "linear": (LINEAR_ENGINES, (96, 640), 256, "linear"),
    "poly": (KERNEL_TASK_ENGINES, (96, 640), 256, "poly"),
    "svr": (KERNEL_TASK_ENGINES, (96, 400), 256, "svr"),
    # the approximate-kernel regime (ISSUE 13): sigmoid is a normal
    # oracle-parity scenario; 'rff' runs the accuracy-delta gate against
    # the exact rbf oracle (run_case_approx — n floored at 192 so the
    # k=128 nystrom landmark draw always fits)
    "sigmoid": (SIGMOID_ENGINES, (96, 640), 256, "sigmoid"),
    "rff": (None, (192, 640), 256, "approx"),
}

# the approx arms of mode='rff': (name, family, config overrides). D and
# k follow the satellite gate (D=2048 at n<=4096; k=128 tile-aligned)
APPROX_ARMS = [
    ("blocked-rff-d2048", "rff", {"rff_dim": 2048}),
    ("blocked-nystrom-k128", "nystrom", {"landmarks": 128}),
]

# held-out accuracy band of the approx arms vs the exact rbf oracle:
# measured max delta over the committed 32-case corpus is 0.0039 (rff,
# low-gamma rings — rings/blobs are cleanly separable, so most cases
# sit at delta 0); the noisy mnist-shaped workload of
# benchmarks/approx_scale.py measures up to ~0.02, and the band holds
# >2x headroom over that — one 256-row held-out flip is 0.0039, so
# 0.055 tolerates ~14 boundary flips before calling the map broken
APPROX_ACC_BAND = 0.055


def _adversarialize(X, Y):
    """Block-sort the labels and duplicate neighbouring rows in place.

    Contiguous +/- label blocks steer the multipair kernel's global pair
    ends into different slot halves (the cross-slot case of ADVICE r5
    #4); pairwise-duplicated rows seed eta == 0 pairs for the shrink
    path, including a contradictory-label duplicate at the block
    boundary. Label counts and the rng stream are untouched.
    """
    order = np.argsort(-Y, kind="stable")
    X, Y = X[order].copy(), Y[order]
    half = X[1::2].shape[0]
    X[1::2] = X[: 2 * half : 2]
    return X, Y


def engines_for(mode: str):
    if mode == "rff":
        return [(name, None, False) for name, _, _ in APPROX_ARMS]
    return MODES[mode][0]


def run_case_approx(seed: int):
    """One accuracy-delta case: exact rbf oracle vs the approx arms.

    The instance draw shares random_instance (the committed-corpus
    geometry family) with 256 EXTRA held-out rows scaled by the train
    stats; the oracle and every arm train on the same scaled rows and
    score the same held-out slice. Gate per arm: CONVERGED status and
    held-out accuracy within APPROX_ACC_BAND of the oracle's.
    """
    from tpusvm.approx import build_map
    from tpusvm.oracle.smo import kernel_row

    _, n_range, q, _ = MODES["rff"]
    rng = np.random.default_rng(seed)
    gen_name, n, X, Y, C, gamma = random_instance(
        rng, seed, n_range, (2, 24), [1.0, 10.0, 100.0],
        [0.125, 0.5, 2.0, 10.0], extra=256)
    sc = MinMaxScaler().fit(X[:n])
    Xs, Xt = sc.transform(X[:n]), sc.transform(X[n:])
    Ytr, Yt = Y[:n], Y[n:]
    cfg = SVMConfig(C=C, gamma=gamma)
    o = smo_train(Xs, Ytr, cfg)
    rec = {"seed": seed, "gen": gen_name, "scenario": "approx",
           "n": n, "d": Xs.shape[1], "n_test": len(Yt),
           "C": C, "gamma": round(gamma, 6),
           "oracle_status": Status(int(o.status)).name,
           "n_sv": int(len(get_sv_indices(o.alpha))),
           "b": float(o.b), "engines": {}, "violations": []}
    if o.status != Status.CONVERGED:
        rec["skipped"] = True
        return rec
    # oracle held-out accuracy: the exact-rbf quality every arm must keep
    coef_o = o.alpha * Ytr
    scores_o = np.array([
        float(kernel_row(Xs, x, cfg) @ coef_o) - o.b for x in Xt])
    acc_o = float(((scores_o > 0) * 2 - 1 == Yt).mean())
    rec["oracle_accuracy"] = round(acc_o, 6)
    for name, family, overrides in APPROX_ARMS:
        acfg = SVMConfig(C=C, gamma=gamma, kernel=family, map_seed=seed,
                         **overrides)
        fmap = build_map(acfg, X_scaled=Xs)
        Z = fmap.transform_np(Xs)
        Zt = fmap.transform_np(Xt)
        r = blocked_smo_solve(
            jnp.asarray(Z), jnp.asarray(Ytr), q=q, max_inner=1024,
            max_outer=2000, C=C, gamma=gamma, eps=cfg.eps, tau=cfg.tau,
            max_iter=cfg.max_iter, kernel=family,
            accum_dtype=jnp.float64)
        coef = np.asarray(r.alpha, np.float64) * Ytr
        acc = float((((Zt.astype(np.float64) @
                       (Z.astype(np.float64).T @ coef)
                       - float(r.b)) > 0) * 2 - 1 == Yt).mean())
        delta = acc_o - acc
        ok = (int(r.status) == Status.CONVERGED
              and delta <= APPROX_ACC_BAND)
        rec["engines"][name] = {
            "status": Status(int(r.status)).name,
            "accuracy": round(acc, 6),
            "acc_delta": round(delta, 6),
            "band": APPROX_ACC_BAND, "ok": bool(ok),
        }
        if not ok:
            rec["violations"].append(name)
    return rec


def run_case(seed: int, mode: str = "xla"):
    if mode == "rff":
        return run_case_approx(seed)
    engines, n_range, q, scenario = MODES[mode]
    rng = np.random.default_rng(seed)
    gen_name, n, X, Y, C, gamma = random_instance(
        rng, seed, n_range, (2, 24), [1.0, 10.0, 100.0],
        [0.125, 0.5, 2.0, 10.0])
    adversarial = mode.endswith("-adv")
    if adversarial:
        # AFTER the rng draws: the derivation shares the base modes'
        # instance stream without perturbing it
        X, Y = _adversarialize(X, Y)
    Xs = MinMaxScaler().fit_transform(X)

    # scenario derivation AFTER the shared instance draws: each mode owns
    # its rng continuation (the base modes' streams are untouched)
    targets = None
    if scenario == "linear":
        cfg = SVMConfig(C=C, gamma=gamma, kernel="linear")
    elif scenario == "sigmoid":
        # scenario derivation (mode owns its seed contract, like poly's
        # degree draw): gamma/8 with coef0=-1.0 — the tanh argument then
        # spans the kernel's informative range on unit-scaled data; the
        # raw rbf-calibrated draws (up to 10) saturate tanh into
        # degenerate-eta geometry and skip ~2/3 of the corpus (the
        # conditionally-PSD caveat, kernels/sigmoid.py)
        cfg = SVMConfig(C=C, gamma=gamma / 8.0, kernel="sigmoid",
                        coef0=-1.0)
    elif scenario == "poly":
        degree = int(rng.choice([2, 3]))
        cfg = SVMConfig(C=C, gamma=gamma, kernel="poly", degree=degree,
                        coef0=1.0)
    elif scenario == "svr":
        # smooth continuous target from the drawn features + noise; the
        # epsilon tube is drawn per instance
        t = (np.sin(4.0 * Xs[:, 0]) + 0.5 * Xs[:, -1]
             + 0.1 * rng.standard_normal(len(Xs)))
        eps_tube = float(rng.choice([0.05, 0.1, 0.2]))
        cfg = SVMConfig(C=C, gamma=gamma, epsilon=eps_tube)
        Y2, z = doubled_problem(t[:n], eps_tube)
        Xs2 = np.concatenate([Xs[:n], Xs[:n]])
        targets = z
    else:
        cfg = SVMConfig(C=C, gamma=gamma)

    if scenario == "svr":
        o = svr_train(Xs[:n], t[:n], cfg)
    else:
        o = smo_train(Xs, Y, cfg)
    # n_sv keeps the historical semantics (raw oracle SV count — for svr
    # the raw 2n betas' count, matching get_sv_indices) so committed rows
    # of the pre-existing modes reproduce byte-for-byte
    rec = {"seed": seed, "gen": gen_name, "adversarial": adversarial,
           "scenario": scenario or "rbf-svc",
           "n": n, "d": Xs.shape[1],
           "C": C, "gamma": round(gamma, 6),
           "kernel": cfg.kernel, "degree": cfg.degree,
           "oracle_status": Status(int(o.status)).name,
           "n_sv": int(len(get_sv_indices(o.alpha))),
           "b": float(o.b), "engines": {}, "violations": []}
    if o.status != Status.CONVERGED:
        # degenerate instance (the oracle itself bailed): skip, recorded
        rec["skipped"] = True
        return rec

    def sv_set(alpha):
        alpha = np.asarray(alpha)
        if scenario == "svr":
            # SV identity lives on the COLLAPSED signed coefficients
            # alpha_i - alpha*_i, the quantities prediction consumes
            coef = collapse_duals(alpha)
            return set(np.nonzero(np.abs(coef) > 1e-8)[0].tolist())
        sv = get_sv_indices(alpha).tolist()
        if adversarial:
            # rows (2k, 2k+1) are exact duplicates: the optimum only
            # determines the SUM of a duplicate pair's alphas, so SV
            # identity within a pair is degenerate — compare
            # duplicate-GROUP membership, not raw indices
            sv = {i - (i % 2) for i in sv}
        return set(sv)

    sv_o = sv_set(o.alpha)

    common = dict(C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
                  max_iter=cfg.max_iter, accum_dtype=jnp.float64,
                  kernel=cfg.kernel, degree=cfg.degree, coef0=cfg.coef0)
    if scenario == "svr":
        X_in, Y_in = Xs2, Y2
    else:
        X_in, Y_in = Xs, Y
    tgt = None if targets is None else jnp.asarray(targets)
    # one jit cache entry per (n, d) shape per engine config; the fuzz
    # intentionally varies shapes, so expect recompiles — correctness run,
    # not a timing run
    for name, opts, f64 in engines:
        if opts is None:
            r = smo_solve(jnp.asarray(X_in, jnp.float64),
                          jnp.asarray(Y_in), targets=tgt, **common)
        else:
            opts = dict(opts)
            inner = opts.pop("inner", "xla")
            r = blocked_smo_solve(
                jnp.asarray(X_in, jnp.float32), jnp.asarray(Y_in),
                q=q, max_inner=1024, max_outer=2000, inner=inner,
                targets=tgt, **opts, **common)
        sv = sv_set(r.alpha)
        sym = len(sv ^ sv_o)
        db = abs(float(r.b) - o.b)
        allowed = 0 if f64 else max(2, len(sv_o) // 25)
        # scale-aware b band (see module docstring); the f64 pair solver
        # is held to the absolute floor alone
        # adversarial instances widen the f32 relative term 5x: pairwise
        # row duplication concentrates ~2x the alpha mass at the C bound
        # (a duplicate pair shares the optimum's mass), and the f32
        # engines' b noise scales with sum|alpha| (see module docstring) —
        # measured 0.07% relative at seed 9107 (|b|~40, BOTH the
        # sequential and multipair kernels, so it is precision, not the
        # slot schedule), vs the 0.005-0.01% of the clean families. The
        # f64 pair solver stays on the absolute floor either way.
        rel = 1e-3 if adversarial else 2e-4
        if f64:
            b_band = 2e-3
        elif scenario == "svr":
            # SVR's b is the centre of an epsilon-tube active-constraint
            # window whose f32 position shifts with the accumulated
            # kernel-evaluation noise — and unlike classification,
            # |b| ~ target scale carries NO information about the dual
            # mass (C=100 instances hold 1e4+ of it over the doubled
            # set), so the |b|-relative term under-covers; at small
            # gamma the near-singular Gram makes the dual outright
            # non-unique and b wanders within the tube (0.065 observed
            # at C=100, gamma=0.031, seed 13036 — SV set still matched
            # to allowance). The dual-mass term carries that scale
            # (RBF diag = 1); refine does not reduce it — it is
            # solution-level indeterminacy within the tolerance, not
            # drift. The f64 engine stays on the classification floor
            # (observed <= 3e-5).
            b_band = max(2.5e-2, rel * abs(o.b),
                         5e-6 * float(np.abs(o.alpha).sum()))
        elif scenario in ("linear", "poly", "sigmoid"):
            # the f32 engines' b noise scales with the DUAL MASS times
            # the KERNEL MAGNITUDE (f accumulates sum_j alpha_j K_ij
            # with ~1e-7 relative evaluation error — the solver's
            # documented noise model, solver/blocked.py refine
            # discussion), while |b| stays O(1): rings x linear at
            # C=100 pins 568 duals at the bound (6e-3 observed at
            # |b|=0.23, seed 11039), and the poly epilogue reaches
            # K ~ (gamma*d + coef0)^degree ~ 1e3 at gamma=10 (1.3e-2
            # observed with only 5 SVs, seed 12006). Both scales are
            # observable from the oracle solution, so the band carries
            # them explicitly — for the NEW scenarios only; the
            # pre-existing modes keep their committed band policy.
            k_diag = (Xs * Xs).sum(axis=1)
            if scenario == "poly":
                k_diag = (cfg.gamma * k_diag + cfg.coef0) ** cfg.degree
            elif scenario == "sigmoid":
                # |tanh| <= 1 bounds the kernel magnitude outright
                k_diag = np.abs(np.tanh(cfg.gamma * k_diag + cfg.coef0))
            b_band = max(2e-3, rel * abs(o.b),
                         1e-6 * float(np.abs(o.alpha).sum())
                         * float(k_diag.max()))
        else:
            b_band = max(2e-3, rel * abs(o.b))
        ok = (int(r.status) == Status.CONVERGED and sym <= allowed
              and db <= b_band)
        rec["engines"][name] = {
            "status": Status(int(r.status)).name,
            "sv_sym_diff": sym, "b_abs_diff": round(db, 8),
            "b_band": round(b_band, 8), "ok": bool(ok),
        }
        if not ok:
            rec["violations"].append(name)
    return rec


def main(n_cases: int = 64, base_seed: int = 1000,
         mode: str = "xla") -> int:
    if mode not in MODES:
        raise SystemExit(
            f"mode must be one of {sorted(MODES)}, got {mode!r}")
    violations = 0
    skipped = 0
    for i in range(n_cases):
        # every case jit-compiles fresh (n, d) shapes for every engine;
        # without eviction the accumulated executables grow the process
        # to an LLVM OOM/segfault around case ~55 at four engines
        # (observed deterministically on the 1-core dev box). This is a
        # correctness harness — recompiles cost time, not signal.
        if i and i % 8 == 0:
            jax.clear_caches()
        rec = run_case(base_seed + i, mode=mode)
        print(json.dumps(rec), flush=True)
        skipped += int(bool(rec.get("skipped")))
        violations += len(rec["violations"])
    print(json.dumps({
        "summary": True, "cases": n_cases, "skipped_degenerate": skipped,
        "mode": mode,
        "engines": [e[0] for e in engines_for(mode)],
        "violations": violations,
        "platform": jax.default_backend(),
    }), flush=True)
    return 0 if violations == 0 else 1


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 64,
                  int(sys.argv[2]) if len(sys.argv) > 2 else 1000,
                  sys.argv[3] if len(sys.argv) > 3 else "xla"))
