#!/usr/bin/env python
"""Cascade shard-count sweep — the reference's MPI scaling study (B4-B13).

The reference trains the cascade at P in {4,8,16,32,64} ranks over 2x32-core
nodes for both topologies (report Tables 3-4) and reports train time,
speedup over serial, and efficiency. This harness reproduces the sweep over
a jax.sharding.Mesh. With one real TPU chip the mesh members are virtual
(XLA_FLAGS=--xla_force_host_platform_device_count=P JAX_PLATFORMS=cpu for a
CPU simulation, SURVEY.md §4), so absolute times on CPU are not TPU
numbers — the sweep's purpose there is convergence behaviour (rounds,
SV-set parity across P, the reference's Fig. 6 claim that ~97% of final
SVs appear in round 1). On a real multi-chip TPU slice the same script is
the wall-clock scaling benchmark.

One JSON line per (topology, P):
  {"topology": ..., "P": ..., "train_s": ..., "rounds": ..., "n_sv": ...,
   "accuracy": ..., "round1_sv_fraction": ..., "sv_set_match_vs_first": ...,
   "sv_jaccard_vs_first": ..., "per_round": [{"round", "sv_count",
   "time_s"}...], "vs_cascade_ref": ..., "vs_serial_ref": ...}

round1_sv_fraction is the reference's Fig. 6 statistic: the fraction of the
FINAL SV set already present after round 1 (|ids_1 ∩ ids_final| /
|ids_final| — the report claims ~97%). sv_set_match_vs_first /
sv_jaccard_vs_first carry the reference's cross-P parity claim ("all runs
achieve the same accuracy ... with 1548 SVs"): every config's final SV-ID
set is compared against the sweep's first completed run.

Usage:
  python benchmarks/sweep_p.py --n 8192 --d 256 --shards 2 4 8
  python benchmarks/sweep_p.py --smoke
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=60000)
    ap.add_argument("--n-test", type=int, default=10000)
    ap.add_argument("--d", type=int, default=784)
    ap.add_argument("--shards", type=int, nargs="+", default=[4, 8])
    ap.add_argument("--topologies", nargs="+", default=["tree", "star"],
                    choices=["tree", "star"])
    ap.add_argument("--sv-capacity", type=int, default=4096)
    ap.add_argument("--solver", choices=["pair", "blocked"], default="blocked",
                    help="per-shard solver; blocked (default) keeps the "
                    "simulated-mesh sweep tractable and is the production "
                    "accelerated-solver-per-shard hybrid; both converge to "
                    "the same stopping criterion (SURVEY.md §4 parity)")
    ap.add_argument("--gamma", type=float, default=0.00125,
                    help="RBF width (reference MNIST value); ~1/d in --smoke")
    ap.add_argument("--platform", choices=["cpu", "native"], default="cpu",
                    help="cpu = simulated multi-device mesh (default); "
                    "native = use the real devices as configured")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)

    if args.smoke:
        args.n, args.d, args.shards = 2048, 64, [2, 4]
        args.n_test = 512
        args.sv_capacity = 1024
        args.gamma = 1.0 / args.d  # keep gamma*d ~ constant at small d
    if args.n_test <= 0:
        ap.error("--n-test must be >= 1 (the sweep reports held-out accuracy)")

    max_p = max(args.shards)
    if args.platform == "cpu":
        # virtual-device CPU mesh. Env-var JAX_PLATFORMS can be overridden
        # by sitecustomize-registered plugins, so select the platform via
        # jax.config (must happen before backend init), like tests/conftest.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={max_p}"
            ).strip()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from benchmarks.common import (
        CASCADE_TRAIN_S,
        SERIAL_TRAIN_S,
        emit,
        log,
        make_workload,
    )
    from tpusvm.config import CascadeConfig, SVMConfig
    from tpusvm.parallel import cascade_fit, make_mesh

    import numpy as np

    from tpusvm.solver.predict import predict as device_predict

    log(f"devices: {len(jax.devices())} x {jax.devices()[0].platform}")
    log(f"workload: n={args.n} d={args.d} n_test={args.n_test}")
    Xs, Y, Xt, Yt = make_workload(args.n, args.d, n_test=args.n_test)
    cfg = SVMConfig(gamma=args.gamma)  # other constants = reference

    first_ids = None  # cross-P SV-set parity baseline (first completed run)
    for topology in args.topologies:
        for p in args.shards:
            if topology == "tree" and (p & (p - 1)) != 0:
                log(f"skip tree P={p} (needs power of two)")
                continue
            mesh = make_mesh(p)
            t0 = time.perf_counter()
            res = cascade_fit(
                Xs, Y, cfg,
                CascadeConfig(n_shards=p, sv_capacity=args.sv_capacity,
                              topology=topology),
                mesh=mesh, accum_dtype=jnp.float64, solver=args.solver,
            )
            train_s = time.perf_counter() - t0

            final_ids = set(res.sv_ids.tolist())
            # the Fig. 6 statistic: final SVs already present after round 1
            ids_r1 = set(res.history[0]["sv_ids"].tolist()) if res.history else set()
            round1_frac = len(ids_r1 & final_ids) / max(len(final_ids), 1)

            if first_ids is None:
                first_ids = final_ids
            jac = (len(final_ids & first_ids)
                   / max(len(final_ids | first_ids), 1))

            yp = np.asarray(device_predict(
                jnp.asarray(Xt, jnp.float32), jnp.asarray(res.sv_X, jnp.float32),
                jnp.asarray(res.sv_Y), jnp.asarray(res.sv_alpha, jnp.float32),
                jnp.asarray(res.b, jnp.float32), gamma=cfg.gamma,
            ))
            ref = CASCADE_TRAIN_S.get((topology, p))
            emit({
                "topology": topology,
                "P": p,
                "solver": args.solver,
                "train_s": round(train_s, 3),
                "rounds": res.rounds,
                "converged": res.converged,
                "n_sv": len(res.sv_ids),
                "b": res.b,
                "accuracy": float((yp == Yt).mean()),
                "round1_sv_fraction": round(round1_frac, 4),
                "sv_set_match_vs_first": final_ids == first_ids,
                "sv_jaccard_vs_first": round(jac, 4),
                "per_round": [
                    {"round": h["round"], "sv_count": h["sv_count"],
                     "time_s": round(h["time_s"], 3)}
                    for h in res.history
                ],
                "vs_cascade_ref": round(ref / train_s, 2) if ref else None,
                "vs_serial_ref": round(SERIAL_TRAIN_S / train_s, 2),
                "platform": jax.devices()[0].platform,
            })
    return 0


if __name__ == "__main__":
    sys.exit(main())
