"""Mid-size virtual-mesh cascade artifact (VERDICT r4 #6).

The multichip dryrun proves the four topology x solver paths compile and
converge at toy size (n=128); the cascade fuzz proves randomized parity at
n <= 650. Neither exercises the cascade at a size where the production
machinery is under real pressure: q-clamping (per-shard n around the
production q), sv_capacity pressure on the merge buffers, and multi-round
ID-set convergence over thousands of SVs. This harness runs ONE
production-scale instance — the bench-recipe workload (the frozen recipe
every headline benchmark trains, bench.py docstring) at n=16384 over a
P=8 mesh — through BOTH topologies with the blocked per-shard solver
(the accelerated-solver-per-rank hybrid, SURVEY.md §2.3 last row), and
checks each against the direct single-shard blocked solve:

  - converged (ID-set fixed point) within max_rounds
    (the reference converges in 6-7 rounds at every P on its n=60k run,
    report §6.2 / mpi_svm_main3.cpp:565-828);
  - SV-set Jaccard vs the direct solve >= 0.85 (the cascade fixed point
    is NOT bitwise the direct optimum; the reference's own claim at
    convergence is accuracy + SV-count agreement);
  - held-out accuracy within 0.01 of the direct solve.

Timing fields are recorded for context but are ANTI-SIGNAL on the
simulated mesh (8 shards execute serially on one host core — same
caveat as sweep_p_sim_cpu.jsonl); convergence behavior is the payload.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  TPUSVM_PROBE_PLATFORM=cpu python benchmarks/midsize_cascade.py
  ... --smoke   # tiny variant for the test suite

A committed run lives in benchmarks/results/midsize_cascade_sim_cpu.jsonl
(re-runnable smoke: tests/test_benchmarks.py).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# the virtual mesh needs the flag BEFORE backend init; respect an existing
# setting (the test conftest already provides 8 host devices)
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from benchmarks.common import (  # noqa: E402
    emit,
    log,
    pin_platform,
    workload_record,
)

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.config import CascadeConfig, SVMConfig  # noqa: E402
from tpusvm.data import MinMaxScaler, mnist_like  # noqa: E402
from tpusvm.data.synthetic import BENCH_NOISE  # noqa: E402
from tpusvm.oracle.smo import get_sv_indices  # noqa: E402
from tpusvm.parallel.cascade import cascade_fit  # noqa: E402
from tpusvm.solver.blocked import (  # noqa: E402
    blocked_smo_solve,
    resolve_solver_config,
)
from tpusvm.solver.predict import predict as device_predict  # noqa: E402
from tpusvm.status import Status  # noqa: E402


def _predict(sv_X, sv_Y, sv_alpha, b, Xq, gamma):
    yp = device_predict(
        jnp.asarray(Xq, jnp.float64), jnp.asarray(sv_X, jnp.float64),
        jnp.asarray(sv_Y), jnp.asarray(sv_alpha, jnp.float64),
        jnp.asarray(b, jnp.float64), gamma=gamma)
    return np.asarray(yp)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=16384)
    ap.add_argument("--n-test", type=int, default=2048)
    ap.add_argument("--d", type=int, default=784)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--sv-capacity", type=int, default=1536,
                    help="per-merge SV buffer capacity — REALISTIC (same "
                    "order as the expected global SV count), so capacity "
                    "pressure on the merge path is genuine, unlike the "
                    "fuzz's capacity=n")
    ap.add_argument("--gamma", type=float, default=0.00125)
    ap.add_argument("--q", type=int, default=2048,
                    help="blocked-solver working set (bench.py's tuned "
                    "value; per-shard n=2048 makes the clamp REAL)")
    ap.add_argument("--max-inner", type=int, default=4096)
    ap.add_argument("--wss", type=int, default=2, choices=(1, 2))
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    if args.smoke:
        args.n, args.n_test, args.d = 1024, 256, 64
        args.gamma = 1.0 / args.d
        args.sv_capacity = 512
        args.q = 256

    n, m = args.n, args.n_test
    log(f"devices: {jax.devices()}")
    if len(jax.devices()) < args.shards:
        log(f"ERROR: need >= {args.shards} devices "
            f"(run under XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{args.shards})")
        return 2

    log(f"generating bench-recipe workload (n={n + m}, d={args.d})...")
    X, Y = mnist_like(n=n + m, d=args.d, noise=BENCH_NOISE if args.smoke
                      else 30.0,
                      label_noise=0.0 if args.smoke else 0.005)
    workload = workload_record(
        mnist_like, n=n + m, d=args.d,
        noise=BENCH_NOISE if args.smoke else 30.0,
        label_noise=0.0 if args.smoke else 0.005)
    # shuffle before partitioning: contiguous partitions on class-ordered
    # data would hand shards a single class (the documented cascade
    # failure mode, raised loudly by cascade_fit)
    rng = np.random.default_rng(587)
    perm = rng.permutation(n + m)
    X, Y = X[perm], Y[perm]
    sc = MinMaxScaler().fit(X[:n])
    Xs = sc.transform(X[:n])
    Xq = sc.transform(X[n:])
    Yq = Y[n:]
    Y = Y[:n]

    cfg = SVMConfig(gamma=args.gamma, max_rounds=15)
    solver_opts = dict(q=args.q, max_inner=args.max_inner, wss=args.wss,
                       max_outer=5000)

    # control: direct single-shard blocked solve (production precision)
    log("direct blocked solve (control)...")
    t0 = time.perf_counter()
    r = blocked_smo_solve(
        jnp.asarray(Xs, jnp.float32), jnp.asarray(Y), C=cfg.C,
        gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau, max_iter=cfg.max_iter,
        accum_dtype=jnp.float64, **solver_opts)
    direct_s = time.perf_counter() - t0
    alpha = np.asarray(r.alpha)
    sv_direct = get_sv_indices(alpha)
    yp_d = _predict(Xs[sv_direct], Y[sv_direct], alpha[sv_direct],
                    float(r.b), Xq, cfg.gamma)
    acc_d = float((yp_d == Yq).mean())
    # the direct solve's SV ids live in the same global row-index space
    # the cascade's ids use (partition assigns global IDs = row index)
    sv_direct_set = set(int(i) for i in sv_direct)
    q_eff, inner_eff, wss_eff, sel_eff = resolve_solver_config(
        n, args.q, wss=args.wss)
    emit({"engine": "direct-blocked", "n": n, "d": args.d,
          "status": Status(int(r.status)).name, "n_sv": len(sv_direct_set),
          "b": float(r.b), "accuracy": acc_d,
          "train_s": round(direct_s, 2),
          "q": q_eff, "inner": inner_eff, "wss": wss_eff,
          "selection": sel_eff,
          "platform": jax.default_backend(), "workload": workload})

    violations = []
    for topo in ("tree", "star"):
        log(f"cascade {topo} (P={args.shards}, "
            f"sv_capacity={args.sv_capacity})...")
        cc = CascadeConfig(n_shards=args.shards,
                           sv_capacity=args.sv_capacity, topology=topo)
        t0 = time.perf_counter()
        res = cascade_fit(Xs, Y, cfg, cc, solver="blocked",
                          solver_opts=solver_opts)
        topo_s = time.perf_counter() - t0
        sv_c = set(int(i) for i in res.sv_ids.tolist())
        yp_c = _predict(res.sv_X, res.sv_Y, res.sv_alpha, res.b, Xq,
                        cfg.gamma)
        acc_c = float((yp_c == Yq).mean())
        jac = len(sv_c & sv_direct_set) / max(len(sv_c | sv_direct_set), 1)
        row = {"engine": f"cascade-{topo}", "n": n, "d": args.d,
               "shards": args.shards, "sv_capacity": args.sv_capacity,
               "converged": bool(res.converged), "rounds": res.rounds,
               "n_sv": len(sv_c), "b": float(res.b), "accuracy": acc_c,
               "sv_jaccard_vs_direct": round(jac, 4),
               "accuracy_gap_vs_direct": round(abs(acc_c - acc_d), 5),
               # ANTI-SIGNAL on the simulated mesh: 8 shards share one
               # host core (see module docstring)
               "train_s_simulated_mesh": round(topo_s, 2),
               "platform": jax.default_backend(), "workload": workload}
        if not res.converged:
            violations.append(f"{topo}-not-converged")
        if jac < 0.85:
            violations.append(f"{topo}-jaccard={jac:.3f}")
        if abs(acc_c - acc_d) > 0.01:
            violations.append(f"{topo}-accuracy-gap={abs(acc_c - acc_d):.4f}")
        row["violations"] = [v for v in violations if v.startswith(topo)]
        emit(row)

    emit({"summary": True, "n": n, "shards": args.shards,
          "violations": violations, "n_devices": len(jax.devices()),
          "platform": jax.default_backend()})
    return 0 if not violations else 1


if __name__ == "__main__":
    sys.exit(main())
