"""Randomized cascade fuzz: tree-vs-star convergence and model parity.

Breadth complement to benchmarks/sweep_p.py: where the P-sweep
demonstrates the reference's cascade properties (convergence in a few
rounds at every P, near-identical SV sets across topologies — report
Tables 3-4 / Fig. 6) on the one bench workload family, this sweeps RANDOM
geometry and checks, per instance:

  - BOTH topologies converge (ID-set fixed point) within max_rounds;
  - the tree and star models agree: SV-set Jaccard >= 0.9 and held-out
    predictions differ on at most max(2, m/50) points (the two merge
    schedules are different optimisation paths to the same fixed-point
    criterion, so tau-band boundary flips are allowed — the same
    standard as the repo's cross-engine parity);
  - cascade accuracy is within 0.05 of a direct single-shard solve on
    the same instance (the cascade's fixed point is NOT bitwise the
    direct optimum — the reference's own claim is accuracy parity).

The per-shard solver alternates pair/blocked by seed so both production
paths ride the fuzz. Rows are shuffled before partitioning (contiguous
partitions on class-sorted data would make shards single-class — the
documented cascade failure mode, raised loudly by cascade_fit).

Usage: python benchmarks/fuzz_cascade.py [n_cases] [base_seed] [shards]
Needs >= `shards` devices (run under
XLA_FLAGS=--xla_force_host_platform_device_count=8 TPUSVM_PROBE_PLATFORM=cpu
off-TPU). Emits one JSON line per case + a summary line. A committed run
lives in benchmarks/results/fuzz_cascade_sim_cpu.jsonl.
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

from benchmarks.common import pin_platform, random_instance  # noqa: E402

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.config import CascadeConfig, SVMConfig  # noqa: E402
from tpusvm.data import MinMaxScaler  # noqa: E402
from tpusvm.parallel.cascade import cascade_fit  # noqa: E402
from tpusvm.solver.blocked import blocked_smo_solve  # noqa: E402
from tpusvm.solver.predict import predict as device_predict  # noqa: E402
from tpusvm.status import Status  # noqa: E402


def _predict(sv_X, sv_Y, sv_alpha, b, Xq, gamma, dtype=jnp.float64):
    yp = device_predict(
        jnp.asarray(Xq, dtype), jnp.asarray(sv_X, dtype),
        jnp.asarray(sv_Y), jnp.asarray(sv_alpha, dtype),
        jnp.asarray(b, dtype), gamma=gamma)
    return np.asarray(yp)


def run_case(seed: int, shards: int):
    rng = np.random.default_rng(seed)
    # n: multiple-of-shards not required (partition pads); >= ~48/shard so
    # shards see both classes after the shuffle; 128 extra rows become
    # the held-out slice
    gen_name, n, X, Y, C, gamma = random_instance(
        rng, seed, (192, 512), (2, 16), [1.0, 10.0], [0.25, 1.0, 4.0],
        extra=128)
    perm = rng.permutation(len(Y))
    X, Y = X[perm], Y[perm]
    Xq, Yq = X[n:], Y[n:]  # held-out slice
    X, Y = X[:n], Y[:n]
    sc = MinMaxScaler().fit(X)
    Xs, Xqs = sc.transform(X), sc.transform(Xq)
    cfg = SVMConfig(C=C, gamma=gamma, max_rounds=10)
    solver = "blocked" if seed % 2 else "pair"
    # capacity = n: rings at large C can make nearly every point an SV,
    # and the tree rounds train (received SVs u own partition)
    cc = lambda topo: CascadeConfig(  # noqa: E731 — tiny local factory
        n_shards=shards, sv_capacity=n, topology=topo)

    rec = {"seed": seed, "gen": gen_name, "n": n, "d": Xs.shape[1],
           "C": C, "gamma": round(gamma, 6), "shards": shards,
           "solver": solver, "topologies": {}, "violations": []}

    models = {}
    for topo in ("tree", "star"):
        res = cascade_fit(Xs, Y, cfg, cc(topo), solver=solver)
        yp = _predict(res.sv_X, res.sv_Y, res.sv_alpha, res.b, Xqs, gamma)
        models[topo] = (set(res.sv_ids.tolist()), yp,
                        float((yp == Yq).mean()))
        rec["topologies"][topo] = {
            "converged": bool(res.converged), "rounds": res.rounds,
            "n_sv": len(res.sv_ids), "b": res.b,
            "accuracy": models[topo][2],
        }
        if not res.converged:
            rec["violations"].append(f"{topo}-not-converged")

    sv_t, yp_t, acc_t = models["tree"]
    sv_s, yp_s, acc_s = models["star"]
    jac = len(sv_t & sv_s) / max(len(sv_t | sv_s), 1)
    flips = int((yp_t != yp_s).sum())
    rec["sv_jaccard"] = round(jac, 4)
    rec["pred_flips"] = flips
    if jac < 0.9:
        rec["violations"].append("jaccard")
    if flips > max(2, len(Yq) // 50):
        rec["violations"].append("pred-disagreement")

    # direct single-shard reference solve on the same instance
    r = blocked_smo_solve(
        jnp.asarray(Xs, jnp.float64), jnp.asarray(Y), C=C, gamma=gamma,
        eps=cfg.eps, tau=cfg.tau, max_iter=cfg.max_iter,
        accum_dtype=jnp.float64)
    alpha = np.asarray(r.alpha)
    sv = alpha > 1e-8
    yp_d = _predict(Xs[sv], Y[sv], alpha[sv], float(r.b), Xqs, gamma)
    rec["direct_accuracy"] = float((yp_d == Yq).mean())
    rec["direct_status"] = Status(int(r.status)).name
    if int(r.status) != Status.CONVERGED:
        # an unconverged reference model would make the accuracy-gap
        # check meaningless in either direction — flag it loudly
        rec["violations"].append("direct-not-converged")
    for topo, acc in (("tree", acc_t), ("star", acc_s)):
        if abs(acc - rec["direct_accuracy"]) > 0.05:
            rec["violations"].append(f"{topo}-accuracy-gap")
    return rec


def main(n_cases: int = 24, base_seed: int = 3000, shards: int = 4) -> int:
    violations = 0
    for i in range(n_cases):
        rec = run_case(base_seed + i, shards)
        print(json.dumps(rec), flush=True)
        violations += len(rec["violations"])
    print(json.dumps({
        "summary": True, "cases": n_cases, "shards": shards,
        "violations": violations, "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }), flush=True)
    return 0 if violations == 0 else 1


if __name__ == "__main__":
    sys.exit(main(*(int(a) for a in sys.argv[1:4])))
