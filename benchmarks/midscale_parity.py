"""Mid-scale oracle-vs-device parity on the bench-recipe workload.

VERDICT r3 #2: the repo's oracle-anchored parity previously topped out at
n~200 — far below the sizes where the blocked solver's production
machinery (q-sized top-k working sets, subproblem caps, approx selection)
actually engages. This harness demonstrates the reference's own
cross-implementation parity criterion — identical SV counts and identical
accuracy between its serial and accelerator builds at n=60k
(/root/reference/README.md:35-38; report §6), plus a b-agreement band of
<0.003% DERIVED from the report's Table 1 b columns (−5.9026206 serial vs
−5.9027319 GPU ≈ 1.3e-4 absolute ≈ 0.002%; the README itself states no b
tolerance) — at n=2048-4096 on the exact optimisation
problem the headline benchmark measures (bench.py frozen recipe:
mnist_like noise=30, label_noise=0.005, gamma=0.00125, C=10).

Engines compared against the float64 NumPy oracle (tpusvm.oracle.smo):
  - pair:           solver/smo.py, f64 features (trajectory-level twin)
  - blocked-exact:  solver/blocked.py, inner=xla, selection=exact,
                    PRODUCTION precision (f32 features + f64 accumulators)
  - blocked-approx: ditto with selection=approx — the shipping TPU default
                    (resolve_solver_config resolves selection='auto' to
                    approx on TPU), forced on explicitly so the CPU run
                    exercises the same code path
  - blocked-{exact,approx}-wss2: ditto with second-order (maximal-gain)
                    partner selection — the wss=2 path every headline
                    benchmark ships (bench.py), on the XLA engine since
                    round 4
  - blocked-cpu-bench-config: the EXACT shipping CPU-fallback config
                    (bench.py off-TPU: q=2048, max_inner=32768, wss=2,
                    selection auto->exact) so the headline-producing
                    configuration itself is oracle-anchored

Usage: python benchmarks/midscale_parity.py \
           [--anchor oracle|pair|blocked64] [--grid full|bench] \
           [--max-iter N] [n ...]
(default: oracle anchor, full grid, max_iter 1e6, sizes 2048 4096;
--grid bench = the two shipping configs only — required for meaningful
beyond-60k summaries, see the grid construction comment; --max-iter
raises the safety bound for every engine, anchor included)
Emits one JSON line per (n, engine) with n_sv / b / accuracy / timings and
per-engine deltas vs the anchor, then one summary line per n. Rows are
appended to benchmarks/results/midscale_parity_cpu.jsonl by hand after a
capture (same convention as the other result files).

--anchor pair skips the NumPy oracle and anchors every comparison on the
f64 PAIR SOLVER instead — for sizes where the oracle's single-core
wall-clock is prohibitive (n=60000: ~7 h vs ~2.5 h). Justified by the
committed oracle-anchored rows: at every size 2048..32768 the pair
solver reproduced the oracle's SV set EXACTLY with b to <= 5e-12%, so at
60k it stands in as the serial-precision anchor (the role the
reference's own n=60k comparison gives its CPU build). Delta/summary
fields carry the anchor name ('..._vs_pair', summary.anchor).

--anchor blocked64 (round 5) goes one rung further for sizes beyond the
reference's 60k ceiling where even the pair solver is prohibitive
(~a week at n=480000 single-core): an f64-end-to-end BLOCKED solve
anchors, cross-checking production f32 precision at scale; the
working-set schedule itself stays anchored transitively by the
committed oracle -> pair -> blocked chain (exact SV sets through
n=60000). See run_size's docstring for the full caveat.
"""
import dataclasses
import json
import os
import sys
import time
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

from benchmarks.common import pin_platform  # noqa: E402

pin_platform()  # TPUSVM_PROBE_PLATFORM=cpu -> CPU backend (see helper)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpusvm.data import MinMaxScaler, mnist_like  # noqa: E402
from tpusvm.oracle import get_sv_indices, smo_train  # noqa: E402
from tpusvm.oracle import predict as oracle_predict  # noqa: E402
from tpusvm.config import SVMConfig  # noqa: E402
from tpusvm.solver import smo_solve  # noqa: E402
from tpusvm.solver.blocked import (  # noqa: E402
    blocked_smo_solve,
    resolve_solver_config,
)
from tpusvm.solver.predict import predict as device_predict  # noqa: E402
from tpusvm.status import Status  # noqa: E402

# the headline recipe's hyperparameters (bench.py)
CFG = SVMConfig(C=10.0, gamma=0.00125, eps=1e-12, tau=1e-5, max_iter=10**6)
# --max-iter overrides CFG.max_iter for EVERY engine (anchor included):
# the safety bound, not the stopping rule. The committed <=60k rows ran
# the 1e6 default; beyond-60k blocked64 runs need more (the sweep's
# q=2048/mi=32768 config alone spends 447k updates at n=120k, and the
# grid's q=1024/mi=4096 engines spend several times that) — comparing
# MAX_ITER-truncated trajectories would not be parity evidence, so
# run_size REFUSES to print a summary row when any engine truncated.
N_TEST = 2000


def effective_cfg(max_iter=None):
    """CFG with the optional --max-iter override applied, as a LOCAL copy.

    run_size used to `global CFG` and mutate the module config in place,
    so a later run_size call without max_iter silently inherited the
    previous override (ADVICE r5) — library/test callers could get parity
    rows under an unintended iteration bound. A dataclasses.replace copy
    keeps the module-level recipe constant immutable.
    """
    if max_iter is None:
        return CFG
    return dataclasses.replace(CFG, max_iter=max_iter)


def _sv_crc(sv: np.ndarray) -> int:
    """CRC of the sorted SV index set — lets a reader diff rows at a glance."""
    return zlib.crc32(np.asarray(sorted(sv), np.int64).tobytes())


def _row(n, engine, status, n_sv, b, acc, train_s, sv, extra=None):
    rec = {
        "n": n,
        "engine": engine,
        "status": Status(int(status)).name,
        "n_sv": int(n_sv),
        "b": float(b),
        "accuracy": float(acc),
        "train_s": round(train_s, 3),
        "sv_crc": _sv_crc(sv),
    }
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)
    return rec


def run_size(n: int, anchor: str = "oracle", max_iter: int = None,
             grid_mode: str = "full"):
    """anchor='oracle' (default): the float64 NumPy oracle anchors every
    comparison — the committed n <= 32768 rows. anchor='pair': the f64
    PAIR SOLVER anchors instead and the NumPy oracle is skipped — for
    sizes where the oracle's single-core wall-clock is prohibitive
    (n=60000: ~7 h vs ~2.5 h). Justification: at every committed
    oracle-anchored size (2048..32768) the pair solver reproduced the
    oracle's SV set EXACTLY with b to <= 5e-12% — it is the oracle's
    trajectory twin, so at 60k it stands in as the serial-precision
    anchor the reference's own comparison used its CPU build for.
    Delta/summary field names carry the anchor ('..._vs_pair').

    anchor='blocked64': a BLOCKED solve with float64 features AND f64
    accumulators (exact selection, wss=2) anchors, and both the oracle
    and the pair solver are skipped — for sizes beyond the reference's
    60k ceiling where even the pair solver is prohibitive (its 60k run
    took 10039 s single-core; at 480k the O(n*d) per-update stream and
    the grown update count put it around a WEEK). This is a weaker
    anchor than oracle/pair — same algorithm family as the engines under
    test, so it cross-checks PRECISION (f64 end-to-end vs production
    f32+f64), not the working-set schedule; the schedule itself is
    anchored transitively by the committed chain (oracle -> pair ->
    blocked, exact SV sets through n=60000). Field names carry
    '..._vs_blocked64'."""
    if anchor not in ("oracle", "pair", "blocked64"):
        raise SystemExit(
            f"anchor must be oracle|pair|blocked64, got {anchor!r}")
    if grid_mode not in ("full", "bench"):
        raise SystemExit(f"grid_mode must be full|bench, got {grid_mode!r}")
    cfg = effective_cfg(max_iter)
    # train/test from sibling seeds of the frozen recipe (bench.py uses
    # seed=587 at n=60k; a different seed here guards against tuning any
    # tolerance to the measured instance)
    X, Y = mnist_like(n=n, d=784, seed=7, noise=30.0, label_noise=0.005)
    Xt, Yt = mnist_like(n=N_TEST, d=784, seed=8, noise=30.0,
                        label_noise=0.005)
    sc = MinMaxScaler().fit(X)
    Xs, Xq = sc.transform(X), sc.transform(Xt)

    def _accuracy(alpha, b, dtype):
        yp = device_predict(
            jnp.asarray(Xq, dtype), jnp.asarray(Xs, dtype), jnp.asarray(Y),
            jnp.asarray(alpha, dtype), jnp.asarray(b, dtype),
            gamma=cfg.gamma)
        return float((np.asarray(yp) == Yt).mean())

    rows = {}
    truncated = []  # engines that hit the max_iter safety bound
    if anchor == "oracle":
        # --- oracle (float64 NumPy, the correctness anchor) ---
        t0 = time.perf_counter()
        o = smo_train(Xs, Y, CFG)
        o_s = time.perf_counter() - t0
        sv_o = get_sv_indices(o.alpha)
        acc_o = float((oracle_predict(Xq, Xs, Y, o.alpha, o.b, cfg.gamma)
                       == Yt).mean())
        _row(n, "oracle", o.status, len(sv_o), o.b, acc_o, o_s, sv_o,
             {"iterations": int(o.n_iter)})
        if int(o.status) == Status.MAX_ITER:
            truncated.append("oracle")
        sv_a, b_a, acc_a = sv_o, float(o.b), acc_o

    def _deltas(sv, b, acc):
        return {
            f"sv_sym_diff_vs_{anchor}": int(len(set(sv) ^ set(sv_a))),
            f"b_rel_diff_pct_vs_{anchor}":
                abs(float(b) - b_a) / abs(b_a) * 100,
            f"acc_delta_vs_{anchor}": round(acc - acc_a, 6),
        }

    if anchor != "blocked64":
        # --- pair solver, f64 features: the oracle's trajectory twin ---
        t0 = time.perf_counter()
        j = smo_solve(jnp.asarray(Xs, jnp.float64), jnp.asarray(Y),
                      C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
                      max_iter=cfg.max_iter)
        a_j = np.asarray(j.alpha)
        j_s = time.perf_counter() - t0
        sv_j = get_sv_indices(a_j)
        acc_j = _accuracy(a_j, j.b, jnp.float64)
        if anchor == "pair":
            sv_a, b_a, acc_a = sv_j, float(j.b), acc_j
            pair_extra = {"iterations": int(j.n_iter), "is_anchor": True}
        else:
            pair_extra = {"iterations": int(j.n_iter),
                          **_deltas(sv_j, float(j.b), acc_j)}
        _row(n, "pair-f64", j.status, len(sv_j), float(j.b), acc_j, j_s,
             sv_j, pair_extra)
        rows["pair-f64"] = (sv_j, float(j.b), acc_j)
        if int(j.status) == Status.MAX_ITER:
            truncated.append("pair-f64")
    else:
        # --- f64-end-to-end blocked anchor (see docstring) ---
        t0 = time.perf_counter()
        jb = blocked_smo_solve(
            jnp.asarray(Xs, jnp.float64), jnp.asarray(Y), C=cfg.C,
            gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
            max_iter=cfg.max_iter, q=2048, max_inner=8192, wss=2,
            selection="exact", max_outer=5000, inner="xla",
            accum_dtype=jnp.float64)
        a_jb = np.asarray(jb.alpha)
        jb_s = time.perf_counter() - t0
        sv_jb = get_sv_indices(a_jb)
        acc_jb = _accuracy(a_jb, float(jb.b), jnp.float64)
        sv_a, b_a, acc_a = sv_jb, float(jb.b), acc_jb
        _row(n, "blocked64", jb.status, len(sv_jb), float(jb.b), acc_jb,
             jb_s, sv_jb,
             {"updates": int(jb.n_iter), "n_outer": int(jb.n_outer),
              "is_anchor": True})
        rows["blocked64"] = (sv_jb, float(jb.b), acc_jb)
        if int(jb.status) == Status.MAX_ITER:
            truncated.append("blocked64")

    # --- blocked solver, production precision, exact + approx selection ---
    if anchor == "oracle":
        rows = {"oracle": (sv_o, float(o.b), acc_o), **rows}
    # the exact shipping CPU-fallback config (bench.py off-TPU), shared
    # by both grid modes — ONE definition so the copies cannot drift
    cpu_bench_cfg = ("blocked-cpu-bench-config",
                     dict(q=2048, max_inner=32768, wss=2, selection="auto"))
    if grid_mode == "bench":
        # shipping configs only (the TPU bench shape + the CPU-fallback
        # shape): at beyond-60k sizes the historical q=1024/mi=4096 grid
        # rows' strict-stop tails outgrow any feasible single-core
        # budget (blocked-exact wss1 ran 4e6 updates at n=120k without
        # closing) — comparing the configs that actually ship keeps the
        # summary meaningful there
        grid = [("blocked-tpu-bench-config",
                 dict(q=2048, max_inner=4096, wss=2, selection="approx")),
                cpu_bench_cfg]
    else:
        grid = [
            (f"blocked-{sel}" + ("-wss2" if wss == 2 else ""),
             dict(q=1024, max_inner=4096, wss=wss, selection=sel))
            for sel, wss in (("exact", 1), ("approx", 1),
                             ("exact", 2), ("approx", 2))
        ]
        grid.append(cpu_bench_cfg)
    for name, opts in grid:
        q_eff, inner_eff, wss_eff, sel_eff = resolve_solver_config(
            n, q=opts["q"], inner="xla", wss=opts["wss"],
            selection=opts["selection"])
        t0 = time.perf_counter()
        r = blocked_smo_solve(
            jnp.asarray(Xs, jnp.float32), jnp.asarray(Y), C=cfg.C,
            gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
            max_iter=cfg.max_iter,
            max_outer=5000, inner="xla", accum_dtype=jnp.float64, **opts)
        a_r = np.asarray(r.alpha)
        r_s = time.perf_counter() - t0
        sv_r = get_sv_indices(a_r)
        acc_r = _accuracy(a_r, float(r.b), jnp.float32)
        _row(n, name, r.status, len(sv_r), float(r.b), acc_r, r_s, sv_r,
             {"updates": int(r.n_iter), "n_outer": int(r.n_outer),
              "solver_config": {"q": q_eff, "inner": inner_eff,
                                "wss": wss_eff, "selection": sel_eff,
                                "max_inner": opts["max_inner"]},
              **_deltas(sv_r, float(r.b), acc_r)})
        rows[name] = (sv_r, float(r.b), acc_r)
        if int(r.status) == Status.MAX_ITER:
            truncated.append(name)

    # --- summary: the reference's parity criterion, stated per engine ---
    # REFUSED when any engine hit the safety bound: two MAX_ITER-truncated
    # trajectories agreeing (or not) says nothing about the converged
    # optima — re-run with a larger --max-iter instead
    if truncated:
        refusal = {"n": n, "engine": "summary", "refused": True,
                   "max_iter": cfg.max_iter, "truncated": truncated,
                   "platform": jax.default_backend(),
                   "reason": "engines hit the max_iter safety bound; "
                             "parity verdicts on truncated trajectories "
                             "are not evidence — raise --max-iter"}
        print(json.dumps(refusal), flush=True)
        return rows, refusal
    anchor_name = {"oracle": "oracle", "pair": "pair-f64",
                   "blocked64": "blocked64"}[anchor]
    summary = {"n": n, "engine": "summary", "anchor": anchor_name,
               "platform": jax.default_backend(),
               "criterion": "identical SV set / b within 0.003% / equal "
                            "accuracy (SV+accuracy: reference "
                            "README.md:35-38 + report §6; the 0.003% b "
                            "band is derived from the report's Table 1 b "
                            "columns, not quoted), "
                            f"vs {anchor_name}"}
    for name, (sv, b, acc) in rows.items():
        if name == anchor_name:
            continue
        summary[name] = {
            "sv_set_identical": bool(set(sv) == set(sv_a)),
            "b_within_0.003pct": bool(
                abs(b - b_a) / abs(b_a) * 100 < 0.003),
            "accuracy_equal": bool(acc == acc_a),
        }
    print(json.dumps(summary), flush=True)
    return rows, summary


if __name__ == "__main__":
    args = sys.argv[1:]
    anchor = "oracle"
    if "--anchor" in args:
        i = args.index("--anchor")
        if i + 1 >= len(args):
            raise SystemExit(
                "--anchor needs a value: oracle|pair|blocked64")
        anchor = args[i + 1]
        del args[i:i + 2]
    for a in args:
        if a.startswith("--anchor="):
            anchor = a.split("=", 1)[1]
            args.remove(a)
            break
    grid_mode = "full"
    if "--grid" in args:
        i = args.index("--grid")
        if i + 1 >= len(args):
            raise SystemExit("--grid needs a value: full|bench")
        grid_mode = args[i + 1]
        del args[i:i + 2]
    for a in args:
        if a.startswith("--grid="):
            grid_mode = a.split("=", 1)[1]
            args.remove(a)
            break
    if grid_mode not in ("full", "bench"):
        raise SystemExit(f"--grid must be full|bench, got {grid_mode!r}")
    max_iter = None
    if "--max-iter" in args:
        i = args.index("--max-iter")
        if i + 1 >= len(args):
            raise SystemExit("--max-iter needs an integer value")
        max_iter = int(args[i + 1])
        del args[i:i + 2]
    for a in args:
        if a.startswith("--max-iter="):
            max_iter = int(a.split("=", 1)[1])
            args.remove(a)
            break
    sizes = [int(a) for a in args] or [2048, 4096]
    for n in sizes:
        run_size(n, anchor=anchor, max_iter=max_iter, grid_mode=grid_mode)
