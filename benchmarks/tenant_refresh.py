#!/usr/bin/env python
"""Coalesced fleet refresh vs N solo daemons: the tenant-platform economics.

The tenants tier's acceptance harness: B drifted tenants sharing one
append-grown corpus are refreshed three ways on identical data —

  solo_warm       B separate ``refresh_fit`` runs, each inside its own
                  profiling scope WITH the jit caches cleared between
                  tenants — per-PROCESS accounting, because this arm
                  models the PR 15 deployment it replaces: one autopilot
                  daemon per tenant, so nothing is shared, not even a
                  compile cache
  coalesced_warm  ONE ``refresh_drifted`` call: the whole tenant set in
                  one power-of-two fleet launch, X loaded and scaled
                  once, every tenant's deployed_seed riding the alpha0
                  lane (tpusvm.tenants.coalesce)
  coalesced_cold  the same launch with warm=False — the control the warm
                  path's update savings are measured against

with HARD parity gates (each coalesced tenant keeps its solo control's
exact SV-ID set, status and held-out accuracy) and the two economics
gates the tenants tier exists for:

  * compiles: the coalesced refresh must compile FEWER XLA executables
    than the N-solo-daemon arm total (B lanes, one program);
  * updates: coalesced_warm must spend strictly fewer total SMO updates
    than coalesced_cold (the warm seeds do real work), and stay within
    10% of solo_warm's total (coalescing must not degrade the
    per-tenant warm quality it inherits).

Wall-clock columns are direction-gated at full level only (--smoke rows
carry them for provenance; benchdiff timing rules skip at smoke level,
where the CI runner is not the baseline machine).

Usage: python benchmarks/tenant_refresh.py [--smoke] [--tenants 16]
           [--n 768] [--grow 256] [--d 8] [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.common import emit, log, pin_platform  # noqa: E402

pin_platform()

import jax  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (schema/CI run): parity + compile "
                    "+ update gates only, no timing claims")
    ap.add_argument("--tenants", type=int, default=16)
    ap.add_argument("--n", type=int, default=768,
                    help="corpus rows at donor provisioning")
    ap.add_argument("--grow", type=int, default=256,
                    help="appended rows the refresh absorbs")
    ap.add_argument("--n-test", type=int, default=128)
    ap.add_argument("--d", type=int, default=8)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--seed", type=int, default=20260806)
    ap.add_argument("--jsonl", default=None,
                    help="also append the records to this file")
    args = ap.parse_args(argv)
    if args.smoke:
        args.tenants, args.n, args.grow = 8, 320, 128
        args.n_test, args.d = 64, 6

    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from tpusvm.models import BinarySVC
    from tpusvm.obs import prof
    from tpusvm.obs.registry import MetricsRegistry
    from tpusvm.serve.refresh import refresh_fit
    from tpusvm.tenants import (
        TenantRecord,
        provision_tenants,
        refresh_drifted,
        tenant_labels,
    )

    B = args.tenants
    K = args.classes
    rng = np.random.default_rng(args.seed)
    n_all = args.n + args.grow + args.n_test
    labels = rng.integers(0, K, size=n_all).astype(np.int32)
    means = rng.normal(0.0, 2.0, size=(K, args.d))
    X = means[labels] + rng.normal(0.0, 1.0, size=(n_all, args.d))
    X[args.n:] += 0.5  # the appended batch is distribution-shifted
    n_train = args.n + args.grow
    Xtr, ytr = X[:n_train], labels[:n_train]
    Xte, yte = X[n_train:], labels[n_train:]
    C_PAL, G_PAL = (1.0, 3.0, 10.0), (0.5, 1.5, 5.0)

    def mk_records():
        return [TenantRecord(
            tenant_id=f"t{i:02d}", positive_label=i % K,
            C=C_PAL[i % 3], gamma=G_PAL[(i // 3) % 3])
            for i in range(B)]

    def accuracy(path, rec):
        m = BinarySVC.load(path, dtype=jnp.float32)
        Yt, _ = tenant_labels(yte, rec)
        pred = np.where(
            np.asarray(m.decision_function(Xte)) >= 0, 1, -1)
        return float((pred == Yt).mean())

    records, violations = [], []
    with tempfile.TemporaryDirectory() as td:
        donors = os.path.join(td, "donors")
        os.makedirs(donors)
        recs = mk_records()
        log(f"provisioning {B} donors (one cold fleet launch, "
            f"n={args.n})...")
        provision_tenants(X[:args.n], labels[:args.n], recs,
                          artifacts_dir=donors)

        arms = {}

        # ---- solo_warm: B daemons, per-process accounting
        sdir = os.path.join(td, "solo")
        os.makedirs(sdir)
        log(f"solo_warm: {B} separate refresh_fit daemons...")
        jax.clear_caches()
        compiles = updates = 0
        t0 = time.perf_counter()
        for rec in recs:
            jax.clear_caches()  # each daemon is its own process
            with prof.profiling(registry=MetricsRegistry()) as obs:
                m = refresh_fit(
                    rec.model_path, Xtr, np.asarray(
                        tenant_labels(ytr, rec)[0]),
                    out_path=os.path.join(sdir, rec.tenant_id + ".npz"))
            compiles += len(obs.records)
            updates += int(m.n_iter_)
        arms["solo_warm"] = dict(
            refresh_s=time.perf_counter() - t0,
            compiles=compiles, updates=updates, outdir=sdir)

        # ---- coalesced arms: one refresh_drifted launch each
        for arm, warm in (("coalesced_warm", True),
                          ("coalesced_cold", False)):
            adir = os.path.join(td, arm)
            os.makedirs(adir)
            log(f"{arm}: one fleet launch over {B} tenants...")
            jax.clear_caches()
            arecs = mk_records()
            for r, src in zip(arecs, recs):
                r.model_path = src.model_path
            t0 = time.perf_counter()
            with prof.profiling(registry=MetricsRegistry()) as obs:
                outcomes = refresh_drifted(
                    Xtr, ytr, arecs, artifacts_dir=adir, warm=warm)
            arms[arm] = dict(
                refresh_s=time.perf_counter() - t0,
                compiles=len(obs.records),
                updates=sum(int(o["n_iter"])
                            for o in outcomes.values()),
                outdir=adir)
            bad = [t for t, o in outcomes.items() if "error" in o]
            if bad:
                violations.append(f"{arm}: failed tenants {bad}")

        # ---- parity: each coalesced tenant vs its solo control
        solo_art = {r.tenant_id: BinarySVC.load(
            os.path.join(sdir, r.tenant_id + ".npz")) for r in recs}
        solo_acc = {r.tenant_id: accuracy(
            os.path.join(sdir, r.tenant_id + ".npz"), r) for r in recs}
        for arm in arms:
            a = arms[arm]
            sv_parity = status_parity = accuracy_parity = True
            statuses_converged = True
            for rec in recs:
                path = os.path.join(a["outdir"], rec.tenant_id + ".npz")
                m = BinarySVC.load(path)
                ctl = solo_art[rec.tenant_id]
                if m.status_.name != "CONVERGED":
                    statuses_converged = False
                if arm == "solo_warm":
                    continue
                if not np.array_equal(m.sv_ids_, ctl.sv_ids_):
                    sv_parity = False
                if m.status_ != ctl.status_:
                    status_parity = False
                if accuracy(path, rec) != solo_acc[rec.tenant_id]:
                    accuracy_parity = False
            a.update(sv_parity=sv_parity, status_parity=status_parity,
                     accuracy_parity=accuracy_parity,
                     statuses_converged=statuses_converged)
            if not statuses_converged:
                violations.append(f"{arm}: a tenant did not converge")
            if arm != "solo_warm" and not (
                    sv_parity and status_parity and accuracy_parity):
                violations.append(
                    f"{arm}: parity vs the solo controls broken "
                    f"(sv {sv_parity}, status {status_parity}, "
                    f"accuracy {accuracy_parity})")

        # ---- the economics gates
        if arms["coalesced_warm"]["compiles"] >= \
                arms["solo_warm"]["compiles"]:
            violations.append(
                "coalesced refresh compiled "
                f"{arms['coalesced_warm']['compiles']} executables, "
                f"not fewer than the {B}-daemon arm's "
                f"{arms['solo_warm']['compiles']}")
        if arms["coalesced_warm"]["updates"] >= \
                arms["coalesced_cold"]["updates"]:
            violations.append(
                "warm coalesced refresh spent "
                f"{arms['coalesced_warm']['updates']} updates, not "
                "strictly fewer than the cold control's "
                f"{arms['coalesced_cold']['updates']}")
        if arms["coalesced_warm"]["updates"] > \
                1.10 * max(1, arms["solo_warm"]["updates"]):
            violations.append(
                "warm coalesced refresh spent "
                f"{arms['coalesced_warm']['updates']} updates, beyond "
                "1.10x the solo-daemon arm's "
                f"{arms['solo_warm']['updates']}")

        for arm, a in arms.items():
            records.append({
                "bench": "tenant_refresh", "arm": arm,
                "B": B, "bucket": 1 << (B - 1).bit_length(),
                "n": n_train, "d": args.d,
                "grow": args.grow, "seed": args.seed,
                "warm": arm != "coalesced_cold",
                "compiles": a["compiles"],
                "updates": a["updates"],
                "sv_parity": a["sv_parity"],
                "status_parity": a["status_parity"],
                "accuracy_parity": a["accuracy_parity"],
                "statuses_converged": a["statuses_converged"],
                "refresh_s": round(a["refresh_s"], 6),
                "tenants_per_s": round(B / a["refresh_s"], 4),
                "smoke": bool(args.smoke),
            })
        records.append({
            "bench": "tenant_refresh", "summary": True,
            "B": B, "n": n_train, "d": args.d,
            "compile_saving": arms["solo_warm"]["compiles"]
            - arms["coalesced_warm"]["compiles"],
            "warm_update_saving": arms["coalesced_cold"]["updates"]
            - arms["coalesced_warm"]["updates"],
            "smoke": bool(args.smoke),
            "violations": violations,
        })

    for rec in records:
        emit(rec)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    if violations:
        for v in violations:
            log(f"GATE FAILED: {v}")
        return 1
    log(f"tenant_refresh: coalesced refresh of {B} tenants compiled "
        f"{arms['coalesced_warm']['compiles']} executables vs the "
        f"{B}-daemon arm's {arms['solo_warm']['compiles']}, warm "
        f"updates {arms['coalesced_warm']['updates']} vs cold "
        f"{arms['coalesced_cold']['updates']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
