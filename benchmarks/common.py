"""Shared benchmark harness pieces.

The reference's benchmark methodology (report §6, SURVEY.md §6): MNIST-60k
RBF SVM (gamma=0.00125, C=10), trained to the Keerthi stopping criterion,
timed train/predict phases excluding IO. Real MNIST CSVs are unavailable in
this environment (zero egress), so the workload is a deterministic
MNIST-shaped synthetic problem tuned so held-out accuracy is informative
(off the 1.0 ceiling, rising with n — see data.synthetic.BENCH_NOISE).
bench.py keeps its original harder recipe (noise=30 + 0.5% label flips)
for round-to-round headline comparability; it reports no accuracy.

Timing: AOT-compile first, then time pure execution, ending at host
materialisation of the result — `jax.block_until_ready` is not a reliable
barrier on this TPU runtime (.claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import json
import sys

import numpy as np

# reference numbers (BASELINE.md): config id -> seconds
GPU_TRAIN_S = {  # B3 n-sweep, 1 GPU
    10000: 3.555, 20000: 6.719, 30000: 10.164, 40000: 16.270,
    50000: 29.790, 60000: 58.570,
}
GPU_PREDICT_S = {  # B3 n-sweep predict (10k test points)
    10000: 6.854, 20000: 13.140, 30000: 19.439, 40000: 25.720,
    50000: 32.011, 60000: 38.297,
}
CASCADE_TRAIN_S = {  # (topology, P) -> seconds, B4-B13, 2x32-core nodes
    ("tree", 4): 1194.269, ("tree", 8): 839.406, ("tree", 16): 662.153,
    ("tree", 32): 671.448, ("tree", 64): 673.580,
    ("star", 4): 886.733, ("star", 8): 649.773, ("star", 16): 440.705,
    ("star", 32): 333.696, ("star", 64): 301.263,
}
SERIAL_TRAIN_S = 3285.662  # B1


def random_instance(rng, seed, n_range, d_range, C_choices, gamma_choices,
                    extra: int = 0):
    """One random binary instance from the shared fuzz geometry family.

    rings/blobs 50/50, n and (blobs-only) d drawn from the given ranges,
    gamma scaled ~1/d. Both fuzz harnesses (fuzz_parity, fuzz_cascade)
    draw through this so their geometry families stay in sync. The draw
    ORDER (gen, n, d, C, gamma) is part of the committed artifacts'
    reproducibility contract — rows are keyed by seed — so do not reorder
    the rng calls. `extra` rows are generated beyond the drawn n (for a
    held-out slice) without affecting the stream. Returns
    (gen_name, n, X, Y, C, gamma) with X of n + extra rows.
    """
    from tpusvm.data import blobs, rings

    gen = rings if rng.random() < 0.5 else blobs
    n = int(rng.integers(*n_range))
    d = int(rng.integers(*d_range)) if gen is blobs else 2
    C = float(rng.choice(C_choices))
    gamma = float(rng.choice(gamma_choices)) / max(1, d // 4)
    kw = dict(n=n + extra, seed=seed)
    if gen is blobs:
        kw["d"] = d
    X, Y = gen(**kw)
    return gen.__name__, n, X, Y, C, gamma


def pin_platform(env_var: str = "TPUSVM_PROBE_PLATFORM") -> None:
    """Pin the JAX backend from an env var, BEFORE backend init.

    TPUSVM_PROBE_PLATFORM=cpu selects the CPU backend for harness runs when
    the accelerator is unavailable (or to use the simulated multi-device
    mesh via XLA_FLAGS=--xla_force_host_platform_device_count=N). The
    env-var JAX_PLATFORMS route does NOT work in this environment —
    sitecustomize force-registers the accelerator plugin and sets
    jax_platforms programmatically, overriding it; only a later
    jax.config.update wins. Call this before any jax.numpy/device use."""
    import os

    import jax

    platform = os.environ.get(env_var)
    if platform:
        jax.config.update("jax_platforms", platform)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_workload(n: int, d: int = 784, seed: int = 587, n_test: int = 0):
    """Scaled float32 MNIST-shaped training set + labels.

    Uses the accuracy-calibrated recipe (data.synthetic.BENCH_NOISE) — NOT
    bench.py's original harder recipe (see module docstring), so sweep
    timings are not directly comparable to the bench.py headline.

    With n_test > 0, also returns a held-out slice scaled with the TRAIN
    min/max (the reference's evaluation protocol): (Xs, Y, Xt, Yt).
    """
    from tpusvm.data import MinMaxScaler, mnist_like
    from tpusvm.data.synthetic import BENCH_LABEL_NOISE, BENCH_NOISE

    X, Y = mnist_like(n=n + n_test, d=d, noise=BENCH_NOISE,
                      label_noise=BENCH_LABEL_NOISE, seed=seed)
    sc = MinMaxScaler().fit(X[:n])
    Xs = sc.transform(X[:n]).astype(np.float32)
    if not n_test:
        return Xs, Y
    Xt = sc.transform(X[n:]).astype(np.float32)
    return Xs, Y[:n], Xt, Y[n:]


def workload_record(gen_fn, **call_kwargs) -> dict:
    """Provenance dict DERIVED from the actual generator call.

    Benchmark rows self-describe synthetic-vs-real data (VERDICT r4 #4).
    Hand-built literal dicts can silently drift from the data actually
    trained (e.g. a hardcoded seed that is only correct while it matches
    the generator's default), so this helper reads the generator's
    signature defaults and overlays the kwargs the caller actually passed
    — pass the SAME kwargs dict to the generator and to this function.
    """
    import inspect

    merged = {
        name: p.default
        for name, p in inspect.signature(gen_fn).parameters.items()
        if p.default is not inspect.Parameter.empty
    }
    merged.update(call_kwargs)
    keep = ("n", "d", "seed", "noise", "label_noise", "n_classes")
    return {"gen": gen_fn.__name__, "synthetic": True,
            **{k: merged[k] for k in keep if k in merged}}


_PROVENANCE = None


def provenance_record() -> dict:
    """Backend/platform provenance for benchmark rows (cached per run).

    Every emitted record carries this so `tpusvm benchdiff` can refuse
    (or annotate) cross-backend comparisons — the BENCH_r02-r05 failure
    was single-CPU fallback rounds masquerading as TPU-comparable
    numbers, with nothing in the rows to flag it."""
    global _PROVENANCE
    if _PROVENANCE is None:
        import platform as _platform
        import socket

        import jax

        dev = jax.devices()[0]
        try:
            import jaxlib

            jaxlib_v = getattr(jaxlib, "__version__", None) or \
                jaxlib.version.__version__
        except Exception:  # noqa: BLE001 — provenance is best-effort
            jaxlib_v = None
        _PROVENANCE = {
            "backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "device_count": jax.device_count(),
            "jax": jax.__version__,
            "jaxlib": jaxlib_v,
            "hostname": socket.gethostname(),
            "python": _platform.python_version(),
        }
    return _PROVENANCE


def emit(record: dict) -> None:
    # provenance is injected here, centrally, so EVERY harness's rows
    # (stdout and --jsonl sinks alike — they serialise the same dict)
    # self-describe their backend without per-harness plumbing
    if isinstance(record, dict):
        record.setdefault("provenance", provenance_record())
    print(json.dumps(record), flush=True)


def h2d_sync(*arrays) -> None:
    """Force pending H2D uploads of `arrays` to COMPLETE before returning.

    device_put on the tunneled axon runtime is lazy, and
    jax.block_until_ready returns early there (it is not a completion
    barrier — see .claude/skills/verify/SKILL.md), so benchmark timers
    started after a bare device_put would absorb the upload. Materialising
    a reduction on the host is the reliable barrier.
    """
    import jax.numpy as jnp
    import numpy as np

    for a in arrays:
        np.asarray(jnp.sum(a))
